"""Model-quality observability (ISSUE 11): prediction drift,
shadow-scored canaries, feedback-joined online metrics, and the quality
promotion gate.

Acceptance spine: a clean server reads healthy (PSI ≈ 0 against its own
scorecard); a promoted generation with an injected score shift is
detected (PSI over threshold on BOTH windows), rolled back through the
existing ``/admin/rollback`` path by the refresh daemon's canary watch,
and the pre-promotion generation serves throughout with zero non-2xx;
``PIO_QUALITY=off`` disables every hook; the scorecard rides the model
wrapper (pickle-atomic with the model, fingerprint-mismatch degrades to
reporting-only); the ``/quality.json`` fleet merge never silently drops
a field.  All drift/hysteresis/trigger tests ride injectable clocks —
zero wall sleeps.
"""

import datetime as dt
import json
import pickle
import threading
from urllib.request import Request, urlopen

import numpy as np
import pytest

from predictionio_tpu.controller import EngineVariant, RuntimeContext
from predictionio_tpu.data.event import DataMap, Event
from predictionio_tpu.data.storage import AccessKey, App, get_storage
from predictionio_tpu.obs import get_registry
from predictionio_tpu.obs.quality import (
    DriftDetector,
    FeedbackJoiner,
    QualityConfig,
    QualityMonitor,
    Scorecard,
    ShadowScorer,
    extract_result_items,
    generation_of_serve_id,
    kl_divergence,
    merge_quality,
    note_feedback_events,
    psi,
    resolve_scorecard,
    scorecard_from_scores,
)
from predictionio_tpu.workflow.core_workflow import load_models, run_train

UTC = dt.timezone.utc


# ==========================================================================
# PSI / scorecard math
# ==========================================================================

class TestScorecardMath:
    def test_psi_zero_for_identical_distributions(self):
        p = [0.25, 0.25, 0.25, 0.25]
        assert psi(p, p) == pytest.approx(0.0)
        assert kl_divergence(p, p) == pytest.approx(0.0)

    def test_psi_known_value(self):
        # hand-computed: Σ (a-e)·ln(a/e)
        e = [0.5, 0.5]
        a = [0.8, 0.2]
        expect = (0.8 - 0.5) * np.log(0.8 / 0.5) \
            + (0.2 - 0.5) * np.log(0.2 / 0.5)
        assert psi(e, a) == pytest.approx(float(expect), abs=1e-9)
        assert psi(e, a) > 0

    def test_psi_smooths_empty_bins(self):
        assert np.isfinite(psi([0.0, 1.0], [1.0, 0.0]))

    def test_scorecard_quantile_bins_carry_equal_mass(self):
        rng = np.random.default_rng(0)
        sc = scorecard_from_scores(rng.normal(0, 1, 4000).tolist(),
                                   bins=16)
        assert sc is not None
        assert sum(sc.probs) == pytest.approx(1.0)
        # quantile construction: every bin holds ~1/16 of the mass
        assert max(sc.probs) < 0.2
        assert sc.n == 4000

    def test_scorecard_degenerate_sample_returns_none(self):
        assert scorecard_from_scores([]) is None
        assert scorecard_from_scores([1.0]) is None
        assert scorecard_from_scores([2.0] * 100) is None
        assert scorecard_from_scores([np.nan, np.inf, 1.0]) is None

    def test_edges_sit_between_observed_values_ulp_robust(self):
        # A tiny discrete sample: serving recomputes the same scores
        # through a different op order, so a value must not sit ON its
        # own bin edge (a 1-ulp difference would flip bins → fake PSI).
        vals = [float(v) for v in range(10)]
        sc = scorecard_from_scores(vals, bins=16)
        for v in vals:
            assert v not in sc.edges
            eps = 1e-9
            assert sc.bin_index(v - eps) == sc.bin_index(v + eps)


# ==========================================================================
# Drift detection (fake clock, zero wall sleeps)
# ==========================================================================

def _cfg(**kw) -> QualityConfig:
    base = dict(sample=1.0, reservoir=600, fast_window=100,
                min_samples=50, psi_threshold=0.25, recovery_s=30.0)
    base.update(kw)
    return QualityConfig(**base)


def _baseline(seed=0, n=2000):
    rng = np.random.default_rng(seed)
    return scorecard_from_scores(rng.normal(0, 1, n).tolist())


class TestDriftDetector:
    def test_clean_stream_never_trips(self):
        t = [0.0]
        det = DriftDetector(_cfg(), _baseline(), clock=lambda: t[0])
        rng = np.random.default_rng(1)
        for v in rng.normal(0, 1, 800):
            det.add(float(v))
        s = det.tick(force=True)
        assert not s["tripped"]
        assert s["psi"]["fast"] < 0.25 and s["psi"]["slow"] < 0.25

    def test_injected_shift_trips_on_both_windows(self):
        t = [0.0]
        det = DriftDetector(_cfg(), _baseline(), clock=lambda: t[0])
        rng = np.random.default_rng(2)
        for v in rng.normal(2.5, 1, 700):
            det.add(float(v))
        s = det.tick(force=True)
        assert s["tripped"]
        assert s["psi"]["fast"] >= 0.25 and s["psi"]["slow"] >= 0.25

    def test_fast_burst_alone_does_not_trip(self):
        # The slow window (generation reservoir) still holds mostly
        # clean mass — one burst must not read as a generation shift.
        t = [0.0]
        det = DriftDetector(_cfg(reservoir=4000), _baseline(),
                            clock=lambda: t[0])
        rng = np.random.default_rng(3)
        for v in rng.normal(0, 1, 3000):
            det.add(float(v))
        for v in rng.normal(3.0, 1, 120):   # fills the fast window only
            det.add(float(v))
        s = det.tick(force=True)
        assert s["psi"]["fast"] >= 0.25
        assert s["psi"]["slow"] < 0.25
        assert not s["tripped"]

    def test_hysteresis_clears_only_after_recovery_dwell(self):
        t = [0.0]
        det = DriftDetector(_cfg(recovery_s=30.0), _baseline(),
                            clock=lambda: t[0])
        rng = np.random.default_rng(4)
        for v in rng.normal(2.5, 1, 700):
            det.add(float(v))
        assert det.tick(force=True)["tripped"]
        # back to clean: both windows drain (reservoir mostly replaced)
        for v in rng.normal(0, 1, 6000):
            det.add(float(v))
        t[0] += 2.0
        s = det.tick(force=True)
        assert s["psi"]["fast"] < 0.25
        assert s["tripped"], "must stay tripped through the dwell"
        t[0] += 10.0
        assert det.tick(force=True)["tripped"]
        t[0] += 31.0
        assert not det.tick(force=True)["tripped"]
        # a flap inside the dwell resets it
        det2 = DriftDetector(_cfg(recovery_s=30.0), _baseline(),
                             clock=lambda: t[0])
        for v in rng.normal(2.5, 1, 700):
            det2.add(float(v))
        assert det2.tick(force=True)["tripped"]
        for v in rng.normal(0, 1, 6000):
            det2.add(float(v))
        t[0] += 10.0
        det2.tick(force=True)          # dwell running
        for v in rng.normal(2.5, 1, 700):
            det2.add(float(v))          # flap back over threshold
        t[0] += 1.0
        assert det2.tick(force=True)["tripped"]
        for v in rng.normal(0, 1, 6000):
            det2.add(float(v))
        t[0] += 20.0                    # 20s < 30s since the flap
        assert det2.tick(force=True)["tripped"]

    def test_cold_app_pass_through_never_trips(self):
        t = [0.0]
        det = DriftDetector(_cfg(min_samples=100), _baseline(),
                            clock=lambda: t[0])
        rng = np.random.default_rng(5)
        for v in rng.normal(5.0, 1, 60):   # wildly shifted but few
            det.add(float(v))
        s = det.tick(force=True)
        assert s["insufficient"]
        assert not s["tripped"]

    def test_missing_scorecard_is_reporting_only(self):
        det = DriftDetector(_cfg(), None, clock=lambda: 0.0)
        det.add(1.0)
        s = det.tick(force=True)
        assert s["reportingOnly"]
        assert s["reason"] == "no_scorecard"
        assert not s["tripped"]


# ==========================================================================
# Shadow scoring
# ==========================================================================

class TestShadowScorer:
    @pytest.fixture(autouse=True)
    def _iso(self, pio_home):
        # fresh registry per test: the scorer's counters/gauges must not
        # leak across cases
        yield

    def _scorer(self, fn, **kw):
        s = ShadowScorer(_cfg(min_samples=3, **kw))
        # arm a session WITHOUT the worker thread: tests drive
        # drain_once() synchronously
        s._fn = fn
        s._generation = 2
        s._prev_generation = 1
        return s

    def test_identical_results_overlap_one(self):
        items = [("a", 1.0), ("b", 0.5)]
        s = self._scorer(lambda q: {"itemScores": [
            {"item": "a", "score": 1.0}, {"item": "b", "score": 0.5}]})
        for _ in range(4):
            s.submit({"user": "u"}, items, generation=2)
            assert s.drain_once() == 1
        snap = s.snapshot()
        assert snap["overlapMean"] == 1.0
        assert not snap["divergent"]

    def test_disjoint_results_divergent_after_min_samples(self):
        s = self._scorer(lambda q: {"itemScores": [
            {"item": "x", "score": 2.0}]})
        for i in range(2):
            s.submit({"u": i}, [("a", 1.0)], generation=2)
            s.drain_once()
        assert s.snapshot()["insufficient"]      # 2 < min_samples=3
        assert not s.snapshot()["divergent"]      # pass-through
        s.submit({"u": 9}, [("a", 1.0)], generation=2)
        s.drain_once()
        snap = s.snapshot()
        assert snap["overlapMean"] == 0.0
        assert snap["divergent"]

    def test_score_delta_recorded_for_shared_items(self):
        reg = get_registry()
        s = self._scorer(lambda q: {"itemScores": [
            {"item": "a", "score": 1.0}]})
        s.submit({}, [("a", 1.5)], generation=2)
        s.drain_once()
        h = reg.get("pio_quality_shadow_delta")
        assert h.count() == 1
        # |1.5-1.0|/1.0 = 0.5
        assert h.sum() == pytest.approx(0.5, rel=1e-4)

    def test_queue_bound_drops_never_blocks(self):
        s = self._scorer(lambda q: {"itemScores": []},
                         shadow_queue=2)
        for i in range(5):
            s.submit({"u": i}, [("a", 1.0)], generation=2)
        reg = get_registry()
        assert reg.get("pio_quality_shadow_total") \
            .value(result="dropped") == 3

    def test_stale_generation_submits_ignored(self):
        s = self._scorer(lambda q: {"itemScores": []})
        s.submit({}, [("a", 1.0)], generation=99)   # not the session's
        assert s.drain_once() == 0

    def test_stop_drops_closure_and_queue(self):
        s = self._scorer(lambda q: {"itemScores": []})
        s.submit({}, [("a", 1.0)], generation=2)
        s.stop("rollback")
        assert not s.active()
        assert s.drain_once() == 0


# ==========================================================================
# Feedback join
# ==========================================================================

class TestFeedbackJoiner:
    def test_hit_miss_unmatched_expired(self, pio_home):
        t = [0.0]
        j = FeedbackJoiner(ttl_s=100.0, clock=lambda: t[0])
        j.note_serve("g3-aaa", 3, ["i1", "i2"])
        assert j.feedback("g3-aaa", "i1") == "hit"
        assert j.feedback("g3-aaa", "i7") == "miss"
        assert j.feedback("g9-zzz", "i1") == "unmatched"
        t[0] += 101.0
        assert j.feedback("g3-aaa", "i1") == "expired"
        snap = j.snapshot()
        assert snap["generations"]["3"] == {
            "hits": 1, "misses": 1, "attributedOnly": 0, "hitRate": 0.5}
        assert snap["generations"]["9"]["attributedOnly"] == 1

    def test_ttl_and_capacity_eviction(self, pio_home):
        t = [0.0]
        j = FeedbackJoiner(ttl_s=10.0, max_records=3, clock=lambda: t[0])
        for i in range(5):
            j.note_serve(f"g1-{i}", 1, ["x"])
        assert j.snapshot()["tracked"] == 3
        t[0] += 11.0
        j.note_serve("g1-new", 1, ["x"])
        assert j.snapshot()["tracked"] == 1   # the TTL swept the rest

    def test_generation_parse(self):
        assert generation_of_serve_id("g12-abcd") == 12
        assert generation_of_serve_id("nope") is None
        assert generation_of_serve_id("gxyz-1") is None

    def test_event_ingest_hook_joins_echoed_serves(self, pio_home,
                                                   monkeypatch):
        monkeypatch.setenv("PIO_QUALITY_SAMPLE", "1.0")
        from predictionio_tpu.obs.quality import feedback_joiner

        j = feedback_joiner()
        j.note_serve("g2-echo", 2, ["i5"])
        ev = Event(event="buy", entity_type="user", entity_id="u1",
                   target_entity_type="item", target_entity_id="i5",
                   properties=DataMap({"pioServeId": "g2-echo"}))
        note_feedback_events([ev])
        reg = get_registry()
        assert reg.get("pio_quality_feedback_total") \
            .value(result="hit") == 1
        # non-feedback event names are ignored even with an echo
        ev2 = Event(event="view", entity_type="user", entity_id="u1",
                    target_entity_type="item", target_entity_id="i5",
                    properties=DataMap({"pioServeId": "g2-echo"}))
        note_feedback_events([ev2])
        assert reg.get("pio_quality_feedback_total") \
            .value(result="hit") == 1

    def test_kill_switch_disables_hook(self, pio_home, monkeypatch):
        monkeypatch.setenv("PIO_QUALITY", "off")
        ev = Event(event="buy", entity_type="user", entity_id="u1",
                   target_entity_type="item", target_entity_id="i5",
                   properties=DataMap({"pioServeId": "g2-x"}))
        note_feedback_events([ev])
        assert get_registry().get("pio_quality_feedback_total") is None


# ==========================================================================
# Monitor facade + kill switch + result extraction
# ==========================================================================

class TestQualityMonitor:
    def test_extract_result_items(self):
        assert extract_result_items(
            {"itemScores": [{"item": "a", "score": 1.5}]}) == [("a", 1.5)]
        assert extract_result_items({"itemScores": []}) == []
        assert extract_result_items({"score": 0.7}) == [(None, 0.7)]
        assert extract_result_items({"label": "x"}) is None
        assert extract_result_items("nope") is None

    def test_observe_samples_and_issues_serve_id(self, pio_home):
        m = QualityMonitor(_cfg(sample=0.5, min_samples=2),
                           clock=lambda: 0.0)
        m.on_generation(4, [])
        sid = m.observe({}, {"itemScores": [{"item": "a", "score": 1.0}]},
                        4, u=0.0)
        assert sid is not None and sid.startswith("g4-")
        # a draw at/above the rate is not sampled: no serve id, no append
        assert m.observe({}, {"itemScores": []}, 4, u=0.999) is None
        reg = get_registry()
        assert reg.get("pio_quality_sampled_total").value() == 1
        assert reg.get("pio_predict_score").count() == 1

    def test_empty_and_diversity_accounting(self, pio_home):
        m = QualityMonitor(_cfg(min_samples=2), clock=lambda: 0.0)
        m.on_generation(1, [])
        m.observe({}, {"itemScores": []}, 1, u=0.0)
        for _ in range(4):
            m.observe({}, {"itemScores": [
                {"item": "hot", "score": 1.0},
                {"item": "x", "score": 0.5}]}, 1, u=0.0)
        doc = m.payload()
        assert doc["sampling"]["emptyTotal"] == 1
        # 8 slots, 2 distinct, "hot" takes half
        assert doc["diversity"]["candidateDiversity"] == pytest.approx(
            2 / 8)
        assert doc["diversity"]["topItemShare"] == pytest.approx(0.5)

    def test_kill_switch_disables_every_hook(self, pio_home, monkeypatch):
        monkeypatch.setenv("PIO_QUALITY", "off")
        m = QualityMonitor()
        assert not m.enabled
        m.on_generation(1, [])               # no-ops, no instruments
        assert m.observe({}, {"itemScores": [
            {"item": "a", "score": 1.0}]}, 1, u=0.0) is None
        assert m.payload() == {"enabled": False}
        assert m.summary() == {"enabled": False}
        m.close()
        reg = get_registry()
        for name in ("pio_quality_sampled_total", "pio_predict_score",
                     "pio_quality_drift", "pio_quality_shadow_total"):
            assert reg.get(name) is None, name

    def test_gate_respects_pass_through_and_gate_switch(self, pio_home):
        sc = _baseline()
        m = QualityMonitor(_cfg(min_samples=50), clock=lambda: 0.0)
        wrapper = type("W", (), {"quality": sc})()
        m.on_generation(1, [wrapper])
        rng = np.random.default_rng(0)
        # massive shift but BELOW min_samples → pass-through
        for v in rng.normal(4.0, 1, 30):
            m.observe({}, {"itemScores": [
                {"item": "a", "score": float(v)}]}, 1, u=0.0)
        doc = m.payload()
        assert doc["verdict"] == "insufficient"
        assert not doc["gate"]["rollback"]
        # past the floor → degraded + rollback verdict
        for v in rng.normal(4.0, 1, 600):
            m.observe({}, {"itemScores": [
                {"item": "a", "score": float(v)}]}, 1, u=0.0)
        m._detector.tick(force=True)
        doc = m.payload()
        assert doc["verdict"] == "degraded"
        assert doc["gate"]["rollback"] and "drift" in doc["gate"]["reasons"]
        # PIO_QUALITY_GATE=off reports but never gates
        m2 = QualityMonitor(_cfg(min_samples=50, gate=False),
                            clock=lambda: 0.0)
        m2.on_generation(1, [wrapper])
        for v in rng.normal(4.0, 1, 700):
            m2.observe({}, {"itemScores": [
                {"item": "a", "score": float(v)}]}, 1, u=0.0)
        m2._detector.tick(force=True)
        doc2 = m2.payload()
        assert doc2["verdict"] == "degraded"
        assert not doc2["gate"]["rollback"]


# ==========================================================================
# Scorecard rides the wrapper (atomic swap + mismatch tripwire)
# ==========================================================================

TT_VARIANT = {
    "id": "default",
    "engineFactory": "predictionio_tpu.templates.twotower:engine",
    "datasource": {"params": {"appName": "app"}},
    "algorithms": [{"name": "twotower",
                    "params": {"embedDim": 8, "hiddenDims": [16],
                               "outDim": 8, "epochs": 2, "batchSize": 32,
                               "seed": 1}}],
}


@pytest.fixture()
def ctx(pio_home):
    return RuntimeContext.create(storage=get_storage())


def _mk_app(ctx, name="app"):
    storage = ctx.storage
    app_id = storage.get_apps().insert(App(id=None, name=name))
    storage.get_events().init(app_id)
    return app_id


def _view(u, i, when=None):
    kw = {"event_time": when} if when is not None else {}
    return Event(event="view", entity_type="user", entity_id=f"u{u}",
                 target_entity_type="item", target_entity_id=f"i{i}", **kw)


def _seed_views(ctx, app_id, n_users=10, n_items=6):
    evs = [_view(u, i) for u in range(n_users) for i in range(n_items)
           if i % 2 == u % 2]
    ctx.storage.get_events().insert_batch(evs, app_id)


def _tt():
    from predictionio_tpu.templates.twotower import engine

    return engine(), EngineVariant.from_dict(TT_VARIANT)


class TestScorecardOnWrapper:
    def test_train_builds_scorecard_and_pickle_keeps_it(self, ctx):
        app_id = _mk_app(ctx)
        _seed_views(ctx, app_id)
        eng, variant = _tt()
        iid = run_train(eng, variant, ctx)
        wrapper = load_models(
            eng, ctx.storage.get_engine_instances().get(iid), ctx)[0]
        sc = wrapper.quality
        assert isinstance(sc, Scorecard) and sc.n > 0
        assert sc.fingerprint
        clone = pickle.loads(pickle.dumps(wrapper))
        assert clone.quality == sc        # model+scorecard = ONE artifact
        got, reason = resolve_scorecard([clone])
        assert got == sc and reason is None

    def test_fingerprint_mismatch_degrades_to_reporting_only(self, ctx):
        app_id = _mk_app(ctx)
        _seed_views(ctx, app_id)
        eng, variant = _tt()
        iid = run_train(eng, variant, ctx)
        wrapper = load_models(
            eng, ctx.storage.get_engine_instances().get(iid), ctx)[0]
        wrapper.item_vecs = np.asarray(wrapper.item_vecs) * 2.0
        got, reason = resolve_scorecard([wrapper])
        assert got is None and reason == "fingerprint_mismatch"
        # and the monitor serves it as reporting-only — never a gate
        m = QualityMonitor(_cfg(min_samples=1), clock=lambda: 0.0)
        m.on_generation(1, [wrapper])
        for _ in range(10):
            m.observe({}, {"itemScores": [
                {"item": "a", "score": 99.0}]}, 1, u=0.0)
        doc = m.payload()
        assert doc["verdict"] == "reporting_only"
        assert not doc["gate"]["rollback"]

    def test_old_pickle_without_scorecard_reports_no_scorecard(self):
        w = type("W", (), {})()
        got, reason = resolve_scorecard([w])
        assert got is None and reason == "no_scorecard"


# ==========================================================================
# /quality.json schema stability under the fleet merge (tier-1)
# ==========================================================================

def _doc_keys(doc, prefix=""):
    out = set()
    for k, v in doc.items():
        out.add(prefix + k)
        if isinstance(v, dict):
            out |= _doc_keys(v, prefix + k + ".")
    return out


class TestQualityFleetMerge:
    def _doc(self, gen=1, hits=2, misses=1):
        m = QualityMonitor(_cfg(min_samples=5), clock=lambda: 0.0)
        sc = _baseline()
        wrapper = type("W", (), {"quality": sc})()
        m.on_generation(gen, [wrapper])
        rng = np.random.default_rng(gen)
        for v in rng.normal(0, 1, 20):
            sid = m.observe({}, {"itemScores": [
                {"item": "a", "score": float(v)}]}, gen, u=0.0)
        for _ in range(hits):
            m.joiner.feedback(sid, "a")
        for _ in range(misses):
            m.joiner.feedback(sid, "zzz")
        return m.payload()

    def test_merge_never_silently_drops_a_field(self, pio_home):
        d1 = self._doc(gen=1)
        d2 = self._doc(gen=2)
        merged = merge_quality([d1, d2])
        missing = (_doc_keys(d1) | _doc_keys(d2)) - _doc_keys(merged)
        assert not missing, f"fleet merge dropped fields: {missing}"

    def test_merge_semantics(self, pio_home):
        d1, d2 = self._doc(gen=1), self._doc(gen=2)
        merged = merge_quality([d1, d2])
        assert merged["instances"] == 2
        assert merged["sampling"]["sampledTotal"] == \
            d1["sampling"]["sampledTotal"] + d2["sampling"]["sampledTotal"]
        # drift magnitudes take the worst, not the sum
        assert merged["drift"]["psi"]["fast"] == max(
            d1["drift"]["psi"]["fast"], d2["drift"]["psi"]["fast"])
        # counts sum per generation, ratios recompute from summed parts
        fb = {"enabled": True, "feedback": {"generations": {
            "1": {"hits": 2, "misses": 1, "hitRate": 0.6667}}}}
        g = merge_quality([fb, json.loads(json.dumps(fb))]) \
            ["feedback"]["generations"]["1"]
        assert g["hits"] == 4 and g["misses"] == 2
        assert g["hitRate"] == pytest.approx(4 / 6, abs=1e-3)
        # verdict worst-of
        assert merge_quality(
            [{"enabled": True, "verdict": "healthy"},
             {"enabled": True, "verdict": "degraded"}])["verdict"] \
            == "degraded"
        # all-disabled merges to disabled
        assert merge_quality([{"enabled": False}]) == {
            "enabled": False, "instances": 1}

    def test_fleet_aggregator_carries_quality(self, pio_home):
        from predictionio_tpu.obs.fleet import FleetAggregator

        doc = self._doc(gen=3)

        def fetch(url):
            if url.endswith("/metrics"):
                return "# TYPE pio_q_x counter\npio_q_x 1\n"
            if url.endswith("/quality.json"):
                return json.dumps(doc)
            raise OSError("nope")

        agg = FleetAggregator(["http://a:1", "http://b:2"], fetch=fetch)
        agg.scrape_once()
        payload = agg.payload()
        assert payload["instances"][0]["quality"]["generation"] == 3
        merged = payload["merged"]["quality"]
        assert merged["enabled"] and merged["instances"] == 2
        assert not (_doc_keys(doc) - _doc_keys(merged))

    def test_lint_rule4_quality_metrics_only_in_quality_module(self):
        import tools.lint_metrics as lint

        bad = ("import x\n"
               "reg.counter('pio_quality_rogue_total', 'h', ())\n")
        v = lint.check_source(bad, "predictionio_tpu/server/foo.py", {})
        assert any("rule 4" in s for s in v)
        ok = lint.check_source(
            bad, "predictionio_tpu/obs/quality.py", {})
        assert not any("rule 4" in s for s in ok)
        # and the real tree passes wholesale
        assert lint.check() == []


# ==========================================================================
# Shared sampling decision (PIO_REQUEST_LOG_SAMPLE)
# ==========================================================================

class TestRequestLogSampling:
    def _finalize(self, u):
        from predictionio_tpu.obs.waterfall import Waterfall

        wf = Waterfall()
        wf.stamp("bind", 1.0)
        wf.sample_u = u
        return wf.finalize(trace_id="t", status=200, total_ms=2.0)

    def test_sample_rate_gates_the_wide_event(self, pio_home,
                                              monkeypatch, tmp_path):
        log = tmp_path / "req.jsonl"
        monkeypatch.setenv("PIO_REQUEST_LOG", str(log))
        monkeypatch.setenv("PIO_REQUEST_LOG_SAMPLE", "0.5")
        self._finalize(u=0.4)     # under the rate → logged
        self._finalize(u=0.9)     # over → skipped
        lines = log.read_text().strip().splitlines()
        assert len(lines) == 1

    def test_default_rate_logs_everything(self, pio_home, monkeypatch,
                                          tmp_path):
        log = tmp_path / "req.jsonl"
        monkeypatch.setenv("PIO_REQUEST_LOG", str(log))
        monkeypatch.delenv("PIO_REQUEST_LOG_SAMPLE", raising=False)
        self._finalize(u=0.99999)
        assert len(log.read_text().strip().splitlines()) == 1

    def test_rate_zero_disables(self, pio_home, monkeypatch, tmp_path):
        log = tmp_path / "req.jsonl"
        monkeypatch.setenv("PIO_REQUEST_LOG", str(log))
        monkeypatch.setenv("PIO_REQUEST_LOG_SAMPLE", "0")
        self._finalize(u=0.0)
        assert not log.exists() or not log.read_text().strip()


# ==========================================================================
# Refresh-daemon trigger mode (fake clock, zero wall sleeps)
# ==========================================================================

class TestRefreshTriggerMode:
    def _daemon(self, ctx, clock, **cfg_kw):
        from predictionio_tpu.refresh import RefreshConfig
        from predictionio_tpu.refresh.daemon import RefreshDaemon

        eng, variant = _tt()
        cfg = RefreshConfig(interval_s=300.0, trigger_poll_s=1.0,
                            **cfg_kw)
        return RefreshDaemon(eng, variant, ctx, config=cfg,
                             clock=clock)

    def test_staleness_threshold_fires(self, ctx):
        app_id = _mk_app(ctx)
        now = dt.datetime.now(UTC)
        ctx.storage.get_events().insert(_view(0, 1, when=now), app_id)
        t = [0.0]
        d = self._daemon(ctx, lambda: t[0], trigger_staleness_s=30.0)
        d._served_wm = now - dt.timedelta(seconds=100)
        fire, reason = d._trigger_ready(cycle_started=0.0)
        assert fire and reason == "staleness"
        # staleness gauge updated at poll cadence
        assert get_registry().get("pio_refresh_staleness_s").value() \
            == pytest.approx(100.0, abs=2.0)

    def test_staleness_under_threshold_does_not_fire(self, ctx):
        app_id = _mk_app(ctx)
        now = dt.datetime.now(UTC)
        ctx.storage.get_events().insert(_view(0, 1, when=now), app_id)
        t = [0.0]
        d = self._daemon(ctx, lambda: t[0], trigger_staleness_s=30.0)
        d._served_wm = now - dt.timedelta(seconds=5)
        fire, reason = d._trigger_ready(cycle_started=0.0)
        assert not fire

    def test_delta_count_threshold_fires(self, ctx):
        app_id = _mk_app(ctx)
        now = dt.datetime.now(UTC)
        t = [0.0]
        d = self._daemon(ctx, lambda: t[0], trigger_delta_count=5)
        d._served_wm = now - dt.timedelta(seconds=60)
        for i in range(4):
            ctx.storage.get_events().insert(
                _view(i, 1, when=now - dt.timedelta(seconds=30)), app_id)
        fire, _ = d._trigger_ready(cycle_started=0.0)
        assert not fire                       # 4 < 5
        ctx.storage.get_events().insert(
            _view(9, 1, when=now - dt.timedelta(seconds=30)), app_id)
        fire, reason = d._trigger_ready(cycle_started=0.0)
        assert fire and reason == "delta_count"

    def test_interval_backstop_fires_without_events(self, ctx):
        _mk_app(ctx)
        t = [0.0]
        d = self._daemon(ctx, lambda: t[0], trigger_staleness_s=1e9)
        fire, _ = d._trigger_ready(cycle_started=0.0)
        assert not fire
        t[0] = 301.0
        fire, reason = d._trigger_ready(cycle_started=0.0)
        assert fire and reason == "interval"

    def test_follow_trigger_loop_zero_wall_sleeps(self, ctx,
                                                  monkeypatch):
        app_id = _mk_app(ctx)
        now = dt.datetime.now(UTC)
        t = [0.0]
        d = self._daemon(ctx, lambda: t[0], trigger_delta_count=3)
        cycles = []

        def fake_run_once():
            cycles.append(t[0])
            d._served_wm = now  # cycle consumed the backlog
            if len(cycles) == 1:
                # new delta lands mid-wait: the second cycle must fire
                # on the trigger, not the 300s cadence
                ctx.storage.get_events().insert_batch(
                    [_view(i, 1, when=now + dt.timedelta(seconds=1))
                     for i in range(3)], app_id)
            else:
                d.stop()
            return {}

        monkeypatch.setattr(d, "run_once", fake_run_once)

        def fake_sleep(s):
            t[0] += s

        n = d.follow(sleep=fake_sleep)
        assert n == 2
        # second cycle fired within poll ticks, far before the cadence
        assert cycles[1] - cycles[0] < 10.0
        assert get_registry().get("pio_refresh_triggers_total") \
            .value(reason="delta_count") == 1

    def test_fixed_cadence_unchanged_without_triggers(self, ctx,
                                                      monkeypatch):
        _mk_app(ctx)
        t = [0.0]
        d = self._daemon(ctx, lambda: t[0])
        assert not d._trigger_mode()
        calls = []

        def fake_run_once():
            calls.append(t[0])
            if len(calls) == 2:
                d.stop()
            return {}

        monkeypatch.setattr(d, "run_once", fake_run_once)
        d.follow(sleep=lambda s: t.__setitem__(0, t[0] + s))
        assert len(calls) == 2
        assert calls[1] - calls[0] == pytest.approx(300.0)


# ==========================================================================
# Live e2e: the acceptance spine
# ==========================================================================

def _http(base, method, path, payload=None):
    data = json.dumps(payload).encode() if payload is not None else None
    req = Request(base + path, data=data, method=method,
                  headers={"Content-Type": "application/json"})
    with urlopen(req, timeout=15) as resp:
        return resp.status, json.loads(resp.read() or b"{}"), resp.headers


class TestQualityGateE2E:
    """A promoted generation with an injected score shift is detected
    (PSI over threshold on both windows), rolled back via the existing
    /admin/rollback path, and the pre-promotion generation serves
    throughout — zero non-2xx during the episode."""

    def test_clean_server_reads_healthy_and_echoes_serve_id(
            self, ctx, monkeypatch):
        monkeypatch.setenv("PIO_QUALITY_SAMPLE", "1.0")
        monkeypatch.setenv("PIO_QUALITY_MIN_SAMPLES", "25")
        monkeypatch.setenv("PIO_QUALITY_FAST_WINDOW", "48")
        app_id = _mk_app(ctx)
        _seed_views(ctx, app_id)
        eng, variant = _tt()
        run_train(eng, variant, ctx)
        from predictionio_tpu.server import EngineServer, EventServer

        srv = EngineServer(eng, variant, ctx.storage, host="127.0.0.1",
                           port=0)
        srv.start()
        base = f"http://127.0.0.1:{srv.port}"
        try:
            sid = None
            for k in range(60):
                st, body, headers = _http(base, "POST", "/queries.json",
                                          {"user": f"u{k % 10}",
                                           "num": 3})
                assert st == 200
                sid = headers.get("X-PIO-Serve-Id") or sid
                if sid and k == 0:
                    assert sid.startswith("g1-")
            assert sid is not None
            st, doc, _ = _http(base, "GET", "/quality.json")
            assert st == 200
            assert doc["verdict"] == "healthy"
            assert doc["drift"]["psi"]["fast"] < 0.25
            assert doc["drift"]["psi"]["slow"] < 0.25
            assert not doc["gate"]["rollback"]
            # feedback round-trip over the LIVE event server
            key = ctx.storage.get_access_keys().insert(
                AccessKey(key="", app_id=app_id))
            evsrv = EventServer(storage=ctx.storage, host="127.0.0.1",
                                port=0)
            evsrv.start()
            try:
                served_item = _http(base, "POST", "/queries.json",
                                    {"user": "u1", "num": 3}
                                    )[1]["itemScores"][0]["item"]
                st, _, _ = _http(
                    f"http://127.0.0.1:{evsrv.port}", "POST",
                    f"/events.json?accessKey={key}",
                    {"event": "buy", "entityType": "user",
                     "entityId": "u1", "targetEntityType": "item",
                     "targetEntityId": served_item,
                     "properties": {"pioServeId": sid}})
                assert st == 201
                st, doc, _ = _http(base, "GET", "/quality.json")
                gens = doc["feedback"]["generations"]
                assert "1" in gens
                assert gens["1"]["hits"] + gens["1"]["misses"] >= 1
            finally:
                evsrv.stop()
            # stats embed + pio status parser see the same series
            st, stats, _ = _http(base, "GET", "/stats.json")
            assert stats["quality"]["verdict"] == "healthy"
        finally:
            srv.stop()

    def test_score_shifted_canary_is_auto_rolled_back(self, ctx,
                                                      monkeypatch):
        monkeypatch.setenv("PIO_QUALITY_SAMPLE", "1.0")
        monkeypatch.setenv("PIO_QUALITY_MIN_SAMPLES", "25")
        monkeypatch.setenv("PIO_QUALITY_FAST_WINDOW", "48")
        app_id = _mk_app(ctx)
        _seed_views(ctx, app_id)
        eng, variant = _tt()
        run_train(eng, variant, ctx)
        from predictionio_tpu.refresh import RefreshConfig
        from predictionio_tpu.refresh.daemon import (
            HttpPromoter,
            RefreshDaemon,
        )
        from predictionio_tpu.server import EngineServer
        from predictionio_tpu.server import engine_server as es_mod

        srv = EngineServer(eng, variant, ctx.storage, host="127.0.0.1",
                           port=0)
        srv.start()
        base = f"http://127.0.0.1:{srv.port}"
        try:
            gen1_instance = srv._instance.id
            ctx.storage.get_events().insert(_view(0, 1), app_id)
            # Poison the SERVER's candidate load with a user-side scale:
            # scores shift 4× (drift) while the ranking — and the
            # item-corpus fingerprint the scorecard is pinned to — stay
            # intact, so ONLY the drift detector can catch it.
            real_load = es_mod.load_models

            def shifted(engine_, instance, c=None):
                models = real_load(engine_, instance, c)
                models[0].user_vecs = np.asarray(
                    models[0].user_vecs) * 4.0
                return models

            monkeypatch.setattr(es_mod, "load_models", shifted)
            stop = threading.Event()
            outcome = {"non200": 0, "n": 0}

            def drive():
                k = 0
                while not stop.is_set():
                    st, _, _ = _http(base, "POST", "/queries.json",
                                     {"user": f"u{k % 10}", "num": 3})
                    if st != 200:
                        outcome["non200"] += 1
                    outcome["n"] += 1
                    k += 1

            t = threading.Thread(target=drive, daemon=True)
            t.start()
            promoter = HttpPromoter(base, canary_window_s=60.0,
                                    canary_poll_s=0.2)
            d = RefreshDaemon(
                eng, variant, ctx,
                config=RefreshConfig(interval_s=0.01,
                                     eval_tolerance=10.0),
                promoter=promoter)
            out = d.run_once()
            stop.set()
            t.join(5)
            assert out["promotion"] == "rolled_back"
            # the pre-promotion generation serves again (and served
            # throughout: zero non-2xx during the whole episode)
            assert srv._instance.id == gen1_instance
            assert outcome["non200"] == 0 and outcome["n"] > 0
            st, body, _ = _http(base, "POST", "/queries.json",
                                {"user": "u1", "num": 3})
            assert st == 200 and body["itemScores"]
            reg = get_registry()
            assert reg.get("pio_refresh_promotions_total") \
                .value(result="rolled_back") == 1
            assert reg.get("pio_quality_drift_tripped") is not None
        finally:
            srv.stop()
