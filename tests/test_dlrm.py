"""DLRM: sharded embedding lookup exactness, learning, mesh equivalence."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from predictionio_tpu.models.dlrm import (
    DLRMConfig,
    init_state,
    predict_proba,
    sharded_embedding_lookup,
    train,
)
from predictionio_tpu.parallel.mesh import make_mesh


def test_sharded_lookup_matches_gather():
    mesh = make_mesh({"expert": 8})
    rng = np.random.default_rng(0)
    table = rng.standard_normal((64, 4)).astype(np.float32)
    idx = rng.integers(0, 64, (16, 3)).astype(np.int32)
    out = sharded_embedding_lookup(mesh, jnp.asarray(table), jnp.asarray(idx))
    np.testing.assert_allclose(np.asarray(out), table[idx], rtol=1e-6)


def _ctr_data(n=2048, seed=0):
    """Label depends on one categorical field + one dense feature."""
    rng = np.random.default_rng(seed)
    dense = rng.standard_normal((n, 4)).astype(np.float32)
    cat = np.stack([rng.integers(0, 16, n), rng.integers(0, 8, n)], axis=1)
    logit = (cat[:, 0] % 2) * 2.0 - 1.0 + dense[:, 0]
    labels = (rng.random(n) < 1 / (1 + np.exp(-logit))).astype(np.float32)
    return dense, cat, labels


def test_learns_signal():
    dense, cat, labels = _ctr_data()
    # epochs=6 at the default adagrad lr left the margin right AT the
    # 0.1 threshold (~0.095 on some BLAS stacks — a persistent flake);
    # 16 epochs at lr=0.1 lands ~0.137 with clear headroom while still
    # proving the same signal-learning claim.
    cfg = DLRMConfig(vocab_sizes=(16, 8), n_dense=4, embed_dim=8,
                     bottom_mlp=(16, 8), top_mlp=(16,), epochs=16,
                     learning_rate=0.1, batch_size=256, seed=1)
    state = train(dense, cat, labels, cfg)
    p = np.asarray(predict_proba(state, dense, cat, cfg))
    # AUC-ish check: positives score higher on average.
    assert p[labels == 1].mean() > p[labels == 0].mean() + 0.1
    # Calibrated enough to beat base-rate log-loss.
    eps = 1e-6
    ll = -(labels * np.log(p + eps) + (1 - labels) * np.log(1 - p + eps)).mean()
    base = labels.mean()
    ll0 = -(base * np.log(base) + (1 - base) * np.log(1 - base))
    assert ll < ll0


def test_mesh_equivalence():
    dense, cat, labels = _ctr_data(n=512, seed=2)
    cfg = DLRMConfig(vocab_sizes=(16, 8), n_dense=4, embed_dim=4,
                     bottom_mlp=(8, 4), top_mlp=(8,), epochs=1,
                     batch_size=128, seed=3)
    s1 = train(dense, cat, labels, cfg)
    mesh = make_mesh({"expert": 8})
    s2 = train(dense, cat, labels, cfg, mesh=mesh)
    p1 = np.asarray(predict_proba(s1, dense[:64], cat[:64], cfg))
    p2 = np.asarray(predict_proba(s2, dense[:64], cat[:64], cfg, mesh=mesh))
    np.testing.assert_allclose(p1, p2, rtol=5e-2, atol=5e-3)


def test_vocab_padding_requirement():
    mesh = make_mesh({"expert": 8})
    table = jnp.zeros((60, 4))  # 60 not divisible by 8
    idx = jnp.zeros((8, 1), jnp.int32)
    with pytest.raises(AssertionError):
        sharded_embedding_lookup(mesh, table, idx)
