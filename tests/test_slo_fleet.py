"""ISSUE 9: per-request latency waterfall, SLO burn-rate engine wired to
/ready, and fleet-aggregated telemetry.

Three layers under test, all on injectable clocks (ZERO wall sleeps in
the SLO/overload paths — acceptance requirement):

- **waterfall** (obs.waterfall + metrics exemplars): per-stage stamps
  ride the ``Pending`` hand-off across the handler/batcher threads, land
  in ``pio_serve_stage_ms{stage}`` with exemplar trace ids, and the
  stage sum reconciles with the server-attested ``X-PIO-Server-Ms``.
- **SLO engine** (obs.slo): multi-window burn rates over the process
  registry, the saturation+burn trip, asymmetric hysteresis, and the
  live ``/ready`` 503 flip.
- **fleet** (obs.fleet): type-correct multi-instance merge (counters
  sum, histogram buckets add, gauges keep an ``instance`` label),
  counter-reset survival, dead-instance staleness, and the dashboard's
  ``/fleet.json`` aggregating two LIVE engine servers.
"""

import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from predictionio_tpu.obs import get_registry
from predictionio_tpu.obs.fleet import (
    CounterResetTracker,
    FleetAggregator,
    histogram_quantile,
    merge_histogram_buckets,
    merge_samples,
    parse_exposition,
)
from predictionio_tpu.obs.slo import SLOConfig, SLOEngine
from predictionio_tpu.obs.waterfall import (
    WALL_STAGES,
    Waterfall,
    begin_request,
    current_waterfall,
    dispatch_sink,
    record_stage,
)


# --------------------------------------------------------------------------
# Metrics additions: exemplars + count_le (the SLO's "good events" read)
# --------------------------------------------------------------------------

class TestHistogramAdditions:
    def test_exemplar_stored_and_rendered_openmetrics_style(self, pio_home):
        h = get_registry().histogram("pio_x_ms", "h", ("stage",))
        h.observe(3.0, exemplar="abc123", stage="bind")
        h.observe(4.0, stage="bind")  # no exemplar: previous one survives
        ex = h.exemplars(stage="bind")
        assert ex[5] == ("abc123", 3.0)  # the le=5 bucket holds 2.5<v<=5
        text = get_registry().render(exemplars=True)
        line = next(l for l in text.splitlines()
                    if l.startswith('pio_x_ms_bucket{stage="bind",le="5"'))
        assert '# {trace_id="abc123"} 3' in line
        # the DEFAULT exposition stays classic-0.0.4 clean — a strict
        # Prometheus scraper rejects exemplar suffixes wholesale
        assert "# {" not in get_registry().render()
        # downstream parsers must tolerate the suffix
        types, samples = parse_exposition(text)
        assert ("pio_x_ms_bucket", {"stage": "bind", "le": "5"}, 2.0) \
            in samples

    def test_count_le_interpolates_and_undercounts_inf(self, pio_home):
        h = get_registry().histogram("pio_y_ms", "h",
                                     buckets=(10.0, 100.0))
        for v in (5.0, 50.0, 99.0, 5000.0):
            h.observe(v)
        # at a bucket bound: everything in buckets up to it
        assert h.count_le(100.0) == pytest.approx(3.0)
        assert h.count_le(10.0) == pytest.approx(1.0)
        # inside (10,100]: 1 + interpolated share of that bucket's 2 obs
        assert h.count_le(55.0) == pytest.approx(1 + 2 * 0.5)
        # past the top finite bound: +Inf observations count as NOT good
        assert h.count_le(9999.0) == pytest.approx(3.0)
        assert h.count_le(0.0, ) == 0.0


# --------------------------------------------------------------------------
# Waterfall collector
# --------------------------------------------------------------------------

class TestWaterfall:
    def test_stamps_accumulate_and_merge(self, pio_home):
        wf = Waterfall()
        wf.stamp("dispatch", 5.0)
        wf.stamp("dispatch", 2.0, batchSize=4)   # retry bills both
        sink = Waterfall()
        with dispatch_sink(sink):
            record_stage("retrieval", 3.0, rung="host")
        stages, attrs = sink.export()
        wf.merge(stages, **attrs)
        snap = wf.snapshot()
        assert snap["dispatch"] == pytest.approx(7.0)
        assert snap["retrieval"] == pytest.approx(3.0)
        assert wf.attrs["rung"] == "host"

    def test_record_stage_prefers_sink_then_request_then_noop(self,
                                                             pio_home):
        record_stage("bind", 1.0)  # no collector anywhere: no crash
        with begin_request() as wf:
            record_stage("bind", 1.0)
            sink = Waterfall()
            with dispatch_sink(sink):
                record_stage("retrieval", 2.0)
            record_stage("serialize", 3.0)
        assert current_waterfall() is None
        assert wf.snapshot() == {"bind": 1.0, "serialize": 3.0}
        assert sink.snapshot() == {"retrieval": 2.0}

    def test_finalize_publishes_once_then_drops_late_stamps(
            self, pio_home, tmp_path, monkeypatch):
        log = tmp_path / "req.jsonl"
        monkeypatch.setenv("PIO_REQUEST_LOG", str(log))
        wf = Waterfall()
        for s, ms in (("queue_wait", 1.0), ("batch_wait", 2.0),
                      ("bind", 0.5), ("dispatch", 10.0),
                      ("retrieval", 6.0), ("serialize", 1.0),
                      ("shed_check", 0.1)):
            wf.stamp(s, ms)
        doc = wf.finalize(trace_id="t1", status=200, total_ms=15.0,
                          attested_ms=13.7)
        assert doc["stages"]["dispatch"] == 10.0
        # retrieval ⊂ dispatch: excluded from the reconciliation sum
        assert doc["stageSumMs"] == pytest.approx(14.6)
        # serialize lies outside the attested wall by construction
        assert doc["attestedSumMs"] == pytest.approx(13.6)
        assert doc["serverMs"] == 13.7
        # close-once: a walked waiter / double-finalize publishes nothing
        wf.stamp("dispatch", 99.0)
        assert wf.finalize(trace_id="t1", status=200, total_ms=15.0) == {}
        hist = get_registry().get("pio_serve_stage_ms")
        assert hist.count(stage="dispatch") == 1
        assert hist.exemplars(stage="dispatch")[10] == ("t1", 10.0)
        rows = [json.loads(l) for l in log.read_text().splitlines()]
        assert len(rows) == 1 and rows[0]["traceId"] == "t1"
        assert rows[0]["stages"]["retrieval"] == 6.0


# --------------------------------------------------------------------------
# SLO engine (fake clock; no wall sleeps anywhere)
# --------------------------------------------------------------------------

class _Tick:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


def _instruments():
    reg = get_registry()
    return (reg.counter("pio_query_requests_total",
                        "Predict requests served."),
            reg.counter("pio_query_errors_total",
                        "Predict requests that failed."),
            reg.histogram("pio_query_latency_ms",
                          "Predict request latency."))


def _engine(clock, saturation=None, **cfg):
    defaults = dict(fast_window_s=300.0, slow_window_s=3600.0,
                    burn_threshold=14.4, min_requests=10,
                    recovery_s=60.0, latency_target_ms=100.0)
    defaults.update(cfg)
    return SLOEngine(SLOConfig(**defaults), clock=clock,
                     saturation_fn=saturation)


def _traffic(req, err, lat, n_good=0, n_bad=0, slow_ms=None):
    req.inc(n_good + n_bad)
    err.inc(n_bad)
    for _ in range(n_good):
        lat.observe(slow_ms if slow_ms is not None else 5.0)


class TestSLOEngine:
    def test_healthy_traffic_never_burns(self, pio_home):
        req, err, lat = _instruments()
        clock = _Tick()
        slo = _engine(clock)
        for _ in range(10):
            _traffic(req, err, lat, n_good=50)
            clock.t += 60
            state = slo.tick(force=True)
        assert state["degraded"] is False
        assert state["burn"]["fast"]["availability"] == 0.0
        assert state["burn"]["fast"]["latency"] == 0.0
        ok, _ = slo.ready()
        assert ok

    def test_fast_spike_alone_does_not_trip(self, pio_home):
        """A single error burst burns the fast window hot while an hour
        of good history keeps the slow window cold — no flip (the
        classic multi-window guard against paging on blips)."""
        req, err, lat = _instruments()
        clock = _Tick()
        slo = _engine(clock)
        for _ in range(60):                      # 1h of clean traffic
            _traffic(req, err, lat, n_good=100)
            clock.t += 60
            slo.tick(force=True)
        _traffic(req, err, lat, n_bad=50)        # 100%-error blip
        clock.t += 30
        state = slo.tick(force=True)
        assert state["burn"]["fast"]["availability"] > 14.4
        assert state["burn"]["slow"]["availability"] < 14.4
        assert state["degraded"] is False

    def test_sustained_burn_trips_then_recovers_with_hysteresis(
            self, pio_home):
        req, err, lat = _instruments()
        clock = _Tick()
        slo = _engine(clock)
        state = None
        for _ in range(70):                      # >1h of 20% errors
            _traffic(req, err, lat, n_good=80, n_bad=20)
            clock.t += 60
            state = slo.tick(force=True)
        assert state["degraded"] is True
        assert "sustained_burn" in state["tripReasons"]
        ok, _ = slo.ready()
        assert not ok
        # errors stop; burn decays as the windows slide past the bad era
        recovered_at = None
        for minute in range(90):
            _traffic(req, err, lat, n_good=100)
            clock.t += 60
            state = slo.tick(force=True)
            if not state["degraded"]:
                recovered_at = minute
                break
        assert recovered_at is not None, "never recovered"
        # hysteresis: clearing needed the trip condition false for a
        # recovery_s dwell, not just one good tick
        assert recovered_at >= 1

    def test_flap_resets_the_recovery_dwell(self, pio_home):
        req, err, lat = _instruments()
        clock = _Tick()
        slo = _engine(clock, fast_window_s=60.0, slow_window_s=120.0,
                      recovery_s=300.0)
        for _ in range(5):
            _traffic(req, err, lat, n_good=10, n_bad=90)
            clock.t += 30
            slo.tick(force=True)
        assert slo.tick(force=True)["degraded"] is True
        # burn clears (windows slide past the errors)...
        clock.t += 150
        _traffic(req, err, lat, n_good=200)
        state = slo.tick(force=True)
        assert state["degraded"] is True          # dwell started, not done
        assert state["recoveringForS"] is not None
        # ...but a fresh burst inside the dwell resets it
        _traffic(req, err, lat, n_good=10, n_bad=90)
        clock.t += 10
        state = slo.tick(force=True)
        assert state["recoveringForS"] is None
        # finally: quiet for the whole dwell → ready again
        for _ in range(16):
            clock.t += 30
            _traffic(req, err, lat, n_good=50)
            slo.tick(force=True)
        assert slo.tick(force=True)["degraded"] is False

    def test_latency_burn_uses_target_threshold(self, pio_home):
        req, err, lat = _instruments()
        clock = _Tick()
        slo = _engine(clock, latency_objective=0.99,
                      latency_target_ms=100.0,
                      fast_window_s=60.0, slow_window_s=120.0)
        for _ in range(6):   # every request answers, but SLOW (500ms)
            _traffic(req, err, lat, n_good=50, slow_ms=500.0)
            clock.t += 30
            state = slo.tick(force=True)
        assert state["burn"]["fast"]["latency"] > 14.4
        assert state["burn"]["fast"]["availability"] == 0.0
        assert state["degraded"] is True

    def test_min_requests_floor_prevents_flapping(self, pio_home):
        req, err, lat = _instruments()
        clock = _Tick()
        slo = _engine(clock, min_requests=10)
        _traffic(req, err, lat, n_bad=3)  # 100% errors... of 3 requests
        clock.t += 30
        assert slo.tick(force=True)["degraded"] is False

    def test_saturation_plus_fast_burn_trips_without_slow_window(
            self, pio_home):
        """The ROADMAP rung: persistent-floor saturation supplies the
        "it's sustained" evidence, so a fast-window burn ≥1 flips /ready
        immediately instead of waiting for the slow window to heat."""
        req, err, lat = _instruments()
        clock = _Tick()
        saturated = {"v": False}
        slo = _engine(clock, saturation=lambda: saturated["v"])
        for _ in range(60):                  # 1h of clean history keeps
            _traffic(req, err, lat, n_good=100)   # the slow window cold
            clock.t += 60
            slo.tick(force=True)
        _traffic(req, err, lat, n_good=80, n_bad=20)   # fast burn hot
        clock.t += 30
        state = slo.tick(force=True)
        assert state["burn"]["fast"]["availability"] > 1.0
        assert state["degraded"] is False              # burn alone: no
        saturated["v"] = True
        _traffic(req, err, lat, n_good=80, n_bad=20)
        clock.t += 30
        state = slo.tick(force=True)
        assert state["degraded"] is True
        assert state["tripReasons"] == ["saturation_with_burn"]
        assert state["saturated"] is True

    def test_saturation_alone_with_slo_met_stays_ready(self, pio_home):
        req, err, lat = _instruments()
        clock = _Tick()
        slo = _engine(clock, saturation=lambda: True)
        _traffic(req, err, lat, n_good=100)
        clock.t += 30
        state = slo.tick(force=True)
        assert state["saturated"] is True
        assert state["degraded"] is False   # the batcher is coping

    def test_ready_slo_off_escape_hatch_reports_but_never_flips(
            self, pio_home):
        req, err, lat = _instruments()
        clock = _Tick()
        slo = _engine(clock, ready_slo=False,
                      saturation=lambda: True)
        slo.tick(force=True)          # baseline snapshot at t=0
        _traffic(req, err, lat, n_good=10, n_bad=90)
        clock.t += 30
        ok, state = slo.ready()
        assert state["degraded"] is True    # the signal still reports
        assert ok is True                   # ...but /ready ignores it
        assert get_registry().get("pio_slo_degraded").value() == 1

    def test_gauges_exported(self, pio_home):
        req, err, lat = _instruments()
        clock = _Tick()
        slo = _engine(clock)
        slo.tick(force=True)          # baseline snapshot at t=0
        _traffic(req, err, lat, n_good=50, n_bad=50)
        clock.t += 30
        slo.tick(force=True)
        reg = get_registry()
        assert reg.get("pio_slo_burn_rate").value(
            slo="availability", window="fast") > 0
        assert reg.get("pio_slo_objective").value(
            slo="availability") == pytest.approx(0.999)
        assert reg.get("pio_slo_latency_target_ms").value() == 100.0

    def test_tick_coalescing_bounds_the_snapshot_ring(self, pio_home):
        _instruments()
        clock = _Tick()
        slo = _engine(clock)
        for _ in range(50):                 # an LB polling at 10 Hz
            clock.t += 0.1
            slo.tick()
        assert len(slo._snaps) <= 6         # ~1 real tick per second

    def test_config_from_env(self, pio_home, monkeypatch):
        monkeypatch.setenv("PIO_BATCH_P99_TARGET_MS", "250")
        monkeypatch.setenv("PIO_SLO_BURN_THRESHOLD", "6")
        monkeypatch.setenv("PIO_READY_SLO", "off")
        cfg = SLOConfig.from_env()
        assert cfg.latency_target_ms == 250.0   # defaults from the
        assert cfg.burn_threshold == 6.0        # autotuner's target
        assert cfg.ready_slo is False
        monkeypatch.setenv("PIO_SLO_LATENCY_TARGET_MS", "80")
        assert SLOConfig.from_env().latency_target_ms == 80.0


class TestSaturationDetector:
    def _floor_pair(self):
        from predictionio_tpu.serving import WindowAutotuner

        class _B:
            window_s = 0.0
            window_min_s = 0.0
            max_size = 8
            _est_dispatch_s = 0.003   # fast dispatch: over-target p99
                                      # means backlog, i.e. load>capacity

            def set_knobs(self, **kw):
                for k, v in kw.items():
                    setattr(self, k, v)

        return _B(), WindowAutotuner("m", 100.0, saturation_streak=3)

    def test_floor_streak_reports_saturated(self, pio_home):
        b, tuner = self._floor_pair()
        for i in range(3):
            assert tuner.saturated() is False, f"tripped at {i}"
            tuner.retune(b, p99_ms=400.0)
        assert tuner.saturated() is True
        assert get_registry().get("pio_batch_saturated").value(
            model="m") == 1

    def test_any_other_action_clears_the_streak(self, pio_home):
        b, tuner = self._floor_pair()
        for _ in range(3):
            tuner.retune(b, p99_ms=400.0)
        assert tuner.saturated() is True
        tuner.retune(b, p99_ms=80.0)      # hold: capacity returned
        assert tuner.saturated() is False
        assert get_registry().get("pio_batch_saturated").value(
            model="m") == 0


# --------------------------------------------------------------------------
# /traces.json filters (exemplar-link resolver)
# --------------------------------------------------------------------------

class TestShedAttribution:
    """Every batcher finish path stamps queue_wait/batch_wait — a 504's
    wall must read as queueing (scale out), never leak into the waiter's
    resume residual (GIL contention): the attribution verdict matters
    most under exactly that overload."""

    def _batcher(self, dispatch_fn, clock):
        from predictionio_tpu.serving.batcher import MicroBatcher
        from predictionio_tpu.serving.queue import ModelQueue
        q = ModelQueue("m", 4)
        return MicroBatcher("m", q, dispatch_fn, clock=clock)

    def test_queue_expired_504_bills_waits_not_resume(self, pio_home):
        from predictionio_tpu.serving.queue import Pending

        class Clock:
            t = 1.0

            def now(self):
                return self.t

        b = self._batcher(lambda qs: ([0] * len(qs), 1), Clock())
        wf = Waterfall()
        dead = Pending("dead", 0.0, deadline_s=0.5, waterfall=wf)
        dead.gathered_s = 0.2
        b.dispatch([dead])
        stages = wf.snapshot()
        assert stages["queue_wait"] == pytest.approx(200.0)
        assert stages["batch_wait"] == pytest.approx(800.0)
        assert "resume" not in stages
        assert "dispatch" not in stages  # no device work happened

    def test_failed_batch_bills_waits_and_dispatch(self, pio_home):
        from predictionio_tpu.serving.queue import Pending

        class Clock:
            t = 1.0

            def now(self):
                Clock.t += 0.010
                return Clock.t

        def boom(qs):
            raise RuntimeError("dead backend")

        b = self._batcher(boom, Clock())
        wf = Waterfall()
        p = Pending("q", 0.5, deadline_s=None, waterfall=wf)
        b.dispatch([p])
        assert isinstance(p.error, RuntimeError)
        stages = wf.snapshot()
        # the waits and the FAILED attempt's wall are both attributed
        assert stages["queue_wait"] > 0
        assert "batch_wait" in stages
        assert stages["dispatch"] > 0


class TestTraceFilters:
    def _ring(self):
        from predictionio_tpu.obs import get_recorder
        from predictionio_tpu.obs.trace import trace

        ids = []
        for i in range(5):
            with trace("req", trace_id=f"{i:032x}") as root:
                root.set(i=i)
            ids.append(f"{i:032x}")
        return get_recorder(), ids

    def test_request_id_resolves_one_trace(self, pio_home):
        rec, ids = self._ring()
        out = rec.recent(50, request_id=ids[2])
        assert len(out) == 1 and out[0]["traceId"] == ids[2]
        assert rec.recent(50, request_id="f" * 32) == []

    def test_min_ms_and_limit(self, pio_home):
        rec, ids = self._ring()
        assert len(rec.recent(2)) == 2
        assert rec.recent(50, min_ms=1e9) == []
        assert len(rec.recent(50, min_ms=0.0)) == 5

    def test_http_params_view(self, pio_home):
        from predictionio_tpu.server.http import traces_payload

        _, ids = self._ring()
        doc = traces_payload({"request_id": [ids[1]]})
        assert [t["traceId"] for t in doc["traces"]] == [ids[1]]
        doc = traces_payload({"limit": ["3"]})
        assert len(doc["traces"]) == 3
        # junk params degrade to defaults, never 500
        doc = traces_payload({"limit": ["x"], "min_ms": ["y"],
                              "request_id": ["../etc"]})
        assert len(doc["traces"]) <= 50


# --------------------------------------------------------------------------
# Fleet merge (unit)
# --------------------------------------------------------------------------

def _expo(counter=0.0, gen=1.0, buckets=(1, 2, 3)):
    b1, b2, b3 = buckets
    return (
        "# TYPE pio_query_requests_total counter\n"
        f"pio_query_requests_total {counter}\n"
        "# TYPE pio_model_generation gauge\n"
        f"pio_model_generation {gen}\n"
        "# TYPE pio_query_latency_ms histogram\n"
        f'pio_query_latency_ms_bucket{{le="10"}} {b1}\n'
        f'pio_query_latency_ms_bucket{{le="100"}} {b2}\n'
        f'pio_query_latency_ms_bucket{{le="+Inf"}} {b3}\n'
        f"pio_query_latency_ms_sum {b3 * 5.0}\n"
        f"pio_query_latency_ms_count {b3}\n")


class TestFleetMerge:
    def test_parse_tolerates_exemplars_and_junk(self, pio_home):
        text = ('# TYPE pio_a_ms histogram\n'
                'pio_a_ms_bucket{le="5"} 2 # {trace_id="abc"} 3.0\n'
                'garbage !!! line\n'
                '{not even a name} 4\n'
                'pio_a_ms_count 2\n')
        types, samples = parse_exposition(text)
        assert types == {"pio_a_ms": "histogram"}
        assert ("pio_a_ms_bucket", {"le": "5"}, 2.0) in samples
        assert ("pio_a_ms_count", {}, 2.0) in samples

    def test_counters_sum_and_gauges_keep_instance_label(self, pio_home):
        merged = merge_samples({
            "http://a": parse_exposition(_expo(counter=10, gen=3)),
            "http://b": parse_exposition(_expo(counter=32, gen=7)),
        })
        assert merged["counters"]["pio_query_requests_total"] == 42.0
        # gauges never sum — and the two instances never collide
        assert merged["gauges"][
            'pio_model_generation{instance="http://a"}'] == 3.0
        assert merged["gauges"][
            'pio_model_generation{instance="http://b"}'] == 7.0
        assert "pio_model_generation" not in merged["counters"]

    def test_histogram_buckets_add_and_quantile_reads_merged(self,
                                                             pio_home):
        merged = merge_samples({
            "a": parse_exposition(_expo(buckets=(1, 2, 4))),
            "b": parse_exposition(_expo(buckets=(0, 6, 8))),
        })
        series = merged["histograms"]["pio_query_latency_ms"]
        row = series["pio_query_latency_ms"]
        assert row["buckets"] == {"10": 1.0, "100": 8.0, "+Inf": 12.0}
        assert row["count"] == 12.0
        q50 = histogram_quantile(row["buckets"], 0.5)
        assert 10.0 < q50 <= 100.0

    def test_bucket_merge_is_associative_and_sum_preserving(self,
                                                            pio_home):
        rng = np.random.default_rng(9)
        parts = [{le: float(rng.integers(0, 100))
                  for le in ("10", "100", "+Inf")} for _ in range(3)]
        a, b, c = parts
        left = merge_histogram_buckets(
            [merge_histogram_buckets([a, b]), c])
        right = merge_histogram_buckets(
            [a, merge_histogram_buckets([b, c])])
        flat = merge_histogram_buckets(parts)
        assert left == right == flat
        for le in ("10", "100", "+Inf"):
            assert flat[le] == a[le] + b[le] + c[le]

    def test_counter_sums_survive_an_instance_restart(self, pio_home):
        """Reset detection: instance b restarts (its raw series drops to
        near zero); the fleet sum must keep the pre-restart total as an
        offset instead of going backwards."""
        tracker = CounterResetTracker()
        m1 = merge_samples({"a": parse_exposition(_expo(counter=100)),
                            "b": parse_exposition(_expo(counter=50))},
                           tracker)
        assert m1["counters"]["pio_query_requests_total"] == 150.0
        # b restarts and serves 7 new requests: raw 50 → 7
        m2 = merge_samples({"a": parse_exposition(_expo(counter=110)),
                            "b": parse_exposition(_expo(counter=7))},
                           tracker)
        assert m2["counters"]["pio_query_requests_total"] == 167.0
        # monotonic from then on
        m3 = merge_samples({"a": parse_exposition(_expo(counter=110)),
                            "b": parse_exposition(_expo(counter=9))},
                           tracker)
        assert m3["counters"]["pio_query_requests_total"] == 169.0

    def test_dead_instance_degrades_to_marked_stale_entry(self, pio_home):
        calls = {"n": 0}

        def fetch(url):
            if url.startswith("http://dead"):
                raise OSError("connection refused")
            calls["n"] += 1
            if url.endswith("/metrics"):
                return _expo(counter=5)
            raise OSError("no stats here")   # stats/timeline optional

        agg = FleetAggregator(["http://live:1", "http://dead:2"],
                              fetch=fetch, clock=_Tick(100.0))
        doc = agg.scrape()
        rows = {r["instance"]: r for r in doc["instances"]}
        assert rows["http://live:1"]["stale"] is False
        assert rows["http://dead:2"]["stale"] is True
        assert "error" in rows["http://dead:2"]
        assert doc["merged"]["counters"][
            "pio_query_requests_total"] == 5.0

    def test_dead_instance_keeps_contributing_last_known_counters(
            self, pio_home):
        """A scrape failure must not make fleet sums dip: the dead
        instance's last-good counters stay in the merge, marked stale."""
        alive = {"v": True}

        def fetch(url):
            if url.startswith("http://b") and not alive["v"]:
                raise OSError("down")
            n = 50 if url.startswith("http://b") else 100
            if url.endswith("/metrics"):
                return _expo(counter=n)
            raise OSError("optional")

        agg = FleetAggregator(["http://a", "http://b"], fetch=fetch,
                              clock=_Tick(0.0))
        assert agg.scrape()["merged"]["counters"][
            "pio_query_requests_total"] == 150.0
        alive["v"] = False
        doc = agg.scrape()
        assert doc["merged"]["counters"][
            "pio_query_requests_total"] == 150.0   # no dip
        rows = {r["instance"]: r for r in doc["instances"]}
        assert rows["http://b"]["stale"] is True


# --------------------------------------------------------------------------
# tools/attribute_serve.py
# --------------------------------------------------------------------------

class TestAttributeServe:
    def _tool(self):
        import sys
        from pathlib import Path
        sys.path.insert(0, str(
            Path(__file__).resolve().parents[1] / "tools"))
        import attribute_serve
        return attribute_serve

    def test_metrics_exposition_names_dominant_stage(self, pio_home):
        t = self._tool()
        text = ('pio_serve_stage_ms_sum{stage="queue_wait"} 900\n'
                'pio_serve_stage_ms_count{stage="queue_wait"} 10\n'
                'pio_serve_stage_ms_sum{stage="dispatch"} 100\n'
                'pio_serve_stage_ms_count{stage="dispatch"} 10\n')
        res = t.attribute_metrics(t.parse_metrics(text))
        assert res["dominant"] == "queue_wait"
        assert "scale out" in res["attack"]

    def test_retrieval_dominating_dispatch_redirects_the_attack(
            self, pio_home):
        t = self._tool()
        rows = [{"stages": {"dispatch": 100.0, "retrieval": 80.0,
                            "bind": 1.0}, "totalMs": 101.0}] * 4
        res = t.attribute_log(rows)
        assert res["dominant"] == "dispatch"
        assert res["retrieval_share_of_dispatch"] == pytest.approx(0.8)
        assert "rung" in res["attack"]

    def test_wide_event_log_reconciliation(self, pio_home):
        t = self._tool()
        wall = 10.0 * len(WALL_STAGES)
        attested = wall - 10.0  # serialize lies outside the header
        rows = [{"stages": {s: 10.0 for s in WALL_STAGES},
                 "totalMs": wall + 2.0, "serverMs": attested + 1.0}
                for _ in range(9)]
        res = t.attribute_log(rows)
        rec = res["reconciliation"]
        assert rec["stage_sum_p50_ms"] == pytest.approx(wall)
        assert rec["total_p50_ms"] == pytest.approx(wall + 2.0)
        assert 0.9 <= rec["ratio"] <= 1.1
        # the attested comparison drops serialize (outside the header)
        assert rec["attested_stage_sum_p50_ms"] == pytest.approx(attested)
        assert rec["server_attested_p50_ms"] == pytest.approx(attested + 1.0)
        assert 0.9 <= rec["attested_ratio"] <= 1.1


# --------------------------------------------------------------------------
# End-to-end over live servers
# --------------------------------------------------------------------------

@pytest.fixture()
def trained(pio_home):
    """A small trained ALS engine + storage (same substrate as
    test_serving_scheduler's HTTP integration tests)."""
    from predictionio_tpu.controller import EngineVariant, RuntimeContext
    from predictionio_tpu.data.event import DataMap, Event
    from predictionio_tpu.data.storage import App, get_storage
    from predictionio_tpu.templates.recommendation import engine
    from predictionio_tpu.workflow.core_workflow import run_train

    storage = get_storage()
    ctx = RuntimeContext.create(storage=storage)
    app_id = storage.get_apps().insert(App(id=None, name="sloapp"))
    storage.get_events().init(app_id)
    rng = np.random.default_rng(0)
    for u in range(8):
        for i in range(6):
            if rng.random() < 0.8:
                storage.get_events().insert(
                    Event(event="rate", entity_type="user",
                          entity_id=f"u{u}", target_entity_type="item",
                          target_entity_id=f"i{i}",
                          properties=DataMap({"rating": 4.0})), app_id)
    variant = EngineVariant.from_dict({
        "engineFactory": "predictionio_tpu.templates.recommendation:engine",
        "datasource": {"params": {"appName": "sloapp"}},
        "algorithms": [{"name": "als",
                        "params": {"rank": 4, "numIterations": 3}}],
    })
    eng = engine()
    run_train(eng, variant, ctx)
    return eng, variant, storage, ctx


def _get(url):
    try:
        with urllib.request.urlopen(url, timeout=10) as resp:
            return resp.status, dict(resp.headers), resp.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


def _read_rows(log, n, timeout_s=10.0):
    """Wide-event rows, polled until ``n`` arrive: the JSONL line lands
    AFTER the response bytes reach the client (the serialize stage wraps
    the respond write), so a client that just got its 200 may race the
    server thread's finalize by a few ms."""
    import time as _time

    deadline = _time.monotonic() + timeout_s
    while _time.monotonic() < deadline:
        if log.exists():
            rows = []
            for line in log.read_text().splitlines():
                try:
                    rows.append(json.loads(line))
                except json.JSONDecodeError:
                    pass   # torn tail mid-write: next poll sees it whole
            if len(rows) >= n:
                return rows
        _time.sleep(0.01)
    raise AssertionError(f"request log never reached {n} rows")


def _post_query(port, user="u0"):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/queries.json",
        data=json.dumps({"user": user, "num": 2}).encode(),
        method="POST", headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=10) as resp:
        return resp.status, dict(resp.headers), json.loads(resp.read())


class TestWaterfallEndToEnd:
    def test_stages_reconcile_with_server_attested_total(
            self, trained, tmp_path, monkeypatch):
        """Acceptance pin: every stage lands on a live /queries.json
        request; the wide-event stage sum reconciles with the server's
        own X-PIO-Server-Ms within 10% at p50; the bucket exemplar
        resolves to ONE trace via /traces.json?request_id=."""
        from predictionio_tpu.server import EngineServer

        log = tmp_path / "requests.jsonl"
        monkeypatch.setenv("PIO_REQUEST_LOG", str(log))
        # This pin is about the DISPATCH-path decomposition (queue_wait/
        # batch_wait/dispatch on every row): repeated users would hit the
        # result cache and legitimately skip those stages, so bypass it.
        monkeypatch.setenv("PIO_RESULT_CACHE", "0")
        eng, variant, storage, _ = trained
        srv = EngineServer(eng, variant, storage, host="127.0.0.1",
                           port=0)
        srv.start()
        try:
            server_ms = {}   # traceId -> X-PIO-Server-Ms
            lock = threading.Lock()

            def one(i):
                s, headers, _body = _post_query(srv.port, f"u{i % 8}")
                assert s == 200
                with lock:
                    server_ms[headers["X-Request-ID"]] = \
                        float(headers["X-PIO-Server-Ms"])

            threads = [threading.Thread(target=one, args=(i,))
                       for i in range(16)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            rows = _read_rows(log, 16)
            assert len(rows) == 16
            # every request carries the full decomposition
            for doc in rows:
                for stage in ("queue_wait", "batch_wait", "bind",
                              "dispatch", "serialize", "shed_check"):
                    assert stage in doc["stages"], doc
                assert "retrieval" in doc["stages"]   # rung-tagged
                assert doc.get("rung")
            # per-request reconciliation at p50 (acceptance: within 10%)
            # — the attested-stage sum vs the SAME X-PIO-Server-Ms
            # reading, which each wide event records as serverMs (pinned
            # here to equal the header the client saw).
            for doc in rows:
                assert doc["serverMs"] == pytest.approx(
                    server_ms[doc["traceId"]], abs=0.06)
            ratios = sorted(
                doc["attestedSumMs"] / doc["serverMs"] for doc in rows)
            assert len(ratios) == 16
            p50 = ratios[len(ratios) // 2]
            assert 0.9 <= p50 <= 1.1, f"stage sum vs server wall: {p50}"
            # the histogram family is live on /metrics; the exemplar
            # suffixes ride only the opt-in view (classic scrapers choke)
            _, _, body = _get(f"http://127.0.0.1:{srv.port}/metrics")
            text = body.decode()
            assert 'pio_serve_stage_ms_bucket{stage="dispatch"' in text
            assert 'trace_id="' not in text
            _, _, body = _get(f"http://127.0.0.1:{srv.port}"
                              f"/metrics?exemplars=1")
            assert 'trace_id="' in body.decode()
            # ...and an exemplar id resolves to exactly one trace
            hist = get_registry().get("pio_serve_stage_ms")
            ex = hist.exemplars(stage="dispatch")
            assert ex, "dispatch bucket carries no exemplar"
            tid = next(iter(ex.values()))[0]
            _, _, body = _get(f"http://127.0.0.1:{srv.port}"
                              f"/traces.json?request_id={tid}")
            traces = json.loads(body)["traces"]
            assert len(traces) == 1
            assert traces[0]["traceId"] == tid
            # the waterfall event rides the request's own span tree
            assert '"waterfall"' in json.dumps(traces[0])
        finally:
            srv.stop()

    def test_unbatched_inline_path_still_stamps_stages(
            self, trained, tmp_path, monkeypatch):
        from predictionio_tpu.server import EngineServer
        from predictionio_tpu.serving import SchedulerConfig

        log = tmp_path / "requests.jsonl"
        monkeypatch.setenv("PIO_REQUEST_LOG", str(log))
        eng, variant, storage, _ = trained
        srv = EngineServer(eng, variant, storage, host="127.0.0.1",
                           port=0,
                           scheduler_config=SchedulerConfig.from_env(
                               enabled=False))
        srv.start()
        try:
            s, _, _ = _post_query(srv.port)
            assert s == 200
            doc = _read_rows(log, 1)[0]
            assert doc["stages"]["dispatch"] > 0
            assert "bind" in doc["stages"]
        finally:
            srv.stop()


class TestReadySLOFlip:
    def _server_with_fake_clock_slo(self, trained, **cfg):
        from predictionio_tpu.server import EngineServer

        eng, variant, storage, _ = trained
        srv = EngineServer(eng, variant, storage, host="127.0.0.1",
                           port=0)
        clock = _Tick()
        saturated = {"v": False}
        defaults = dict(fast_window_s=300.0, slow_window_s=3600.0,
                        min_requests=10, recovery_s=60.0)
        defaults.update(cfg)
        srv.slo = SLOEngine(SLOConfig(**defaults),
                            clock=clock,
                            saturation_fn=lambda: saturated["v"])
        return srv, clock, saturated

    def test_overload_flips_ready_503_and_recovers_with_hysteresis(
            self, trained):
        """Acceptance pin: synthetic overload (autotuner pinned at floor
        + fast burn over threshold) flips /ready to 503; healing holds
        through the recovery dwell before 200 returns.  Fake clock, no
        wall sleeps."""
        srv, clock, saturated = self._server_with_fake_clock_slo(trained)
        srv.start()
        try:
            base = f"http://127.0.0.1:{srv.port}"
            req, err, lat = _instruments()
            _traffic(req, err, lat, n_good=100)
            clock.t += 30
            s, _, body = _get(f"{base}/ready")
            assert s == 200
            assert json.loads(body)["status"] == "ready"
            # synthetic overload: saturation + queue sheds burning the
            # availability SLO in the fast window
            saturated["v"] = True
            _traffic(req, err, lat, n_good=50, n_bad=50)
            clock.t += 30
            s, _, body = _get(f"{base}/ready")
            doc = json.loads(body)
            assert s == 503
            assert doc["status"] == "degraded"
            assert "saturation_with_burn" in doc["slo"]["tripReasons"]
            assert doc["slo"]["saturated"] is True
            # overload clears: burn still in-window keeps it degraded
            saturated["v"] = False
            clock.t += 300          # errors slide out of the fast window
            _traffic(req, err, lat, n_good=200)
            clock.t += 30
            s, _, body = _get(f"{base}/ready")
            assert s == 503         # hysteresis dwell running
            assert json.loads(body)["slo"]["recoveringForS"] is not None
            clock.t += 61           # dwell (60s) elapses, still healthy
            s, _, body = _get(f"{base}/ready")
            assert s == 200
            assert json.loads(body)["status"] == "ready"
            # the /stats.json + status page carry the same state doc
            _, _, body = _get(f"{base}/stats.json")
            assert json.loads(body)["slo"]["degraded"] is False
        finally:
            srv.stop()

    def test_escape_hatch_keeps_ready_200_while_reporting(self, trained):
        srv, clock, saturated = self._server_with_fake_clock_slo(
            trained, ready_slo=False)
        srv.start()
        try:
            req, err, lat = _instruments()
            _get(f"http://127.0.0.1:{srv.port}/ready")  # baseline tick
            saturated["v"] = True
            _traffic(req, err, lat, n_good=10, n_bad=90)
            clock.t += 30
            s, _, body = _get(f"http://127.0.0.1:{srv.port}/ready")
            doc = json.loads(body)
            assert s == 200                       # hatch holds it in
            assert doc["slo"]["degraded"] is True  # signal still honest
        finally:
            srv.stop()


class TestFleetEndToEnd:
    def test_fleet_json_aggregates_two_live_instances(self, trained):
        """Acceptance pin: /fleet.json merges ≥2 live instances —
        merged counters equal the per-instance sums, per-instance SLO
        state is visible, and a stopped instance degrades to a marked
        stale row (its counters still contributing)."""
        from predictionio_tpu.server import EngineServer
        from predictionio_tpu.server.dashboard import DashboardServer

        eng, variant, storage, _ = trained
        srv1 = EngineServer(eng, variant, storage, host="127.0.0.1",
                            port=0)
        srv2 = EngineServer(eng, variant, storage, host="127.0.0.1",
                            port=0)
        srv1.start()
        srv2.start()
        dash = DashboardServer(
            storage=storage, host="127.0.0.1", port=0,
            fleet=[f"http://127.0.0.1:{srv1.port}",
                   f"http://127.0.0.1:{srv2.port}"])
        dash.start(block=False)
        try:
            for port, n in ((srv1.port, 3), (srv2.port, 2)):
                for i in range(n):
                    assert _post_query(port, f"u{i}")[0] == 200
            # ground truth: each instance's own exposition
            per_instance = []
            for srv in (srv1, srv2):
                _, _, body = _get(
                    f"http://127.0.0.1:{srv.port}/metrics")
                _, samples = parse_exposition(body.decode())
                per_instance.append(sum(
                    v for name, labels, v in samples
                    if name == "pio_query_requests_total"))
            s, _, body = _get(
                f"http://127.0.0.1:{dash.port}/fleet.json")
            assert s == 200
            doc = json.loads(body)
            assert len(doc["instances"]) == 2
            for row in doc["instances"]:
                assert row["stale"] is False
                assert "slo" in row       # per-instance SLO state
                assert "degraded" in row["slo"]
            assert doc["merged"]["counters"][
                "pio_query_requests_total"] == sum(per_instance)
            # per-instance gauges never collide
            gen_keys = [k for k in doc["merged"]["gauges"]
                        if k.startswith("pio_model_generation{")]
            assert len(gen_keys) == 2
            # merged latency histogram carries fleet quantiles
            q = doc["merged"]["histogramQuantiles"][
                "pio_query_latency_ms"]["pio_query_latency_ms"]
            # NOTE: both live instances share this test process's ONE
            # metrics registry, so each reports the same totals; the
            # aggregator's contract (merged == sum of what each
            # instance reported) is what's pinned here.
            assert q["count"] == sum(per_instance)
            assert q["p99"] > 0
            # one instance dies: stale row, sums keep last-known value
            srv2.stop()
            s, _, body = _get(
                f"http://127.0.0.1:{dash.port}/fleet.json")
            doc = json.loads(body)
            rows = {r["instance"]: r for r in doc["instances"]}
            assert rows[f"http://127.0.0.1:{srv2.port}"]["stale"] is True
            assert doc["merged"]["counters"][
                "pio_query_requests_total"] == sum(per_instance)
        finally:
            try:
                srv1.stop()
            finally:
                try:
                    srv2.stop()
                except Exception:
                    pass
                dash.stop()

    def test_dashboard_without_fleet_config_says_so(self, pio_home):
        from predictionio_tpu.server.dashboard import DashboardServer

        dash = DashboardServer(host="127.0.0.1", port=0)
        dash.start(block=False)
        try:
            s, _, body = _get(
                f"http://127.0.0.1:{dash.port}/fleet.json")
            doc = json.loads(body)
            assert s == 200
            assert doc["instances"] == []
            assert "PIO_FLEET_INSTANCES" in doc["message"]
        finally:
            dash.stop()

    def test_pio_status_fleet_summary(self, trained, capsys):
        from predictionio_tpu.cli.main import _print_fleet_status
        from predictionio_tpu.server import EngineServer

        eng, variant, storage, _ = trained
        srv = EngineServer(eng, variant, storage, host="127.0.0.1",
                           port=0)
        srv.start()
        try:
            assert _post_query(srv.port)[0] == 200
            _print_fleet_status(f"http://127.0.0.1:{srv.port}")
            out = capsys.readouterr().out
            assert "fleet: 1 instance(s)" in out
            assert "healthy" in out
            assert "pio_query_requests_total" in out
            assert "p99" in out
        finally:
            srv.stop()
