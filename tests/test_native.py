"""Native components: C++ feeder + continuous-batching frontend."""

import concurrent.futures
import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from predictionio_tpu.native.build import native_available


needs_native = pytest.mark.skipif(
    not native_available("feeder") or not native_available("serving_frontend"),
    reason="g++ build unavailable")


@needs_native
class TestFeeder:
    def test_roundtrip_epoch(self, tmp_path):
        from predictionio_tpu.native.feeder import EventFeeder, write_cache

        users = np.arange(100, dtype=np.uint32)
        items = (np.arange(100, dtype=np.uint32) * 7) % 31
        vals = np.linspace(0, 1, 100).astype(np.float32)
        path = write_cache(tmp_path / "events.piof", users, items, vals)
        with EventFeeder(path, batch_size=32, seed=1) as f:
            assert len(f) == 100
            got_u, got_i, got_v = [], [], []
            for u, i, v in f.epoch():
                got_u.append(u)
                got_i.append(i)
                got_v.append(v)
            all_u = np.concatenate(got_u)
            assert len(all_u) == 100
            # Shuffled permutation of the input, values follow their rows.
            order = np.argsort(all_u)
            np.testing.assert_array_equal(all_u[order], users)
            np.testing.assert_array_equal(np.concatenate(got_i)[order], items)
            np.testing.assert_allclose(np.concatenate(got_v)[order], vals)

    def test_epochs_differ_deterministically(self, tmp_path):
        from predictionio_tpu.native.feeder import EventFeeder, write_cache

        users = np.arange(64, dtype=np.uint32)
        path = write_cache(tmp_path / "e.piof", users, users)
        with EventFeeder(path, batch_size=64, seed=5) as f:
            e1 = f.next_batch()[0]
            assert f.next_batch() is None  # epoch boundary
            e2 = f.next_batch()[0]
        assert not np.array_equal(e1, e2)  # re-shuffled
        with EventFeeder(path, batch_size=64, seed=5) as f:
            r1 = f.next_batch()[0]
        np.testing.assert_array_equal(e1, r1)  # deterministic per seed

    def test_no_shuffle_preserves_order(self, tmp_path):
        from predictionio_tpu.native.feeder import EventFeeder, write_cache

        users = np.arange(10, dtype=np.uint32)
        path = write_cache(tmp_path / "o.piof", users, users)
        with EventFeeder(path, batch_size=4, shuffle=False) as f:
            u1, _, _ = f.next_batch()
            np.testing.assert_array_equal(u1, [0, 1, 2, 3])


@needs_native
class TestNativeFrontend:
    def test_batched_serving(self):
        from predictionio_tpu.native.frontend import NativeFrontend

        seen_batches = []

        def handler(batch):
            seen_batches.append(len(batch))
            return [{"echo": q, "n": len(batch)} for q in batch]

        fe = NativeFrontend(handler, host="127.0.0.1", port=0,
                            max_batch=8, max_wait_us=20000)
        port = fe.start()
        try:
            def post(i):
                req = urllib.request.Request(
                    f"http://127.0.0.1:{port}/queries.json",
                    data=json.dumps({"user": f"u{i}"}).encode(),
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=10) as r:
                    return json.loads(r.read())

            with concurrent.futures.ThreadPoolExecutor(16) as ex:
                results = list(ex.map(post, range(16)))
            users = sorted(r["echo"]["user"] for r in results)
            assert users == sorted(f"u{i}" for i in range(16))
            # Concurrency actually produced multi-request batches.
            assert max(r["n"] for r in results) > 1
        finally:
            fe.stop()

    def test_serves_trained_engine(self, pio_home):
        """Full path: trained ALS engine behind the native frontend."""
        import numpy as np

        from predictionio_tpu.controller import EngineVariant, RuntimeContext
        from predictionio_tpu.data.event import DataMap, Event
        from predictionio_tpu.data.storage import App, get_storage
        from predictionio_tpu.native.frontend import NativeFrontend
        from predictionio_tpu.server import EngineServer
        from predictionio_tpu.templates.recommendation import engine
        from predictionio_tpu.workflow.core_workflow import run_train

        storage = get_storage()
        ctx = RuntimeContext.create(storage=storage)
        app_id = storage.get_apps().insert(App(id=None, name="testapp"))
        storage.get_events().init(app_id)
        rng = np.random.default_rng(0)
        for u in range(10):
            for i in range(8):
                if i % 2 == u % 2 and rng.random() < 0.95:
                    storage.get_events().insert(
                        Event(event="rate", entity_type="user",
                              entity_id=f"u{u}", target_entity_type="item",
                              target_entity_id=f"i{i}",
                              properties=DataMap({"rating": 4.0})), app_id)
        variant = EngineVariant.from_dict({
            "engineFactory": "predictionio_tpu.templates.recommendation:engine",
            "datasource": {"params": {"appName": "testapp"}},
            "algorithms": [{"name": "als",
                            "params": {"rank": 4, "numIterations": 5}}],
        })
        eng = engine()
        run_train(eng, variant, ctx)
        srv = EngineServer(eng, variant, storage, host="127.0.0.1", port=0)
        fe = NativeFrontend(srv.query_batch, host="127.0.0.1", port=0,
                            max_batch=8, max_wait_us=10000)
        port = fe.start()
        try:
            def post(u):
                req = urllib.request.Request(
                    f"http://127.0.0.1:{port}/queries.json",
                    data=json.dumps({"user": u, "num": 3}).encode(),
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=10) as r:
                    return json.loads(r.read())

            with concurrent.futures.ThreadPoolExecutor(8) as ex:
                results = list(ex.map(post, [f"u{i}" for i in range(8)]))
            for u, res in zip(range(8), results):
                assert len(res["itemScores"]) == 3
                par = u % 2
                top = [int(s["item"][1:]) % 2 for s in res["itemScores"]]
                assert sum(1 for t in top if t == par) >= 2
        finally:
            fe.stop()

    def test_status_metrics_and_errors(self):
        from predictionio_tpu.native.frontend import NativeFrontend

        fe = NativeFrontend(lambda b: [{"ok": True} for _ in b],
                            host="127.0.0.1", port=0)
        port = fe.start()
        try:
            with urllib.request.urlopen(f"http://127.0.0.1:{port}/",
                                        timeout=10) as r:
                assert json.loads(r.read())["status"] == "alive"
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/queries.json",
                data=b"{not json", headers={"Content-Type": "application/json"})
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req, timeout=10)
            assert ei.value.code == 400
            with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics",
                                        timeout=10) as r:
                text = r.read().decode()
            assert "pio_frontend_requests_total" in text
        finally:
            fe.stop()
