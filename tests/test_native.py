"""Native components: C++ feeder + continuous-batching frontend."""

import concurrent.futures
import json
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from predictionio_tpu.native.build import native_available


needs_native = pytest.mark.skipif(
    not native_available("feeder") or not native_available("serving_frontend"),
    reason="g++ build unavailable")


@needs_native
class TestFeeder:
    def test_roundtrip_epoch(self, tmp_path):
        from predictionio_tpu.native.feeder import EventFeeder, write_cache

        users = np.arange(100, dtype=np.uint32)
        items = (np.arange(100, dtype=np.uint32) * 7) % 31
        vals = np.linspace(0, 1, 100).astype(np.float32)
        path = write_cache(tmp_path / "events.piof", users, items, vals)
        with EventFeeder(path, batch_size=32, seed=1) as f:
            assert len(f) == 100
            got_u, got_i, got_v = [], [], []
            for u, i, v in f.epoch():
                got_u.append(u)
                got_i.append(i)
                got_v.append(v)
            all_u = np.concatenate(got_u)
            assert len(all_u) == 100
            # Shuffled permutation of the input, values follow their rows.
            order = np.argsort(all_u)
            np.testing.assert_array_equal(all_u[order], users)
            np.testing.assert_array_equal(np.concatenate(got_i)[order], items)
            np.testing.assert_allclose(np.concatenate(got_v)[order], vals)

    def test_v2_extras_roundtrip(self, tmp_path):
        """n_extra > 0: the 7-arg next_batch ABI carries extra columns
        (round-2 advisor: the 6-arg binding read a garbage pointer)."""
        from predictionio_tpu.native.feeder import EventFeeder, write_cache

        rng = np.random.default_rng(0)
        n = 77  # odd count exercises the v2 8-byte alignment pad
        users = np.arange(n, dtype=np.uint32)
        items = (users * 3) % 13
        vals = rng.random(n).astype(np.float32)
        extras = rng.random((n, 3)).astype(np.float32)
        path = write_cache(tmp_path / "v2.piof", users, items, vals,
                           extras=extras)
        with EventFeeder(path, batch_size=19, seed=2) as f:
            assert f.n_extra == 3
            got = [b for b in f.epoch()]
            all_u = np.concatenate([b[0] for b in got])
            all_e = np.concatenate([b[3] for b in got])
            order = np.argsort(all_u)
            np.testing.assert_array_equal(all_u[order], users)
            np.testing.assert_allclose(all_e[order], extras)

    def test_epochs_differ_deterministically(self, tmp_path):
        from predictionio_tpu.native.feeder import EventFeeder, write_cache

        users = np.arange(64, dtype=np.uint32)
        path = write_cache(tmp_path / "e.piof", users, users)
        with EventFeeder(path, batch_size=64, seed=5) as f:
            e1 = f.next_batch()[0]
            assert f.next_batch() is None  # epoch boundary
            e2 = f.next_batch()[0]
        assert not np.array_equal(e1, e2)  # re-shuffled
        with EventFeeder(path, batch_size=64, seed=5) as f:
            r1 = f.next_batch()[0]
        np.testing.assert_array_equal(e1, r1)  # deterministic per seed

    def test_no_shuffle_preserves_order(self, tmp_path):
        from predictionio_tpu.native.feeder import EventFeeder, write_cache

        users = np.arange(10, dtype=np.uint32)
        path = write_cache(tmp_path / "o.piof", users, users)
        with EventFeeder(path, batch_size=4, shuffle=False) as f:
            u1, _, _ = f.next_batch()
            np.testing.assert_array_equal(u1, [0, 1, 2, 3])


@needs_native
class TestNativeFrontend:
    def test_batched_serving(self):
        from predictionio_tpu.native.frontend import NativeFrontend

        seen_batches = []

        def handler(batch):
            seen_batches.append(len(batch))
            return [{"echo": q, "n": len(batch)} for q in batch]

        fe = NativeFrontend(handler, host="127.0.0.1", port=0,
                            max_batch=8, max_wait_us=20000)
        port = fe.start()
        try:
            def post(i):
                req = urllib.request.Request(
                    f"http://127.0.0.1:{port}/queries.json",
                    data=json.dumps({"user": f"u{i}"}).encode(),
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=10) as r:
                    return json.loads(r.read())

            with concurrent.futures.ThreadPoolExecutor(16) as ex:
                results = list(ex.map(post, range(16)))
            users = sorted(r["echo"]["user"] for r in results)
            assert users == sorted(f"u{i}" for i in range(16))
            # Concurrency actually produced multi-request batches.
            assert max(r["n"] for r in results) > 1
        finally:
            fe.stop()

    def test_serves_trained_engine(self, pio_home):
        """Full path: trained ALS engine behind the native frontend."""
        import numpy as np

        from predictionio_tpu.controller import EngineVariant, RuntimeContext
        from predictionio_tpu.data.event import DataMap, Event
        from predictionio_tpu.data.storage import App, get_storage
        from predictionio_tpu.native.frontend import NativeFrontend
        from predictionio_tpu.server import EngineServer
        from predictionio_tpu.templates.recommendation import engine
        from predictionio_tpu.workflow.core_workflow import run_train

        storage = get_storage()
        ctx = RuntimeContext.create(storage=storage)
        app_id = storage.get_apps().insert(App(id=None, name="testapp"))
        storage.get_events().init(app_id)
        rng = np.random.default_rng(0)
        for u in range(10):
            for i in range(8):
                if i % 2 == u % 2 and rng.random() < 0.95:
                    storage.get_events().insert(
                        Event(event="rate", entity_type="user",
                              entity_id=f"u{u}", target_entity_type="item",
                              target_entity_id=f"i{i}",
                              properties=DataMap({"rating": 4.0})), app_id)
        variant = EngineVariant.from_dict({
            "engineFactory": "predictionio_tpu.templates.recommendation:engine",
            "datasource": {"params": {"appName": "testapp"}},
            "algorithms": [{"name": "als",
                            "params": {"rank": 4, "numIterations": 5}}],
        })
        eng = engine()
        run_train(eng, variant, ctx)
        srv = EngineServer(eng, variant, storage, host="127.0.0.1", port=0)
        fe = NativeFrontend(srv.query_batch, host="127.0.0.1", port=0,
                            max_batch=8, max_wait_us=10000)
        port = fe.start()
        try:
            def post(u):
                req = urllib.request.Request(
                    f"http://127.0.0.1:{port}/queries.json",
                    data=json.dumps({"user": u, "num": 3}).encode(),
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=10) as r:
                    return json.loads(r.read())

            with concurrent.futures.ThreadPoolExecutor(8) as ex:
                results = list(ex.map(post, [f"u{i}" for i in range(8)]))
            for u, res in zip(range(8), results):
                assert len(res["itemScores"]) == 3
                par = u % 2
                top = [int(s["item"][1:]) % 2 for s in res["itemScores"]]
                assert sum(1 for t in top if t == par) >= 2
        finally:
            fe.stop()

    def test_status_metrics_and_errors(self):
        from predictionio_tpu.native.frontend import NativeFrontend

        fe = NativeFrontend(lambda b: [{"ok": True} for _ in b],
                            host="127.0.0.1", port=0)
        port = fe.start()
        try:
            with urllib.request.urlopen(f"http://127.0.0.1:{port}/",
                                        timeout=10) as r:
                assert json.loads(r.read())["status"] == "alive"
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/queries.json",
                data=b"{not json", headers={"Content-Type": "application/json"})
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req, timeout=10)
            assert ei.value.code == 400
            with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics",
                                        timeout=10) as r:
                text = r.read().decode()
            assert "pio_frontend_requests_total" in text
        finally:
            fe.stop()


@needs_native
class TestNativePluginSeam:
    def test_plugin_header_through_native_frontend(self, pio_home,
                                                   monkeypatch):
        """The plugin seam must hold behind the C++ frontend too: the
        hook sees (route, status, ms) per item and its headers reach
        the wire via pio_batch_respond_ex (SURVEY §5.1)."""
        import tests.plugin_fixture as pf
        from predictionio_tpu.data.storage import get_storage
        from predictionio_tpu.data.storage.base import AccessKey, App
        from predictionio_tpu.native.frontend import NativeFrontend
        from predictionio_tpu.server.event_server import EventServer

        monkeypatch.setenv("PIO_EVENTSERVER_PLUGINS",
                           "tests.plugin_fixture:make_plugin")
        storage = get_storage()
        app_id = storage.get_apps().insert(App(id=None, name="npl"))
        storage.get_events().init(app_id)
        key = storage.get_access_keys().insert(AccessKey.generate(app_id))
        srv = EventServer(storage)
        plugin = pf.LAST
        fe = NativeFrontend(None, host="127.0.0.1", port=0,
                            max_batch=16, max_wait_us=2000,
                            fallback_batch=srv.native_fallback_batch,
                            plugin_hook=srv.plugins.header_block)
        port = fe.start()
        try:
            ev = {"event": "rate", "entityType": "user", "entityId": "u1"}
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/events.json?accessKey={key}",
                data=json.dumps(ev).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=10) as r:
                assert r.status == 201
                assert r.headers["X-Plugin-Count"] == "1"
            # a second request over the SAME seam increments the count
            with urllib.request.urlopen(req, timeout=10) as r:
                assert r.headers["X-Plugin-Count"] == "2"
            assert [x[0] for x in plugin.requests] == \
                ["POST /events.json", "POST /events.json"]
            assert all(x[1] == 201 for x in plugin.requests)
        finally:
            fe.stop()


@needs_native
class TestAdaptiveLinger:
    def test_unloaded_request_skips_batch_linger(self):
        """A lone client must NOT pay the continuous-batching linger: with
        one live connection nobody else can join the batch, so the
        batcher dispatches immediately (VERDICT r4 item 4 — native
        unloaded p50 was ~4x python's because the linger taxed every
        idle-server request by max_wait_us)."""
        import socket

        from predictionio_tpu.native.frontend import NativeFrontend

        wait_us = 50_000  # deliberately huge so the old behavior is obvious
        fe = NativeFrontend(lambda b: [{"ok": True} for _ in b],
                            host="127.0.0.1", port=0, max_batch=64,
                            max_wait_us=wait_us)
        port = fe.start()
        try:
            s = socket.create_connection(("127.0.0.1", port))
            payload = b'{"q": 1}'
            req = (b"POST /queries.json HTTP/1.1\r\nHost: x\r\n"
                   b"Content-Type: application/json\r\nContent-Length: " +
                   str(len(payload)).encode() + b"\r\n\r\n" + payload)
            lats = []
            for _ in range(30):
                t0 = time.perf_counter()
                s.sendall(req)
                buf = b""
                while b"ok" not in buf:
                    buf += s.recv(65536)
                lats.append(time.perf_counter() - t0)
            s.close()
            lats.sort()
            p50 = lats[len(lats) // 2]
            # old behavior: every request waited the full 50 ms linger
            assert p50 < wait_us / 1e6 / 2, f"p50 {p50*1e3:.1f} ms"
        finally:
            fe.stop()


@needs_native
class TestFrontendRound2:
    """Round-2 fixes: worker pool (no thread growth), keep-alive, shutdown
    drain (ADVICE.md mediums 1-2, VERDICT.md weak-3)."""

    def _thread_count(self):
        import os

        return len(os.listdir("/proc/self/task"))

    def test_keep_alive_reuses_connection(self):
        import http.client

        from predictionio_tpu.native.frontend import NativeFrontend

        fe = NativeFrontend(lambda batch: [{"ok": True} for _ in batch],
                            host="127.0.0.1", port=0, max_batch=8,
                            max_wait_us=100)
        port = fe.start()
        try:
            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
            for i in range(50):  # one TCP connection, many requests
                conn.request("POST", "/queries.json",
                             body=json.dumps({"i": i}),
                             headers={"Content-Type": "application/json"})
                resp = conn.getresponse()
                assert resp.status == 200
                assert json.loads(resp.read()) == {"ok": True}
                assert resp.getheader("Connection") == "keep-alive"
            conn.close()
        finally:
            fe.stop()

    def test_thread_count_flat_under_load(self):
        import http.client

        from predictionio_tpu.native.frontend import NativeFrontend

        fe = NativeFrontend(lambda batch: [{"ok": True} for _ in batch],
                            host="127.0.0.1", port=0, max_batch=16,
                            max_wait_us=100)
        port = fe.start()
        try:
            def hammer(n):
                conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
                for i in range(n):
                    conn.request("POST", "/queries.json",
                                 body=json.dumps({"i": i}))
                    r = conn.getresponse()
                    assert r.status == 200
                    r.read()
                conn.close()

            hammer(20)
            before = self._thread_count()
            with concurrent.futures.ThreadPoolExecutor(8) as ex:
                list(ex.map(hammer, [250] * 8))  # 2000 requests
            after = self._thread_count()
            # Round 1 grew one C++ thread per request (would be ~+2000).
            assert after - before <= 8, (before, after)
        finally:
            fe.stop()

    def test_stop_with_queued_requests_does_not_hang(self):
        import threading
        import time as _t

        from predictionio_tpu.native.frontend import NativeFrontend

        release = threading.Event()

        def slow_handler(batch):
            release.wait(timeout=15)
            return [{"ok": True} for _ in batch]

        fe = NativeFrontend(slow_handler, host="127.0.0.1", port=0,
                            max_batch=1, max_wait_us=0)
        port = fe.start()

        statuses = []

        def post():
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/queries.json", data=b"{}",
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req, timeout=20) as r:
                    statuses.append(r.status)
            except urllib.error.HTTPError as e:
                statuses.append(e.code)
            except Exception:
                statuses.append(-1)

        threads = [threading.Thread(target=post) for _ in range(6)]
        for t in threads:
            t.start()
        _t.sleep(0.3)  # first request in callback, rest queued
        release.set()
        stopper = threading.Thread(target=fe.stop)
        stopper.start()
        stopper.join(timeout=10)
        assert not stopper.is_alive(), "pio_frontend_stop hung (round-1 bug)"
        for t in threads:
            t.join(timeout=10)
            assert not t.is_alive()
        # Every client got SOME definitive answer (200 or 503), none hung.
        assert len(statuses) == 6
        assert all(s in (200, 503, -1) for s in statuses)

    def test_stop_with_idle_keepalive_connection(self):
        """A worker parked in recv on an idle keep-alive socket must not
        pin pio_frontend_stop (SO_RCVTIMEO poll + running check)."""
        import http.client
        import threading
        import time as _t

        from predictionio_tpu.native.frontend import NativeFrontend

        fe = NativeFrontend(lambda b: [{"ok": True} for _ in b],
                            host="127.0.0.1", port=0, max_batch=4,
                            max_wait_us=100)
        port = fe.start()
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        conn.request("POST", "/queries.json", body=json.dumps({}))
        conn.getresponse().read()
        # Connection left OPEN and idle.
        stopper = threading.Thread(target=fe.stop)
        t0 = _t.perf_counter()
        stopper.start()
        stopper.join(timeout=5)
        alive = stopper.is_alive()
        conn.close()
        assert not alive, "stop() hung on idle keep-alive connection"
        assert _t.perf_counter() - t0 < 5


@needs_native
class TestFeederTrainingIntegration:
    """Round-2 verdict items 2/3: the feeder must FEED training, not just
    pass its own round-trip tests.  Both minibatch loops pull epochs from
    the mmap cache; same example multiset per epoch as the numpy path."""

    def test_two_tower_feeder_vs_numpy(self):
        import numpy as np
        from predictionio_tpu.models import two_tower as tt

        rng = np.random.default_rng(0)
        users = rng.integers(0, 16, 300)
        items = rng.integers(0, 8, 300)
        cfg = tt.TwoTowerConfig(n_users=16, n_items=8, embed_dim=8,
                                hidden_dims=(16,), out_dim=8,
                                batch_size=64, epochs=2, seed=3)
        s_np = tt.train(users, items, cfg, data_source="numpy")
        s_fd = tt.train(users, items, cfg, data_source="feeder")
        # Orders differ (host permutation vs SplitMix64), so params are
        # not bitwise equal — but both must train to a working retrieval
        # model over the same data.  Compare in-batch loss on a fixed
        # probe batch.
        import jax.numpy as jnp

        def probe():
            # fresh device buffers per call: train_step donates its
            # batch tensors (a reused jnp array would be deleted on
            # donation-capable backends)
            return (jnp.asarray(users[:64]), jnp.asarray(items[:64]),
                    jnp.asarray(np.ones(64, np.float32)))

        _, l_np = tt.train_step(s_np, *probe(), cfg)
        _, l_fd = tt.train_step(s_fd, *probe(), cfg)
        assert abs(float(l_np) - float(l_fd)) < 0.5 * max(float(l_np), 0.1)

    def test_dlrm_feeder_vs_numpy_same_examples(self):
        """The feeder path must present exactly the dataset each epoch —
        multiset equality of (cat0, cat1, label, dense) rows."""
        import numpy as np
        from predictionio_tpu.native.feeder import EventFeeder, write_cache

        rng = np.random.default_rng(1)
        n = 257  # odd: exercises ragged last batch + alignment pad
        u = rng.integers(0, 50, n).astype(np.uint32)
        i = rng.integers(0, 20, n).astype(np.uint32)
        y = rng.integers(0, 2, n).astype(np.float32)
        dense = rng.random((n, 3), np.float32)
        path = write_cache("/tmp/pio_test_dlrm_eq.piof", u, i, y,
                           extras=dense)
        with EventFeeder(path, batch_size=64, seed=9) as f:
            rows = []
            for bu, bi, by, bx in f.epoch():
                for k in range(len(bu)):
                    rows.append((int(bu[k]), int(bi[k]), float(by[k]),
                                 tuple(np.round(bx[k], 6))))
        expect = sorted((int(a), int(b), float(c), tuple(np.round(d, 6)))
                        for a, b, c, d in zip(u, i, y, dense))
        assert sorted(rows) == expect

    def test_feeder_v3_cats_roundtrip(self, tmp_path):
        """F=4 categorical columns (v3 cache): multiset equality of
        (cat0..3, label, dense) rows across one epoch."""
        import numpy as np
        from predictionio_tpu.native.feeder import EventFeeder, write_cache

        rng = np.random.default_rng(2)
        n = 203
        cats = rng.integers(0, 30, (n, 4)).astype(np.uint32)
        y = rng.integers(0, 2, n).astype(np.float32)
        dense = rng.random((n, 2), np.float32)
        path = write_cache(tmp_path / "v3.piof", cats=cats, values=y,
                           extras=dense)
        with EventFeeder(path, batch_size=48, seed=5) as f:
            assert f.n_cat == 4 and f.n_extra == 2
            rows = []
            for bc, by, bx in f.epoch_cats():
                for k in range(len(by)):
                    rows.append((tuple(int(v) for v in bc[k]), float(by[k]),
                                 tuple(np.round(bx[k], 6))))
        expect = sorted((tuple(int(v) for v in c), float(a),
                         tuple(np.round(d, 6)))
                        for c, a, d in zip(cats, y, dense))
        assert sorted(rows) == expect

    def test_dlrm_feeder_f4_trains_like_numpy(self):
        """Round-3 weakness 6: the native data path must serve real CTR
        shapes (F=4 here), not just user/item.  Same dataset through the
        feeder and the numpy loader → comparable fit on a probe batch."""
        import numpy as np
        from predictionio_tpu.models import dlrm as dlrm_lib

        rng = np.random.default_rng(7)
        n = 600
        cat = np.stack([rng.integers(0, 12, n), rng.integers(0, 8, n),
                        rng.integers(0, 6, n), rng.integers(0, 4, n)],
                       axis=1)
        # Learnable signal: label depends on field 0.
        labels = (cat[:, 0] < 6).astype(np.float32)
        dense = rng.random((n, 3), np.float32)
        # 16 epochs at lr 0.1: 600 rows / batch 64 gives few steps per
        # epoch, and adagrad at the default 0.05 leaves both loaders
        # short of the 0.2 separation this asserts — undertrained, not
        # loader-divergent.
        cfg = dlrm_lib.DLRMConfig(vocab_sizes=(12, 8, 6, 4), n_dense=3,
                                  embed_dim=8, bottom_mlp=(16, 8),
                                  top_mlp=(16, 8), batch_size=64, epochs=16,
                                  learning_rate=0.1, seed=3)
        s_np = dlrm_lib.train(dense, cat, labels, cfg, data_source="numpy")
        s_fd = dlrm_lib.train(dense, cat, labels, cfg, data_source="feeder")
        p_np = np.asarray(dlrm_lib.predict_proba(s_np, dense, cat, cfg))
        p_fd = np.asarray(dlrm_lib.predict_proba(s_fd, dense, cat, cfg))
        pos, neg = labels == 1, labels == 0
        # Both loaders learned the field-0 signal (shuffle order differs,
        # exact params need not match).
        assert p_np[pos].mean() > p_np[neg].mean() + 0.2
        assert p_fd[pos].mean() > p_fd[neg].mean() + 0.2
        # And the two fits agree closely on the probe predictions.
        assert abs(p_np.mean() - p_fd.mean()) < 0.1

    def test_dlrm_feeder_no_dense(self):
        """n_dense == 0 must work through the feeder (round-3 advisor:
        the old path crashed unpacking the missing extras column)."""
        import numpy as np
        from predictionio_tpu.models import dlrm as dlrm_lib

        rng = np.random.default_rng(8)
        n = 300
        cat = np.stack([rng.integers(0, 10, n), rng.integers(0, 5, n)],
                       axis=1)
        labels = rng.integers(0, 2, n).astype(np.float32)
        dense = np.zeros((n, 0), np.float32)
        cfg = dlrm_lib.DLRMConfig(vocab_sizes=(10, 5), n_dense=0,
                                  embed_dim=8, bottom_mlp=(16, 8),
                                  top_mlp=(16,), batch_size=64, epochs=1,
                                  seed=4)
        state = dlrm_lib.train(dense, cat, labels, cfg,
                               data_source="feeder")
        p = np.asarray(dlrm_lib.predict_proba(state, dense, cat, cfg))
        assert np.isfinite(p).all() and p.shape == (n,)


@needs_native
class TestNativeEventIngest:
    """Event API through the C++ frontend (pio eventserver --native):
    routing metadata, per-item statuses, and the group-committed insert."""

    def _setup_server(self, pio_home):
        from predictionio_tpu.data.storage import App, get_storage
        from predictionio_tpu.data.storage.base import AccessKey
        from predictionio_tpu.server.event_server import EventServer

        storage = get_storage()
        app_id = storage.get_apps().insert(App(id=None, name="nativeapp"))
        storage.get_events().init(app_id)
        key = storage.get_access_keys().insert(AccessKey.generate(app_id))
        return EventServer(storage), storage, app_id, key

    def test_full_event_api_through_frontend(self, pio_home):
        from predictionio_tpu.native.frontend import NativeFrontend

        srv, storage, app_id, key = self._setup_server(pio_home)
        fe = NativeFrontend(None, host="127.0.0.1", port=0,
                            max_batch=16, max_wait_us=5000,
                            fallback_batch=srv.native_fallback_batch)
        port = fe.start()
        try:
            def post(path, payload, expect):
                req = urllib.request.Request(
                    f"http://127.0.0.1:{port}{path}",
                    data=json.dumps(payload).encode(),
                    headers={"Content-Type": "application/json"})
                try:
                    with urllib.request.urlopen(req, timeout=10) as r:
                        assert r.status == expect, (r.status, expect)
                        return json.loads(r.read())
                except urllib.error.HTTPError as e:
                    assert e.code == expect, (e.code, expect)
                    return json.load(e)

            ev = {"event": "rate", "entityType": "user", "entityId": "u1",
                  "targetEntityType": "item", "targetEntityId": "i1",
                  "properties": {"rating": 4}}
            out = post(f"/events.json?accessKey={key}", ev, 201)
            assert "eventId" in out
            # bad key -> 401, malformed -> 400, batch endpoint -> 200 list
            post("/events.json?accessKey=WRONG", ev, 401)
            post(f"/events.json?accessKey={key}", {"entityId": "x"}, 400)
            out = post(f"/batch/events.json?accessKey={key}", [ev, ev], 200)
            assert [o["status"] for o in out] == [201, 201]
            # GET query through the fallback path
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/events.json?accessKey={key}"
                "&entityId=u1&limit=-1")
            with urllib.request.urlopen(req, timeout=10) as r:
                found = json.loads(r.read())
            assert len(found) == 3
            # stats counted all successful inserts
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/stats.json?accessKey={key}")
            with urllib.request.urlopen(req, timeout=10) as r:
                stats = json.loads(r.read())
            assert stats["statusCounts"].get("201", 0) >= 1
            assert stats["eventCounts"].get("rate", 0) >= 1
            # /metrics reaches the EVENT server (forward_all), not the
            # frontend's own counters
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/metrics")
            with urllib.request.urlopen(req, timeout=10) as r:
                text = r.read().decode()
            assert "pio_event_requests_total" in text, text[:200]
        finally:
            fe.stop()

    def test_per_item_error_isolation(self, pio_home):
        """One malformed body (bad UTF-8 / bad JSON) must not fail its
        peers in a grouped ingest or the fallback singles loop — the
        peers' inserts may already be committed, and a run-wide 500
        invites client-retry duplicates (ADVICE r4, medium)."""
        srv, storage, app_id, key = self._setup_server(pio_home)
        good = json.dumps({"event": "view", "entityType": "user",
                           "entityId": "u1", "targetEntityType": "item",
                           "targetEntityId": "i1"}).encode()
        bad_utf8 = b'\xff\xfe{"event": "view"}'
        bad_json = b"{nope"
        # grouped path (concurrent same-route singles)
        outs = srv.native_fallback_batch(
            "POST", f"/events.json?accessKey={key}",
            [good, bad_utf8, good, bad_json, good])
        statuses = [o[0] for o in outs]
        assert statuses == [201, 400, 201, 400, 201], statuses
        # singles loop (mixed-route fallback, len==1 groups)
        outs = srv.native_fallback_batch(
            "POST", f"/events.json?accessKey={key}", [bad_utf8])
        assert outs[0][0] == 400, outs
        # bad access key on a GROUPED run: per-item 401s, not a crash
        outs = srv.native_fallback_batch(
            "POST", "/events.json?accessKey=WRONG", [good, good])
        assert [o[0] for o in outs] == [401, 401], outs
        # bad channel on a grouped run: per-item 400s
        outs = srv.native_fallback_batch(
            "POST", f"/events.json?accessKey={key}&channel=nope",
            [good, good])
        assert [o[0] for o in outs] == [400, 400], outs
        stored = list(storage.get_events().find(app_id, None, limit=None))
        assert len(stored) == 3

    def test_concurrent_singles_group_commit(self, pio_home):
        from predictionio_tpu.native.frontend import NativeFrontend

        srv, storage, app_id, key = self._setup_server(pio_home)
        calls = []
        orig = srv._ingest_group

        def spy(params, bodies):
            calls.append(len(bodies))
            return orig(params, bodies)

        srv._ingest_group = spy
        fe = NativeFrontend(None, host="127.0.0.1", port=0,
                            max_batch=32, max_wait_us=20000,
                            fallback_batch=srv.native_fallback_batch)
        port = fe.start()
        try:
            def post(i):
                ev = {"event": "view", "entityType": "user",
                      "entityId": f"u{i}", "targetEntityType": "item",
                      "targetEntityId": f"i{i % 5}"}
                req = urllib.request.Request(
                    f"http://127.0.0.1:{port}/events.json?accessKey={key}",
                    data=json.dumps(ev).encode(),
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=10) as r:
                    return json.loads(r.read())["eventId"]

            with concurrent.futures.ThreadPoolExecutor(24) as ex:
                ids = list(ex.map(post, range(24)))
            assert len(set(ids)) == 24  # every event stored, distinct ids
            stored = list(storage.get_events().find(app_id, None, limit=None))
            assert len(stored) == 24
            assert sorted(e.entity_id for e in stored) == \
                sorted(f"u{i}" for i in range(24))
            # concurrency actually produced at least one grouped insert
            assert calls and max(calls) > 1
        finally:
            fe.stop()


@needs_native
class TestNativeDeployFallback:
    def test_status_and_reload_behind_native_frontend(self, pio_home):
        """pio deploy --native forwards non-query routes to the engine
        server: "/" status and POST /reload (the reference's hot-reload
        after retrain) must work through the C++ layer."""
        import numpy as np

        from predictionio_tpu.controller import EngineVariant, RuntimeContext
        from predictionio_tpu.data.event import DataMap, Event
        from predictionio_tpu.data.storage import App, get_storage
        from predictionio_tpu.native.frontend import NativeFrontend
        from predictionio_tpu.server import EngineServer
        from predictionio_tpu.templates.recommendation import engine
        from predictionio_tpu.workflow.core_workflow import run_train

        storage = get_storage()
        ctx = RuntimeContext.create(storage=storage)
        app_id = storage.get_apps().insert(App(id=None, name="testapp"))
        storage.get_events().init(app_id)
        rng = np.random.default_rng(0)
        for u in range(8):
            for i in range(6):
                if rng.random() < 0.8:
                    storage.get_events().insert(
                        Event(event="rate", entity_type="user",
                              entity_id=f"u{u}", target_entity_type="item",
                              target_entity_id=f"i{i}",
                              properties=DataMap({"rating": 3.0})), app_id)
        variant = EngineVariant.from_dict({
            "engineFactory":
                "predictionio_tpu.templates.recommendation:engine",
            "datasource": {"params": {"appName": "testapp"}},
            "algorithms": [{"name": "als",
                            "params": {"rank": 4, "numIterations": 3}}],
        })
        eng = engine()
        run_train(eng, variant, ctx)
        srv = EngineServer(eng, variant, storage, host="127.0.0.1", port=0)

        def fallback(method, path_with_qs, body):
            return srv.handle(method, path_with_qs.split("?", 1)[0], body)

        fe = NativeFrontend(srv.query_batch, host="127.0.0.1", port=0,
                            max_batch=8, max_wait_us=5000,
                            fallback=fallback)
        port = fe.start()
        try:
            # "/" stays a C++-level liveness probe in deploy mode
            with urllib.request.urlopen(f"http://127.0.0.1:{port}/",
                                        timeout=10) as r:
                alive = json.loads(r.read())
            assert alive == {"status": "alive", "frontend": "native"}
            first_instance = srv._instance.id
            # retrain, then hot-reload through the native layer
            run_train(eng, variant, ctx)
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/reload", b"", method="POST")
            with urllib.request.urlopen(req, timeout=30) as r:
                reloaded = json.loads(r.read())
            assert reloaded["status"] == "reloaded"
            assert reloaded["engineInstanceId"] != first_instance
            # queries still answered after the swap
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/queries.json",
                json.dumps({"user": "u1", "num": 2}).encode(),
                {"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=10) as r:
                res = json.loads(r.read())
            assert len(res["itemScores"]) == 2
        finally:
            fe.stop()
