"""Recommendation eval sweep: Precision@K over rank candidates."""

import numpy as np
import pytest

from predictionio_tpu.controller import RuntimeContext
from predictionio_tpu.data.event import DataMap, Event
from predictionio_tpu.data.storage import App, get_storage
from predictionio_tpu.templates.recommendation.evaluation import (
    PrecisionAtK,
    RecallAtK,
    default_params_generator,
    evaluation,
)
from predictionio_tpu.templates.recommendation.engine import (
    ItemScore,
    PredictedResult,
    Query,
)
from predictionio_tpu.workflow.core_workflow import run_evaluation


def test_precision_math():
    m = PrecisionAtK(k=2)
    pred = PredictedResult(itemScores=[ItemScore("a", 1.0), ItemScore("b", 0.5)])
    assert m.calculate_one(Query(user="u"), pred, ["a", "c"]) == 0.5
    assert m.calculate_one(Query(user="u"), pred, []) is None
    r = RecallAtK(k=2)
    assert r.calculate_one(Query(user="u"), pred, ["a", "c"]) == 0.5


def test_eval_sweep_end_to_end(pio_home):
    ctx = RuntimeContext.create(storage=get_storage())
    storage = ctx.storage
    app_id = storage.get_apps().insert(App(id=None, name="testapp"))
    storage.get_events().init(app_id)
    rng = np.random.default_rng(0)
    # 50% density: held-out positives need free slots in the top-K — at
    # high density the training items crowd it and cap precision.
    for u in range(16):
        for i in range(10):
            if i % 2 == u % 2 and rng.random() < 0.5:
                storage.get_events().insert(
                    Event(event="rate", entity_type="user", entity_id=f"u{u}",
                          target_entity_type="item", target_entity_id=f"i{i}",
                          properties=DataMap({"rating": 4.0})), app_id)
    from predictionio_tpu.templates.recommendation.evaluation import (
        RecommendationEvaluation,
    )

    ev = RecommendationEvaluation(k=3)
    gen = default_params_generator("testapp", eval_k=2, ranks=(4, 8))
    iid, result = run_evaluation(ev, gen, ctx)
    assert len(result.candidate_scores) == 2
    assert result.metric_header == "Precision@3"
    # Clique structure → held-out positives retrievable above the random
    # baseline (≈ held-out/catalog ≈ 0.12).  The ceiling is intrinsically
    # low: the model can't distinguish held-out from trained clique items,
    # and trained ones crowd the top-K (reference eval behaves the same).
    assert result.best_score > 0.14
    others = result.candidate_scores[result.best_index][2]
    assert others and 0.0 <= others[0] <= 1.0  # Recall@3 computed
    inst = ctx.storage.get_evaluation_instances().get(iid)
    assert inst.status == "EVALCOMPLETED"


def test_eval_chunked_prediction_matches_monolithic(pio_home, monkeypatch):
    """ISSUE 7 satellite: the eval fold streams through DevicePrefetcher
    in PIO_EVAL_BATCH chunks — per-query results must be identical to
    the old one-monolithic-batch path."""
    ctx = RuntimeContext.create(storage=get_storage())
    storage = ctx.storage
    app_id = storage.get_apps().insert(App(id=None, name="testapp"))
    storage.get_events().init(app_id)
    rng = np.random.default_rng(1)
    for u in range(16):
        for i in range(10):
            if i % 2 == u % 2 and rng.random() < 0.5:
                storage.get_events().insert(
                    Event(event="rate", entity_type="user", entity_id=f"u{u}",
                          target_entity_type="item", target_entity_id=f"i{i}",
                          properties=DataMap({"rating": 4.0})), app_id)
    ev = evaluation()
    gen = default_params_generator("testapp", eval_k=2, ranks=(4,))
    (engine_params,) = gen.engine_params_list

    monkeypatch.setenv("PIO_EVAL_BATCH", "0")  # monolithic (pre-ISSUE-7)
    mono = ev.engine.eval(ctx, engine_params)
    monkeypatch.setenv("PIO_EVAL_BATCH", "3")  # tiny chunks, many windows
    chunked = ev.engine.eval(ctx, engine_params)

    assert len(mono) == len(chunked)
    for (ei_m, qpa_m), (ei_c, qpa_c) in zip(mono, chunked):
        assert len(qpa_m) == len(qpa_c)
        for (qm, pm, am), (qc, pc, ac) in zip(qpa_m, qpa_c):
            assert qm.user == qc.user and am == ac
            assert [(s.item, s.score) for s in pm.itemScores] == \
                [(s.item, s.score) for s in pc.itemScores]


def test_eval_sweep_shares_data_pass(pio_home, monkeypatch):
    """3 candidates varying only algorithm params must read + prepare the
    fold data ONCE (round-2 verdict item 9)."""
    import numpy as np
    from predictionio_tpu.controller import RuntimeContext
    from predictionio_tpu.data.event import DataMap, Event
    from predictionio_tpu.data.storage import App, get_storage
    from predictionio_tpu.templates.recommendation.engine import (
        RecommendationDataSource, RecommendationPreparator, engine,
    )

    storage = get_storage()
    app_id = storage.get_apps().insert(App(id=None, name="sweepapp"))
    storage.get_events().init(app_id)
    rng = np.random.default_rng(0)
    evs = [Event(event="rate", entity_type="user", entity_id=f"u{u}",
                 target_entity_type="item", target_entity_id=f"i{i}",
                 properties=DataMap({"rating": float(r)}))
           for u, i, r in zip(rng.integers(0, 20, 400),
                              rng.integers(0, 15, 400),
                              rng.integers(1, 6, 400))]
    storage.get_events().insert_batch(evs, app_id)

    reads = {"n": 0}
    prepares = {"n": 0}
    real_read = RecommendationDataSource.read_eval
    real_prep = RecommendationPreparator.prepare

    def counting_read(self, ctx):
        reads["n"] += 1
        return real_read(self, ctx)

    def counting_prepare(self, ctx, td):
        prepares["n"] += 1
        return real_prep(self, ctx, td)

    monkeypatch.setattr(RecommendationDataSource, "read_eval", counting_read)
    monkeypatch.setattr(RecommendationPreparator, "prepare", counting_prepare)

    eng = engine()
    candidates = [
        eng.bind_engine_params({
            "datasource": {"params": {"appName": "sweepapp", "evalK": 2}},
            "algorithms": [{"name": "als",
                            "params": {"rank": 4, "numIterations": 2,
                                       "lambda": lam}}],
        }) for lam in (0.01, 0.1, 1.0)
    ]
    ctx = RuntimeContext.create(storage=storage)
    results = eng.eval_multi(ctx, candidates)
    assert len(results) == 3
    assert all(len(r) == 2 for r in results)      # 2 folds each
    assert reads["n"] == 1                         # ONE data pass
    assert prepares["n"] == 2                      # once per fold, not x3
