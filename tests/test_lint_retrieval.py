"""tools/lint_retrieval.py: serving reaches the corpus via the facade.

ISSUE 8 satellite — locks in the retrieval consolidation: a template or
server handler that calls ``ops.topk`` primitives directly (forfeiting
routing, staging caches, IVF, and retrieval metrics) fails tier-1.
"""

import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO / "tools"))

import lint_retrieval  # noqa: E402


def test_tree_is_clean():
    assert lint_retrieval.check(REPO) == []


def test_detects_banned_import_from():
    src = """
from predictionio_tpu.ops.topk import host_top_k, top_k_scores
"""
    violations = lint_retrieval.check_source(src, "t.py")
    assert len(violations) == 1
    assert "host_top_k, top_k_scores" in violations[0]
    assert "predictionio_tpu.retrieval" in violations[0]


def test_detects_banned_module_import():
    src = """
import predictionio_tpu.ops.topk as topk
import predictionio_tpu.ops.pallas_kernels
"""
    violations = lint_retrieval.check_source(src, "t.py")
    assert len(violations) == 2


def test_detects_primitive_calls_any_spelling():
    src = """
import numpy as np

def predict(model, q):
    s1, i1 = top_k_scores(q, model.vecs, 10)
    s2, i2 = ops.topk.chunked_top_k(q, model.vecs, 10)
    s3, i3 = fused_topk(q, model.vecs, 10)
    return host_top_k(q, model.vecs, 10)
"""
    violations = lint_retrieval.check_source(src, "t.py")
    assert len(violations) == 4
    assert all("Retriever.topk" in v for v in violations)


def test_detects_pq_primitives_and_module_import():
    """ISSUE 13: pq_scan / codebook access only via the facade — a
    handler LUT-scoring codes directly would skip the fingerprint
    tripwire and the exact re-rank."""
    src = """
from predictionio_tpu.retrieval.pq import PQCodebook

def predict(model, q):
    s, i = pq_scan(luts, model.pq.codes, 40)
    s2, i2, _ = search_pq_host(model.pq, vecs, q, 10, 40)
    t = retrieval.pq.lut_tables(model.pq, q)
    cb = build_pq(model.item_vecs, m=8)
    return decode_pq(model.pq)
"""
    violations = lint_retrieval.check_source(src, "t.py")
    assert len(violations) == 6  # 1 import + 5 calls
    assert any("PQCodebook" in v for v in violations)


def test_facade_usage_is_clean():
    src = """
from predictionio_tpu.retrieval import Retriever, cached_retriever, iter_hits

def predict(model, q):
    r = cached_retriever(model, lambda: Retriever(model.vecs, name="x"))
    scores, ids, info = r.topk(q, 10)
    return list(iter_hits(scores[0], ids[0], 10))
"""
    assert lint_retrieval.check_source(src, "t.py",
                                       engine_module=True) == []


def test_detects_uncached_retriever_in_engine_module():
    src = """
from predictionio_tpu.retrieval import Retriever

def predict(model, q):
    r = Retriever(model.vecs, name="fresh-every-call")
    return r.topk(q, 10)
"""
    violations = lint_retrieval.check_source(src, "t.py",
                                             engine_module=True)
    assert len(violations) == 1
    assert "cached_retriever" in violations[0]
    # outside engine modules the construction rule does not apply (the
    # facade itself and tests build retrievers directly)
    assert lint_retrieval.check_source(src, "t.py") == []


def test_cli_exit_codes(tmp_path, capsys):
    assert lint_retrieval.main([str(REPO)]) == 0
    pkg = tmp_path / "predictionio_tpu"
    for scope in ("templates", "server", "serving"):
        (pkg / scope).mkdir(parents=True)
    (pkg / "templates" / "bad.py").write_text(
        "from predictionio_tpu.ops.topk import top_k_scores\n")
    assert lint_retrieval.main([str(tmp_path)]) == 1
