"""Retrieval subsystem (ISSUE 8): rung parity, IVF recall, generation
atomicity.

Parity suite pins sharded-exact ≡ single-device exact (same id set,
scores within fp tolerance) across shard counts, k ≥ per-shard rows, and
tail-padded corpora; IVF holds recall@10 ≥ 0.95 on a synthetic clustered
corpus while scanning < 25% of candidates; the exact fallback below
``PIO_IVF_MIN_ITEMS`` is contract, not accident; and a server-level
reload/rollback test proves index+model swap atomically (a generation-N
index can never serve next to generation-M vectors — the fingerprint
tripwire drops it loudly).  CPU-only: the 8-device virtual mesh from
conftest gives real sharding semantics.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from predictionio_tpu.ops.topk import chunked_top_k, top_k_scores
from predictionio_tpu.parallel.mesh import make_mesh
from predictionio_tpu.retrieval import (
    K_MENU,
    IVFIndex,
    Retriever,
    build_ivf,
    build_train_index,
    cached_retriever,
    corpus_fingerprint,
    iter_hits,
    menu_k,
)
from predictionio_tpu.retrieval.ivf import (
    ivf_build_config,
    search_ivf_device,
    search_ivf_host,
)


def _corpus(n=256, d=16, seed=0):
    rng = np.random.default_rng(seed)
    items = rng.normal(size=(n, d)).astype(np.float32)
    queries = rng.normal(size=(4, d)).astype(np.float32)
    return queries, items


def _clustered_corpus(n=4000, d=16, n_clusters=40, seed=0):
    """Well-separated direction clusters + queries near members — the
    IVF design target (normalized two-tower-style corpus)."""
    rng = np.random.default_rng(seed)
    centers = rng.normal(size=(n_clusters, d)).astype(np.float32)
    centers /= np.linalg.norm(centers, axis=1, keepdims=True)
    assign = rng.integers(0, n_clusters, n)
    items = centers[assign] + 0.15 * rng.normal(size=(n, d)).astype(
        np.float32)
    items /= np.linalg.norm(items, axis=1, keepdims=True)
    q_src = rng.integers(0, n, 64)
    queries = items[q_src] + 0.05 * rng.normal(size=(64, d)).astype(
        np.float32)
    queries /= np.linalg.norm(queries, axis=1, keepdims=True)
    return queries.astype(np.float32), items.astype(np.float32)


def _exact_ids(queries, items, k):
    s = queries @ items.T
    return np.argsort(-s, axis=1, kind="stable")[:, :k]


# -- facade routing ----------------------------------------------------------


class TestRouting:
    def test_menu_k_pads_to_menu_and_clamps(self):
        assert menu_k(3, 10_000) == 10
        assert menu_k(10, 10_000) == 10
        assert menu_k(11, 10_000) == 100
        assert menu_k(5000, 10_000) == 5000  # past the menu: as asked
        assert menu_k(100, 7) == 7           # never beyond the corpus
        assert K_MENU == (1, 10, 100, 1000)

    def test_small_work_routes_host(self, monkeypatch):
        q, items = _corpus()
        r = Retriever(items, name="t-host")
        assert r.plan(1, 10).rung == "host"

    def test_large_work_routes_device(self, monkeypatch):
        q, items = _corpus()
        monkeypatch.setenv("PIO_SERVE_HOST_MACS", "10")
        r = Retriever(items, name="t-dev")
        assert r.plan(4, 10).rung == "device"

    def test_chunk_threshold_routes_chunked(self, monkeypatch):
        q, items = _corpus()
        monkeypatch.setenv("PIO_SERVE_HOST_MACS", "10")
        monkeypatch.setenv("PIO_SERVE_CHUNK_ABOVE", "100")
        r = Retriever(items, name="t-chunk")
        assert r.plan(4, 10).rung == "chunked"

    def test_forced_rung_env(self, monkeypatch):
        q, items = _corpus()
        monkeypatch.setenv("PIO_RETRIEVAL_RUNG", "device")
        r = Retriever(items, name="t-forced")
        assert r.plan(1, 10).rung == "device"

    def test_unrecognized_forced_rung_warns_and_autos(
            self, monkeypatch, caplog):
        """A typo'd forcing must degrade as loudly as an impossible one —
        a benchmark must not silently measure auto routing."""
        import logging

        q, items = _corpus()
        monkeypatch.setenv("PIO_RETRIEVAL_RUNG", "shard")  # typo
        r = Retriever(items, name="t-typo")
        with caplog.at_level(logging.WARNING,
                             logger="predictionio_tpu.retrieval"):
            p = r.plan(1, 10)
        assert p.rung == "host"
        assert any("PIO_RETRIEVAL_RUNG" in rec.getMessage()
                   for rec in caplog.records)

    def test_device_rung_padding_mask_staged_once(self):
        """The n_items<n padding mask is request-invariant — staged as a
        [N] device row once, never rebuilt [B, N] host-side per request."""
        from predictionio_tpu.retrieval.exact import exact_device

        q, items = _corpus(n=120)
        padded = np.concatenate(
            [items, np.ones((8, items.shape[1]), np.float32) * 100])
        cache = {}
        s1, i1 = exact_device(q, jnp.asarray(padded), 120, 10,
                              jit_cache=cache)
        assert ("pad_row", 128, 120) in cache
        assert (i1 < 120).all()  # padding rows never surface
        want = _exact_ids(q, items, 10)
        np.testing.assert_array_equal(np.sort(i1, axis=1),
                                      np.sort(want, axis=1))
        # padding + per-request exclude compose on device
        excl = np.zeros((len(q), 120), dtype=bool)
        excl[np.arange(len(q)), want[:, 0]] = True
        _, i2 = exact_device(q, jnp.asarray(padded), 120, 10,
                             jit_cache=cache, exclude=excl)
        assert (i2 < 120).all()
        for row in range(len(q)):
            assert want[row, 0] not in i2[row]

    def test_forced_sharded_without_mesh_degrades_to_device(
            self, monkeypatch):
        q, items = _corpus()
        monkeypatch.setenv("PIO_RETRIEVAL_RUNG", "sharded")
        r = Retriever(items, name="t-noshard")
        assert r.plan(1, 10).rung == "device"

    def test_exclude_pins_exact_even_with_ivf(self, monkeypatch):
        monkeypatch.setenv("PIO_IVF_MIN_ITEMS", "100")
        q, items = _clustered_corpus(n=600, n_clusters=10)
        idx = build_ivf(items, nlist=8, force=True)
        r = Retriever(items, ivf=idx, name="t-excl")
        assert r.plan(1, 10).rung == "ivf"
        assert r.plan(1, 10, has_exclude=True).rung == "host"

    def test_forced_nonexact_rung_with_exclude_serves_exact(
            self, monkeypatch):
        """A forced sharded/ivf rung takes no per-request mask — the
        exclusion must win over the forcing (a blacklisted item may
        never be returned).  A forced chunked rung carries the mask
        through the scan, so it keeps the forcing AND the exclusion."""
        monkeypatch.setenv("PIO_IVF_MIN_ITEMS", "100")
        q, items = _clustered_corpus(n=600, n_clusters=10)
        idx = build_ivf(items, nlist=8, force=True)
        excl = np.zeros((1, len(items)), dtype=bool)
        excl[0, _exact_ids(q[:1], items, 1)[0, 0]] = True
        for rung in ("sharded", "ivf"):
            monkeypatch.setenv("PIO_RETRIEVAL_RUNG", rung)
            r = Retriever(items, ivf=idx, name=f"t-exclforce-{rung}")
            assert r.plan(1, 10, has_exclude=True).rung in ("host",
                                                            "device")
            _, ids, info = r.topk(q[:1], 10, exclude=excl)
            assert info["rung"] in ("host", "device")
            assert excl[0, ids[0]].sum() == 0
        monkeypatch.setenv("PIO_RETRIEVAL_RUNG", "chunked")
        r = Retriever(items, ivf=idx, name="t-exclforce-chunked")
        assert r.plan(1, 10, has_exclude=True).rung == "chunked"
        _, ids, info = r.topk(q[:1], 10, exclude=excl)
        assert info["rung"] == "chunked"
        assert excl[0, ids[0]].sum() == 0

    def test_exclude_above_chunk_threshold_rides_chunked(
            self, monkeypatch):
        """Exclude queries past PIO_SERVE_CHUNK_ABOVE must not fall onto
        the dense device rung (a fresh [B, N] mask upload + [B, N] score
        block per request) — the mask rides the bounded-memory scan."""
        monkeypatch.setenv("PIO_SERVE_HOST_MACS", "1")
        monkeypatch.setenv("PIO_SERVE_CHUNK_ABOVE", "100")
        q, items = _corpus(n=300)
        q = q[:2]
        excl = np.zeros((2, len(items)), dtype=bool)
        want = _exact_ids(q, items, 1)
        excl[np.arange(2), want[:, 0]] = True
        r = Retriever(items, name="t-excl-chunk")
        assert r.plan(2, 10, has_exclude=True).rung == "chunked"
        _, ids, info = r.topk(q, 10, exclude=excl)
        assert info["rung"] == "chunked"
        for row in range(2):
            assert want[row, 0] not in ids[row]

    def test_device_exclude_with_non_pow2_batch(self, monkeypatch):
        """The pow2 batch pad must pad the exclude mask too — B=3 with a
        mask used to crash the device rung on a shape mismatch."""
        monkeypatch.setenv("PIO_RETRIEVAL_RUNG", "device")
        q, items = _corpus(n=300)
        q = q[:3]
        excl = np.zeros((3, len(items)), dtype=bool)
        want = _exact_ids(q, items, 1)
        excl[np.arange(3), want[:, 0]] = True
        r = Retriever(items, name="t-excl-pow2")
        _, ids, info = r.topk(q, 10, exclude=excl)
        assert info["rung"] == "device"
        assert ids.shape[0] == 3
        for row in range(3):
            assert want[row, 0] not in ids[row]

    def test_all_rungs_agree_on_ids(self, monkeypatch):
        """Every forced exact rung returns the SAME top-k id set."""
        q, items = _corpus(n=300)
        want = _exact_ids(q, items, 10)
        for rung in ("host", "device", "chunked"):
            monkeypatch.setenv("PIO_RETRIEVAL_RUNG", rung)
            r = Retriever(items, name=f"t-agree-{rung}")
            scores, ids, info = r.topk(q, 10)
            assert info["rung"] == rung
            np.testing.assert_array_equal(np.sort(ids, axis=1),
                                          np.sort(want, axis=1),
                                          err_msg=rung)


# -- sharded-exact ≡ single-device parity (tentpole acceptance) --------------


class TestShardedParity:
    def _sharded_retriever(self, items, n_shards, monkeypatch,
                           n_items=None):
        monkeypatch.setenv("PIO_SERVE_SHARD_ABOVE", "1")
        # Force the work past the host fast path so routing picks the
        # sharded rung for these small parity corpora.
        monkeypatch.setenv("PIO_SERVE_HOST_MACS", "1")
        mesh = make_mesh({"data": n_shards})
        r = Retriever(items, n_items=n_items, name=f"t-sh{n_shards}")
        assert r.maybe_shard(mesh)
        assert r.sharded
        return r

    @pytest.mark.parametrize("n_shards", [2, 4, 8])
    def test_parity_across_shard_counts(self, n_shards, monkeypatch):
        q, items = _corpus(n=320)
        r = self._sharded_retriever(items, n_shards, monkeypatch)
        assert r.plan(4, 10).rung == "sharded"
        scores, ids, info = r.topk(q, 10)
        want_ids = _exact_ids(q, items, 10)
        want_s = np.take_along_axis(q @ items.T, want_ids, axis=1)
        np.testing.assert_array_equal(np.sort(ids, axis=1),
                                      np.sort(want_ids, axis=1))
        np.testing.assert_allclose(scores, want_s, rtol=1e-5, atol=1e-5)

    def test_parity_k_geq_per_shard_rows(self, monkeypatch):
        """k greater than any shard's row count: the local top-k takes
        the whole shard and the merge must still be globally exact."""
        q, items = _corpus(n=32)
        r = self._sharded_retriever(items, 8, monkeypatch)  # 4 rows/shard
        scores, ids, _ = r.topk(q, 8)  # menu pads k to 10; slice num=8
        np.testing.assert_array_equal(np.sort(ids[:, :8], axis=1),
                                      np.sort(_exact_ids(q, items, 8),
                                              axis=1))

    def test_parity_tail_padded_corpus(self, monkeypatch):
        """A corpus that does not divide the mesh is host-padded by
        maybe_shard; the padding rows must never appear in results."""
        q, items = _corpus(n=301)  # 301 % 8 != 0
        r = self._sharded_retriever(items, 8, monkeypatch)
        assert r.vecs.shape[0] == 304  # padded to the mesh
        scores, ids, _ = r.topk(q, 20)  # menu pads k to 100; slice 20
        assert int(ids.max()) < 301
        np.testing.assert_array_equal(np.sort(ids[:, :20], axis=1),
                                      np.sort(_exact_ids(q, items, 20),
                                              axis=1))

    def test_below_threshold_does_not_shard(self, monkeypatch):
        monkeypatch.setenv("PIO_SERVE_SHARD_ABOVE", "1000000")
        q, items = _corpus()
        r = Retriever(items, name="t-noshard")
        assert not r.maybe_shard(make_mesh({"data": 2}))
        assert not r.sharded


# -- chunked auto-pad (satellite: no more n % chunk == 0 assert) -------------


class TestChunkedAutoPad:
    @pytest.mark.parametrize("n", [100, 128, 129, 255])
    def test_ragged_tail_matches_dense(self, n):
        q, items = _corpus(n=n)
        s1, i1 = top_k_scores(jnp.asarray(q), jnp.asarray(items), 7)
        s2, i2 = chunked_top_k(jnp.asarray(q), jnp.asarray(items), 7,
                               chunk=64)
        np.testing.assert_allclose(np.asarray(s1), np.asarray(s2),
                                   rtol=1e-5)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))

    def test_biases_ride_the_tail_chunk(self):
        q, items = _corpus(n=150)
        bias = np.linspace(0, 3, 150).astype(np.float32)
        s1, i1 = top_k_scores(jnp.asarray(q), jnp.asarray(items), 5,
                              biases=jnp.asarray(bias))
        s2, i2 = chunked_top_k(jnp.asarray(q), jnp.asarray(items), 5,
                               chunk=64, biases=jnp.asarray(bias))
        np.testing.assert_allclose(np.asarray(s1), np.asarray(s2),
                                   rtol=1e-5)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))

    def test_exclude_mask_sliced_per_chunk(self):
        q, items = _corpus(n=130)
        top1 = np.argmax(q @ items.T, axis=1)
        excl = np.zeros((4, 130), dtype=bool)
        excl[np.arange(4), top1] = True
        _, ids = chunked_top_k(jnp.asarray(q), jnp.asarray(items), 5,
                               chunk=64, exclude=jnp.asarray(excl))
        ids = np.asarray(ids)
        assert not any(top1[b] in ids[b] for b in range(4))

    def test_n_valid_masks_padding_rows(self):
        q, items = _corpus(n=192)
        items[150:] = 100.0  # poison rows that MUST be masked
        _, ids = chunked_top_k(jnp.asarray(q), jnp.asarray(items), 9,
                               chunk=64, n_valid=150)
        assert int(np.asarray(ids).max()) < 150
        # single-dispatch small-corpus path folds n_valid the same way
        _, ids2 = chunked_top_k(jnp.asarray(q), jnp.asarray(items), 9,
                                chunk=256, n_valid=150)
        np.testing.assert_array_equal(np.asarray(ids), np.asarray(ids2))


# -- IVF ---------------------------------------------------------------------


class TestIVF:
    def test_recall_and_sublinear_scan(self, monkeypatch):
        """Acceptance: recall@10 ≥ 0.95 at default nprobe while scanning
        < 25% of candidates on the synthetic clustered corpus."""
        monkeypatch.delenv("PIO_IVF_NPROBE", raising=False)
        q, items = _clustered_corpus()
        idx = build_ivf(items, force=True)
        r = Retriever(items, ivf=idx, name="t-recall")
        p = r.plan(len(q), 10)
        assert p.rung == "ivf"
        scores, ids, info = r.topk(q, 10)
        want = _exact_ids(q, items, 10)
        hit = sum(len(set(ids[b, :10]) & set(want[b])) for b in
                  range(len(q)))
        recall = hit / want.size
        assert recall >= 0.95, f"recall@10={recall:.3f}"
        assert info["candidates"] < 0.25 * len(q) * len(items), info

    def test_host_and_device_search_agree(self):
        q, items = _clustered_corpus(n=1200, n_clusters=12)
        idx = build_ivf(items, nlist=12, force=True)
        s1, i1, c1 = search_ivf_host(idx, items, q, 10, nprobe=4)
        s2, i2, c2 = search_ivf_device(idx, jnp.asarray(items), q, 10,
                                       nprobe=4, jit_cache={})
        np.testing.assert_array_equal(np.sort(i1, axis=1),
                                      np.sort(i2, axis=1))
        np.testing.assert_allclose(np.sort(s1, axis=1),
                                   np.sort(s2, axis=1), rtol=1e-5,
                                   atol=1e-5)
        assert c1 == c2

    def test_exact_fallback_below_threshold(self, monkeypatch):
        """Below PIO_IVF_MIN_ITEMS no index is built — even with
        PIO_IVF=on the threshold is the contract."""
        monkeypatch.setenv("PIO_IVF", "on")
        monkeypatch.setenv("PIO_IVF_MIN_ITEMS", "1000")
        build, nlist, min_items = ivf_build_config(999)
        assert (build, min_items) == (False, 1000)
        assert build_train_index(np.ones((999, 4), np.float32),
                                 name="t") is None
        build, nlist, _ = ivf_build_config(1000)
        assert build and nlist == 32  # ~sqrt(N)

    def test_off_switch_never_builds(self, monkeypatch):
        monkeypatch.setenv("PIO_IVF", "off")
        monkeypatch.setenv("PIO_IVF_MIN_ITEMS", "1")
        assert build_train_index(np.ones((500, 4), np.float32),
                                 name="t") is None

    def test_nprobe_env_override_and_clamp(self, monkeypatch):
        q, items = _clustered_corpus(n=900, n_clusters=9)
        idx = build_ivf(items, nlist=9, force=True)
        monkeypatch.setenv("PIO_IVF_NPROBE", "3")
        assert idx.default_nprobe() == 3
        monkeypatch.setenv("PIO_IVF_NPROBE", "999")
        assert idx.default_nprobe() == 9  # clamped to nlist
        monkeypatch.delenv("PIO_IVF_NPROBE")
        assert idx.default_nprobe() == 2  # ~nlist/8, >= 1

    def test_plan_widens_nprobe_until_k_reachable(self, monkeypatch):
        """Static-shape guard: probed lists must cover k candidates."""
        monkeypatch.setenv("PIO_IVF_NPROBE", "1")
        q, items = _clustered_corpus(n=800, n_clusters=8)
        idx = build_ivf(items, nlist=8, force=True)
        r = Retriever(items, ivf=idx, name="t-widen")
        k = idx.pad_len + 1  # one probed list can never cover k
        p = r.plan(1, k)
        assert p.rung == "ivf" and p.nprobe >= 2

    def test_widening_uses_true_lengths_not_pad_len(self):
        """Skewed clusters: one giant list sets pad_len while typical
        lists hold a couple of items — nprobe·pad_len ≥ k is satisfied
        at nprobe=1 yet the probed lists can hold < k real candidates.
        The bound must use TRUE list lengths (worst case: the query
        lands on the shortest lists)."""
        idx = IVFIndex(centroids=np.zeros((4, 8), np.float32),
                       lists=np.full((4, 50), -1, np.int32),
                       list_lengths=np.array([50, 2, 2, 2], np.int32),
                       n_items=56, dim=8, nlist=4, pad_len=50,
                       fingerprint="x")
        assert idx.min_nprobe_for(2) == 1
        assert idx.min_nprobe_for(6) == 3   # 2+2+2 covers 6
        assert idx.min_nprobe_for(10) == 4  # needs the giant list too
        assert idx.min_nprobe_for(57) == 4  # > total: every list

    def test_ivf_device_constants_staged_once(self):
        """Centroids + padded lists are generation constants — staged on
        the retriever ONCE, never re-uploaded per request."""
        q, items = _clustered_corpus(n=1200, n_clusters=12)
        idx = build_ivf(items, nlist=12, force=True)
        r = Retriever(items, ivf=idx, name="t-staged")
        a1 = r.ivf_device_arrays()
        a2 = r.ivf_device_arrays()
        assert a1[0] is a2[0] and a1[1] is a2[1]
        s1, i1, _ = search_ivf_host(idx, items, q[:4], 10, 4)
        _, i2, _ = search_ivf_device(idx, jnp.asarray(items), q[:4], 10,
                                     4, jit_cache={}, consts=a1)
        np.testing.assert_array_equal(np.sort(i1, axis=1),
                                      np.sort(i2, axis=1))

    def test_malformed_nlist_env_falls_back(self, monkeypatch):
        """A typo'd PIO_IVF_NLIST must not crash pio train after the
        expensive fit — fall back to the ~sqrt(N) default loudly."""
        monkeypatch.setenv("PIO_IVF", "on")
        monkeypatch.setenv("PIO_IVF_MIN_ITEMS", "1")
        monkeypatch.setenv("PIO_IVF_NLIST", "2e3")
        build, nlist, _ = ivf_build_config(1024)
        assert build and nlist == 32

    def test_norm_variant_corpus_requires_explicit_on(self, monkeypatch):
        """Raw ALS factors are a poor IVF fit (norm-variant corpus) —
        the ALS template's index builds only under an explicit
        PIO_IVF=on, never auto (the README's 'opt in knowingly')."""
        monkeypatch.setenv("PIO_IVF_MIN_ITEMS", "1")
        monkeypatch.delenv("PIO_IVF", raising=False)
        _, items = _clustered_corpus(n=300, n_clusters=3)
        assert build_train_index(items, name="als",
                                 require_explicit=True) is None
        monkeypatch.setenv("PIO_IVF", "on")
        assert build_train_index(items, name="als",
                                 require_explicit=True) is not None

    def test_seedless_build_is_deterministic(self, monkeypatch):
        """Templates with no configured seed still build the SAME index
        over the same data — recall characteristics and bench
        comparisons must not drift run-to-run."""
        monkeypatch.setenv("PIO_IVF_MIN_ITEMS", "1")
        _, items = _clustered_corpus(n=400, n_clusters=4)
        a = build_train_index(items, name="t")
        b = build_train_index(items, name="t")
        np.testing.assert_array_equal(a.centroids, b.centroids)
        np.testing.assert_array_equal(a.lists, b.lists)

    def test_rows_short_of_k_pad_with_sentinels(self):
        q, items = _corpus(n=40)
        idx = build_ivf(items, nlist=4, force=True)
        s, i, _ = search_ivf_host(idx, items, q[:1], 39, nprobe=1)
        assert (i[0] == -1).any()  # one probed list cannot reach k=39
        assert list(iter_hits(s[0], i[0], 39))  # sentinels skipped


# -- generation versioning (the tripwire) ------------------------------------


class TestGenerationAtomicity:
    def test_fingerprint_stable_across_roundtrip(self):
        _, items = _corpus()
        import pickle

        again = pickle.loads(pickle.dumps(items))
        assert corpus_fingerprint(items) == corpus_fingerprint(again)
        assert corpus_fingerprint(items) != corpus_fingerprint(items + 1)

    def test_mismatched_index_dropped_loudly(self, pio_home):
        """A generation-N index next to generation-N+1 vectors is
        dropped (exact serving continues, counter increments) — recall
        never silently collapses through a stale index."""
        from predictionio_tpu.obs import get_registry

        q, items_n = _clustered_corpus(n=600, n_clusters=6, seed=1)
        _, items_n1 = _clustered_corpus(n=600, n_clusters=6, seed=2)
        stale = build_ivf(items_n, nlist=6, force=True)
        r = Retriever(items_n1, ivf=stale, name="t-mixed")
        assert r.ivf_index() is None  # dropped at first validation
        scores, ids, info = r.topk(q, 10)
        assert info["rung"] != "ivf"
        np.testing.assert_array_equal(
            np.sort(ids, axis=1),
            np.sort(_exact_ids(q, items_n1, 10), axis=1))
        c = get_registry().counter("pio_retrieval_ivf_rejected_total",
                                   "", ("corpus",))
        assert c.value(corpus="t-mixed") == 1

    def test_matching_index_survives_validation(self):
        q, items = _clustered_corpus(n=600, n_clusters=6)
        idx = build_ivf(items, nlist=6, force=True)
        r = Retriever(items, ivf=idx, name="t-match")
        assert r.ivf_index() is idx

    def test_wrapper_pickle_carries_index(self, monkeypatch):
        """Model and index are ONE artifact: the pickle round-trip the
        generation swap moves keeps them consistent by construction."""
        import pickle

        from predictionio_tpu.data.event import BiMap
        from predictionio_tpu.templates.twotower.engine import (
            TwoTowerModelWrapper,
        )

        _, items = _clustered_corpus(n=600, n_clusters=6)
        idx_map = BiMap.string_int([f"i{j}" for j in range(len(items))])
        u_map = BiMap.string_int(["u0"])
        w = TwoTowerModelWrapper(
            user_vecs=np.ones((1, items.shape[1]), np.float32),
            item_vecs=items, user_index=u_map, item_index=idx_map,
            ivf=build_ivf(items, nlist=6, force=True))
        w2 = pickle.loads(pickle.dumps(w))
        assert w2.ivf is not None
        assert Retriever(w2.item_vecs, ivf=w2.ivf,
                         name="t-pickle").ivf_index() is w2.ivf


# -- server-level reload/rollback atomicity ----------------------------------


def _trained_ivf_server(storage, seed_rank):
    """ALS engine server with IVF forced on (tiny threshold)."""
    from predictionio_tpu.controller import EngineVariant, RuntimeContext
    from predictionio_tpu.data.event import DataMap, Event
    from predictionio_tpu.data.storage import App
    from predictionio_tpu.server import EngineServer
    from predictionio_tpu.templates.recommendation import engine
    from predictionio_tpu.workflow.core_workflow import run_train

    ctx = RuntimeContext.create(storage=storage)
    app_id = storage.get_apps().insert(App(id=None, name="ivfapp"))
    storage.get_events().init(app_id)
    rng = np.random.default_rng(7)
    storage.get_events().insert_batch(
        [Event(event="rate", entity_type="user", entity_id=f"u{u}",
               target_entity_type="item", target_entity_id=f"i{i}",
               properties=DataMap({"rating": float(r)}))
         for u, i, r in zip(rng.integers(0, 30, 600),
                            rng.integers(0, 64, 600),
                            rng.integers(1, 6, 600))], app_id)
    variant = EngineVariant.from_dict({
        "engineFactory": "predictionio_tpu.templates.recommendation:engine",
        "datasource": {"params": {"appName": "ivfapp"}},
        "algorithms": [{"name": "als",
                        "params": {"rank": seed_rank,
                                   "numIterations": 2}}],
    })
    eng = engine()
    run_train(eng, variant, ctx)
    srv = EngineServer(eng, variant, storage, host="127.0.0.1", port=0)
    return srv, eng, variant, ctx


def _serving_wrapper(srv):
    return srv._models[0]


def _assert_generation_consistent(wrapper):
    """The served index MUST fingerprint-match the served vectors."""
    idx = wrapper.retriever().ivf_index()
    assert idx is not None, "IVF index missing from the serving wrapper"
    host = wrapper.host_factors()[1]
    assert idx.fingerprint == corpus_fingerprint(host)
    return idx


def test_reload_and_rollback_swap_index_with_model(pio_home, monkeypatch):
    """ISSUE 8 acceptance: the staged-reload/canary/rollback path swaps
    index+model atomically — a rollback never serves generation-N
    vectors through a generation-N+1 index."""
    monkeypatch.setenv("PIO_IVF", "on")
    monkeypatch.setenv("PIO_IVF_MIN_ITEMS", "10")
    from predictionio_tpu.data.storage import get_storage
    from predictionio_tpu.workflow.core_workflow import run_train

    storage = get_storage()
    srv, eng, variant, ctx = _trained_ivf_server(storage, seed_rank=4)
    idx1 = _assert_generation_consistent(_serving_wrapper(srv))
    fp1 = idx1.fingerprint

    # Generation 2: more events → different factor matrix → a NEW
    # fingerprint.  The reload must carry its OWN index.
    from predictionio_tpu.data.event import DataMap, Event

    app_id = storage.get_apps().get_by_name("ivfapp").id
    rng = np.random.default_rng(11)
    storage.get_events().insert_batch(
        [Event(event="rate", entity_type="user", entity_id=f"u{u}",
               target_entity_type="item", target_entity_id=f"i{i}",
               properties=DataMap({"rating": float(r)}))
         for u, i, r in zip(rng.integers(0, 30, 200),
                            rng.integers(0, 64, 200),
                            rng.integers(1, 6, 200))], app_id)
    run_train(eng, variant, ctx)
    st, body = srv.handle("POST", "/reload", b"")
    assert st == 200 and body["generation"] == 2
    idx2 = _assert_generation_consistent(_serving_wrapper(srv))
    assert idx2.fingerprint != fp1

    # Rollback: generation 1's model AND generation 1's index return
    # together — never gen-1 vectors under the gen-2 index.
    st, body = srv.handle("POST", "/admin/rollback", b"")
    assert st == 200
    idx_back = _assert_generation_consistent(_serving_wrapper(srv))
    assert idx_back.fingerprint == fp1

    # And the rolled-back generation actually serves through its index.
    monkeypatch.setenv("PIO_RETRIEVAL_RUNG", "ivf")
    st, body = srv.handle("POST", "/queries.json",
                          b'{"user": "u1", "num": 3}')
    assert st == 200 and body["itemScores"]


def test_ivf_rides_train_and_serves(pio_home, monkeypatch):
    """End-to-end: `pio train` builds the index, serving routes the IVF
    rung, and the result ids match exact retrieval (tiny corpus →
    nprobe covers it)."""
    monkeypatch.setenv("PIO_IVF", "on")
    monkeypatch.setenv("PIO_IVF_MIN_ITEMS", "10")
    from predictionio_tpu.data.storage import get_storage

    storage = get_storage()
    srv, *_ = _trained_ivf_server(storage, seed_rank=4)
    w = _serving_wrapper(srv)
    _assert_generation_consistent(w)
    monkeypatch.setenv("PIO_RETRIEVAL_RUNG", "ivf")
    st, body = srv.handle("POST", "/queries.json",
                          b'{"user": "u2", "num": 5}')
    assert st == 200 and len(body["itemScores"]) == 5


# -- per-model retriever cache ----------------------------------------------


class TestRetrieverCache:
    def test_one_retriever_per_owner_dies_with_it(self):
        class Owner:
            pass

        _, items = _corpus()
        o = Owner()
        r1 = cached_retriever(o, lambda: Retriever(items, name="t-c1"))
        r2 = cached_retriever(o, lambda: Retriever(items, name="t-c2"))
        assert r1 is r2 and r1.name == "t-c1"
        import weakref

        ref = weakref.ref(r1)
        del r1, r2, o
        import gc

        gc.collect()
        assert ref() is None  # died with the generation

    def test_als_wrapper_retriever_does_not_pin_generation(self):
        """The ALS retriever's host_fn must hold the wrapper weakly: a
        strong capture would make the weak cache's value pin its own key
        and leak every swapped-out generation's factors."""
        import gc
        import weakref
        from types import SimpleNamespace

        from predictionio_tpu.data.event import BiMap
        from predictionio_tpu.templates.recommendation.engine import (
            ALSModelWrapper,
        )

        _, items = _corpus(n=64, d=8)
        wrapper = ALSModelWrapper(
            model=SimpleNamespace(user_factors=items[:8],
                                  item_factors=items),
            user_index=BiMap({f"u{j}": j for j in range(8)}),
            item_index=BiMap({f"i{j}": j for j in range(64)}))
        r = wrapper.retriever()
        # host_fn path works through the weakref while the wrapper lives
        assert r.host_vecs().shape == (64, 8)
        ref = weakref.ref(wrapper)
        del wrapper, r
        gc.collect()
        assert ref() is None  # generation NOT pinned by its retriever


# -- iter_hits ---------------------------------------------------------------


def test_iter_hits_skips_sentinels_and_honors_num():
    scores = np.array([5.0, -1e38, 3.0, 2.0], np.float32)
    ids = np.array([7, -1, 3, 9], np.int32)
    assert list(iter_hits(scores, ids, 2)) == [(7, 5.0), (3, 3.0)]
    assert list(iter_hits(scores, ids, 10)) == [(7, 5.0), (3, 3.0),
                                                (9, 2.0)]
