"""data/prefetch.py: the overlapped input pipeline (ISSUE 5 tentpole).

Unit tests drive :class:`DevicePrefetcher` with injectable put/clock
fakes (no accelerator stack): ordering, bounded-depth backpressure,
resume fast-forward, exception propagation from the prep thread, and
clean shutdown under the supervision exceptions
(``TrainPreempted``/``TrainDiverged``).  The equivalence classes pin the
prefetched two-tower/DLRM training paths bitwise against the pre-PR
inline staging loop on CPU — the refactor must be a pure scheduling
change, not a numerics change.
"""

import threading
import time

import numpy as np
import pytest

from predictionio_tpu.data.prefetch import (
    DevicePrefetcher,
    PrefetchedBatch,
    prefetch_depth,
)


def _identity_put(arrays):
    return arrays


class _RecordingSource:
    """Iterator that records pulls and whether close() ran (generator
    cleanup must happen on the prep thread)."""

    def __init__(self, batches, gate: threading.Event = None):
        self._batches = list(batches)
        self._i = 0
        self.pulled = 0
        self.closed = False
        self._gate = gate

    def __iter__(self):
        return self

    def __next__(self):
        if self._gate is not None:
            self._gate.wait(timeout=5.0)
        if self._i >= len(self._batches):
            raise StopIteration
        self.pulled += 1
        b = self._batches[self._i]
        self._i += 1
        return b

    def close(self):
        self.closed = True


def _batches(n, size=4):
    return [(np.full(size, k, np.int64),) for k in range(1, n + 1)]


class TestDevicePrefetcher:
    def test_ordering_and_step_numbers(self):
        src = _RecordingSource(_batches(5))
        seen = []
        with DevicePrefetcher(src, lambda b: b[0] * 2,
                              put_fn=_identity_put, depth=2) as pf:
            for batch in pf:
                assert isinstance(batch, PrefetchedBatch)
                seen.append(batch)
        assert [b.step for b in seen] == [1, 2, 3, 4, 5]
        for k, b in enumerate(seen, start=1):
            assert np.array_equal(b.args, np.full(4, 2 * k))
            assert b.examples == 4
        assert src.closed  # generator cleanup ran

    def test_bounded_depth_backpressure(self):
        # With nothing consuming, the prep thread may hold at most
        # depth staged batches + 1 blocked on the full queue.
        src = _RecordingSource(_batches(50))
        pf = DevicePrefetcher(src, lambda b: b, put_fn=_identity_put,
                              depth=2)
        try:
            deadline = time.time() + 5.0
            while src.pulled < 3 and time.time() < deadline:
                time.sleep(0.01)
            time.sleep(0.2)  # would overrun here if unbounded
            assert src.pulled == 3  # depth (2) + 1 in flight
            next(iter(pf))  # consume one -> exactly one more pull
            deadline = time.time() + 5.0
            while src.pulled < 4 and time.time() < deadline:
                time.sleep(0.01)
            time.sleep(0.2)
            assert src.pulled == 4
        finally:
            pf.close()

    def test_skip_steps_spends_no_prep_work(self):
        prepped = []

        def prep(b):
            prepped.append(int(b[0][0]))
            return b

        src = _RecordingSource(_batches(5))
        with DevicePrefetcher(src, prep, put_fn=_identity_put,
                              depth=2, skip_steps=3) as pf:
            steps = [b.step for b in pf]
        assert steps == [4, 5]          # resume fast-forward
        assert prepped == [4, 5]        # no prep on skipped batches
        assert src.pulled == 5          # but the shuffle order advanced

    def test_prep_exception_propagates_to_consumer(self):
        def prep(b):
            if int(b[0][0]) == 3:
                raise ValueError("bad batch")
            return b

        src = _RecordingSource(_batches(5))
        seen = []
        with pytest.raises(ValueError, match="bad batch"):
            with DevicePrefetcher(src, prep, put_fn=_identity_put,
                                  depth=2) as pf:
                for batch in pf:
                    seen.append(batch.step)
        assert seen == [1, 2]
        assert src.closed

    def test_source_exception_propagates(self):
        def bad_source():
            yield (np.ones(2),)
            raise RuntimeError("feeder died")

        with pytest.raises(RuntimeError, match="feeder died"):
            with DevicePrefetcher(bad_source(), lambda b: b,
                                  put_fn=_identity_put) as pf:
                for _ in pf:
                    pass

    def test_put_exception_propagates(self):
        def put(arrays):
            raise MemoryError("HBM full")

        with pytest.raises(MemoryError):
            with DevicePrefetcher(iter(_batches(2)), lambda b: b,
                                  put_fn=put) as pf:
                for _ in pf:
                    pass

    @pytest.mark.parametrize("exc_name", ["TrainPreempted", "TrainDiverged"])
    def test_shutdown_on_supervision_exceptions(self, exc_name):
        from predictionio_tpu.resilience import supervision

        if exc_name == "TrainPreempted":
            exc = supervision.TrainPreempted("m", 1, True)
        else:
            exc = supervision.TrainDiverged("m", 1, "loss=nan", 0)
        src = _RecordingSource(_batches(50))
        pf = DevicePrefetcher(src, lambda b: b, put_fn=_identity_put,
                              depth=2)
        with pytest.raises(type(exc)):
            with pf:
                for batch in pf:
                    raise exc  # mid-stream abort, queue still full
        assert not pf._thread.is_alive()
        assert src.closed
        # iteration after close terminates instead of hanging
        assert list(pf) == []

    def test_close_is_idempotent_and_unblocks_producer(self):
        src = _RecordingSource(_batches(100))
        pf = DevicePrefetcher(src, lambda b: b, put_fn=_identity_put,
                              depth=1)
        deadline = time.time() + 5.0
        while src.pulled < 2 and time.time() < deadline:
            time.sleep(0.01)
        pf.close()
        pf.close()
        assert not pf._thread.is_alive()

    def test_tail_batch_padding_and_examples(self):
        # A ragged tail padded by prep keeps the REAL example count.
        bs = 8

        def prep(b):
            (x,) = b
            pad = bs - len(x)
            return np.concatenate([x, np.zeros(pad, x.dtype)])

        src = iter([(np.arange(8, dtype=np.int64),),
                    (np.arange(3, dtype=np.int64),)])
        with DevicePrefetcher(src, prep, put_fn=_identity_put) as pf:
            got = list(pf)
        assert [b.examples for b in got] == [8, 3]
        assert all(len(b.args) == bs for b in got)
        # padded tail matches the inline-path layout exactly
        assert np.array_equal(got[1].args,
                              np.concatenate([np.arange(3),
                                              np.zeros(5, np.int64)]))

    def test_h2d_ms_uses_injected_clock(self):
        t = [0.0]

        def clock():
            return t[0]

        def prep(b):
            t[0] += 0.25  # "250 ms" of prep+transfer on the fake clock
            return b

        with DevicePrefetcher(iter(_batches(1)), prep,
                              put_fn=_identity_put, clock=clock) as pf:
            (batch,) = list(pf)
        assert batch.h2d_ms == pytest.approx(250.0)

    def test_depth_env_parsing(self, monkeypatch):
        monkeypatch.setenv("PIO_PREFETCH_DEPTH", "4")
        assert prefetch_depth() == 4
        monkeypatch.setenv("PIO_PREFETCH_DEPTH", "0")
        assert prefetch_depth() == 1  # min 1: depth 0 would deadlock
        monkeypatch.setenv("PIO_PREFETCH_DEPTH", "not-a-number")
        assert prefetch_depth() == 2
        monkeypatch.delenv("PIO_PREFETCH_DEPTH")
        assert prefetch_depth() == 2


# -- bitwise equivalence vs the pre-PR inline loops --------------------------

class TestInlineEquivalence:
    """The prefetched train paths must be pure scheduling changes: same
    batches, same order, same padding, same dtypes — bitwise-identical
    parameters to the historical inline staging loop on CPU."""

    def _tree_equal(self, a, b):
        import jax

        la = jax.tree_util.tree_leaves(a)
        lb = jax.tree_util.tree_leaves(b)
        assert len(la) == len(lb)
        for x, y in zip(la, lb):
            assert np.array_equal(np.asarray(x), np.asarray(y)), \
                "prefetched train diverged bitwise from the inline loop"

    def test_two_tower_matches_inline_loop(self):
        import jax.numpy as jnp

        from predictionio_tpu.models import two_tower as tt

        rng = np.random.default_rng(7)
        n = 100
        users = rng.integers(0, 24, n)
        items = rng.integers(0, 12, n)
        cfg = tt.TwoTowerConfig(n_users=24, n_items=12, embed_dim=8,
                                hidden_dims=(16,), out_dim=8,
                                batch_size=32, epochs=2, seed=5)

        # pre-PR inline staging loop, verbatim semantics
        state = tt.init_state(cfg)
        weights = np.ones(n, np.float32)
        bs = cfg.batch_size
        for epoch in range(cfg.epochs):
            order = np.random.default_rng(cfg.seed + epoch).permutation(n)
            for start in range(0, n, bs):
                sel = order[start:start + bs]
                u, i, w = users[sel], items[sel], weights[sel]
                pad = bs - len(u)
                u = np.concatenate([np.asarray(u, np.int64),
                                    np.zeros(pad, np.int64)])
                i = np.concatenate([np.asarray(i, np.int64),
                                    np.zeros(pad, np.int64)])
                w = np.concatenate([np.asarray(w, np.float32),
                                    np.zeros(pad, np.float32)])
                state, _ = tt.train_step(
                    state, jnp.asarray(u), jnp.asarray(i), jnp.asarray(w),
                    cfg)

        prefetched = tt.train(users, items, cfg, data_source="numpy")
        self._tree_equal(state.params, prefetched.params)
        self._tree_equal(state.opt_state, prefetched.opt_state)
        assert int(state.step) == int(prefetched.step)

    def test_dlrm_matches_inline_loop(self):
        import jax.numpy as jnp

        from predictionio_tpu.models import dlrm

        rng = np.random.default_rng(11)
        n = 70
        cfg = dlrm.DLRMConfig(vocab_sizes=(50, 30), n_dense=3,
                              embed_dim=8, bottom_mlp=(16, 8),
                              top_mlp=(16, 8), batch_size=16, epochs=2,
                              seed=3)
        dense = rng.standard_normal((n, 3)).astype(np.float32)
        cat = np.stack([rng.integers(0, v, n) for v in cfg.vocab_sizes],
                       axis=1)
        labels = (rng.random(n) < 0.4).astype(np.float32)

        # pre-PR inline staging loop, verbatim semantics
        cat_global = (np.asarray(cat, np.int64)
                      + cfg.offsets[None, :]).astype(np.int32)
        state = dlrm.init_state(cfg, None)
        bs = cfg.batch_size
        for epoch in range(cfg.epochs):
            order = np.random.default_rng(cfg.seed + epoch).permutation(n)
            for start in range(0, n, bs):
                sel = order[start:start + bs]
                d = dense[sel]
                c = cat_global[sel]
                y = labels[sel].astype(np.float32)
                pad = bs - len(y)
                d = np.concatenate([d, np.zeros((pad, cfg.n_dense),
                                                np.float32)])
                c = np.concatenate([c, np.zeros((pad, cat.shape[1]),
                                                np.int32)])
                w = np.concatenate([np.ones(len(y), np.float32),
                                    np.zeros(pad, np.float32)])
                y = np.concatenate([y, np.zeros(pad, np.float32)])
                state, _ = dlrm.train_step(
                    state, jnp.asarray(d, jnp.float32), jnp.asarray(c),
                    jnp.asarray(y, jnp.float32), jnp.asarray(w), cfg, None)

        prefetched = dlrm.train(dense, cat, labels, cfg,
                                data_source="numpy")
        self._tree_equal(state.params, prefetched.params)
        self._tree_equal(state.opt_state, prefetched.opt_state)
        assert int(state.step) == int(prefetched.step)

    def test_prefetched_loop_overlaps_staging(self):
        """The scheduling claim itself: while step N executes (simulated
        by a slow consumer), the prep thread stages N+1 — the staging
        wall time disappears from the consumer's critical path."""
        staged = []

        def prep(b):
            time.sleep(0.05)  # "expensive" prep
            staged.append(time.perf_counter())
            return b

        with DevicePrefetcher(iter(_batches(4)), prep,
                              put_fn=_identity_put, depth=2) as pf:
            it = iter(pf)
            next(it)                    # first batch: cold start
            t0 = time.perf_counter()
            time.sleep(0.12)            # "device step" for batch 1
            next(it)                    # batch 2 must already be staged
            waited = time.perf_counter() - t0 - 0.12
        assert waited < 0.04, (
            f"queue wait {waited * 1e3:.0f} ms — staging did not overlap "
            "the simulated device step")

    def test_queue_depth_gauge_counts_real_batches_only(self):
        from predictionio_tpu.obs.metrics import MetricsRegistry

        reg = MetricsRegistry()
        src = _RecordingSource(_batches(3))
        with DevicePrefetcher(src, lambda b: b, put_fn=_identity_put,
                              depth=2, model="toy", registry=reg) as pf:
            g = reg.get("pio_prefetch_queue_depth")
            seen = 0
            for batch in pf:
                seen += 1
                # never exceeds depth, never counts the DONE sentinel
                assert 0 <= g.value(model="toy") <= 2
        assert seen == 3
        assert g.value(model="toy") == 0  # drained at stream end


class TestPinnedStaging:
    """ISSUE 13 satellite: page-aligned reusable host buffers for
    superbatch assembly (carried since PR 5)."""

    def test_aligned_and_rotates_after_slots(self):
        from predictionio_tpu.data.prefetch import StagingPool

        pool = StagingPool(3)
        bufs = [pool.take((4, 8), np.float32) for _ in range(3)]
        assert all(b.ctypes.data % 4096 == 0 for b in bufs)
        assert pool.allocated == 3 and pool.reused == 0
        again = [pool.take((4, 8), np.float32) for _ in range(3)]
        assert [id(b) for b in again] == [id(b) for b in bufs]
        assert pool.reused == 3
        # a different shape gets its own ring
        other = pool.take((2, 8), np.float32)
        assert id(other) not in {id(b) for b in bufs}

    def test_tagged_leaves_do_not_share_rings(self):
        from predictionio_tpu.data.prefetch import StagingPool

        pool = StagingPool(2)
        a = pool.take((4,), np.int64, tag=0)
        b = pool.take((4,), np.int64, tag=1)
        assert id(a) != id(b)
        # same tag rotates within its own ring only
        a2 = pool.take((4,), np.int64, tag=0)
        a3 = pool.take((4,), np.int64, tag=0)
        assert id(a3) == id(a)  # ring of 2: third take reuses first

    def test_pooled_concat_handles_unequal_rows(self):
        from predictionio_tpu.data.prefetch import (
            StagingPool,
            _pooled_concat,
        )

        pool = StagingPool(2)
        a = np.arange(6, dtype=np.float32).reshape(2, 3)
        b = np.arange(12, dtype=np.float32).reshape(4, 3)
        out = _pooled_concat([a, b], pool)
        np.testing.assert_array_equal(out, np.concatenate([a, b]))
        assert out.ctypes.data % 4096 == 0
        # dtype mismatch falls back to a fresh allocation (correctness
        # over reuse)
        c = np.arange(6, dtype=np.int64).reshape(2, 3)
        out2 = _pooled_concat([a, c.astype(np.float64)], None)
        np.testing.assert_array_equal(
            out2, np.concatenate([a, c.astype(np.float64)]))

    def test_superbatch_parity_and_reuse(self):
        """Pooled assembly produces the SAME superbatch contents as
        np.stack, and after slots windows the buffers rotate."""
        raw = _batches(15, size=4)
        leaf_ids = []
        contents = []
        with DevicePrefetcher(iter(raw), prep_fn=lambda b: b,
                              put_fn=_identity_put, depth=1,
                              fuse_steps=3, pin_buffers=True) as pf:
            for batch in pf:
                assert batch.k == 3
                leaf_ids.append(id(batch.args[0]))
                # identity put means a later window may REUSE this very
                # buffer (the unsafe-on-CPU case pin_buffers=True opts
                # into knowingly) — copy out before pulling more.
                contents.append(np.array(batch.args[0]))
        assert len(contents) == 5
        for w, got in enumerate(contents):
            want = np.stack([raw[w * 3 + j][0] for j in range(3)])
            np.testing.assert_array_equal(got, want)
        # depth=1 → ring of 3: window 4 rewrites window 1's buffer
        assert leaf_ids[3] == leaf_ids[0]
        assert len(set(leaf_ids[:3])) == 3

    def test_auto_gate_disables_on_cpu_backend(self):
        """pin_buffers=None + PIO_PINNED_STAGING=auto on the CPU
        backend must NOT pool — the CPU client may alias numpy buffers
        into its arrays zero-copy."""
        raw = _batches(9, size=4)
        ids = []
        with DevicePrefetcher(iter(raw), prep_fn=lambda b: b,
                              put_fn=_identity_put, depth=1,
                              fuse_steps=3) as pf:
            for batch in pf:
                ids.append(id(batch.args[0]))
        assert len(set(ids)) == 3       # fresh array every window
        assert pf._pin is False

    def test_env_on_engages_and_counter_counts(self, monkeypatch):
        from predictionio_tpu.obs import get_registry

        monkeypatch.setenv("PIO_PINNED_STAGING", "on")
        c = get_registry().counter(
            "pio_prefetch_pinned_reuse_total", "", ("model",))
        before = c.value(model="pin-toy")
        raw = _batches(15, size=4)
        with DevicePrefetcher(iter(raw), prep_fn=lambda b: b,
                              put_fn=_identity_put, depth=1,
                              fuse_steps=3, model="pin-toy") as pf:
            list(pf)
        assert pf._pin is True
        # 5 windows, ring of 3 → 2 reused stagings counted
        assert c.value(model="pin-toy") - before == 2

    def test_env_off_wins_over_param_default(self, monkeypatch):
        monkeypatch.setenv("PIO_PINNED_STAGING", "off")
        raw = _batches(9, size=4)
        with DevicePrefetcher(iter(raw), prep_fn=lambda b: b,
                              put_fn=_identity_put, depth=1,
                              fuse_steps=3) as pf:
            list(pf)
        assert pf._pin is False


class TestALSSharedInputPath:
    """ISSUE 13 satellite: ALS bucket staging rides DevicePrefetcher
    (the shared input path) instead of a private transfer loop."""

    def test_prepare_als_inputs_rides_prefetcher_metrics(self):
        from predictionio_tpu.models.als import (
            ALSConfig,
            prepare_als_inputs,
        )
        from predictionio_tpu.obs import get_registry

        rng = np.random.default_rng(0)
        n_u, n_i, nnz = 30, 20, 200
        inputs = prepare_als_inputs(
            rng.integers(0, n_u, nnz).astype(np.int64),
            rng.integers(0, n_i, nnz).astype(np.int64),
            rng.uniform(1, 5, nnz).astype(np.float32),
            n_u, n_i, ALSConfig(rank=4, iterations=1, seed=0))
        assert inputs.user_buckets and inputs.item_buckets
        for kind, *arrs in inputs.user_buckets + inputs.item_buckets:
            assert kind in ("plain", "merged", "plain_w", "merged_w")
            assert all(hasattr(a, "shape") for a in arrs)
        # the staging went through the shared pipeline: the prefetch
        # depth gauge now carries an "als" series (drained back to 0)
        g = get_registry().gauge("pio_prefetch_queue_depth", "",
                                 ("model",))
        assert g.value(model="als") == 0

    def test_lint_requires_prefetcher_in_device_buckets(self, tmp_path):
        import sys
        from pathlib import Path

        sys.path.insert(0, str(Path(__file__).resolve().parents[1]
                               / "tools"))
        import lint_trainloop

        bad = """
def _device_buckets(buckets, mesh):
    out = []
    for p in buckets:
        out.append(jnp.asarray(p))
    return out
"""
        violations = lint_trainloop.check_source(
            bad, "als.py", require_staging_fn="_device_buckets")
        assert len(violations) == 1
        assert "DevicePrefetcher" in violations[0]
        missing = lint_trainloop.check_source(
            "x = 1\n", "als.py", require_staging_fn="_device_buckets")
        assert any("_device_buckets" in v for v in missing)
        # the real tree is clean
        assert lint_trainloop.check(
            Path(__file__).resolve().parents[1]) == []
