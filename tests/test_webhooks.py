"""Webhook connectors + event-server webhook routes."""

import json
import urllib.parse
import urllib.request

import pytest

from predictionio_tpu.data.storage import AccessKey, App, get_storage
from predictionio_tpu.data.webhooks import (
    ConnectorError,
    MailchimpConnector,
    SegmentIOConnector,
    get_connector,
)
from predictionio_tpu.server import EventServer


class TestSegmentIO:
    def test_track(self):
        out = SegmentIOConnector().to_event_json({
            "type": "track", "userId": "u1", "event": "Item Purchased",
            "properties": {"revenue": 39.95},
            "timestamp": "2026-01-01T00:00:00Z"})
        assert out["event"] == "Item Purchased"
        assert out["entityId"] == "u1"
        assert out["properties"]["revenue"] == 39.95
        assert out["eventTime"].startswith("2026-01-01")

    def test_identify_becomes_set(self):
        out = SegmentIOConnector().to_event_json({
            "type": "identify", "userId": "u2", "traits": {"plan": "pro"}})
        assert out["event"] == "$set"
        assert out["properties"] == {"plan": "pro"}

    def test_missing_user_rejected(self):
        with pytest.raises(ConnectorError):
            SegmentIOConnector().to_event_json({"type": "track", "event": "x"})

    def test_batch_delivery_coalesces(self):
        """Segment's ``{"batch": [...]}`` envelope → one event list; a
        malformed message inside the burst becomes a per-item
        ConnectorError placeholder, not a whole-delivery failure."""
        out = SegmentIOConnector().to_events_json({"batch": [
            {"type": "track", "userId": "u1", "event": "a"},
            {"type": "track", "event": "no-user"},
            {"type": "identify", "userId": "u2", "traits": {"x": 1}},
        ]})
        assert len(out) == 3
        assert out[0]["event"] == "a"
        assert isinstance(out[1], ConnectorError)
        assert out[2]["event"] == "$set"

    def test_single_delivery_still_wraps(self):
        out = SegmentIOConnector().to_events_json(
            {"type": "track", "userId": "u1", "event": "a"})
        assert len(out) == 1 and out[0]["event"] == "a"


class TestMailchimp:
    def test_subscribe(self):
        out = MailchimpConnector().to_event_json({
            "type": "subscribe", "fired_at": "2026-01-02 03:04:05",
            "data[email]": "a@b.c", "data[list_id]": "L1"})
        assert out["event"] == "subscribe"
        assert out["entityId"] == "a@b.c"
        assert out["properties"]["list_id"] == "L1"
        assert out["eventTime"] == "2026-01-02T03:04:05+00:00"

    def test_unknown_type(self):
        with pytest.raises(ConnectorError):
            MailchimpConnector().to_event_json({"type": "nope"})


def test_registry():
    assert get_connector("segmentio")
    with pytest.raises(ConnectorError):
        get_connector("missing")


@pytest.fixture()
def server(pio_home):
    storage = get_storage()
    app_id = storage.get_apps().insert(App(id=None, name="app1"))
    storage.get_events().init(app_id)
    key = storage.get_access_keys().insert(AccessKey(key="", app_id=app_id))
    srv = EventServer(storage=storage, host="127.0.0.1", port=0)
    srv.start()
    yield srv, key, storage, app_id
    srv.stop()


def test_webhook_json_route(server):
    srv, key, storage, app_id = server
    payload = {"type": "track", "userId": "u9", "event": "buy",
               "properties": {"sku": "X"}}
    req = urllib.request.Request(
        f"http://127.0.0.1:{srv.port}/webhooks/segmentio.json?accessKey={key}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=10) as r:
        assert r.status == 201
    evs = list(storage.get_events().find(app_id, entity_id="u9"))
    assert len(evs) == 1 and evs[0].event == "buy"
    assert evs[0].properties.get("sku") == "X"


def test_webhook_form_route(server):
    srv, key, storage, app_id = server
    form = urllib.parse.urlencode({
        "type": "subscribe", "data[email]": "a@b.c", "data[list_id]": "L1"})
    req = urllib.request.Request(
        f"http://127.0.0.1:{srv.port}/webhooks/mailchimp?accessKey={key}",
        data=form.encode(),
        headers={"Content-Type": "application/x-www-form-urlencoded"})
    with urllib.request.urlopen(req, timeout=10) as r:
        assert r.status == 201
    evs = list(storage.get_events().find(app_id, entity_id="a@b.c"))
    assert len(evs) == 1 and evs[0].event == "subscribe"


def test_webhook_batch_route_one_group_commit(server):
    """A segment.io batch delivery rides the batched-ingest fold: ONE
    storage round trip, per-item statuses, the malformed message answers
    its own 400 while the rest of the burst lands."""
    import unittest.mock as mock

    srv, key, storage, app_id = server
    events_repo = storage.get_events()
    real = type(events_repo).create_batch
    calls = []

    def counting(self, evs, *a, **kw):
        calls.append(len(evs))
        return real(self, evs, *a, **kw)

    payload = {"batch": [
        {"type": "track", "userId": "b1", "event": "buy"},
        {"type": "track", "event": "missing-user"},
        {"type": "track", "userId": "b2", "event": "view"},
    ]}
    req = urllib.request.Request(
        f"http://127.0.0.1:{srv.port}/webhooks/segmentio.json?accessKey={key}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with mock.patch.object(type(events_repo), "create_batch", counting):
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.status == 200
            results = json.loads(r.read())
    assert [it["status"] for it in results] == [201, 400, 201]
    assert "userId" in results[1]["message"]
    assert calls == [2], "burst must land as ONE group commit"
    assert len(list(storage.get_events().find(app_id, entity_id="b1"))) == 1
    assert len(list(storage.get_events().find(app_id, entity_id="b2"))) == 1


def test_webhook_bad_connector_404ish(server):
    import urllib.error

    srv, key, *_ = server
    req = urllib.request.Request(
        f"http://127.0.0.1:{srv.port}/webhooks/nope.json?accessKey={key}",
        data=b"{}", headers={"Content-Type": "application/json"})
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req, timeout=10)
    assert ei.value.code == 400
