"""Classification template: $set aggregation → NB/LR → predict → eval sweep."""

import numpy as np
import pytest

from predictionio_tpu.controller import EngineVariant, RuntimeContext
from predictionio_tpu.data.event import DataMap, Event
from predictionio_tpu.data.storage import App, get_storage
from predictionio_tpu.templates.classification import (
    Query,
    default_params_generator,
    engine,
    evaluation,
)
from predictionio_tpu.workflow.core_workflow import (
    load_models,
    run_evaluation,
    run_train,
)


@pytest.fixture()
def ctx(pio_home):
    return RuntimeContext.create(storage=get_storage())


def _seed(ctx, n=120, seed=0):
    """Three separable classes on attr0..attr2 counts (NB-friendly)."""
    storage = ctx.storage
    app_id = storage.get_apps().insert(App(id=None, name="testapp"))
    storage.get_events().init(app_id)
    rng = np.random.default_rng(seed)
    events = storage.get_events()
    for i in range(n):
        label = i % 3
        base = np.zeros(3)
        base[label] = 6
        attrs = rng.poisson(base + 1).astype(float)
        events.insert(
            Event(event="$set", entity_type="user", entity_id=f"u{i}",
                  properties=DataMap({"attr0": attrs[0], "attr1": attrs[1],
                                      "attr2": attrs[2], "plan": float(label)})),
            app_id)
    # One user updates their label later — last-write-wins must apply.
    events.insert(
        Event(event="$set", entity_type="user", entity_id="u0",
              properties=DataMap({"plan": 2.0})), app_id)
    return app_id


def _variant(algo):
    return EngineVariant.from_dict({
        "engineFactory": "predictionio_tpu.templates.classification:engine",
        "datasource": {"params": {"appName": "testapp"}},
        "algorithms": [algo],
    })


@pytest.mark.parametrize("algo", [
    {"name": "naive", "params": {"lambda_": 1.0}},
    {"name": "lr", "params": {"maxIter": 150, "stepSize": 0.3}},
])
def test_train_predict(ctx, algo):
    _seed(ctx)
    eng = engine()
    variant = _variant(algo)
    instance_id = run_train(eng, variant, ctx)
    instance = ctx.storage.get_engine_instances().get(instance_id)
    models = load_models(eng, instance, ctx)
    a = eng.make_algorithms(eng.bind_engine_params(variant.raw))[0]
    assert a.predict(models[0], Query(attr0=9, attr1=1, attr2=1)).label == 0.0
    assert a.predict(models[0], Query(attr0=1, attr1=9, attr2=1)).label == 1.0
    assert a.predict(models[0], Query(attr0=1, attr1=1, attr2=9)).label == 2.0


def test_set_aggregation_last_write_wins(ctx):
    _seed(ctx, n=9)
    eng = engine()
    ds = eng.datasource_class(eng.bind_engine_params(
        _variant({"name": "naive"}).raw).datasource_params)
    data = ds.read_training(ctx)
    # u0 was class 0 then re-$set to plan=2.0.
    i = sorted(f"u{j}" for j in range(9)).index("u0")
    assert data.classes[data.y[i]] == 2.0


def test_eval_sweep(ctx):
    _seed(ctx)
    ev = evaluation()
    gen = default_params_generator("testapp", eval_k=2, lambdas=(0.5, 1.0))
    instance_id, result = run_evaluation(ev, gen, ctx)
    assert result.best_score > 0.7  # separable classes → high accuracy
    assert len(result.candidate_scores) == 2
    inst = ctx.storage.get_evaluation_instances().get(instance_id)
    assert inst.status == "EVALCOMPLETED"
    assert "Accuracy" in inst.evaluator_results
