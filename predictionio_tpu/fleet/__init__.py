"""Fleet-scale serving plane: coordinated generation rollout (ISSUE 15).

PR 9 gave the fleet *eyes* (merged telemetry, per-instance SLO burn) and
PR 10/11 gave each instance a private promotion loop (staged-reload
canary + SLO/quality auto-rollback).  This package is the fleet's
*hands*: one controller that promotes a new generation across N replicas
in waves (1 → 25% → 100%, configurable), gates every wave on the
fleet-merged SLO burn AND the merged ``/quality.json`` verdict, and —
when a wave degrades — halts and rolls back EVERY already-promoted
instance through the existing ``/admin/rollback`` path, so a bad
generation can never stay half-promoted across a load-balanced fleet.

Structural rule (``tools/lint_refresh.py`` rule 4): multi-instance
promotion goes through :class:`~predictionio_tpu.fleet.rollout.
RolloutController` — a loop POSTing ``/reload`` over an instance list
anywhere outside this package is a lint violation, because a bare loop
has no wave gate, no journaled state to resume from, and no whole-fleet
unwind.

Entry points: ``pio rollout`` (one coordinated rollout, resumable), and
the PR-10 refresh daemon — ``pio train --follow`` with a comma-separated
``--promote-url`` list promotes every cycle through a
:class:`~predictionio_tpu.fleet.rollout.FleetPromoter` instead of a
single-instance ``HttpPromoter``.
"""

from predictionio_tpu.fleet.rollout import (  # noqa: F401
    FleetPromoter,
    RolloutConfig,
    RolloutController,
    parse_waves,
    rollout_state_path,
)

__all__ = [
    "RolloutController",
    "RolloutConfig",
    "FleetPromoter",
    "parse_waves",
    "rollout_state_path",
]
