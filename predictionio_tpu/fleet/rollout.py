"""Wave-based generation rollout with whole-fleet rollback (ISSUE 15).

One :class:`RolloutController` drives ONE candidate generation across N
engine-server replicas:

1. **Waves** — instances promote in configurable tranches
   (``1,25%,100%`` by default): one canary instance first, then a
   quarter of the fleet, then everyone.  Each ``POST /reload`` carries
   the target ``engineInstanceId`` (pinned from the first successful
   promotion when not given explicitly), so a newer COMPLETED train
   landing mid-wave can never split the fleet across generations.
2. **Gate** — after each wave the controller bakes for
   ``RolloutConfig.bake_s``, polling the fleet-merged view
   (:class:`~predictionio_tpu.obs.fleet.FleetAggregator`): any
   non-stale instance whose SLO is degraded or whose fast-window burn
   crosses the threshold, or a merged ``/quality.json`` rollback
   verdict, halts the rollout.  One degraded canary protects the other
   N-1 replicas — they never load the candidate.
3. **Halt = whole-fleet rollback** — every already-promoted instance is
   rolled back through ``POST /admin/rollback`` (the PR-4 instant swap;
   the pre-promotion generation is retained server-side exactly for
   this).  Per-instance 409s and dead instances are recorded and
   skipped — the unwind reports, it never wedges.
4. **Journal** — every step is written ahead to a state file
   (``PIO_ROLLOUT_STATE``, default ``$PIO_HOME/rollout/state.json``), so
   a preempted controller resumes (``pio rollout --resume``: re-verifies
   which instances actually serve the target, then continues the wave)
   or unwinds (``--unwind``) deterministically instead of leaving the
   fleet half-promoted.

Dead instances and per-instance rejections (409 from the staged-reload
validation gate) are **skip-and-report**: the wave completes with what
it has, the skip is in the state file and the summary, and the operator
decides.  A wave where NO instance accepted the candidate fails the
rollout without touching anyone.
"""

from __future__ import annotations

import dataclasses
import datetime as _dt
import json
import logging
import math
import os
import time
import uuid
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Sequence
from urllib.request import Request, urlopen

from predictionio_tpu.obs import get_registry, publish_event
from predictionio_tpu.obs.fleet import FleetAggregator

logger = logging.getLogger(__name__)

__all__ = ["RolloutConfig", "RolloutController", "FleetPromoter",
           "parse_waves", "rollout_state_path"]


def _env_f(key: str, default: float) -> float:
    try:
        return float(os.environ.get(key, "") or default)
    except (TypeError, ValueError):
        return default


def rollout_state_path(explicit: Optional[str] = None) -> Path:
    """State-journal location: explicit > ``PIO_ROLLOUT_STATE`` >
    ``$PIO_HOME/rollout/state.json``."""
    cand = explicit or os.environ.get("PIO_ROLLOUT_STATE")
    if cand:
        return Path(cand)
    from predictionio_tpu.config import pio_home

    return pio_home() / "rollout" / "state.json"


def parse_waves(spec: str, n_instances: int) -> List[int]:
    """``"1,25%,100%"`` → cumulative instance counts, e.g. ``[1, 2, 8]``
    for 8 instances.  Absolute integers and percentages mix freely;
    counts are clamped to the fleet, forced strictly nondecreasing, and
    a final 100% wave is appended when the spec stops short — a rollout
    that never reaches the whole fleet is a config typo, not a policy."""
    counts: List[int] = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        try:
            if part.endswith("%"):
                frac = float(part[:-1]) / 100.0
                if not 0.0 < frac <= 1.0:
                    raise ValueError
                n = max(1, math.ceil(frac * n_instances))
            else:
                n = int(part)
                if n < 1:
                    raise ValueError
        except ValueError:
            raise ValueError(
                f"bad wave {part!r} (want a positive count or a "
                f"percentage like 25%)") from None
        counts.append(min(n, n_instances))
    if not counts:
        counts = [n_instances]
    # strictly nondecreasing; drop redundant equal steps
    out: List[int] = []
    for c in counts:
        c = max(c, out[-1] if out else 1)
        if not out or c > out[-1]:
            out.append(c)
    if out[-1] < n_instances:
        out.append(n_instances)
    return out


@dataclasses.dataclass
class RolloutConfig:
    """Rollout knobs; :meth:`from_env` is the production constructor."""

    waves: str = "1,25%,100%"
    bake_s: float = 10.0            # per-wave observation window
    poll_s: float = 1.0             # gate poll cadence inside the bake
    burn_threshold: float = 14.4    # fast-burn trip level (SLO page point)
    reload_timeout_s: float = 300.0  # a reload stages + validates a model
    state_path: Optional[str] = None

    @classmethod
    def from_env(cls, **overrides) -> "RolloutConfig":
        cfg = cls(
            waves=os.environ.get("PIO_ROLLOUT_WAVES", "1,25%,100%"),
            bake_s=_env_f("PIO_ROLLOUT_BAKE_S", 10.0),
            poll_s=_env_f("PIO_ROLLOUT_POLL_S", 1.0),
            burn_threshold=_env_f("PIO_SLO_BURN_THRESHOLD", 14.4),
            reload_timeout_s=_env_f("PIO_ROLLOUT_RELOAD_TIMEOUT_S", 300.0),
            state_path=os.environ.get("PIO_ROLLOUT_STATE") or None,
        )
        for k, v in overrides.items():
            if v is not None:
                setattr(cfg, k, v)
        return cfg


class RolloutController:
    """Drive one candidate generation across the fleet in gated waves.

    Clock / sleep / HTTP opener / aggregator are injectable so the test
    matrix stages degraded canaries and preempted controllers with zero
    wall sleeps and real servers."""

    def __init__(self, instances: Sequence[str],
                 config: Optional[RolloutConfig] = None, *,
                 aggregator: Optional[FleetAggregator] = None,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep,
                 opener: Callable = urlopen,
                 registry=None):
        self.instances = [u.rstrip("/") for u in instances if u.strip()]
        if not self.instances:
            raise ValueError("rollout needs at least one instance URL")
        self.config = config or RolloutConfig.from_env()
        # Remember ownership: a self-built aggregator's scrape pool is
        # released at _finish; an injected one belongs to the caller.
        self._owns_aggregator = aggregator is None
        self.aggregator = aggregator or FleetAggregator(self.instances)
        self._clock = clock
        self._sleep = sleep
        self._opener = opener
        self.state_path = rollout_state_path(self.config.state_path)
        reg = registry or get_registry()
        self._waves_total = reg.counter(
            "pio_rollout_waves_total",
            "Rollout waves completed by outcome (ok/halted).", ("result",))
        self._rollouts_total = reg.counter(
            "pio_rollout_total",
            "Coordinated rollouts by outcome "
            "(promoted/rolled_back/failed).", ("result",))
        self._wave_gauge = reg.gauge(
            "pio_rollout_wave",
            "Wave index the active rollout is promoting (-1 when idle).")

    # -- state journal ------------------------------------------------------

    def _save(self, state: Dict[str, Any]) -> None:
        """Write-ahead journal: atomic tmp+replace, flushed before every
        action, so a controller killed between any two HTTP calls can
        reconstruct exactly what it had already done."""
        state["updatedAt"] = _dt.datetime.now(
            _dt.timezone.utc).isoformat(timespec="seconds")
        self.state_path.parent.mkdir(parents=True, exist_ok=True)
        tmp = self.state_path.with_suffix(".tmp")
        tmp.write_text(json.dumps(state, indent=1))
        tmp.replace(self.state_path)

    def load_state(self) -> Optional[Dict[str, Any]]:
        try:
            return json.loads(self.state_path.read_text())
        except (OSError, ValueError):
            return None

    # -- per-instance HTTP ops ----------------------------------------------

    def _http_json(self, url: str, method: str = "GET",
                   body: Optional[dict] = None,
                   timeout: float = 30.0) -> tuple:
        data = json.dumps(body).encode() if body is not None else \
            (b"" if method == "POST" else None)
        req = Request(url, data=data, method=method,
                      headers={"Content-Type": "application/json"})
        with self._opener(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read() or b"{}")

    def served_instance(self, url: str) -> Optional[str]:
        """The engine instance id ``url`` is serving right now; None when
        unreachable."""
        try:
            _, body = self._http_json(url + "/")
            return body.get("engineInstanceId")
        except Exception:
            return None

    def _promote_instance(self, url: str, target: Optional[str]) -> tuple:
        """(outcome, detail): ``("ok", loaded_id)`` /
        ``("rejected", msg)`` / ``("unreachable", msg)``."""
        from urllib.error import HTTPError

        body = {"engineInstanceId": target} if target else None
        try:
            _, out = self._http_json(url + "/reload", "POST", body=body,
                                     timeout=self.config.reload_timeout_s)
            return "ok", out.get("engineInstanceId")
        except HTTPError as e:
            payload = e.read()
            try:
                msg = json.loads(payload).get("message", "")
            except Exception:
                msg = payload.decode(errors="replace")[:200]
            if e.code == 409:
                return "rejected", msg[:200]
            return "unreachable", f"HTTP {e.code}: {msg[:200]}"
        except Exception as e:
            return "unreachable", f"{type(e).__name__}: {e}"[:200]

    def _rollback_instance(self, url: str) -> tuple:
        from urllib.error import HTTPError

        try:
            self._http_json(url + "/admin/rollback", "POST",
                            timeout=self.config.reload_timeout_s)
            return "ok", None
        except HTTPError as e:
            return ("no_previous" if e.code == 409
                    else "error"), f"HTTP {e.code}"
        except Exception as e:
            return "unreachable", f"{type(e).__name__}: {e}"[:200]

    # -- the fleet gate -----------------------------------------------------

    def fleet_tripped(self) -> tuple:
        """(tripped?, reason) from ONE fleet-merged scrape: any non-stale
        instance SLO-degraded or fast-burning, or the merged quality
        gate demanding rollback.  A stale (dead) instance never trips
        the gate — it is reported, not treated as burn."""
        try:
            doc = self.aggregator.scrape()
        except Exception as e:
            logger.warning("fleet gate scrape failed: %s", e)
            return False, None
        thr = self.config.burn_threshold
        for row in doc.get("instances", []):
            if row.get("stale"):
                continue
            slo = row.get("slo") or {}
            fast = (slo.get("burn") or {}).get("fast") or {}
            burn = max(float(fast.get("availability", 0.0)),
                       float(fast.get("latency", 0.0)))
            if slo.get("degraded") or burn >= thr:
                return True, (f"slo burn on {row.get('instance')}: "
                              f"degraded={bool(slo.get('degraded'))} "
                              f"fast={burn:g}")
        gate = ((doc.get("merged") or {}).get("quality")
                or {}).get("gate") or {}
        if gate.get("rollback"):
            return True, (f"fleet quality gate: "
                          f"{gate.get('reasons') or 'rollback'}")
        return False, None

    def _bake(self, state: Dict[str, Any]) -> tuple:
        """Watch the fleet gate for the wave's bake window."""
        deadline = self._clock() + self.config.bake_s
        while True:
            tripped, reason = self.fleet_tripped()
            if tripped:
                return True, reason
            if self._clock() >= deadline:
                return False, None
            self._sleep(min(self.config.poll_s,
                            max(deadline - self._clock(), 0.01)))

    # -- drive --------------------------------------------------------------

    def run(self, instance_id: Optional[str] = None) -> Dict[str, Any]:
        """One coordinated rollout of ``instance_id`` (None = each
        server's latest COMPLETED; the first successful promotion pins
        the target for the rest of the fleet).  Returns the final state
        document (also journaled)."""
        wave_counts = parse_waves(self.config.waves, len(self.instances))
        state: Dict[str, Any] = {
            "rolloutId": uuid.uuid4().hex[:12],
            "status": "in_progress",
            "target": instance_id,
            "instances": list(self.instances),
            "waveCounts": wave_counts,
            "wave": 0,
            "promoted": [],
            "skipped": {},
            "rolledBack": [],
            "unwindFailures": {},
            "haltReason": None,
            "startedAt": _dt.datetime.now(
                _dt.timezone.utc).isoformat(timespec="seconds"),
        }
        # Pre-promotion fleet snapshot: what /admin/rollback should
        # restore — recorded so `pio status --fleet` (and the operator)
        # can verify the unwind actually landed.
        state["preRollout"] = {u: self.served_instance(u)
                               for u in self.instances}
        self._save(state)
        publish_event("rollout.start", rolloutId=state["rolloutId"],
                      target=instance_id, instances=len(self.instances))
        return self._execute(state)

    def resume(self, unwind: bool = False) -> Dict[str, Any]:
        """Continue (or unwind) a journaled in-progress rollout after the
        controller was preempted.  Re-verifies which instances ACTUALLY
        serve the target before trusting the journal — a /reload whose
        reply was lost still counts as promoted."""
        state = self.load_state()
        if state is None:
            raise RuntimeError(f"no rollout state at {self.state_path}")
        if state.get("status") not in ("in_progress", "rolling_back"):
            return state  # already terminal
        state["instances"] = [u for u in state.get("instances", [])
                              ] or list(self.instances)
        target = state.get("target")
        if target:
            promoted = set(state.get("promoted", []))
            for url in state["instances"]:
                served = self.served_instance(url)
                if served == target:
                    promoted.add(url)
                elif url in promoted and served is not None:
                    # journal said promoted but the server serves
                    # something else (it rolled itself back, or the
                    # reload never landed) — trust the server
                    promoted.discard(url)
            state["promoted"] = [u for u in state["instances"]
                                 if u in promoted]
        if unwind or state.get("status") == "rolling_back":
            state["haltReason"] = state.get("haltReason") or \
                "operator unwind"
            return self._unwind(state)
        return self._execute(state)

    def _execute(self, state: Dict[str, Any]) -> Dict[str, Any]:
        wave_counts = state["waveCounts"]
        for wave_idx in range(int(state.get("wave", 0)), len(wave_counts)):
            state["wave"] = wave_idx
            self._wave_gauge.set(wave_idx)
            self._save(state)
            target_count = wave_counts[wave_idx]
            for url in state["instances"]:
                if len(state["promoted"]) >= target_count:
                    break
                if url in state["promoted"] or url in state["skipped"]:
                    continue
                outcome, detail = self._promote_instance(
                    url, state.get("target"))
                if outcome == "ok":
                    if state.get("target") is None and detail:
                        # first success pins the fleet-wide target: every
                        # later /reload names THIS instance id, so a
                        # newer COMPLETED train cannot split the wave
                        state["target"] = detail
                    elif detail and state.get("target") \
                            and detail != state["target"]:
                        logger.warning(
                            "rollout: %s loaded %s, not the wave target "
                            "%s", url, detail, state["target"])
                    state["promoted"].append(url)
                    publish_event("rollout.promoted", instance=url,
                                  wave=wave_idx, target=state["target"])
                else:
                    # skip-and-report — a rejecting or dead instance
                    # must never wedge the wave
                    state["skipped"][url] = f"{outcome}: {detail}"
                    publish_event("rollout.skipped", instance=url,
                                  wave=wave_idx, outcome=outcome)
                    logger.warning("rollout: skipping %s (%s: %s)",
                                   url, outcome, detail)
                self._save(state)
            if not state["promoted"]:
                state["status"] = "failed"
                state["haltReason"] = ("no instance accepted the "
                                       "candidate")
                self._finish(state)
                return state
            tripped, reason = self._bake(state)
            if tripped:
                self._waves_total.inc(result="halted")
                state["haltReason"] = reason
                logger.warning("rollout halted at wave %d: %s",
                               wave_idx, reason)
                publish_event("rollout.halted", wave=wave_idx,
                              reason=str(reason)[:200])
                return self._unwind(state)
            self._waves_total.inc(result="ok")
        state["status"] = "promoted"
        self._finish(state)
        return state

    def _unwind(self, state: Dict[str, Any]) -> Dict[str, Any]:
        """Roll back EVERY promoted instance (newest first), journaling
        each step; failures are recorded and skipped, never fatal."""
        state["status"] = "rolling_back"
        self._save(state)
        for url in list(reversed(state.get("promoted", []))):
            if url in state.get("rolledBack", []):
                continue
            outcome, detail = self._rollback_instance(url)
            if outcome == "ok":
                state.setdefault("rolledBack", []).append(url)
                publish_event("rollout.rolled_back", instance=url)
            else:
                state.setdefault("unwindFailures", {})[url] = \
                    f"{outcome}: {detail}"
                logger.error("rollout unwind: %s failed on %s (%s)",
                             outcome, url, detail)
            self._save(state)
        state["postRollback"] = {u: self.served_instance(u)
                                 for u in state.get("instances", [])}
        state["status"] = "rolled_back"
        self._finish(state)
        return state

    def _finish(self, state: Dict[str, Any]) -> None:
        self._wave_gauge.set(-1)
        if self._owns_aggregator:
            try:
                self.aggregator.close()
            except Exception:
                pass
        self._rollouts_total.inc(result=state["status"])
        state["finishedAt"] = _dt.datetime.now(
            _dt.timezone.utc).isoformat(timespec="seconds")
        self._save(state)
        publish_event("rollout.finished", rolloutId=state.get("rolloutId"),
                      status=state["status"],
                      promoted=len(state.get("promoted", [])),
                      haltReason=(str(state.get("haltReason"))[:200]
                                  if state.get("haltReason") else None))


class FleetPromoter:
    """The refresh daemon's promoter interface over a wave rollout.

    ``pio train --follow --promote-url URL1,URL2,...`` constructs one of
    these instead of a single-instance ``HttpPromoter``: each refresh
    cycle's new generation rolls across the fleet in gated waves, and
    the daemon's canary verdict is the rollout's outcome (the bake IS
    the canary — there is no second watch window)."""

    def __init__(self, instances: Sequence[str],
                 config: Optional[RolloutConfig] = None, *,
                 opener: Callable = urlopen,
                 controller_factory: Optional[Callable] = None):
        self.instances = [u.rstrip("/") for u in instances if u.strip()]
        self.config = config or RolloutConfig.from_env()
        self._opener = opener
        self._factory = controller_factory or (
            lambda: RolloutController(self.instances, self.config,
                                      opener=self._opener))
        # Non-zero so RefreshDaemon._promote asks for the canary verdict.
        self.canary_window_s = max(self.config.bake_s, 0.001)
        self._last: Optional[Dict[str, Any]] = None

    def promote(self, instance_id: str) -> Dict[str, Any]:
        from predictionio_tpu.refresh.daemon import PromotionRejected

        self._last = self._factory().run(instance_id)
        if self._last.get("status") == "failed":
            raise PromotionRejected(
                f"fleet rollout failed: {self._last.get('haltReason')} "
                f"(skipped: {self._last.get('skipped')})")
        return {"engineInstanceId": self._last.get("target"),
                "rollout": self._last.get("rolloutId")}

    def canary_watch(self) -> str:
        if self._last is not None \
                and self._last.get("status") == "promoted":
            return "promoted"
        return "rolled_back"

    def served_watermark(self):
        """The OLDEST served data watermark across reachable instances —
        the conservative anchor for the staleness gauge: freshness the
        whole fleet serves, not just the luckiest replica."""
        import datetime as dt

        oldest = None
        for url in self.instances:
            try:
                req = Request(url + "/", method="GET")
                with self._opener(req, timeout=10) as resp:
                    body = json.loads(resp.read() or b"{}")
            except Exception:
                continue
            raw = body.get("dataWatermark")
            if not raw:
                continue
            wm = dt.datetime.fromisoformat(raw)
            if oldest is None or wm < oldest:
                oldest = wm
        return oldest
