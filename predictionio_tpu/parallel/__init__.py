"""Mesh, sharding, and collective infrastructure.

Replaces the reference's compute-distribution substrate (Spark executors +
netty shuffle + spark-submit; SURVEY.md §2.5): here the device mesh IS the
cluster, XLA collectives over ICI are the shuffle, and `jax.distributed`
is the control plane.
"""

from predictionio_tpu.parallel.mesh import (
    AXIS_DATA,
    AXIS_EXPERT,
    AXIS_MODEL,
    AXIS_SEQUENCE,
    batch_sharding,
    cpu_devices_requested,
    make_mesh,
    replicated,
    sharding,
)

__all__ = [
    "AXIS_DATA",
    "AXIS_EXPERT",
    "AXIS_MODEL",
    "AXIS_SEQUENCE",
    "batch_sharding",
    "cpu_devices_requested",
    "make_mesh",
    "replicated",
    "sharding",
]
