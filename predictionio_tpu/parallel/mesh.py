"""Mesh construction and sharding-spec helpers.

The standard mesh axes of the framework (SURVEY.md §2.4):

- ``data``     — batch/data parallelism (the reference's RDD partitions)
- ``model``    — tensor/factor-block parallelism (the reference's ALS
  user×item blocking, MLlib-internal)
- ``sequence`` — sequence/context parallelism (absent in the reference;
  reserved so long-context engines can shard tokens without redesign)
- ``expert``   — embedding-table / expert sharding (the EP-shaped axis the
  DLRM engine uses for row-sharded tables + all_to_all)

Tests run on a virtual CPU mesh (``XLA_FLAGS=--xla_force_host_platform_
device_count=N`` — the moral equivalent of the reference's Spark
``local[n]``, SURVEY.md §4).
"""

from __future__ import annotations

import math
import os
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = [
    "AXIS_DATA",
    "put_sharded",
    "AXIS_MODEL",
    "AXIS_SEQUENCE",
    "AXIS_EXPERT",
    "make_mesh",
    "parse_mesh_spec",
    "mesh_from_spec",
    "sharding",
    "batch_sharding",
    "replicated",
    "cpu_devices_requested",
]

AXIS_DATA = "data"
AXIS_MODEL = "model"
AXIS_SEQUENCE = "sequence"
AXIS_EXPERT = "expert"


def cpu_devices_requested() -> int:
    """How many virtual CPU devices XLA_FLAGS requests (test introspection)."""
    flags = os.environ.get("XLA_FLAGS", "")
    for tok in flags.split():
        if tok.startswith("--xla_force_host_platform_device_count="):
            return int(tok.split("=", 1)[1])
    return 1


def make_mesh(
    axis_sizes: Optional[Dict[str, int]] = None,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a named mesh over the available devices.

    ``axis_sizes`` maps axis name → size; at most one axis may be ``-1``
    (absorbs remaining devices).  Default: all devices on the ``data`` axis.

    The axis order given is the device-assignment order — on real TPU
    hardware put the fastest-varying (innermost) axis on the most
    bandwidth-hungry dimension so its collectives ride nearest-neighbor ICI
    links.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if not axis_sizes:
        axis_sizes = {AXIS_DATA: n}
    sizes = dict(axis_sizes)
    wild = [k for k, v in sizes.items() if v == -1]
    if len(wild) > 1:
        raise ValueError("At most one mesh axis may be -1.")
    fixed = math.prod(v for v in sizes.values() if v != -1)
    if wild:
        if n % fixed != 0:
            raise ValueError(
                f"Cannot infer axis {wild[0]!r}: {n} devices not divisible by {fixed}."
            )
        sizes[wild[0]] = n // fixed
    total = math.prod(sizes.values())
    if total > n:
        raise ValueError(
            f"Mesh axes {sizes} need {total} devices but {n} are available."
        )
    # Fewer than available is allowed (e.g. `--mesh data=2` on an 8-chip
    # host): take a device prefix so small meshes work anywhere.
    mesh_devices = np.array(devices[:total]).reshape(*sizes.values())
    return Mesh(mesh_devices, axis_names=tuple(sizes))


def parse_mesh_spec(spec: str) -> Dict[str, int]:
    """Parse the CLI/env mesh spec (``pio train --mesh`` / ``PIO_MESH``).

    Grammar: ``axis=size[,axis=size...]`` with at most one ``-1`` wildcard
    (``data=-1,model=2``), or the shorthands ``auto`` (all devices on the
    ``data`` axis) and a bare integer N (``data=N``).
    """
    spec = (spec or "").strip()
    if not spec or spec.lower() == "auto":
        return {AXIS_DATA: -1}
    if spec.isdigit():
        return {AXIS_DATA: int(spec)}
    sizes: Dict[str, int] = {}
    for tok in spec.split(","):
        tok = tok.strip()
        if not tok:
            continue
        if "=" not in tok:
            raise ValueError(
                f"Bad mesh spec token {tok!r}: expected axis=size "
                "(e.g. 'data=8,model=2', 'data=-1', or 'auto')."
            )
        axis, _, size = tok.partition("=")
        sizes[axis.strip()] = int(size)
    for axis, size in sizes.items():
        if size != -1 and size < 1:
            raise ValueError(
                f"Bad mesh axis size {axis}={size}: must be >= 1 "
                "(or -1 to absorb remaining devices)."
            )
    return sizes


def mesh_from_spec(
    spec: str, *, devices: Optional[Sequence[jax.Device]] = None
) -> Optional[Mesh]:
    """Build a mesh from a CLI/env spec string; ``""``/``"none"`` → None.

    This is the production entry point `pio train/deploy --mesh` and
    ``PIO_MESH`` go through (SURVEY.md §2.5 — mesh bring-up is the
    framework's, not the engine author's, job).
    """
    spec = (spec or "").strip()
    if not spec or spec.lower() in ("none", "off"):
        return None
    return make_mesh(parse_mesh_spec(spec), devices=devices)


def sharding(mesh: Mesh, *spec: Optional[str | Tuple[str, ...]]) -> NamedSharding:
    """NamedSharding over ``mesh`` with one spec entry per array dim.

    ``sharding(mesh, "data", None)`` shards dim 0 over ``data`` and
    replicates dim 1.
    """
    return NamedSharding(mesh, PartitionSpec(*spec))


def batch_sharding(mesh: Mesh, axis: str = AXIS_DATA) -> NamedSharding:
    """Shard the leading (batch) dim, replicate the rest."""
    return NamedSharding(mesh, PartitionSpec(axis))


def replicated(mesh: Mesh) -> NamedSharding:
    """Fully replicated (the reference's ``sc.broadcast`` analogue)."""
    return NamedSharding(mesh, PartitionSpec())


def put_sharded(arr, mesh: Mesh, spec) -> "jax.Array":
    """Place a host array onto the mesh — multi-process safe.

    Single-process this is ``jax.device_put(arr, NamedSharding(mesh,
    spec))``.  In a multi-host gang (SURVEY §2.5) ``device_put`` of a
    host array cannot address other processes' devices; every process
    instead calls this with the SAME full array (data paths here are
    deterministic from shared inputs) and contributes only its
    addressable shards via ``make_array_from_callback``.
    """
    ns = spec if isinstance(spec, NamedSharding) else NamedSharding(mesh, spec)
    if jax.process_count() == 1:
        return jax.device_put(arr, ns)
    a = np.asarray(arr)
    return jax.make_array_from_callback(a.shape, ns, lambda idx: a[idx])
