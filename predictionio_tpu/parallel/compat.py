"""jax API compatibility shims for the parallel layer.

``shard_map`` graduated from ``jax.experimental.shard_map`` to
``jax.shard_map`` (and renamed ``check_rep`` → ``check_vma``) across the
jax versions this repo meets in the wild; call sites import the one shim
here instead of pinning either spelling.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax

__all__ = ["shard_map"]


def shard_map(f: Callable, *, mesh, in_specs, out_specs,
              check_vma: Optional[bool] = None):
    """``jax.shard_map`` where available, else the experimental one.

    ``check_vma`` maps onto the old API's ``check_rep``; None means
    "library default" on both.
    """
    kwargs = dict(mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        if check_vma is not None:
            kwargs["check_vma"] = check_vma
        return sm(f, **kwargs)
    from jax.experimental.shard_map import shard_map as _shard_map

    if check_vma is not None:
        kwargs["check_rep"] = check_vma
    return _shard_map(f, **kwargs)
