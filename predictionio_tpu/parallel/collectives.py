"""Collective micro-benchmark harness.

Reference analogue: none (Spark's shuffle metrics live in the external Spark
UI).  SURVEY.md §2.5 makes a collective micro-bench a first-class build
deliverable — it grounds the samples/sec/chip numbers in measured ICI
bandwidth and catches sharding regressions on real hardware.
"""

from __future__ import annotations

import time
from functools import partial
from typing import Dict, List

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from predictionio_tpu.parallel.compat import shard_map

__all__ = ["collective_microbench"]


def _timed(fn, *args, iters: int = 5) -> float:
    fn(*args).block_until_ready()  # warmup/compile
    t0 = time.perf_counter()
    for _ in range(iters):
        fn(*args).block_until_ready()
    return (time.perf_counter() - t0) / iters


def collective_microbench(
    mesh: Mesh, *, size_mb: float = 4.0, axis: str | None = None, iters: int = 5
) -> Dict[str, Dict[str, float]]:
    """Measure all_reduce / all_gather / all_to_all over one mesh axis.

    Returns {collective: {seconds, algo_bw_gbps}} where algo bandwidth is
    payload_bytes / time (the ring-efficiency factor is left to the reader —
    this is a regression harness, not a NIC spec sheet).
    """
    axis = axis or mesh.axis_names[0]
    n = mesh.shape[axis]
    per_device_rows = max(1, int(size_mb * 1024 * 1024 / 4) // 128)
    global_shape = (per_device_rows * n, 128)
    x = jnp.zeros(global_shape, jnp.float32)
    x = jax.device_put(x, NamedSharding(mesh, PartitionSpec(axis)))
    bytes_payload = x.size * x.dtype.itemsize

    in_spec = PartitionSpec(axis)
    results: Dict[str, Dict[str, float]] = {}

    @partial(
        shard_map, mesh=mesh, in_specs=in_spec, out_specs=PartitionSpec()
    )
    def _psum(v):
        return jax.lax.psum(v, axis)

    @partial(
        shard_map, mesh=mesh, in_specs=in_spec, out_specs=PartitionSpec(),
        check_vma=False,  # all_gather output replication isn't statically inferable
    )
    def _all_gather(v):
        return jax.lax.all_gather(v, axis, tiled=True)

    @partial(
        shard_map, mesh=mesh, in_specs=in_spec, out_specs=in_spec
    )
    def _all_to_all(v):
        return jax.lax.all_to_all(
            v.reshape(n, v.shape[0] // n, v.shape[1]), axis, 0, 0, tiled=False
        ).reshape(v.shape)

    for name, fn in (("all_reduce", _psum), ("all_gather", _all_gather),
                     ("all_to_all", _all_to_all)):
        jitted = jax.jit(fn)
        secs = _timed(jitted, x, iters=iters)
        results[name] = {
            "seconds": secs,
            "algo_bw_gbps": bytes_payload / secs / 1e9,
            "payload_mb": bytes_payload / 1e6,
            "axis_size": float(n),
        }
    return results
