"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

The reference has no sequence models at all (SURVEY.md §5.7), but the
rebuild treats long-context as first-class: the mesh reserves a
``sequence`` axis (parallel/mesh.py) and this module supplies the two
standard SP attention strategies so sequence engines can shard tokens
without redesign:

- :func:`ring_attention` — K/V blocks rotate around the ring via
  ``ppermute`` (nearest-neighbor ICI traffic) while each device keeps its
  resident Q block; softmax is accumulated online (flash-attention style
  running max / denominator), so the full [S, S] score matrix never
  materializes.  Memory per device: O(S/n · S/n) per step.
- :func:`ulysses_attention` — ``all_to_all`` re-shards from
  sequence-sharded to head-sharded before a standard local attention,
  then back.  Cheaper at modest sequence lengths when heads ≥ devices.

Both are numerically equivalent to full attention (tests assert it) and
compose under ``jit``/``grad``.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from predictionio_tpu.parallel.compat import shard_map
from predictionio_tpu.parallel.mesh import AXIS_SEQUENCE

__all__ = ["ring_attention", "ulysses_attention", "local_attention"]

_NEG = jnp.float32(-1e30)


def local_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = False,
                    q_offset: int | jax.Array = 0,
                    k_offset: int | jax.Array = 0) -> jax.Array:
    """Plain softmax attention on one device. Shapes [B, S, H, D]."""
    scale = q.shape[-1] ** -0.5
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        qpos = q_offset + jnp.arange(q.shape[1])
        kpos = k_offset + jnp.arange(k.shape[1])
        mask = qpos[:, None] >= kpos[None, :]
        scores = jnp.where(mask[None, None, :, :], scores, _NEG)
    w = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", w.astype(v.dtype), v,
                      preferred_element_type=jnp.float32).astype(q.dtype)


def ring_attention(
    q: jax.Array,  # [B, S, H, D] sharded on S over AXIS_SEQUENCE
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    causal: bool = False,
    axis: str = AXIS_SEQUENCE,
) -> jax.Array:
    """Exact attention over sequence-sharded Q/K/V with ring K/V rotation."""
    n = mesh.shape[axis]
    seq = q.shape[1]
    assert seq % n == 0, f"pad sequence ({seq}) to a multiple of {n}"
    s_local = seq // n
    scale = None  # applied inside local step

    def local(q_blk, k_blk, v_blk):
        # q_blk: [B, S/n, H, D]
        me = jax.lax.axis_index(axis)
        b, sl, h, d = q_blk.shape
        scale = d ** -0.5
        q_pos = me * sl + jnp.arange(sl)

        def step(t, carry):
            k_cur, v_cur, m, l, acc = carry
            src = (me - t) % n                      # owner of the visiting block
            k_pos = src * sl + jnp.arange(sl)
            s = jnp.einsum("bqhd,bkhd->bhqk", q_blk, k_cur,
                           preferred_element_type=jnp.float32) * scale
            if causal:
                mask = q_pos[:, None] >= k_pos[None, :]
                s = jnp.where(mask[None, None, :, :], s, _NEG)
            m_new = jnp.maximum(m, s.max(axis=-1))
            p = jnp.exp(s - m_new[..., None])
            corr = jnp.exp(m - m_new)
            l_new = l * corr + p.sum(axis=-1)
            acc_new = acc * corr[..., None] + jnp.einsum(
                "bhqk,bkhd->bhqd", p, v_cur.astype(jnp.float32),
                preferred_element_type=jnp.float32)
            # Rotate K/V to the next device on the ring.
            perm = [(i, (i + 1) % n) for i in range(n)]
            k_nxt = jax.lax.ppermute(k_cur, axis, perm)
            v_nxt = jax.lax.ppermute(v_cur, axis, perm)
            return k_nxt, v_nxt, m_new, l_new, acc_new

        # pcast-to-varying: the accumulators become device-varying after
        # step 1; the loop carry must start with matching varying-axis
        # types.  Older jax has no varying-axis tracking (and no pcast) —
        # there the plain zeros ARE the right carry.
        _pcast = getattr(jax.lax, "pcast", None)
        if _pcast is None:
            def _pcast(x, _axis, to):
                return x
        m0 = _pcast(jnp.full((b, h, sl), _NEG, jnp.float32), axis, to='varying')
        l0 = _pcast(jnp.zeros((b, h, sl), jnp.float32), axis, to='varying')
        acc0 = _pcast(jnp.zeros((b, h, sl, d), jnp.float32), axis, to='varying')
        _, _, m, l, acc = jax.lax.fori_loop(
            0, n, step, (k_blk, v_blk, m0, l0, acc0))
        out = acc / jnp.maximum(l, 1e-30)[..., None]          # [B,H,S/n,D]
        return out.transpose(0, 2, 1, 3).astype(q_blk.dtype)  # [B,S/n,H,D]

    return shard_map(
        local, mesh=mesh,
        in_specs=(P(None, axis), P(None, axis), P(None, axis)),
        out_specs=P(None, axis),
    )(q, k, v)


def ulysses_attention(
    q: jax.Array,  # [B, S, H, D] sharded on S over AXIS_SEQUENCE
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    causal: bool = False,
    axis: str = AXIS_SEQUENCE,
) -> jax.Array:
    """DeepSpeed-Ulysses-style SP: all_to_all seq→head reshard, local
    attention over the FULL sequence for H/n heads, all_to_all back."""
    n = mesh.shape[axis]
    seq, heads = q.shape[1], q.shape[2]
    assert seq % n == 0, f"pad sequence ({seq}) to a multiple of {n}"
    assert heads % n == 0, f"heads ({heads}) must divide over {n} devices"

    def local(q_blk, k_blk, v_blk):
        # [B, S/n, H, D] → exchange so each device gets all S for H/n heads.
        def seq_to_heads(x):
            # split_axis=2 (heads), concat_axis=1 (sequence)
            return jax.lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                                      tiled=True)

        def heads_to_seq(x):
            return jax.lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                                      tiled=True)

        qf, kf, vf = seq_to_heads(q_blk), seq_to_heads(k_blk), seq_to_heads(v_blk)
        out = local_attention(qf, kf, vf, causal=causal)
        return heads_to_seq(out)

    return shard_map(
        local, mesh=mesh,
        in_specs=(P(None, axis), P(None, axis), P(None, axis)),
        out_specs=P(None, axis),
    )(q, k, v)
