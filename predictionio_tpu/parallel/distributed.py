"""Multi-host control-plane bring-up.

Reference: SURVEY.md §2.5 — the reference has no first-party comm layer
(spark-submit + netty shuffle are external).  The TPU equivalent:
``jax.distributed.initialize`` forms the multi-host gang (one process per
host, all chips of a slice in one ``jax.devices()`` view); all data-plane
traffic is XLA collectives over ICI/DCN — nothing NCCL/MPI-like to hand-roll.

Env contract (subset of the standard JAX one, prefixed for pio):

- ``PIO_COORDINATOR_ADDRESS`` — host:port of process 0
- ``PIO_NUM_PROCESSES``       — gang size
- ``PIO_PROCESS_ID``          — this process's rank
"""

from __future__ import annotations

import logging
import os
from typing import Optional

import jax

logger = logging.getLogger(__name__)

__all__ = ["initialize_distributed", "is_multi_host", "process_index", "process_count"]

_initialized = False


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Join the multi-host gang if configured; no-op on a single host.

    Returns True if distributed mode is active.  Safe to call repeatedly.
    """
    global _initialized
    if _initialized:
        return True
    coordinator_address = coordinator_address or os.environ.get("PIO_COORDINATOR_ADDRESS")
    if coordinator_address is None:
        return False
    num_processes = num_processes or int(os.environ.get("PIO_NUM_PROCESSES", "1"))
    process_id = (
        process_id
        if process_id is not None
        else int(os.environ.get("PIO_PROCESS_ID", "0"))
    )
    logger.info(
        "jax.distributed.initialize(%s, num_processes=%d, process_id=%d)",
        coordinator_address, num_processes, process_id,
    )
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized = True
    return True


def is_multi_host() -> bool:
    return jax.process_count() > 1


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()
