"""Env-driven fault-injection harness.

Nothing in the tree could previously *simulate* a fault; every resilience
mechanism (breaker, spill journal, write retry) would have shipped
untested.  This module is the one switchboard:

    PIO_FAULTS="storage.create:error:0.3,storage.find:delay:200ms"

Grammar (comma-separated rules)::

    <point>:error[:<probability>][:<max-count>]
    <point>:delay:<duration>[:<probability>][:<max-count>]

``<point>`` is an instrumented fault-point name or a ``prefix.*`` glob;
``<duration>`` takes an ``ms``/``s`` suffix (bare numbers are ms).
Probability defaults to 1.0; ``max-count`` bounds how many times the
rule fires (e.g. kill exactly one RPC reply).  ``PIO_FAULTS_SEED`` makes
probabilistic rules reproducible.

Instrumented points:

- ``storage.create`` / ``storage.find`` / ``storage.get`` /
  ``storage.delete`` / ``storage.init`` — the storage base layer (every
  ``Storage.get_events()`` repository call routes through these).
- ``rpc.send`` / ``rpc.recv`` — the JSON-RPC framing in the remote
  storage client (``rpc.recv`` fires AFTER the request hit the wire:
  the server may have committed, which is exactly the lost-reply case
  idempotency tokens exist for); ``rpc.dispatch`` server-side.
- ``http.event`` / ``http.engine`` — the HTTP handlers.

Injected errors raise :class:`FaultInjected` (a ``ConnectionError``), so
they travel the same except-paths a real dead backend would.  Tests and
``bench_serving.py`` can bypass the env with :func:`install`.
"""

from __future__ import annotations

import os
import random
import threading
import time
from typing import Any, Callable, List, Optional, Tuple

from predictionio_tpu.obs import get_registry

__all__ = [
    "FaultInjected",
    "FaultRule",
    "FaultPlan",
    "parse_plan",
    "install",
    "clear",
    "active",
    "fault_point",
    "wrap_events",
    "wrap_instances",
    "wrap_models",
    "wrap_spill_queues",
    "wrap_kv",
]


class FaultInjected(ConnectionError):
    """An injected fault — walks the real connection-failure paths."""

    def __init__(self, point: str):
        super().__init__(f"injected fault at {point}")
        self.point = point


class FaultRule:
    def __init__(self, match: str, kind: str, probability: float = 1.0,
                 delay_ms: float = 0.0, max_count: Optional[int] = None):
        if kind not in ("error", "delay"):
            raise ValueError(f"unknown fault kind {kind!r}")
        self.match = match
        self.kind = kind
        self.probability = float(probability)
        self.delay_ms = float(delay_ms)
        self.max_count = max_count
        self._fired = 0
        self._lock = threading.Lock()

    def matches(self, point: str) -> bool:
        if self.match.endswith("*"):
            return point.startswith(self.match[:-1])
        return point == self.match

    def try_fire(self, rng: random.Random) -> bool:
        """Atomically claim one firing (respects probability + max_count)."""
        with self._lock:
            if self.max_count is not None and self._fired >= self.max_count:
                return False
            if self.probability < 1.0 and rng.random() >= self.probability:
                return False
            self._fired += 1
            return True


class FaultPlan:
    def __init__(self, rules: List[FaultRule],
                 rng: Optional[random.Random] = None,
                 sleep: Callable[[float], None] = time.sleep):
        self.rules = list(rules)
        self.rng = rng or random.Random(
            int(os.environ["PIO_FAULTS_SEED"])
            if os.environ.get("PIO_FAULTS_SEED") else None)
        self.sleep = sleep

    def apply(self, point: str) -> None:
        for rule in self.rules:
            if not rule.matches(point) or not rule.try_fire(self.rng):
                continue
            get_registry().counter(
                "pio_faults_injected_total",
                "Faults injected by the PIO_FAULTS harness.",
                ("point", "kind")).inc(point=point, kind=rule.kind)
            if rule.kind == "delay":
                self.sleep(rule.delay_ms / 1e3)
            else:
                raise FaultInjected(point)


def _parse_duration_ms(text: str) -> float:
    t = text.strip().lower()
    if t.endswith("ms"):
        return float(t[:-2])
    if t.endswith("s"):
        return float(t[:-1]) * 1e3
    return float(t)


def parse_plan(spec: str) -> FaultPlan:
    rules: List[FaultRule] = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        if len(fields) < 2:
            raise ValueError(f"bad PIO_FAULTS rule {part!r} "
                             "(want point:kind[:args])")
        point, kind, args = fields[0], fields[1], fields[2:]
        if kind == "delay":
            if not args:
                raise ValueError(f"delay rule {part!r} needs a duration")
            delay = _parse_duration_ms(args[0])
            p = float(args[1]) if len(args) > 1 else 1.0
            mc = int(args[2]) if len(args) > 2 else None
            rules.append(FaultRule(point, "delay", p, delay, mc))
        elif kind == "error":
            p = float(args[0]) if args else 1.0
            mc = int(args[1]) if len(args) > 1 else None
            rules.append(FaultRule(point, "error", p, max_count=mc))
        else:
            raise ValueError(f"unknown fault kind in {part!r}")
    return FaultPlan(rules)


# -- process-wide plan state ------------------------------------------------

_installed: Optional[FaultPlan] = None
# (spec, plan) cache so PIO_FAULTS is re-parsed only when it changes.
_env_cache: Tuple[Optional[str], Optional[FaultPlan]] = (None, None)
_state_lock = threading.Lock()


def install(plan) -> FaultPlan:
    """Programmatic plan (tests/bench); overrides PIO_FAULTS until
    :func:`clear`.  Accepts a :class:`FaultPlan` or a spec string."""
    global _installed
    if isinstance(plan, str):
        plan = parse_plan(plan)
    with _state_lock:
        _installed = plan
    return plan


def clear() -> None:
    global _installed, _env_cache
    with _state_lock:
        _installed = None
        _env_cache = (None, None)


def _current_plan() -> Optional[FaultPlan]:
    if _installed is not None:
        return _installed
    spec = os.environ.get("PIO_FAULTS")
    if not spec:
        return None
    global _env_cache
    with _state_lock:
        if _env_cache[0] != spec:
            _env_cache = (spec, parse_plan(spec))
        return _env_cache[1]


def active() -> bool:
    return _current_plan() is not None


def fault_point(name: str) -> None:
    """Instrument a code path: no-op unless a matching rule is active."""
    plan = _current_plan()
    if plan is not None:
        plan.apply(name)


# -- storage base-layer hook ------------------------------------------------

# Repository methods share fault points by intent, not by exact name —
# ``storage.create`` covers every write path a "storage.create:error"
# rule should break, whichever insert variant the server picked.
_EVENTS_POINTS = {
    "insert": "storage.create",
    "insert_batch": "storage.create",
    "create_batch": "storage.create",
    "insert_columnar": "storage.create",
    "find": "storage.find",
    "find_columnar": "storage.find",
    "aggregate_properties": "storage.find",
    "get": "storage.get",
    "delete": "storage.delete",
    "remove": "storage.delete",
    "init": "storage.init",
}


class _FaultyRepo:
    """Transparent proxy running a fault point before each repo call."""

    def __init__(self, inner: Any, points: dict):
        self._inner = inner
        self._points = points

    def __getattr__(self, attr: str) -> Any:
        val = getattr(self._inner, attr)
        if not callable(val):
            return val
        point = self._points.get(attr, f"storage.{attr}")

        def wrapped(*args, **kwargs):
            fault_point(point)
            return val(*args, **kwargs)

        wrapped.__name__ = attr
        return wrapped


def wrap_events(events: Any) -> Any:
    """Wrap an Events repository with fault points when a plan is active
    (the storage registry calls this on every ``get_events()``, so a plan
    installed mid-process takes effect without rebuilding storage)."""
    if _current_plan() is None:
        return events
    return _FaultyRepo(events, _EVENTS_POINTS)


# Model-lifecycle repositories (ISSUE 4: the engine server's staged
# reload reads engine instances + model blobs — "storage.find:error"
# must be able to break a reload so fail-closed serving is testable).
_INSTANCES_POINTS = {
    "get": "storage.find",
    "get_all": "storage.find",
    "get_latest_completed": "storage.find",
    "get_completed": "storage.find",
    "insert": "storage.create",
    "update": "storage.create",
    "delete": "storage.delete",
}

_MODELS_POINTS = {
    "get": "storage.find",
    "insert": "storage.create",
    "delete": "storage.delete",
}


def wrap_instances(instances: Any) -> Any:
    """Fault seam over an EngineInstances repository (reload reads)."""
    if _current_plan() is None:
        return instances
    return _FaultyRepo(instances, _INSTANCES_POINTS)


def wrap_models(models: Any) -> Any:
    """Fault seam over a Models (blob store) repository (reload reads)."""
    if _current_plan() is None:
        return models
    return _FaultyRepo(models, _MODELS_POINTS)


# Shared spill backplane (ISSUE 15): every queue op is individually
# breakable so chaos tests can stage a lease steal ("spillq.lease:error"
# on one instance), an expired-lease race, or a storage error mid-ack
# ("spillq.ack:error:1.0:1" — the records stay leased, expire, and
# another drainer replays them; idempotency tokens keep that
# exactly-once).
_SPILLQ_POINTS = {
    "enqueue": "spillq.enqueue",
    "lease": "spillq.lease",
    "ack": "spillq.ack",
    "nack": "spillq.nack",
    "dead_letter": "spillq.dead_letter",
    "requeue_dead": "spillq.requeue_dead",
    "stats": "spillq.stats",
    "peek": "spillq.stats",
}

_KV_POINTS = {
    "get": "kv.get",
    "count": "kv.get",
    "put": "kv.put",
    "prune": "kv.put",
    "delete": "kv.delete",
}


def wrap_spill_queues(queues: Any) -> Any:
    """Fault seam over a SpillQueues repository (the shared backplane)."""
    if _current_plan() is None:
        return queues
    return _FaultyRepo(queues, _SPILLQ_POINTS)


def wrap_kv(kv: Any) -> Any:
    """Fault seam over a KV repository (the durable fold-in cache)."""
    if _current_plan() is None:
        return kv
    return _FaultyRepo(kv, _KV_POINTS)
