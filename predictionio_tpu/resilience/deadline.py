"""Request-deadline propagation (``X-PIO-Deadline-Ms``).

A client sends the remaining budget of its request as a header; every
server enters a :func:`deadline_scope` for the handled request, and any
layer beneath it — handler logic, the storage :class:`RemoteClient` —
can ask :func:`remaining_ms` / :func:`check` whether the work is still
worth doing.  A request that cannot finish in budget sheds early with
504 instead of queueing behind a saturated backend, which is what keeps
p99 bounded under partial failure (the tail-at-scale argument).

Scopes nest by taking the MINIMUM: an inner layer can only tighten the
budget, never extend the caller's.
"""

from __future__ import annotations

import contextlib
import contextvars
import time
from typing import Iterator, Optional

__all__ = [
    "DEADLINE_HEADER",
    "DeadlineExceeded",
    "deadline_scope",
    "remaining_ms",
    "exceeded",
    "check",
]

DEADLINE_HEADER = "X-PIO-Deadline-Ms"

# Absolute deadline in time.monotonic() seconds; None = no deadline.
_DEADLINE: contextvars.ContextVar[Optional[float]] = contextvars.ContextVar(
    "pio_deadline", default=None)


class DeadlineExceeded(RuntimeError):
    """The request's time budget ran out; mapped to HTTP 504 upstream."""

    retriable = True


@contextlib.contextmanager
def deadline_scope(budget_ms: Optional[float]) -> Iterator[None]:
    """Bound everything inside to ``budget_ms`` from now (no-op on None);
    nested scopes keep the tighter of the two deadlines."""
    if budget_ms is None:
        yield
        return
    new = time.monotonic() + max(float(budget_ms), 0.0) / 1e3
    cur = _DEADLINE.get()
    tok = _DEADLINE.set(new if cur is None else min(cur, new))
    try:
        yield
    finally:
        _DEADLINE.reset(tok)


def remaining_ms() -> Optional[float]:
    """Budget left in the current scope (may be negative); None outside."""
    d = _DEADLINE.get()
    if d is None:
        return None
    return (d - time.monotonic()) * 1e3


def exceeded() -> bool:
    r = remaining_ms()
    return r is not None and r <= 0.0


def check(what: str = "request") -> None:
    """Raise :class:`DeadlineExceeded` when the budget is spent — called
    before each unit of expensive work so a doomed request sheds instead
    of burning backend time."""
    r = remaining_ms()
    if r is not None and r <= 0.0:
        raise DeadlineExceeded(
            f"deadline exceeded before {what} ({-r:.0f}ms over budget)")
