"""Retry and circuit-breaker policies (Dean & Barroso, CACM 2013).

Both classes take injectable ``clock``/``sleep``/``rng`` so the fault
matrix in ``tests/test_resilience.py`` runs on a fake clock — tier-1
tests must not sleep for real (> 50 ms) to prove a backoff schedule.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Callable, Optional, Tuple, Type

from predictionio_tpu.obs import get_registry, publish_event

__all__ = ["RetryPolicy", "CircuitBreaker", "CircuitOpenError"]


class RetryPolicy:
    """Jittered exponential backoff, ``Retry-After``-aware.

    ``run(fn)`` retries ``fn`` up to ``max_attempts`` times.  A raised
    exception is retried when ``retriable(exc)`` says so (default: a
    truthy ``exc.retriable`` attribute, else not retried).  When the
    exception carries ``retry_after_s`` (parsed from an HTTP
    ``Retry-After`` header or a breaker's remaining recovery time), that
    server-provided hint replaces the computed backoff, capped at
    ``retry_after_cap_ms``.
    """

    def __init__(self, max_attempts: int = 3, base_delay_ms: float = 50.0,
                 max_delay_ms: float = 5_000.0, multiplier: float = 2.0,
                 jitter: float = 0.25, retry_after_cap_ms: float = 30_000.0,
                 sleep: Callable[[float], None] = time.sleep,
                 rng: Optional[random.Random] = None):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        self.max_attempts = int(max_attempts)
        self.base_delay_ms = float(base_delay_ms)
        self.max_delay_ms = float(max_delay_ms)
        self.multiplier = float(multiplier)
        self.jitter = float(jitter)
        self.retry_after_cap_ms = float(retry_after_cap_ms)
        self._sleep = sleep
        self._rng = rng or random.Random()

    def backoff_ms(self, attempt: int,
                   retry_after_ms: Optional[float] = None) -> float:
        """Delay before retry number ``attempt`` (0-based)."""
        if retry_after_ms is not None:
            return min(max(retry_after_ms, 0.0), self.retry_after_cap_ms)
        d = min(self.max_delay_ms,
                self.base_delay_ms * (self.multiplier ** attempt))
        if self.jitter:
            d *= 1.0 + self._rng.uniform(-self.jitter, self.jitter)
        return max(d, 0.0)

    def sleep_backoff(self, attempt: int,
                      retry_after_ms: Optional[float] = None) -> float:
        ms = self.backoff_ms(attempt, retry_after_ms)
        self._sleep(ms / 1e3)
        return ms

    def run(self, fn: Callable[[], Any], *,
            retriable: Optional[Callable[[BaseException], bool]] = None,
            on_retry: Optional[Callable[[int, BaseException], None]] = None,
            deadline_ts: Optional[float] = None,
            clock: Callable[[], float] = time.monotonic) -> Any:
        """``deadline_ts`` (absolute ``clock()`` seconds) bounds the WHOLE
        run: when the computed backoff — including a server's Retry-After
        hint, which can be far larger than any budget — would sleep past
        it, the last failure is re-raised immediately instead of
        sleeping through a budget that is already lost."""
        if retriable is None:
            retriable = lambda e: bool(getattr(e, "retriable", False))  # noqa: E731
        for attempt in range(self.max_attempts):
            try:
                return fn()
            except Exception as e:
                if attempt == self.max_attempts - 1 or not retriable(e):
                    raise
                ra = getattr(e, "retry_after_s", None)
                backoff_ms = self.backoff_ms(
                    attempt, None if ra is None else float(ra) * 1e3)
                if deadline_ts is not None and \
                        clock() + backoff_ms / 1e3 >= deadline_ts:
                    raise
                if on_retry is not None:
                    on_retry(attempt, e)
                self._sleep(backoff_ms / 1e3)


class CircuitOpenError(RuntimeError):
    """Shed without touching the backend: the breaker is open."""

    retriable = True

    def __init__(self, name: str, retry_after_s: float):
        super().__init__(
            f"circuit breaker {name!r} is open "
            f"(retry in ~{retry_after_s:.1f}s)")
        self.breaker = name
        self.retry_after_s = retry_after_s


# pio_breaker_state gauge encoding
_STATE_VALUE = {"closed": 0, "half-open": 1, "open": 2}


class CircuitBreaker:
    """closed → open after ``failure_threshold`` consecutive failures;
    open → half-open after ``recovery_time_s``; half-open → closed after
    ``half_open_successes`` successful probes (one failure re-opens).

    Only exceptions matching ``failure_types`` count as failures — a
    validation error must not open the breaker that guards availability.
    State is exported as ``pio_breaker_state{breaker=<name>}``
    (0 closed / 1 half-open / 2 open).
    """

    def __init__(self, name: str, failure_threshold: int = 5,
                 recovery_time_s: float = 10.0,
                 half_open_successes: int = 1,
                 failure_types: Tuple[Type[BaseException], ...] = (Exception,),
                 clock: Callable[[], float] = time.monotonic,
                 registry=None):
        self.name = name
        self.failure_threshold = max(1, int(failure_threshold))
        self.recovery_time_s = float(recovery_time_s)
        self.half_open_successes = max(1, int(half_open_successes))
        self.failure_types = failure_types
        self._clock = clock
        self._lock = threading.Lock()
        self._state = "closed"
        self._failures = 0
        self._successes = 0
        self._opened_at: Optional[float] = None
        reg = registry or get_registry()
        self._gauge = reg.gauge(
            "pio_breaker_state",
            "Circuit breaker state: 0 closed, 1 half-open, 2 open.",
            ("breaker",))
        self._transitions = reg.counter(
            "pio_breaker_transitions_total",
            "Circuit breaker state transitions.", ("breaker", "to"))
        self._gauge.set(0, breaker=name)

    # -- state machine (call with self._lock held) -------------------------

    def _set_state(self, state: str) -> None:
        if state == self._state:
            return
        prev, self._state = self._state, state
        self._gauge.set(_STATE_VALUE[state], breaker=self.name)
        self._transitions.inc(breaker=self.name, to=state)
        # Trace-ring correlation (obs.runtime): a request that trips or
        # probes the breaker carries the transition in its span tree, so
        # resilience incidents join up with request ids.
        publish_event("breaker.transition", breaker=self.name,
                      to=state, **{"from": prev})

    def _tick(self) -> None:
        if self._state == "open" and self._opened_at is not None and \
                self._clock() - self._opened_at >= self.recovery_time_s:
            self._successes = 0
            self._set_state("half-open")

    # -- public API --------------------------------------------------------

    @property
    def state(self) -> str:
        with self._lock:
            self._tick()
            return self._state

    def retry_after_s(self) -> float:
        """Seconds until the next probe is allowed (0 when not open)."""
        with self._lock:
            self._tick()
            if self._state != "open" or self._opened_at is None:
                return 0.0
            return max(
                0.0,
                self.recovery_time_s - (self._clock() - self._opened_at))

    def allow(self) -> bool:
        with self._lock:
            self._tick()
            return self._state != "open"

    def record_success(self) -> None:
        with self._lock:
            self._tick()
            self._failures = 0
            if self._state == "half-open":
                self._successes += 1
                if self._successes >= self.half_open_successes:
                    self._set_state("closed")
            elif self._state == "closed":
                pass  # steady state

    def record_failure(self) -> None:
        with self._lock:
            self._tick()
            if self._state == "half-open":
                self._opened_at = self._clock()
                self._set_state("open")
                return
            self._failures += 1
            if self._state == "closed" and \
                    self._failures >= self.failure_threshold:
                self._opened_at = self._clock()
                self._set_state("open")

    def call(self, fn: Callable[..., Any], *args, **kwargs) -> Any:
        """Run ``fn`` under the breaker; shed with
        :class:`CircuitOpenError` when open."""
        if not self.allow():
            raise CircuitOpenError(self.name, self.retry_after_s())
        try:
            out = fn(*args, **kwargs)
        except self.failure_types:
            self.record_failure()
            raise
        except BaseException:
            raise  # non-availability errors are neutral: no state change
        self.record_success()
        return out
