"""Storage-outage spill journal + recovery replay.

When the event store is unreachable (breaker open or a write fails with
an availability error), the event server must not 500 and silently drop
the batch — ingest durability is the product promise.  Instead the
failed write's events are appended to a durable JSONL journal on local
disk, the client gets **202 + Retry-After**, and a background
:class:`ReplayWorker` drains the journal into storage once it recovers.

Journal layout (``PIO_SPILL_DIR``, default ``$PIO_HOME/spill``)::

    spill.jsonl       one record per FAILED WRITE (a single insert or a
                      whole batch), carrying the idempotency token that
                      write was issued under:
                      {"token": ..., "appId": ..., "channelId": ...,
                       "events": [{...}, ...]}
    spill.offset      count of leading records already replayed
    spill.dead.jsonl  dead-lettered records (permanent replay failures)

Records keep the ORIGINAL write's idempotency token so replay re-issues
the semantically identical request: if the outage was really a lost
reply (the backend committed before the connection died), the storage
server's dedup window answers the replay without re-inserting.  Records
are only marked replayed AFTER the insert succeeds (advance the offset,
never rewrite history), so a crash mid-replay re-runs at-least-once and
the token turns that into exactly-once against dedup-capable backends.

A partial trailing line (crash mid-append, before the fsync returned and
therefore before any 202 was sent) is truncated away at open.  A record
that fails replay with a PERMANENT error (validation, schema drift) is
dead-lettered — logged, counted, moved to ``spill.dead.jsonl`` — instead
of blocking every record behind it forever.

Metrics: ``pio_spill_queue_depth`` (gauge, in events),
``pio_spill_spilled_total`` / ``pio_spill_replayed_total`` /
``pio_spill_dead_lettered_total`` (counters, in events).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import uuid
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Tuple, Type

from predictionio_tpu.obs import get_registry, publish_event
from predictionio_tpu.resilience.policy import CircuitOpenError

logger = logging.getLogger(__name__)

__all__ = ["SpillJournal", "ReplayWorker", "resolve_spill_dir",
           "journal_summary"]

_DISABLED = ("off", "none", "disabled", "0")


def resolve_spill_dir(explicit: Optional[str], home: Optional[Path]
                      ) -> Optional[Path]:
    """Spill directory per precedence: explicit arg > ``PIO_SPILL_DIR``
    env > ``<home>/spill``; the sentinel values off/none/disabled/0 (or
    no resolvable home) disable spilling entirely."""
    cand = explicit if explicit is not None else os.environ.get("PIO_SPILL_DIR")
    if cand is not None:
        return None if cand.strip().lower() in _DISABLED or not cand.strip() \
            else Path(cand)
    return Path(home) / "spill" if home else None


class SpillJournal:
    """Durable append-only JSONL queue with a persisted replay offset.

    One record per failed write; ``depth()`` counts pending EVENTS (what
    operators care about), the offset counts records.

    ``divert_if_locked=False`` (the ``pio spill`` manual-ops path) turns
    the locked-directory divert into a hard error instead — an operator
    draining a journal wants THE journal, not a fresh private one."""

    def __init__(self, directory: Path, registry=None, *,
                 divert_if_locked: bool = True):
        base = Path(directory)
        base.mkdir(parents=True, exist_ok=True)
        # Cross-process exclusion: the journal format assumes a SINGLE
        # appender/replayer.  First comer flocks the directory; any other
        # process (second event server on the same PIO_HOME) diverts to a
        # private instance-<pid>-<rand> subdirectory so neither can
        # truncate under the other or double-replay the same records.
        self._lock_f = None
        self._divert_if_locked = divert_if_locked
        self.dir = self._acquire_dir(base)
        self.path = self.dir / "spill.jsonl"
        self.offset_path = self.dir / "spill.offset"
        self.dead_path = self.dir / "spill.dead.jsonl"
        self._lock = threading.RLock()
        reg = registry or get_registry()
        self._depth_gauge = reg.gauge(
            "pio_spill_queue_depth",
            "Spilled events awaiting replay into storage.")
        self._spilled = reg.counter(
            "pio_spill_spilled_total",
            "Events diverted to the spill journal during storage outages.")
        self._replayed = reg.counter(
            "pio_spill_replayed_total",
            "Spilled events successfully replayed into storage.")
        self._dead = reg.counter(
            "pio_spill_dead_lettered_total",
            "Spilled events moved to the dead-letter file after a "
            "permanent replay failure.")
        self._offset = 0
        if self.offset_path.exists():
            try:
                self._offset = int(self.offset_path.read_text().strip() or 0)
            except ValueError:
                self._offset = 0
        self._count = 0            # valid records on disk
        self._pending_events = 0   # events in records past the offset
        self._read_pos = 0         # byte position of record #_offset
        self._recover()
        self._f = open(self.path, "a", encoding="utf-8")
        self._depth_gauge.set(self._pending_events)
        # Trace-ring incident record: a journal opening with backlog is
        # the first sign of a prior outage/crash worth correlating.
        publish_event("spill.open", dir=str(self.dir),
                      pendingEvents=self._pending_events)

    def _acquire_dir(self, base: Path) -> Path:
        try:
            import fcntl
        except ImportError:  # non-posix: single-instance risk accepted
            return base
        f = open(base / ".lock", "a")
        try:
            fcntl.flock(f, fcntl.LOCK_EX | fcntl.LOCK_NB)
            self._lock_f = f
            return base
        except OSError:
            f.close()
        if not self._divert_if_locked:
            raise RuntimeError(
                f"spill journal {base} is locked by a running event "
                "server — stop it (or point --dir at its private "
                "instance-* directory) before draining/requeueing")
        inst = base / f"instance-{os.getpid()}-{uuid.uuid4().hex[:8]}"
        inst.mkdir(parents=True, exist_ok=True)
        logger.warning(
            "spill journal %s is locked by another instance; using "
            "private directory %s (its records replay only while THIS "
            "process lives — prefer one event server per PIO_SPILL_DIR)",
            base, inst)
        f = open(inst / ".lock", "a")
        fcntl.flock(f, fcntl.LOCK_EX | fcntl.LOCK_NB)  # fresh dir: free
        self._lock_f = f
        return inst

    def _recover(self) -> None:
        """Count records/pending events; truncate a partial trailing line
        (crash mid-append — its 202 was never sent, dropping it is safe);
        reconcile a stale offset file (crash between drain-truncate and
        offset reset) so the journal can never wedge."""
        if not self.path.exists():
            return
        valid_bytes = 0
        with open(self.path, "rb") as f:
            data = f.read()
        pos = 0
        while pos < len(data):
            nl = data.find(b"\n", pos)
            if nl < 0:
                break  # no terminator: partial trailing line
            line = data[pos:nl].strip()
            if line:
                try:
                    rec = json.loads(line)
                    n_events = len(rec["events"])
                except (ValueError, KeyError, TypeError):
                    # corruption mid-file cannot happen with our single
                    # appender; treat everything from here as the torn tail
                    break
                if self._count >= self._offset:
                    self._pending_events += n_events
                self._count += 1
                if self._count == self._offset:
                    self._read_pos = nl + 1
            pos = nl + 1
            valid_bytes = pos
        if valid_bytes < len(data):
            logger.warning("spill journal: truncating %d torn byte(s) at "
                           "the tail of %s", len(data) - valid_bytes,
                           self.path)
            with open(self.path, "r+b") as f:
                f.truncate(valid_bytes)
        if self._offset > self._count:
            # stale offset file outliving a drain-truncate: without this
            # clamp peek() would skip PAST every future record forever
            logger.warning("spill journal: clamping stale offset %d to "
                           "%d record(s)", self._offset, self._count)
            self._offset = self._count
            self._read_pos = valid_bytes

    def depth(self) -> int:
        """Events (not records) awaiting replay."""
        with self._lock:
            return self._pending_events

    def append(self, events_json: List[Dict[str, Any]], app_id: int,
               channel_id: Optional[int], token: Optional[str] = None,
               tokens: Optional[List[str]] = None) -> str:
        """Durably queue one failed write (1..n events) under the
        idempotency token that write was issued with; returns the token.

        ``tokens`` (ISSUE 17) carries the bulk endpoint's PER-ITEM
        sub-tokens: replay then lands through ``create_batch`` with ids
        derived from them, so a batch that partially committed before
        the crash dedups row-by-row instead of all-or-nothing."""
        token = token or uuid.uuid4().hex
        record = {"token": token, "appId": app_id, "channelId": channel_id,
                  "events": list(events_json)}
        if tokens is not None:
            record["tokens"] = list(tokens)
        line = json.dumps(record, separators=(",", ":"))
        with self._lock:
            # Remember the pre-write size and roll back to it if the
            # write/flush/fsync fails: a half-durable line that the
            # in-memory accounting never counted would desynchronize the
            # position-based peek()/advance() from the file and could
            # truncate a LATER acked record unreplayed.
            pos = self._f.seek(0, os.SEEK_END)
            try:
                self._f.write(line + "\n")
                self._f.flush()
                os.fsync(self._f.fileno())
            except OSError:
                try:
                    self._f.truncate(pos)
                except OSError:
                    logger.exception(
                        "spill journal rollback failed; closing the "
                        "journal (fails ingest to 503 rather than "
                        "risking misaligned replay)")
                    self._f.close()
                raise
            self._count += 1
            self._pending_events += len(record["events"])
            self._depth_gauge.set(self._pending_events)
        self._spilled.inc(len(record["events"]))
        # Inside the ingest request's trace: THIS request degraded to the
        # journal — the 202 in the ring explains itself.
        publish_event("spill.append", token=token,
                      events=len(record["events"]),
                      pendingEvents=self.depth())
        return token

    def peek(self, n: int) -> List[Dict[str, Any]]:
        """Next ``n`` unreplayed records (oldest first).  Seeks straight
        to the current offset's byte position — no rescan of the
        already-replayed prefix, so a large-outage drain stays O(n)."""
        with self._lock:
            out: List[Dict[str, Any]] = []
            remaining = self._count - self._offset
            with open(self.path, "rb") as f:
                f.seek(self._read_pos)
                while len(out) < min(n, remaining):
                    line = f.readline()
                    if not line.endswith(b"\n"):
                        break  # torn tail (pre-truncation) — never acked
                    if line.strip():
                        out.append(json.loads(line))
            return out

    def _advance(self, records: List[Dict[str, Any]]) -> None:
        """Move the durable offset past ``records``; a fully drained
        journal truncates back to empty (call with the lock held)."""
        self._offset += len(records)
        self._pending_events -= sum(len(r["events"]) for r in records)
        if self._offset >= self._count:
            # Reset the offset file BEFORE truncating: a crash in between
            # then re-replays from 0 (at-least-once, token-dedup'd) rather
            # than leaving a stale offset pointing past an empty file.
            self.offset_path.unlink(missing_ok=True)
            self._f.close()
            self._f = open(self.path, "w", encoding="utf-8")
            self._offset = 0
            self._count = 0
            self._pending_events = 0
            self._read_pos = 0
        else:
            with open(self.path, "rb") as f:
                f.seek(self._read_pos)
                for _ in range(len(records)):
                    f.readline()
                self._read_pos = f.tell()
            tmp = self.offset_path.with_suffix(".offset.tmp")
            tmp.write_text(str(self._offset))
            tmp.replace(self.offset_path)
        self._depth_gauge.set(self._pending_events)

    def mark_replayed(self, records: List[Dict[str, Any]]) -> None:
        if not records:
            return
        with self._lock:
            self._advance(records)
        n = sum(len(r["events"]) for r in records)
        self._replayed.inc(n)
        publish_event("spill.replayed", events=n,
                      pendingEvents=self.depth())

    def dead_letter(self, record: Dict[str, Any], reason: str) -> None:
        """Skip a permanently unreplayable record: persist it to the
        dead-letter file for the operator, advance past it."""
        logger.error("spill replay dead-lettering %d event(s) "
                     "(token %s): %s", len(record["events"]),
                     record.get("token"), reason)
        with self._lock:
            with open(self.dead_path, "a", encoding="utf-8") as f:
                f.write(json.dumps({"reason": reason, **record},
                                   separators=(",", ":")) + "\n")
            self._advance([record])
        self._dead.inc(len(record["events"]))
        publish_event("spill.dead_letter", token=record.get("token"),
                      events=len(record["events"]), reason=reason)

    def dead_records(self) -> List[Dict[str, Any]]:
        """Parse the dead-letter file (operator inspection/requeue)."""
        if not self.dead_path.exists():
            return []
        out: List[Dict[str, Any]] = []
        with open(self.dead_path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    logger.warning("spill dead-letter file has an "
                                   "unparseable line; skipping it")
        return out

    def requeue_dead(self) -> int:
        """Move every dead-lettered record back into the live journal
        (``pio spill requeue-dead`` — after the operator fixed whatever
        made replay reject them).  Each record re-queues under its
        ORIGINAL idempotency token, so a record that was dead-lettered
        for a transient miscategorized as permanent still dedups.
        Returns the number of EVENTS requeued."""
        records = self.dead_records()
        n_events = 0
        for rec in records:
            self.append(rec["events"], rec["appId"], rec.get("channelId"),
                        token=rec.get("token"))
            n_events += len(rec["events"])
        if records:
            self.dead_path.unlink()
            publish_event("spill.requeue_dead", records=len(records),
                          events=n_events)
        return n_events

    def close(self) -> None:
        with self._lock:
            try:
                self._f.flush()
                os.fsync(self._f.fileno())
            except (OSError, ValueError):
                pass
            self._f.close()
            if self._lock_f is not None:
                self._lock_f.close()  # releases the flock
                self._lock_f = None


def journal_summary(directory: Path) -> Dict[str, Any]:
    """Read-only spill-journal summary (``pio spill inspect``) — parses
    the files directly, takes NO lock, never mutates: safe to run while
    the owning event server is live (the numbers are a point-in-time
    snapshot)."""
    d = Path(directory)
    path, offset_path, dead_path = (d / "spill.jsonl", d / "spill.offset",
                                    d / "spill.dead.jsonl")
    offset = 0
    if offset_path.exists():
        try:
            offset = int(offset_path.read_text().strip() or 0)
        except ValueError:
            offset = 0
    records = pending_events = 0
    tokens: List[str] = []
    if path.exists():
        with open(path, "rb") as f:
            for line in f:
                if not line.endswith(b"\n") or not line.strip():
                    continue  # torn tail / blank
                try:
                    rec = json.loads(line)
                    n = len(rec["events"])
                except (ValueError, KeyError, TypeError):
                    continue
                records += 1
                if records > offset:
                    pending_events += n
                    if len(tokens) < 5:
                        tokens.append(rec.get("token"))
    dead_records = dead_events = 0
    if dead_path.exists():
        with open(dead_path, "rb") as f:
            for line in f:
                if not line.strip():
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                dead_records += 1
                dead_events += len(rec.get("events", []))
    instances = sorted(p.name for p in d.glob("instance-*") if p.is_dir())
    return {
        "dir": str(d),
        "records": records,
        "replayedOffset": min(offset, records),
        "pendingRecords": max(records - offset, 0),
        "pendingEvents": pending_events,
        "pendingTokens": tokens,
        "deadRecords": dead_records,
        "deadEvents": dead_events,
        "privateInstanceDirs": instances,
    }


# Replay failures that mean "storage still down, try again next tick" —
# anything else is permanent for that record and dead-letters it.
_DEFAULT_TRANSIENT: Tuple[Type[BaseException], ...] = (
    CircuitOpenError, ConnectionError, OSError)


class ReplayWorker:
    """Background thread draining a :class:`SpillJournal` into storage.

    ``insert_fn(record)`` performs one write (the event server routes it
    through its circuit breaker, making this worker the natural half-open
    prober).  A ``transient_types`` failure pauses the drain until the
    next tick; any other exception dead-letters THAT record and keeps
    draining — one poison record must not wedge the queue.  The journal
    only advances past records that landed (or were dead-lettered)."""

    def __init__(self, journal: SpillJournal,
                 insert_fn: Callable[[Dict[str, Any]], Any],
                 interval_s: float = 0.25, batch: int = 100,
                 transient_types: Tuple[Type[BaseException], ...]
                 = _DEFAULT_TRANSIENT,
                 wait: Optional[Callable[[threading.Event, float], bool]]
                 = None):
        self.journal = journal
        self.insert_fn = insert_fn
        self.interval_s = float(interval_s)
        self.batch = int(batch)
        self.transient_types = transient_types
        self._stop = threading.Event()
        # Injectable tick wait (ISSUE 9 deflake satellite): the default
        # rides the stop event's wall-clock wait; tests inject a waiter
        # that parks the thread (or advances a fake clock) so replay
        # timing is driven deterministically — the same injectable-clock
        # discipline as serving.queue.Clock / CircuitBreaker.
        self._wait = wait if wait is not None else \
            (lambda ev, timeout: ev.wait(timeout))
        self._thread = threading.Thread(
            target=self._run, name="pio-spill-replay", daemon=True)

    def start(self) -> None:
        self._thread.start()

    def _run(self) -> None:
        while not self._wait(self._stop, self.interval_s):
            try:
                self.drain_once()
            except Exception:
                # belt-and-suspenders: a surprise here must not kill the
                # only thread that can ever drain the journal
                logger.exception("spill replay tick failed")

    def drain_once(self) -> int:
        """Replay as much as currently possible; returns events landed."""
        landed = 0
        while not self._stop.is_set():
            if self.journal.depth() == 0:
                break
            records = self.journal.peek(self.batch)
            if not records:
                break
            done: List[Dict[str, Any]] = []
            paused = False
            for rec in records:
                try:
                    self.insert_fn(rec)
                except self.transient_types as e:
                    logger.debug("spill replay paused after %d/%d: %s",
                                 len(done), len(records), e)
                    paused = True
                    break
                except Exception as e:
                    # flush what landed so the dead-letter advance (which
                    # also moves the offset) stays in order
                    self.journal.mark_replayed(done)
                    landed += sum(len(r["events"]) for r in done)
                    done = []
                    self.journal.dead_letter(rec, f"{type(e).__name__}: {e}")
                else:
                    done.append(rec)
            self.journal.mark_replayed(done)
            landed += sum(len(r["events"]) for r in done)
            if paused:
                break
        return landed

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout)
        self.journal.close()
