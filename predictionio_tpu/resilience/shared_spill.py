"""Shared spill backplane: the fleet-scale durable home for failed
event writes (ISSUE 15).

The PR-2 :class:`~predictionio_tpu.resilience.spill.SpillJournal` is a
per-instance JSONL file: durable, but a crashed event server strands its
journaled events until THAT box comes back.  This module moves the
durable home into the storage layer's shared queue
(:class:`~predictionio_tpu.data.storage.base.SpillQueues` — sqlite /
memory / pioserver all implement it), with lease/ack semantics:

- every instance's failed writes land in ONE queue, under the original
  write's idempotency token (enqueue is token-idempotent, so a lost
  enqueue reply resent by a retry cannot duplicate the record);
- a :class:`LeaseDrainer` on ANY instance leases a batch with a TTL,
  replays it into storage, and acks; a drainer that crashes mid-lease
  simply stops renewing — the lease expires and another instance's
  drainer re-leases the batch.  Replay is at-least-once by construction
  and exactly-once against dedup-capable backends because every replay
  re-issues the identical write under the record's pinned token;
- transient replay failures (storage still down) nack the untouched
  records back to pending; permanent failures dead-letter THAT record
  and keep draining — one poison record must not wedge the fleet's
  queue (the PR-2 contract, carried over).

Backend selection (``PIO_SPILL_BACKEND``):

- ``local`` — the PR-2 journal only (single-instance default shape);
- ``shared`` — the storage-backed queue, with the local journal kept as
  the LAST-RESORT spill-of-the-spill: when storage itself is the outage
  the shared enqueue fails too, and the failed write degrades to the
  local file exactly as before;
- ``auto`` (default) — ``shared`` when the EVENTDATA source is a
  genuinely out-of-process store (``pioserver``), ``local`` otherwise.
  A sqlite fleet sharing one database file opts in explicitly with
  ``PIO_SPILL_BACKEND=shared``.
"""

from __future__ import annotations

import logging
import os
import threading
import time
import uuid
from typing import Any, Callable, Dict, List, Optional, Tuple, Type

from predictionio_tpu.obs import get_registry, publish_event
from predictionio_tpu.resilience.policy import CircuitOpenError

logger = logging.getLogger(__name__)

__all__ = ["SharedSpillQueue", "LeaseDrainer", "resolve_spill_backend",
           "SPILL_QUEUE_NAME"]

# One logical queue for event-write spill; other subsystems may claim
# their own names on the same SpillQueues repo later.
SPILL_QUEUE_NAME = "events"

_BACKENDS = ("auto", "local", "shared")


def resolve_spill_backend(explicit: Optional[str],
                          eventdata_type: Optional[str]) -> str:
    """``local`` or ``shared`` per precedence: explicit arg >
    ``PIO_SPILL_BACKEND`` env > ``auto``.  ``auto`` resolves to shared
    only for an out-of-process EVENTDATA source (pioserver) — the one
    shape where N instances already share a store across boxes."""
    raw = (explicit if explicit is not None
           else os.environ.get("PIO_SPILL_BACKEND", "auto"))
    raw = (raw or "auto").strip().lower()
    if raw not in _BACKENDS:
        logger.warning("PIO_SPILL_BACKEND=%r is not auto|local|shared — "
                       "falling back to auto", raw)
        raw = "auto"
    if raw == "auto":
        return "shared" if eventdata_type == "pioserver" else "local"
    return raw


class SharedSpillQueue:
    """Journal-shaped facade over a storage ``SpillQueues`` repo.

    Mirrors the :class:`SpillJournal` operator surface (append / depth /
    dead_records / requeue_dead / summary) so the event server and ``pio
    spill`` treat both homes uniformly, and adds the lease-cycle verbs
    the :class:`LeaseDrainer` runs.  The repo handle is re-fetched from
    ``storage`` per call — the registry re-wraps fault seams per call,
    and a remote client reconnects lazily."""

    def __init__(self, storage, registry=None,
                 clock: Callable[[], float] = time.time,
                 queue: str = SPILL_QUEUE_NAME):
        self._storage = storage
        self._clock = clock
        self.queue = queue
        # Last depth a real read observed: health endpoints report THIS
        # (cached_depth) instead of issuing a storage RPC — a /ready
        # probe must never block on the very storage whose outage the
        # queue exists to absorb.
        self._last_depth = 0
        reg = registry or get_registry()
        self._depth_gauge = reg.gauge(
            "pio_spill_shared_depth",
            "Events pending (or leased) in the shared spill queue.")
        self._spilled = reg.counter(
            "pio_spill_shared_spilled_total",
            "Events enqueued to the shared spill queue during storage "
            "outages.")
        self._replayed = reg.counter(
            "pio_spill_shared_replayed_total",
            "Shared-queue events successfully replayed into storage.")
        self._dead = reg.counter(
            "pio_spill_shared_dead_total",
            "Shared-queue events dead-lettered after a permanent replay "
            "failure.")
        self._lease_lost = reg.counter(
            "pio_spill_lease_lost_total",
            "Leased records another drainer took over after this "
            "instance's lease expired (detected at ack).")

    def _repo(self):
        return self._storage.get_spill_queues()

    # -- journal-shaped surface ---------------------------------------------

    def append(self, events_json: List[Dict[str, Any]], app_id: int,
               channel_id: Optional[int], token: Optional[str] = None,
               tokens: Optional[List[str]] = None) -> str:
        """Durably enqueue one failed write under its idempotency token.
        Raises on storage failure — the caller (event server) degrades
        to the local journal, the spill-of-the-spill.  ``tokens`` are the
        bulk endpoint's per-item sub-tokens (see SpillJournal.append)."""
        token = token or uuid.uuid4().hex
        record = {"token": token, "appId": app_id, "channelId": channel_id,
                  "events": list(events_json)}
        if tokens is not None:
            record["tokens"] = list(tokens)
        self._repo().enqueue(self.queue, record, token=token,
                             events=len(record["events"]),
                             now_s=self._clock())
        self._spilled.inc(len(record["events"]))
        # Incremental depth bump — NO stats round-trip on the degraded
        # request path (it already paid the enqueue RPC); the drainer's
        # end-of-tick refresh reconciles against the real queue.
        self._last_depth += len(record["events"])
        self._depth_gauge.set(self._last_depth)
        publish_event("spill.shared.append", token=token,
                      events=len(record["events"]))
        return token

    def depth(self) -> int:
        """Events not yet replayed (pending + leased), fleet-wide.
        One storage read — health/status paths use :meth:`cached_depth`
        instead."""
        st = self.stats()
        d = int(st.get("pendingEvents", 0)) + \
            int(st.get("leasedEvents", 0))
        self._last_depth = d
        return d

    def cached_depth(self) -> int:
        """The last observed depth, NO storage round-trip — refreshed by
        every append, drain tick, and explicit :meth:`depth` read."""
        return self._last_depth

    def stats(self) -> Dict[str, Any]:
        return self._repo().stats(self.queue, now_s=self._clock())

    def dead_records(self) -> List[Dict[str, Any]]:
        return [r.payload for r in
                self._repo().peek(self.queue, n=1_000_000, state="dead")]

    def requeue_dead(self) -> int:
        n = self._repo().requeue_dead(self.queue)
        if n:
            publish_event("spill.shared.requeue_dead", events=n)
        self._publish_depth()
        return n

    def _publish_depth(self) -> None:
        try:
            self._depth_gauge.set(self.depth())
        except Exception:  # depth is observability, never the hot path
            logger.debug("shared spill depth probe failed", exc_info=True)

    # -- lease cycle (the drainer's verbs) ----------------------------------

    def lease(self, owner: str, n: int, ttl_s: float):
        return self._repo().lease(self.queue, owner, n, ttl_s,
                                  now_s=self._clock())

    def ack(self, ids: List[str], owner: str) -> int:
        got = self._repo().ack(self.queue, ids, owner)
        if got < len(ids):
            # Some leases expired and were re-leased elsewhere mid-replay:
            # those records will be replayed again by the new owner, and
            # the idempotency tokens make that a no-op server-side.
            self._lease_lost.inc(len(ids) - got)
        return got

    def nack(self, ids: List[str], owner: str) -> int:
        return self._repo().nack(self.queue, ids, owner)

    def dead_letter(self, record, owner: str, reason: str) -> bool:
        ok = self._repo().dead_letter(self.queue, record.id, owner, reason)
        if ok:
            self._dead.inc(record.events)
            publish_event("spill.shared.dead_letter",
                          token=record.token, events=record.events,
                          reason=reason[:200])
        return ok

    def note_replayed(self, n_events: int) -> None:
        self._replayed.inc(n_events)


# Same transient taxonomy as the local ReplayWorker: these mean "storage
# still down, try next tick"; anything else dead-letters the record.
_DEFAULT_TRANSIENT: Tuple[Type[BaseException], ...] = (
    CircuitOpenError, ConnectionError, OSError)


class LeaseDrainer:
    """Background lease→replay→ack worker over a :class:`SharedSpillQueue`.

    Any fleet instance runs one; the queue's lease TTL is the crash
    contract — a drainer that dies mid-batch leaves its records leased,
    they expire after ``lease_ttl_s``, and a peer's next lease picks them
    up.  ``insert_fn(payload)`` performs one replay write (the event
    server routes it through its breaker and pins the record's token via
    ``idempotency_key``)."""

    def __init__(self, shared: SharedSpillQueue,
                 insert_fn: Callable[[Dict[str, Any]], Any],
                 owner: Optional[str] = None, *,
                 interval_s: float = 0.5, batch: int = 100,
                 lease_ttl_s: Optional[float] = None,
                 transient_types: Tuple[Type[BaseException], ...]
                 = _DEFAULT_TRANSIENT,
                 wait: Optional[Callable[[threading.Event, float], bool]]
                 = None):
        self.shared = shared
        self.insert_fn = insert_fn
        self.owner = owner or f"{os.getpid()}-{uuid.uuid4().hex[:8]}"
        self.interval_s = float(interval_s)
        self.batch = int(batch)
        self.lease_ttl_s = float(
            lease_ttl_s if lease_ttl_s is not None
            else os.environ.get("PIO_SPILL_LEASE_TTL_S", "30"))
        self.transient_types = transient_types
        self._stop = threading.Event()
        self._wait = wait if wait is not None else \
            (lambda ev, timeout: ev.wait(timeout))
        self._thread = threading.Thread(
            target=self._run, name="pio-spill-lease-drain", daemon=True)

    def start(self) -> None:
        self._thread.start()

    def _run(self) -> None:
        while not self._wait(self._stop, self.interval_s):
            try:
                self.drain_once()
            except Exception:
                # The drainer must outlive any surprise — it may be the
                # only instance currently draining the fleet's queue.
                logger.exception("shared spill drain tick failed")

    def drain_once(self) -> int:
        """Lease and replay as much as currently possible; returns events
        landed.  A transient failure nacks the untouched remainder (so a
        recovered peer can drain it immediately instead of waiting out
        this instance's lease) and pauses until the next tick."""
        try:
            return self._drain_once_inner()
        finally:
            # Refresh the cached depth ONCE per tick, even when this
            # instance leased nothing — a PEER draining the queue must
            # not leave this instance's /ready and the fleet status
            # reporting phantom backlog forever.
            self.shared._publish_depth()

    def _drain_once_inner(self) -> int:
        landed = 0
        while not self._stop.is_set():
            try:
                records = self.shared.lease(self.owner, self.batch,
                                            self.lease_ttl_s)
            except Exception as e:
                logger.debug("shared spill lease failed: %s", e)
                break
            if not records:
                break
            done_ids: List[str] = []
            batch_events = 0
            paused = False
            for i, rec in enumerate(records):
                try:
                    self.insert_fn(rec.payload)
                except self.transient_types as e:
                    logger.debug("shared spill replay paused after "
                                 "%d/%d: %s", i, len(records), e)
                    try:
                        self.shared.nack([r.id for r in records[i:]],
                                         self.owner)
                    except Exception:
                        logger.debug("shared spill nack failed "
                                     "(leases will expire)",
                                     exc_info=True)
                    paused = True
                    break
                except Exception as e:
                    try:
                        self.shared.dead_letter(
                            rec, self.owner, f"{type(e).__name__}: {e}")
                    except Exception:
                        logger.debug("dead-letter failed (lease will "
                                     "expire and replay retries)",
                                     exc_info=True)
                else:
                    done_ids.append(rec.id)
                    batch_events += rec.events
            if done_ids:
                try:
                    acked = self.shared.ack(done_ids, self.owner)
                except Exception:
                    # Storage error mid-ack: the records stay leased and
                    # expire; a peer re-replays them and the idempotency
                    # tokens dedup.  Never fatal.
                    logger.warning("shared spill ack failed — records "
                                   "re-lease after TTL and replay dedups "
                                   "by token", exc_info=True)
                    acked = 0
                if acked:
                    self.shared.note_replayed(batch_events)
                    publish_event("spill.shared.replayed",
                                  events=batch_events, owner=self.owner)
                landed += batch_events
            if paused or len(records) < self.batch:
                break
        return landed

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout)
