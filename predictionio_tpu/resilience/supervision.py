"""Run supervision: the crash-safe model-lifecycle layer.

The reference's availability story is actor supervision — MasterActor
restarts a wedged ServerActor and swaps models after retrain.  The Python
rebuild replaced that with a bare swap-under-lock and nothing watching
the training loop at all: a hung device step blocked ``pio train``
forever, a NaN'd run persisted straight into serving, and a SIGTERM'd
train threw away its progress.  This module is the supervision half of
PR 2's resilience subsystem, wired through both sides of the model
lifecycle:

Training side (models/two_tower.py, models/dlrm.py, models/als.py):

- :class:`StepWatchdog` — a device step exceeding ``PIO_STEP_TIMEOUT_S``
  fires ``pio_watchdog_fired_total{fn}``, publishes a ``watchdog.fired``
  trace-ring event carrying the last step-timeline entry, flushes any
  pending async checkpoint saves (so the resume point is durable), and
  aborts the run instead of hanging forever.  Injectable clock — the
  test matrix runs on fakes with no wall sleeps.
- :class:`DivergenceGuard` — a non-finite loss or parameter tree rolls
  the run back to the last-good :class:`TrainCheckpointer` step, at most
  ``PIO_DIVERGENCE_RETRIES`` times, then raises :class:`TrainDiverged`.
  A NaN model is never silently persisted.
- Preemption — ``SIGTERM`` during ``pio train`` sets a process-wide flag
  (:func:`install_preemption_handler`); the loops notice at the next
  step boundary, write a final checkpoint, and raise
  :class:`TrainPreempted`, which the CLI maps to exit code
  :data:`PREEMPTED_EXIT_CODE` — a supervisor's rerun resumes through the
  existing checkpoint-restore path.

Serving side (server/engine_server.py): :func:`validate_model_finite`
is the finite-params sanity gate of the staged reload — a candidate
model instance whose arrays carry NaN/Inf never reaches the swap.

Like the rest of :mod:`predictionio_tpu.resilience`, importing this
module never imports jax (all array touches are lazy), so the jax-free
event server can share the package.
"""

from __future__ import annotations

import json
import logging
import math
import os
import signal
import threading
import time
import _thread
from typing import Any, Callable, Iterator, List, Optional, Tuple

from predictionio_tpu.obs import get_registry, publish_event

logger = logging.getLogger(__name__)

__all__ = [
    "PREEMPTED_EXIT_CODE",
    "StepTimedOut",
    "TrainDiverged",
    "TrainPreempted",
    "RollbackRequested",
    "ModelValidationError",
    "StepWatchdog",
    "DivergenceGuard",
    "install_preemption_handler",
    "request_preemption",
    "preemption_requested",
    "clear_preemption",
    "all_finite",
    "iter_model_arrays",
    "validate_model_finite",
]

# Exit code `pio train` uses after a SIGTERM-triggered final checkpoint.
# 143 = 128 + SIGTERM: the code a supervisor already expects from a
# terminated process, except here it certifies a CLEAN preemption — the
# final checkpoint is durable and a rerun resumes from it.
PREEMPTED_EXIT_CODE = 143


class StepTimedOut(RuntimeError):
    """A device step exceeded ``PIO_STEP_TIMEOUT_S`` (watchdog abort)."""


class TrainDiverged(RuntimeError):
    """Training produced non-finite state and exhausted its rollbacks."""

    def __init__(self, fn: str, step: int, what: str, rollbacks: int):
        super().__init__(
            f"{fn} training diverged at step {step} ({what}) after "
            f"{rollbacks} rollback(s) to the last-good checkpoint; the "
            "non-finite model was NOT persisted.  Lower the learning "
            "rate or inspect the data for this window.")
        self.fn = fn
        self.step = step
        self.rollbacks = rollbacks


class TrainPreempted(RuntimeError):
    """SIGTERM during training: final checkpoint written, run handed back.

    ``checkpointed`` says whether a resume point exists (False when the
    run had no checkpoint directory — the rerun then starts fresh)."""

    def __init__(self, fn: str, step: int, checkpointed: bool):
        how = ("final checkpoint written — a rerun resumes from it"
               if checkpointed else
               "no checkpoint dir — a rerun restarts from scratch")
        super().__init__(
            f"{fn} training preempted at step {step} ({how}).")
        self.fn = fn
        self.step = step
        self.checkpointed = checkpointed


class RollbackRequested(Exception):
    """Internal control flow: re-enter the training loop from the last
    checkpoint.  Never escapes a ``train()`` entry point."""

    def __init__(self, step: int, what: str):
        super().__init__(f"rollback from step {step}: {what}")
        self.step = step
        self.what = what


class ModelValidationError(RuntimeError):
    """A candidate model failed reload validation (finite check/canary)."""


# -- preemption flag ---------------------------------------------------------

_preempted = threading.Event()


def request_preemption() -> None:
    """Ask the running training loops to checkpoint and hand back."""
    _preempted.set()


def preemption_requested() -> bool:
    return _preempted.is_set()


def clear_preemption() -> None:
    _preempted.clear()


def install_preemption_handler() -> bool:
    """SIGTERM → preemption flag (idempotent; False off the main thread).

    The loops notice at the next step boundary, force a final checkpoint,
    and raise :class:`TrainPreempted`; ``pio train`` exits with
    :data:`PREEMPTED_EXIT_CODE`.  SIGINT keeps its KeyboardInterrupt
    semantics (interactive Ctrl-C should stop NOW, not checkpoint)."""

    def _handler(signum, frame):
        logger.warning("SIGTERM: preemption requested — training will "
                       "checkpoint at the next step boundary and exit %d",
                       PREEMPTED_EXIT_CODE)
        request_preemption()

    try:
        signal.signal(signal.SIGTERM, _handler)
        return True
    except (ValueError, OSError):  # non-main thread / exotic platform
        return False


# -- finiteness --------------------------------------------------------------

def _leaf_finite(x: Any) -> bool:
    """True when ``x`` is not a non-finite float array/scalar."""
    dtype = getattr(x, "dtype", None)
    if dtype is None:
        if isinstance(x, float):
            return math.isfinite(x)
        return True
    import numpy as np

    if not np.issubdtype(np.dtype(dtype), np.inexact):
        return True
    if x.__class__.__module__.startswith("jax") or hasattr(x, "addressable_shards"):
        # Reduce on device; only the scalar crosses to host.
        import jax.numpy as jnp

        return bool(jnp.isfinite(x).all())
    return bool(np.isfinite(np.asarray(x)).all())


def all_finite(tree: Any) -> bool:
    """Every inexact leaf of a pytree is finite (lazy jax import)."""
    import jax

    return all(_leaf_finite(leaf) for leaf in jax.tree_util.tree_leaves(tree))


_ATOMIC = (str, bytes, bool, int, float, complex, type(None))


def iter_model_arrays(obj: Any, max_depth: int = 6,
                      _path: str = "model") -> Iterator[Tuple[str, Any]]:
    """Yield ``(path, array)`` for every array reachable inside an
    arbitrary model object (dataclasses, dicts, sequences, plain
    ``__dict__`` objects), bounded by ``max_depth``.

    Loaded engine models are wrapper objects, not pytrees — this is the
    walk the staged-reload finite check uses to find their tensors."""
    if max_depth < 0 or isinstance(obj, _ATOMIC):
        return
    if hasattr(obj, "dtype") and hasattr(obj, "shape"):
        yield _path, obj
        return
    # Atomic children are filtered BEFORE any path-string formatting: a
    # model holding a large plain dict (str → score) must cost one
    # isinstance per entry here, not a formatted path per entry.
    if isinstance(obj, dict):
        items = ((f"{_path}[{k!r}]", v) for k, v in obj.items()
                 if not isinstance(v, _ATOMIC))
    elif isinstance(obj, (list, tuple)):
        items = ((f"{_path}[{i}]", v) for i, v in enumerate(obj)
                 if not isinstance(v, _ATOMIC))
    elif hasattr(obj, "__dict__"):
        items = ((f"{_path}.{k}", v) for k, v in vars(obj).items()
                 if not k.startswith("_") and not isinstance(v, _ATOMIC))
    else:
        return
    for p, v in items:
        yield from iter_model_arrays(v, max_depth - 1, p)


def validate_model_finite(model: Any, name: str = "model") -> None:
    """Raise :class:`ModelValidationError` naming the first non-finite
    array found anywhere inside ``model`` (the reload sanity gate)."""
    for path, arr in iter_model_arrays(model, _path=name):
        if not _leaf_finite(arr):
            raise ModelValidationError(
                f"candidate model has non-finite values at {path} "
                f"(shape {getattr(arr, 'shape', '?')}) — refusing to "
                "serve it")


# -- divergence guard --------------------------------------------------------

class DivergenceGuard:
    """Bounded-rollback divergence policy for one training run.

    ``check(loss, step)`` / ``check_params(tree, step)`` return silently
    while the values are finite.  On the first non-finite observation
    they raise :class:`RollbackRequested` (the loop re-enters from the
    last-good checkpoint); after ``max_rollbacks`` observations they
    raise :class:`TrainDiverged`.  Every observation increments
    ``pio_train_divergence_total{fn}`` and lands a ``train.diverged``
    event in the trace ring."""

    def __init__(self, fn: str, max_rollbacks: Optional[int] = None,
                 registry=None):
        if max_rollbacks is None:
            try:
                max_rollbacks = int(
                    os.environ.get("PIO_DIVERGENCE_RETRIES", "2"))
            except ValueError:
                max_rollbacks = 2
        self.fn = fn
        self.max_rollbacks = max(0, int(max_rollbacks))
        self.rollbacks = 0
        self._registry = registry

    def _counter(self):
        return (self._registry or get_registry()).counter(
            "pio_train_divergence_total",
            "Non-finite loss/params observations per training loop.",
            ("fn",))

    def check(self, loss: Any, step: int) -> None:
        """Host-side finiteness check of a READY loss scalar.  The loops
        call this right after the pipeline probe's sync — the value is
        already materialized, so the check costs one float()."""
        try:
            value = float(loss)
        except TypeError:
            return
        if math.isfinite(value):
            return
        self.diverged(step, f"loss={value}")

    def check_vector(self, losses: Any, steps: Any) -> None:
        """Finiteness check of a fused window's per-step loss vector.

        ``losses`` is the K-vector a fused ``lax.scan`` dispatch returned
        (or a scalar — the unfused path); ``steps`` maps each slot to its
        global step number (scalar or sequence, aligned with ``losses``).
        The loops call this after the probe's sync, so the vector is
        materialized and the check costs one host read of K floats.  A
        NaN at slot j attributes the divergence to slot j's step — the
        rollback target (always a fusion-boundary checkpoint) precedes
        it by construction."""
        import numpy as np

        arr = np.asarray(losses, dtype=np.float64).reshape(-1)
        bad = np.flatnonzero(~np.isfinite(arr))
        if bad.size == 0:
            return
        j = int(bad[0])
        step_list = np.asarray(steps).reshape(-1)
        step = int(step_list[min(j, len(step_list) - 1)])
        what = f"loss={arr[j]}"
        if arr.size > 1:
            what += f" (slot {j + 1}/{arr.size} of the fused window)"
        self.diverged(step, what)

    def check_params(self, tree: Any, step: int) -> None:
        if all_finite(tree):
            return
        self.diverged(step, "non-finite params")

    def diverged(self, step: int, what: str) -> None:
        """Record one observed divergence: raises
        :class:`RollbackRequested` while rollbacks remain, then
        :class:`TrainDiverged`."""
        self._counter().inc(fn=self.fn)
        will_rollback = self.rollbacks < self.max_rollbacks
        publish_event("train.diverged", fn=self.fn, step=int(step),
                      what=what, rollback=will_rollback)
        if not will_rollback:
            raise TrainDiverged(self.fn, step, what, self.rollbacks)
        self.rollbacks += 1
        logger.warning(
            "%s: non-finite training state at step %d (%s) — rolling "
            "back to the last-good checkpoint (rollback %d/%d)",
            self.fn, step, what, self.rollbacks, self.max_rollbacks)
        raise RollbackRequested(step, what)


# -- step watchdog -----------------------------------------------------------

def _default_abort() -> None:
    """Raise KeyboardInterrupt in the main thread — unwinds ``pio train``
    through its normal teardown.  A runtime hung inside a C call may not
    honor it; ``PIO_STEP_TIMEOUT_KILL`` (below) or the supervisor's
    process-level timeout is the backstop."""
    _thread.interrupt_main()


def _default_kill() -> None:
    """Hard escalation: SIGKILL this process.  The soft abort above
    cannot unwind a runtime wedged inside a C call (libtpu collective,
    stuck RPC) — interrupt_main only fires when the interpreter next
    runs bytecode.  By the time this runs the checkpoint flush already
    happened at fire time, so the kill loses nothing a resume needs."""
    os.kill(os.getpid(), signal.SIGKILL)


class StepWatchdog:
    """Deadline monitor over individual device steps.

    The training loop arms the watchdog before blocking on a step and
    disarms after the step dispatches; a step still armed past
    ``timeout_s`` (env ``PIO_STEP_TIMEOUT_S``; unset/0 disables) fires
    exactly once: ``pio_watchdog_fired_total{fn}`` increments, a
    ``watchdog.fired`` event carrying the last step-timeline entry lands
    in the trace ring, ``checkpoint_fn`` runs (the loops pass the
    checkpointer's flush, making the resume point durable), then
    ``abort_fn`` aborts the run instead of letting it hang forever.

    **Hard escalation** (opt-in, ``PIO_STEP_TIMEOUT_KILL`` = grace
    seconds): the soft abort raises KeyboardInterrupt in the main
    thread, which a runtime wedged inside a C call (libtpu collective,
    hung RPC) never observes — the carried-forward ROADMAP gap.  With a
    kill grace set, the poller keeps watching after a fire; if the run
    has not unwound (reached :meth:`stop`) within the grace, it
    escalates to ``kill_fn`` (default: SIGKILL self).  The fire-time
    checkpoint flush already made the resume point durable, so the kill
    trades a clean traceback for actually releasing the supervisor.

    ``clock`` / ``abort_fn`` / ``checkpoint_fn`` / ``kill_fn`` are
    injectable and :meth:`poll` is public, so the fault matrix drives
    expiry AND escalation on a fake clock with no wall sleeps and no
    real signals.  The background poller thread starts lazily on the
    first :meth:`arm` (never when disabled, or when
    ``poll_interval_s <= 0``)."""

    def __init__(self, fn: str, timeout_s: Optional[float] = None, *,
                 clock: Callable[[], float] = time.monotonic,
                 checkpoint_fn: Optional[Callable[[], None]] = None,
                 abort_fn: Callable[[], None] = _default_abort,
                 kill_grace_s: Optional[float] = None,
                 kill_fn: Callable[[], None] = _default_kill,
                 poll_interval_s: Optional[float] = None,
                 registry=None, timeline=None):
        if timeout_s is None:
            try:
                timeout_s = float(os.environ.get("PIO_STEP_TIMEOUT_S", "0"))
            except ValueError:
                timeout_s = 0.0
        if kill_grace_s is None:
            try:
                kill_grace_s = float(
                    os.environ.get("PIO_STEP_TIMEOUT_KILL", "0"))
            except ValueError:
                kill_grace_s = 0.0
        self.fn = fn
        self.timeout_s = float(timeout_s)
        self.kill_grace_s = float(kill_grace_s)
        self._clock = clock
        self._checkpoint_fn = checkpoint_fn
        self._abort_fn = abort_fn
        self._kill_fn = kill_fn
        self._fired_at: Optional[float] = None
        self._killed = False
        if poll_interval_s is None:
            poll_interval_s = min(1.0, self.timeout_s / 4) \
                if self.timeout_s > 0 else 0.0
        self.poll_interval_s = float(poll_interval_s)
        self._registry = registry
        self._timeline = timeline
        self._lock = threading.Lock()
        self._armed: Optional[Tuple[int, float]] = None  # (step, deadline)
        self.fired_steps: List[int] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def enabled(self) -> bool:
        return self.timeout_s > 0

    def _counter(self):
        return (self._registry or get_registry()).counter(
            "pio_watchdog_fired_total",
            "Device steps that exceeded PIO_STEP_TIMEOUT_S.", ("fn",))

    def arm(self, step: int, scale: int = 1) -> None:
        """Arm for one device dispatch.  ``scale`` stretches the deadline
        for fused dispatches covering K steps: the timeout stays a
        per-step budget, so K fused steps get K times the wall."""
        if not self.enabled:
            return
        deadline = self._clock() + self.timeout_s * max(int(scale), 1)
        with self._lock:
            self._armed = (int(step), deadline)
        self._ensure_thread()

    def disarm(self) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._armed = None

    def poll(self) -> bool:
        """Check the armed deadline; fire (once) when expired.  After a
        fire, keep watching for the opt-in hard escalation: a run that
        has not unwound (stopped this watchdog) within
        ``kill_grace_s`` of the fire is wedged past what the soft abort
        can reach — ``kill_fn`` it."""
        with self._lock:
            if self._armed is None:
                if (self._fired_at is not None and self.kill_grace_s > 0
                        and not self._killed
                        and self._clock() - self._fired_at
                        >= self.kill_grace_s):
                    self._killed = True
                else:
                    return False
                escalate = True
            else:
                step, deadline = self._armed
                if self._clock() < deadline:
                    return False
                self._armed = None  # consume: fire exactly once per arm
                escalate = False
        if escalate:
            self._escalate()
            return True
        self._fire(step)
        return True

    def _escalate(self) -> None:
        self._kill_counter().inc(fn=self.fn)
        publish_event("watchdog.killed", fn=self.fn,
                      graceS=self.kill_grace_s)
        logger.critical(
            "%s: run did not unwind within PIO_STEP_TIMEOUT_KILL=%.1fs of "
            "the watchdog abort (runtime wedged in a C call?) — hard-"
            "killing the process; the fire-time checkpoint flush is the "
            "resume point", self.fn, self.kill_grace_s)
        self._kill_fn()

    def _kill_counter(self):
        return (self._registry or get_registry()).counter(
            "pio_watchdog_killed_total",
            "Hard kills after a fired watchdog failed to unwind within "
            "PIO_STEP_TIMEOUT_KILL.", ("fn",))

    def _fire(self, step: int) -> None:
        self.fired_steps.append(step)
        with self._lock:
            self._fired_at = self._clock()
        self._counter().inc(fn=self.fn)
        from predictionio_tpu.obs.runtime import get_timeline

        last = (self._timeline or get_timeline()).recent(1, model=self.fn)
        # JSON-encoded: trace attrs keep only primitives, and the last
        # timeline entry is the evidence ("the step before the hang
        # looked like THIS") an operator reads out of /traces.json.
        publish_event("watchdog.fired", fn=self.fn, step=step,
                      timeoutS=self.timeout_s,
                      lastStep=json.dumps(last[0]) if last else None)
        logger.critical(
            "%s: device step %d exceeded PIO_STEP_TIMEOUT_S=%.1fs — "
            "flushing checkpoints and aborting the run (last timeline "
            "entry: %s)", self.fn, step, self.timeout_s,
            last[0] if last else "none")
        if self._checkpoint_fn is not None:
            try:
                self._checkpoint_fn()
            except Exception:
                logger.exception("watchdog checkpoint flush failed")
        self._abort_fn()

    # -- background poller ---------------------------------------------------

    def _ensure_thread(self) -> None:
        if self.poll_interval_s <= 0:
            return
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name=f"pio-watchdog-{self.fn}",
                daemon=True)
            self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            try:
                self.poll()
            except Exception:
                logger.exception("watchdog poll failed")

    def stop(self, timeout: float = 2.0) -> None:
        self._stop.set()
        with self._lock:
            # Reaching stop() IS the unwind the kill escalation waits
            # for — the abort worked, stand down.
            self._fired_at = None
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout)
        self._thread = None
        self.disarm()
