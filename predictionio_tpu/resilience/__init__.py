"""Process-wide fault-tolerance layer (tail-at-scale machinery).

The paper positions predictionio_tpu as a production ML *server*: ingest
must not lose events and queries must degrade gracefully under partial
failure.  This package is the one home for that machinery, wired through
every network hop (SDK → event/engine servers → RemoteClient):

- :mod:`predictionio_tpu.resilience.policy` — :class:`RetryPolicy`
  (jittered exponential backoff, ``Retry-After``-aware) and
  :class:`CircuitBreaker` (closed/open/half-open, exported as
  ``pio_breaker_state`` gauges).
- :mod:`predictionio_tpu.resilience.deadline` — ``X-PIO-Deadline-Ms``
  budget propagation; a request that cannot finish in budget sheds early
  with 504 instead of queueing.
- :mod:`predictionio_tpu.resilience.faults` — env-driven fault injection
  (``PIO_FAULTS="storage.create:error:0.3,storage.find:delay:200ms"``)
  hooked into the storage base layer, the JSON-RPC framing, and the HTTP
  handlers; used by tests and ``bench_serving.py``.
- :mod:`predictionio_tpu.resilience.spill` — storage-outage spill
  journal: a durable append-only JSONL file the event server degrades
  into (202 + ``Retry-After``) plus the background replay worker that
  drains it on recovery.
- :mod:`predictionio_tpu.resilience.supervision` — run supervision for
  the model lifecycle: step watchdog (``PIO_STEP_TIMEOUT_S``),
  divergence rollback (``PIO_DIVERGENCE_RETRIES``), SIGTERM preemption
  (``pio train`` exits :data:`~supervision.PREEMPTED_EXIT_CODE` after a
  final checkpoint), and the finite-model validation behind the engine
  server's staged reload.

Idempotency tokens make remote-storage writes *safely* retriable: the
JSON-RPC client stamps every write with a client-generated token, the
server keeps a bounded dedup window, and :func:`idempotency_key` lets
the spill-replay path pin a persisted token so a crashed replay never
double-inserts.

stdlib-only on import (same constraint as :mod:`predictionio_tpu.obs`).
"""

from __future__ import annotations

import contextlib
import contextvars
from typing import Iterator, Optional

from predictionio_tpu.resilience.deadline import (
    DEADLINE_HEADER,
    DeadlineExceeded,
    deadline_scope,
    remaining_ms,
)
from predictionio_tpu.resilience.faults import (
    FaultInjected,
    FaultPlan,
    fault_point,
)
from predictionio_tpu.resilience.policy import (
    CircuitBreaker,
    CircuitOpenError,
    RetryPolicy,
)
from predictionio_tpu.resilience.spill import ReplayWorker, SpillJournal
from predictionio_tpu.resilience.supervision import (
    PREEMPTED_EXIT_CODE,
    DivergenceGuard,
    ModelValidationError,
    StepWatchdog,
    TrainDiverged,
    TrainPreempted,
    install_preemption_handler,
    preemption_requested,
    request_preemption,
    validate_model_finite,
)

__all__ = [
    "CircuitBreaker",
    "CircuitOpenError",
    "RetryPolicy",
    "DEADLINE_HEADER",
    "DeadlineExceeded",
    "deadline_scope",
    "remaining_ms",
    "FaultInjected",
    "FaultPlan",
    "fault_point",
    "ReplayWorker",
    "SpillJournal",
    "idempotency_key",
    "current_idempotency_key",
    "PREEMPTED_EXIT_CODE",
    "DivergenceGuard",
    "ModelValidationError",
    "StepWatchdog",
    "TrainDiverged",
    "TrainPreempted",
    "install_preemption_handler",
    "preemption_requested",
    "request_preemption",
    "validate_model_finite",
]


# -- idempotency-token plumbing --------------------------------------------
#
# The JSON-RPC client (data/storage/remote.py) stamps every write with a
# fresh client-generated token unless one is pinned here.  The spill
# replay worker pins the token PERSISTED in the journal so that a replay
# retried after a lost reply (or a process crash between insert and
# journal compaction) dedups server-side instead of double-inserting.

_IDEM_TOKEN: contextvars.ContextVar[Optional[str]] = contextvars.ContextVar(
    "pio_idempotency_token", default=None)


@contextlib.contextmanager
def idempotency_key(token: str) -> Iterator[str]:
    """Pin the idempotency token used by the NEXT remote-storage write on
    this thread/context (nested scopes override)."""
    tok = _IDEM_TOKEN.set(token)
    try:
        yield token
    finally:
        _IDEM_TOKEN.reset(tok)


def current_idempotency_key() -> Optional[str]:
    return _IDEM_TOKEN.get()
