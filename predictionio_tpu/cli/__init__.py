"""`pio` CLI (reference: tools/src/main/scala/org/apache/predictionio/tools/)."""
