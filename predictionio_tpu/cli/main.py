"""The `pio` command-line console.

Reference: tools/.../tools/console/Console.scala (scopt verb dispatch) and
tools/.../commands/{App,AccessKey,...} — SURVEY.md §2.1 "Tools/CLI" and
Appendix A's CLI verb list.  Verbs:

    pio status
    pio app new <name> | list | delete <name> | data-delete <name>
    pio app channel-new <app> <channel> | channel-delete <app> <channel>
    pio accesskey new <appname> [event ...] | list [appname] | delete <key>
    pio train   --engine-json engine.json [--seed N]
    pio import  --appid N --input events.ndjson
    pio export  --appid N --output events.ndjson
    pio eval    <EvaluationClass> <EngineParamsGeneratorClass>
    pio eventserver --port 7070        (added with the server layer)
    pio deploy  --engine-json ... --port 8000
    pio profile [--url http://HOST:7071] [--duration-ms N]

Where the reference's `pio train`/`pio deploy` shell out to spark-submit,
these run the workflow in-process — there is no cluster-manager boundary on
a TPU slice; multi-host launch is `pio train` once per host with
PIO_COORDINATOR_ADDRESS set (parallel/distributed.py).
"""

from __future__ import annotations

import argparse
import datetime as _dt
import os
import json
import logging
import re
import sys
from pathlib import Path
from typing import List, Optional

from predictionio_tpu.version import __version__

logger = logging.getLogger(__name__)


def _storage():
    from predictionio_tpu.data.storage import get_storage

    return get_storage()


def _die(msg: str, code: int = 1) -> "NoReturn":  # noqa: F821
    print(f"[error] {msg}", file=sys.stderr)
    raise SystemExit(code)


# --------------------------------------------------------------------------
# pio status
# --------------------------------------------------------------------------

def cmd_status(args) -> int:
    from predictionio_tpu.config import load_config

    cfg = load_config()
    print(f"predictionio_tpu {__version__}")
    print(f"PIO_HOME: {cfg.home}")
    try:
        repo_types = _storage().verify()
    except Exception as e:
        _die(f"storage verification failed: {e}")
    for repo, t in repo_types.items():
        src = cfg.source_for(repo)
        print(f"  {repo}: type={t} path={src.path or '-'}")
    _print_segment_status()
    try:
        import jax

        devs = jax.devices()
        print(f"devices: {len(devs)} x {devs[0].platform if devs else '-'}"
              f" ({devs[0].device_kind if devs else '-'})")
        _print_device_memory()
    except Exception as e:  # TPU tunnel may be down; status should still work
        print(f"devices: unavailable ({e})")
    fleet = getattr(args, "fleet", None)
    metrics_url = getattr(args, "metrics_url", None)
    # An explicit --metrics-url outranks the ambient PIO_FLEET_INSTANCES:
    # the operator asked about ONE process, not the fleet the env
    # happens to describe.  --fleet (also explicit) still wins over it.
    if fleet is not None or (metrics_url is None
                             and os.environ.get("PIO_FLEET_INSTANCES")):
        _print_fleet_status(fleet)
    else:
        _print_metrics_snapshot(metrics_url)
    print("(sanity check OK)")
    return 0


def _print_segment_status() -> None:
    """ISSUE 17 lines for `pio status`: the columnar segment store (read
    straight from the on-disk manifests — works with no server running)
    and the write-path admission knobs."""
    from predictionio_tpu.data.columnar import resolve_segment_root

    seg_root = resolve_segment_root()
    if seg_root is None:
        print("segments: off (PIO_SEGMENTS=off)")
    else:
        entries = []
        for mpath in sorted(seg_root.glob("app_*/*/manifest.json")):
            try:
                man = json.loads(mpath.read_text())
            except (OSError, ValueError):
                continue
            segs = man.get("segments", [])
            entries.append((str(mpath.parent.relative_to(seg_root)),
                            len(segs), sum(e["rows"] for e in segs),
                            sum(e["bytes"] for e in segs)))
        print(f"segments: root={seg_root} dirs={len(entries)} "
              f"sealed={sum(e[1] for e in entries)} "
              f"rows={sum(e[2] for e in entries)}")
        for d, s, r, b in entries:
            print(f"  {d}: segments={s} rows={r} bytes={b}")
    budget = os.environ.get("PIO_INGEST_QUEUE_BUDGET") or "unbounded"
    min_free = os.environ.get("PIO_DISK_MIN_FREE_BYTES") or "0"
    print(f"ingest: admission budget={budget} "
          f"max batch={os.environ.get('PIO_MAX_BATCH_SIZE', '50')} "
          f"disk min free bytes={min_free}")


def _print_fleet_status(fleet_arg: Optional[str]) -> None:
    """`pio status --fleet URL,URL` (ISSUE 9): scrape every instance's
    /metrics + SLO state, merge type-correctly (obs.fleet), and print
    the operator summary — per-instance readiness next to fleet-summed
    traffic counters."""
    from predictionio_tpu.obs.fleet import (
        FleetAggregator,
        fleet_instances_from_env,
    )

    urls = ([u.strip().rstrip("/") for u in fleet_arg.split(",")
             if u.strip()] if fleet_arg else fleet_instances_from_env())
    if not urls:
        print("fleet: no instances configured (--fleet URL,URL or "
              "PIO_FLEET_INSTANCES)")
        return
    agg = FleetAggregator(urls)
    doc = agg.scrape()
    print(f"fleet: {len(urls)} instance(s)")
    for row in doc["instances"]:
        state = "STALE" if row["stale"] else "up"
        parts = [state]
        slo = row.get("slo")
        if slo:
            parts.append("degraded" if slo.get("degraded") else "healthy")
            if slo.get("saturated"):
                parts.append("saturated")
            fast = slo.get("burn", {}).get("fast", {})
            parts.append(f"burn fast a={fast.get('availability', 0):g}"
                         f"/l={fast.get('latency', 0):g}")
        if row.get("error"):
            parts.append(row["error"])
        print(f"  {row['instance']}: {', '.join(parts)}")
    counters = doc["merged"]["counters"]
    interesting = ("pio_query_requests_total", "pio_query_errors_total",
                   "pio_event_requests_total", "pio_queue_rejected_total",
                   "pio_deadline_shed_total")
    shown = {k: v for k, v in counters.items()
             if any(k.startswith(p) for p in interesting)}
    if shown:
        print("  fleet totals:")
        for k, v in sorted(shown.items()):
            print(f"    {k} {v:g}")
    q = doc["merged"]["histogramQuantiles"].get("pio_query_latency_ms", {})
    for key, row in sorted(q.items()):
        print(f"  fleet {key}: p50 {row['p50']:g}ms p99 {row['p99']:g}ms "
              f"over {row['count']:g} requests")
    _print_fleet_plane(doc)


def _print_fleet_plane(doc) -> None:
    """ISSUE 15 lines for `pio status --fleet`: shared spill-queue depth
    (scraped gauges first, storage second) and the journaled rollout
    wave state."""
    gauges = doc["merged"].get("gauges", {})
    shared = {k: v for k, v in gauges.items()
              if k.startswith("pio_spill_shared_depth")}
    if shared:
        print(f"  shared spill queue: {max(shared.values()):g} event(s) "
              "pending/leased (per-instance view of one fleet queue)")
    else:
        # No event server in the scraped set — best-effort direct read
        # of THIS process's configured storage.
        try:
            from predictionio_tpu.resilience.shared_spill import (
                SharedSpillQueue,
            )

            st = SharedSpillQueue(_storage()).stats()
            print(f"  shared spill queue: {st.get('pendingEvents', 0)} "
                  f"pending / {st.get('leasedEvents', 0)} leased / "
                  f"{st.get('deadEvents', 0)} dead event(s)")
        except Exception:
            pass
    try:
        from predictionio_tpu.fleet import rollout_state_path

        state = json.loads(rollout_state_path().read_text())
    except Exception:
        return
    line = (f"  rollout [{state.get('rolloutId')}]: "
            f"{state.get('status')} — wave {state.get('wave')} of "
            f"{len(state.get('waveCounts') or [])}, "
            f"{len(state.get('promoted') or [])} promoted, "
            f"{len(state.get('skipped') or {})} skipped")
    if state.get("haltReason"):
        line += f", halt: {state['haltReason']}"
    print(line)


def _print_device_memory() -> None:
    """Device-memory snapshot (obs.runtime sampler): live allocator stats
    for this process, plus any per-train-run peaks a local run recorded.
    A remote server's peaks arrive via --metrics-url (the sampler exports
    pio_device_mem_bytes / pio_device_mem_peak_bytes there)."""
    from predictionio_tpu.obs import get_memory_sampler

    sampler = get_memory_sampler()
    try:
        sample = sampler.sample_once()
    except Exception as e:
        print(f"device memory: unavailable ({e})")
        return
    if not sample:
        print("device memory: no allocator stats on this backend")
        return
    peaks = sampler.peaks()
    for dev, row in sorted(sample.items()):
        parts = []
        for kind in ("bytes_in_use", "bytes_limit", "live_bytes",
                     "live_arrays"):
            if kind in row:
                v = row[kind]
                parts.append(f"{kind}={int(v):,}" if kind != "live_arrays"
                             else f"{kind}={int(v)}")
        if dev in peaks:
            parts.append(f"peak={int(peaks[dev]):,}")
        print(f"device memory [{dev}]: {' '.join(parts) or '(empty)'}")


def _print_metrics_snapshot(metrics_url: Optional[str]) -> None:
    """Metrics view for `pio status`: scrape a running server's /metrics
    when --metrics-url is given, else render this process's registry (the
    sanity checks above already touched storage, so it is non-empty only
    if instrumented code ran — say so rather than print nothing)."""
    if metrics_url:
        from urllib.request import urlopen

        url = metrics_url.rstrip("/")
        if not url.endswith("/metrics"):
            url += "/metrics"
        try:
            with urlopen(url, timeout=10) as resp:
                text = resp.read().decode()
        except Exception as e:
            print(f"metrics: cannot scrape {url} ({e})")
            return
        _print_serving_snapshot(text.splitlines())
        print(f"metrics (scraped from {url}):")
        for line in text.splitlines():
            if line and not line.startswith("#"):
                print(f"  {line}")
        return
    from predictionio_tpu.obs import get_registry

    metrics = get_registry().metrics()
    samples = [line for m in metrics for line in m.render()]
    if not samples:
        print("metrics: none recorded in this process "
              "(use --metrics-url http://HOST:PORT to scrape a server)")
        return
    _print_serving_snapshot(samples)
    print("metrics (this process):")
    for line in samples:
        print(f"  {line}")


_BREAKER_STATES = {0: "closed", 1: "half-open", 2: "open"}
_METRIC_LINE = None  # compiled lazily (keep the import-light CLI startup)


def _parse_metric_lines(lines):
    """(name, labels-dict, value) triples from Prometheus text lines."""
    import re

    global _METRIC_LINE
    if _METRIC_LINE is None:
        _METRIC_LINE = re.compile(
            r'^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)'
            r'(?:\{(?P<labels>[^}]*)\})?\s+(?P<value>\S+)$')
    for line in lines:
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        # Strip OpenMetrics exemplar suffixes (pio_serve_stage_ms buckets
        # carry ` # {trace_id="..."} v` after the sample value).
        line = line.split(" # ", 1)[0].rstrip()
        m = _METRIC_LINE.match(line)
        if not m:
            continue
        labels = {}
        for part in (m.group("labels") or "").split(","):
            if "=" in part:
                k, _, v = part.partition("=")
                labels[k.strip()] = v.strip().strip('"')
        try:
            yield m.group("name"), labels, float(m.group("value"))
        except ValueError:
            continue


def _print_serving_snapshot(lines) -> None:
    """Model-lifecycle view for `pio status` (ISSUE 4 satellite): the
    serving generation, reload outcomes, and breaker states out of a
    metrics exposition — printed alongside the device-memory snapshot so
    one `pio status --metrics-url` answers "what model is live and is
    its storage healthy"."""
    generation = None
    reloads = {}
    breakers = {}
    watchdog = {}
    batcher = {}
    latest_ts = {}
    staleness = None
    refresh_runs = {}
    quality = {}
    recall = {}
    rcache = {}

    def _b(model):
        return batcher.setdefault(model, {})

    for name, labels, value in _parse_metric_lines(lines):
        if name == "pio_model_generation":
            generation = int(value)
        elif name == "pio_events_latest_ts":
            latest_ts[labels.get("app", "?")] = value
        elif name == "pio_refresh_staleness_s":
            staleness = value
        elif name == "pio_refresh_runs_total" and value > 0:
            refresh_runs[labels.get("result", "?")] = int(value)
        elif name == "pio_quality_drift":
            quality.setdefault("drift", {})[
                f"{labels.get('metric', '?')}_{labels.get('window', '?')}"
            ] = value
        elif name == "pio_quality_drift_tripped":
            quality["tripped"] = bool(value)
        elif name == "pio_quality_reporting_only" and value > 0:
            quality["reporting_only"] = True
        elif name == "pio_quality_shadow_overlap":
            quality["shadow_overlap"] = value
        elif name == "pio_quality_online_hit_rate":
            quality["hit_rate"] = value
        elif name == "pio_quality_gate_rollback":
            quality["gate_rollback"] = bool(value)
        elif name == "pio_quality_sampled_total" and value > 0:
            quality["sampled"] = int(value)
        elif name == "pio_retrieval_recall":
            if labels.get("window") == "fast":
                recall.setdefault("rungs", {})[
                    labels.get("rung", "?")] = value
                recall["k"] = labels.get("k", "?")
        elif name == "pio_retrieval_recall_baseline":
            recall.setdefault("baselines", {})[
                labels.get("rung", "?")] = value
        elif name == "pio_retrieval_recall_tripped" and value > 0:
            recall["tripped"] = True
        elif name == "pio_retrieval_recall_reporting_only" and value > 0:
            recall["reporting_only"] = True
        elif name == "pio_result_cache_hits_total":
            rcache["hits"] = rcache.get("hits", 0) + int(value)
        elif name == "pio_result_cache_misses_total":
            rcache["misses"] = int(value)
        elif name == "pio_result_cache_hit_rate":
            rcache["hit_rate"] = value
        elif name == "pio_result_cache_entries":
            rcache["entries"] = int(value)
        elif name == "pio_result_cache_bytes":
            rcache["bytes"] = int(value)
        elif name == "pio_result_cache_evictions_total" and value > 0:
            rcache["evictions"] = int(value)
        elif name == "pio_result_cache_shared_errors_total" and value > 0:
            rcache["shared_errors"] = int(value)
        elif name == "pio_model_reload_total":
            reloads[labels.get("result", "?")] = int(value)
        elif name == "pio_breaker_state":
            breakers[labels.get("breaker", "?")] = \
                _BREAKER_STATES.get(int(value), str(value))
        elif name == "pio_watchdog_fired_total" and value > 0:
            watchdog[labels.get("fn", "?")] = int(value)
        elif name == "pio_batch_window_ms":
            _b(labels.get("model", "?"))["window_ms"] = value
        elif name == "pio_batch_max_size":
            _b(labels.get("model", "?"))["max"] = int(value)
        elif name == "pio_queue_depth":
            _b(labels.get("model", "?"))["queued"] = int(value)
        elif name == "pio_batch_dispatch_total":
            _b(labels.get("model", "?"))["dispatches"] = int(value)
        elif name == "pio_batch_requests_total":
            _b(labels.get("model", "?"))["requests"] = int(value)
        elif name == "pio_queue_rejected_total" and value > 0:
            _b(labels.get("model", "?"))["rejected"] = int(value)
        elif name == "pio_queue_shed_total" and value > 0:
            shed = _b(labels.get("model", "?")).setdefault("shed", {})
            shed[labels.get("reason", "?")] = int(value)
    if generation is None and not reloads and not breakers and not batcher \
            and not latest_ts and not refresh_runs and staleness is None \
            and not quality and not recall and not rcache:
        return
    if generation is not None:
        print(f"serving: model generation {generation}")
    # Freshness (ISSUE 10): ingest high-watermark per app + the refresh
    # loop's event→servable staleness, when the scraped process runs it.
    for app, ts in sorted(latest_ts.items()):
        iso = _dt.datetime.fromtimestamp(
            ts, tz=_dt.timezone.utc).isoformat(timespec="seconds")
        print(f"  events latest [app {app}]: {iso}")
    if staleness is not None:
        print(f"  refresh staleness: {staleness:g}s event→servable")
    if refresh_runs:
        parts = ", ".join(f"{k}={v}" for k, v in sorted(refresh_runs.items()))
        print(f"  refresh runs: {parts}")
    # Model quality (ISSUE 11): drift vs the training scorecard, shadow
    # canary overlap, online hit-rate, and the promotion-gate verdict.
    if quality:
        parts = []
        drift = quality.get("drift", {})
        if "psi_fast" in drift or "psi_slow" in drift:
            parts.append(f"psi fast={drift.get('psi_fast', 0):.3f}"
                         f"/slow={drift.get('psi_slow', 0):.3f}")
        if quality.get("tripped"):
            parts.append("DRIFT TRIPPED")
        if quality.get("reporting_only"):
            parts.append("reporting-only (no trusted scorecard)")
        if "shadow_overlap" in quality:
            parts.append(f"shadow overlap {quality['shadow_overlap']:.2f}")
        if "hit_rate" in quality:
            parts.append(f"online hit-rate {quality['hit_rate']:.3f}")
        if quality.get("gate_rollback"):
            parts.append("GATE=ROLLBACK")
        if "sampled" in quality:
            parts.append(f"sampled {quality['sampled']}")
        if parts:
            print(f"  quality: {', '.join(parts)}")
    # Retrieval recall (ISSUE 16): live sampled recall@k per approximate
    # rung vs the generation's own baked baseline.
    if recall:
        parts = []
        baselines = recall.get("baselines", {})
        for rung, v in sorted(recall.get("rungs", {}).items()):
            b = baselines.get(rung)
            parts.append(f"{rung} {v:.3f}"
                         + (f" (baseline {b:.3f})" if b is not None
                            else ""))
        if recall.get("tripped"):
            parts.append("RECALL TRIPPED")
        if recall.get("reporting_only"):
            parts.append("reporting-only (no trusted recall scorecard)")
        if parts:
            k = recall.get("k", "?")
            print(f"  recall@{k}: {', '.join(parts)}")
    # Result cache (ISSUE 20): the serve fast path — hit rate, residency,
    # and whether the shared tier is degrading to local-only.
    if rcache:
        parts = []
        if "hit_rate" in rcache:
            parts.append(f"hit-rate {rcache['hit_rate']:.3f}")
        if "hits" in rcache or "misses" in rcache:
            parts.append(f"hits {rcache.get('hits', 0)}"
                         f"/misses {rcache.get('misses', 0)}")
        if "entries" in rcache:
            parts.append(f"entries {rcache['entries']}")
        if "bytes" in rcache:
            parts.append(f"{rcache['bytes'] / 1024:.0f}KiB")
        if rcache.get("evictions"):
            parts.append(f"evictions {rcache['evictions']}")
        if rcache.get("shared_errors"):
            parts.append(f"SHARED-TIER ERRORS {rcache['shared_errors']}")
        if parts:
            print(f"  result cache: {', '.join(parts)}")
    if reloads:
        parts = ", ".join(f"{k}={v}" for k, v in sorted(reloads.items()))
        print(f"  model reloads: {parts}")
    for b, st in sorted(breakers.items()):
        print(f"  breaker [{b}]: {st}")
    for fn, n in sorted(watchdog.items()):
        print(f"  watchdog fired [{fn}]: {n}")
    # Batcher snapshot (ISSUE 6): coalescing health per model lane.
    for model, row in sorted(batcher.items()):
        disp, reqs = row.get("dispatches", 0), row.get("requests", 0)
        parts = [f"window {row.get('window_ms', 0):g}ms",
                 f"max {row.get('max', '?')}",
                 f"queued {row.get('queued', 0)}",
                 f"requests {reqs}", f"dispatches {disp}"]
        if disp:
            parts.append(f"mean batch {reqs / disp:.2f}")
        if row.get("rejected"):
            parts.append(f"rejected(429) {row['rejected']}")
        for reason, n in sorted(row.get("shed", {}).items()):
            parts.append(f"shed[{reason}] {n}")
        print(f"  batcher [{model}]: {', '.join(parts)}")


# --------------------------------------------------------------------------
# pio app ...
# --------------------------------------------------------------------------

def cmd_app_new(args) -> int:
    from predictionio_tpu.data.storage import AccessKey, App

    s = _storage()
    app_id = s.get_apps().insert(App(id=None, name=args.name, description=args.description))
    if app_id is None:
        _die(f"App {args.name!r} already exists.")
    s.get_events().init(app_id)
    key = s.get_access_keys().insert(AccessKey(key=args.access_key or "", app_id=app_id))
    print("Created a new app:")
    print(f"      Name: {args.name}")
    print(f"        ID: {app_id}")
    print(f"Access Key: {key}")
    return 0


def cmd_app_list(args) -> int:
    s = _storage()
    apps = s.get_apps().get_all()
    keys = s.get_access_keys()
    print(f"{'Name':20} {'ID':>4}  Access Key")
    for app in apps:
        ks = keys.get_by_app_id(app.id)
        first = ks[0].key if ks else "-"
        print(f"{app.name:20} {app.id:>4}  {first}")
    print(f"Finished listing {len(apps)} app(s).")
    return 0


def cmd_app_delete(args) -> int:
    s = _storage()
    app = s.get_apps().get_by_name(args.name)
    if app is None:
        _die(f"App {args.name!r} does not exist.")
    if not args.force:
        ans = input(f"Delete app {args.name!r} and ALL its data? (YES to confirm): ")
        if ans.strip() != "YES":
            print("Aborted.")
            return 1
    for ch in s.get_channels().get_by_app_id(app.id):
        s.get_events().remove(app.id, ch.id)
        s.get_channels().delete(ch.id)
    s.get_events().remove(app.id)
    for k in s.get_access_keys().get_by_app_id(app.id):
        s.get_access_keys().delete(k.key)
    s.get_apps().delete(app.id)
    print(f"Deleted app {args.name}.")
    return 0


def cmd_app_data_delete(args) -> int:
    s = _storage()
    app = s.get_apps().get_by_name(args.name)
    if app is None:
        _die(f"App {args.name!r} does not exist.")
    channel_id = None
    if args.channel:
        chans = s.get_channels().get_by_app_id(app.id)
        ch = next((c for c in chans if c.name == args.channel), None)
        if ch is None:
            _die(f"Channel {args.channel!r} does not exist in app {args.name!r}.")
        channel_id = ch.id
    if not args.force:
        where = f"channel {args.channel!r} of " if args.channel else ""
        ans = input(f"Delete all event data of {where}app {args.name!r}? (YES to confirm): ")
        if ans.strip() != "YES":
            print("Aborted.")
            return 1
    ev = s.get_events()
    ev.remove(app.id, channel_id)
    ev.init(app.id, channel_id)
    print("Event data deleted.")
    return 0


def cmd_app_channel_new(args) -> int:
    from predictionio_tpu.data.storage import Channel

    s = _storage()
    app = s.get_apps().get_by_name(args.app)
    if app is None:
        _die(f"App {args.app!r} does not exist.")
    cid = s.get_channels().insert(Channel(id=None, name=args.channel, app_id=app.id))
    if cid is None:
        _die(f"Invalid or duplicate channel name {args.channel!r} "
             "(1-16 chars, [a-zA-Z0-9-]).")
    s.get_events().init(app.id, cid)
    print(f"Created channel {args.channel} (ID {cid}) in app {args.app}.")
    return 0


def cmd_app_channel_delete(args) -> int:
    s = _storage()
    app = s.get_apps().get_by_name(args.app)
    if app is None:
        _die(f"App {args.app!r} does not exist.")
    ch = next((c for c in s.get_channels().get_by_app_id(app.id)
               if c.name == args.channel), None)
    if ch is None:
        _die(f"Channel {args.channel!r} does not exist in app {args.app!r}.")
    s.get_events().remove(app.id, ch.id)
    s.get_channels().delete(ch.id)
    print(f"Deleted channel {args.channel} from app {args.app}.")
    return 0


# --------------------------------------------------------------------------
# pio accesskey ...
# --------------------------------------------------------------------------

def cmd_accesskey_new(args) -> int:
    from predictionio_tpu.data.storage import AccessKey

    s = _storage()
    app = s.get_apps().get_by_name(args.app)
    if app is None:
        _die(f"App {args.app!r} does not exist.")
    key = s.get_access_keys().insert(
        AccessKey(key="", app_id=app.id, events=tuple(args.events))
    )
    print(f"Created new access key: {key}")
    if args.events:
        print(f"  (restricted to events: {', '.join(args.events)})")
    return 0


def cmd_accesskey_list(args) -> int:
    s = _storage()
    keys = s.get_access_keys()
    if args.app:
        app = s.get_apps().get_by_name(args.app)
        if app is None:
            _die(f"App {args.app!r} does not exist.")
        rows = keys.get_by_app_id(app.id)
    else:
        rows = keys.get_all()
    for k in rows:
        ev = ",".join(k.events) if k.events else "(all)"
        print(f"{k.key}  app={k.app_id}  events={ev}")
    print(f"Finished listing {len(rows)} access key(s).")
    return 0


def cmd_accesskey_delete(args) -> int:
    if not _storage().get_access_keys().delete(args.key):
        _die(f"Access key {args.key!r} does not exist.")
    print("Deleted access key.")
    return 0


# --------------------------------------------------------------------------
# pio train / eval
# --------------------------------------------------------------------------

def cmd_train(args) -> int:
    from predictionio_tpu.controller import EngineVariant, RuntimeContext, load_engine_factory
    from predictionio_tpu.parallel.distributed import initialize_distributed
    from predictionio_tpu.resilience.supervision import (
        PREEMPTED_EXIT_CODE,
        TrainPreempted,
        install_preemption_handler,
    )
    from predictionio_tpu.workflow import run_train

    initialize_distributed()
    # SIGTERM during training → final checkpoint + exit 143 (preemption
    # contract, README "Training supervision"): the supervisor's rerun
    # resumes via --checkpoint-dir.
    install_preemption_handler()
    if getattr(args, "checkpoint_dir", None):
        if args.checkpoint_every <= 0:
            _die("--checkpoint-dir requires --checkpoint-every N (the save "
                 "cadence); without it no checkpoints would be written and "
                 "a killed train could not resume.")
        os.environ["PIO_CHECKPOINT_DIR"] = args.checkpoint_dir
        os.environ["PIO_CHECKPOINT_EVERY"] = str(args.checkpoint_every)
    elif getattr(args, "checkpoint_every", 0) > 0:
        _die("--checkpoint-every requires --checkpoint-dir DIR (where to "
             "save); without it no checkpoints would be written.")
    if getattr(args, "prefetch_depth", 0) > 0:
        # Overlapped input pipeline (data/prefetch.py): the deep-model
        # train loops read this when constructing their DevicePrefetcher.
        os.environ["PIO_PREFETCH_DEPTH"] = str(args.prefetch_depth)
    if getattr(args, "fuse_steps", None):
        # K-step fused dispatch (data/fusion.py): an int pins the scan
        # depth, "auto" hands it to the HBM-guided autotuner.
        text = str(args.fuse_steps).strip().lower()
        if text != "auto":
            try:
                if int(text) < 1:
                    _die("--fuse-steps must be a positive integer or "
                         "'auto'.")
            except ValueError:
                _die(f"--fuse-steps {args.fuse_steps!r} is neither an "
                     "integer nor 'auto'.")
        os.environ["PIO_FUSE_STEPS"] = text
    if getattr(args, "batch_autoscale", False):
        # Opt-in: wider (concatenated) optimizer steps once fusion depth
        # caps out — a semantics change, so never on by default.
        os.environ["PIO_BATCH_AUTOSCALE"] = "on"
    if getattr(args, "pq", None):
        # Quantized-corpus build policy (retrieval/pq.py): templates
        # read PIO_PQ at train time when deciding whether to serialize
        # residual codes next to the IVF index.
        text = str(args.pq).strip().lower()
        if text not in ("auto", "on", "off"):
            _die(f"--pq {args.pq!r} must be auto|on|off.")
        os.environ["PIO_PQ"] = text
    if getattr(args, "pq_m", 0):
        if args.pq_m < 1:
            _die("--pq-m must be a positive integer (subspace count).")
        os.environ["PIO_PQ_M"] = str(args.pq_m)
    variant_path = Path(args.engine_json)
    if not variant_path.exists():
        _die(f"{variant_path} not found (expected an engine.json).")
    variant = EngineVariant.from_file(variant_path)
    engine = load_engine_factory(variant.engine_factory)()
    ctx = RuntimeContext.create(seed=args.seed, mesh_spec=args.mesh)
    if ctx.mesh is not None:
        print(f"Mesh: {dict(ctx.mesh.shape)} over {ctx.mesh.devices.size} device(s)")
    if getattr(args, "follow", False):
        return _train_follow(args, engine, variant, ctx)
    try:
        instance_id = run_train(engine, variant, ctx)
    except TrainPreempted as e:
        print(f"[preempted] {e}", file=sys.stderr)
        print("[preempted] rerun the same `pio train` command to resume.",
              file=sys.stderr)
        return PREEMPTED_EXIT_CODE
    print(f"Training completed. Engine instance ID: {instance_id}")
    return 0


def _train_follow(args, engine, variant, ctx) -> int:
    """`pio train --follow` (ISSUE 10): the continuous-refresh daemon.

    Retrains on a cadence — delta warm-start when the last generation
    carries a watermark and continuable state, full retrain otherwise —
    and, with --promote-url / PIO_REFRESH_PROMOTE_URL, promotes each
    generation through the serving server's staged-reload canary gate
    (rolling back if the SLO burn trips inside the canary window).
    SIGTERM/SIGINT stop the loop; one mid-train exits with the
    preemption contract (checkpoint + exit 143) like any other train."""
    import signal

    from predictionio_tpu.refresh import RefreshConfig
    from predictionio_tpu.refresh.daemon import RefreshDaemon
    from predictionio_tpu.resilience.supervision import (
        PREEMPTED_EXIT_CODE,
        TrainPreempted,
        request_preemption,
    )

    cfg = RefreshConfig.from_env(
        interval_s=getattr(args, "refresh_interval", None),
        promote_url=getattr(args, "promote_url", None),
        canary_window_s=getattr(args, "canary_window", None),
        trigger_staleness_s=getattr(args, "trigger_staleness", None),
        trigger_delta_count=getattr(args, "trigger_delta_count", None),
    )
    daemon = RefreshDaemon(engine, variant, ctx, config=cfg)

    def _stop(signum, frame):
        print(f"[follow] signal {signum}: stopping after the current "
              "cycle (mid-train: checkpoint + resume semantics apply)",
              file=sys.stderr)
        request_preemption()   # an in-flight train checkpoints and exits
        daemon.stop()          # the between-cycles wait wakes immediately

    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(sig, _stop)
        except (ValueError, OSError):
            continue
    where = f", promoting via {cfg.promote_url}" if cfg.promote_url else \
        " (no promote URL — serving servers reload on their own)"
    if cfg.trigger_staleness_s is not None \
            or cfg.trigger_delta_count is not None:
        trig = []
        if cfg.trigger_staleness_s is not None:
            trig.append(f"staleness≥{cfg.trigger_staleness_s:g}s")
        if cfg.trigger_delta_count is not None:
            trig.append(f"delta≥{cfg.trigger_delta_count} events")
        print(f"Refresh daemon: trigger mode ({' or '.join(trig)}, "
              f"backstop every {cfg.interval_s:g}s){where}. Ctrl-C to "
              "stop.")
    else:
        print(f"Refresh daemon: retraining every {cfg.interval_s:g}s"
              f"{where}. Ctrl-C to stop.")
    try:
        cycles = daemon.follow()
    except TrainPreempted as e:
        print(f"[preempted] {e}", file=sys.stderr)
        return PREEMPTED_EXIT_CODE
    print(f"Refresh daemon stopped after {cycles} cycle(s).")
    return 0


def cmd_eval(args) -> int:
    from predictionio_tpu.controller import load_engine_factory, RuntimeContext
    from predictionio_tpu.parallel.distributed import initialize_distributed
    from predictionio_tpu.resilience.supervision import (
        PREEMPTED_EXIT_CODE,
        TrainPreempted,
        install_preemption_handler,
    )
    from predictionio_tpu.workflow import run_evaluation

    initialize_distributed()
    # Same preemption contract as training (ISSUE 15 satellite): SIGTERM
    # checkpoints the sweep at the current (candidate, fold) boundary and
    # exits 143; rerunning the same command resumes.
    install_preemption_handler()
    evaluation = load_engine_factory(args.evaluation_class)()
    generator = load_engine_factory(args.params_generator_class)()
    ctx = RuntimeContext.create(seed=args.seed, mesh_spec=args.mesh)
    try:
        instance_id, result = run_evaluation(
            evaluation,
            generator,
            ctx,
            evaluation_class=args.evaluation_class,
            params_generator_class=args.params_generator_class,
            checkpoint_dir=getattr(args, "checkpoint_dir", None),
        )
    except TrainPreempted as e:
        print(f"[preempted] {e}", file=sys.stderr)
        print("[preempted] rerun the same `pio eval` command to resume "
              "from the checkpointed folds.", file=sys.stderr)
        return PREEMPTED_EXIT_CODE
    print(result.summary())
    print(f"Evaluation instance ID: {instance_id}")
    if args.output_json:
        inst = ctx.storage.get_evaluation_instances().get(instance_id)
        Path(args.output_json).write_text(inst.evaluator_results_json)
        print(f"Results written to {args.output_json}")
    return 0


def cmd_build(args) -> int:
    """Reference: `pio build` compiles the engine via sbt; with a Python
    engine there is nothing to compile, so this validates instead: the
    engine.json parses, the factory imports, params bind, and (with
    --compile-check) the flagship predict path traces under jit."""
    from predictionio_tpu.controller import EngineVariant, load_engine_factory

    variant_path = Path(args.engine_json)
    if not variant_path.exists():
        _die(f"{variant_path} not found (expected an engine.json).")
    variant = EngineVariant.from_file(variant_path)
    engine = load_engine_factory(variant.engine_factory)()
    params = engine.bind_engine_params(variant.raw)
    n_algos = len(params.algorithms_params)
    print(f"Engine factory {variant.engine_factory} OK "
          f"({n_algos} algorithm(s): "
          f"{', '.join(n for n, _ in params.algorithms_params)}).")
    print("Engine variant params bind cleanly. Build successful.")
    return 0


# --------------------------------------------------------------------------
# pio eventserver / deploy / dashboard
# --------------------------------------------------------------------------

def _install_drain_handlers(drain) -> None:
    """SIGTERM/SIGINT → graceful drain: stop accepting, finish in-flight
    requests, flush the spill journal — a k8s rolling restart must not
    lose events that were already 202-accepted."""
    import signal

    def _handler(signum, frame):
        logger.info("signal %d: draining", signum)
        try:
            drain()
        except Exception:
            # exit NON-zero with the traceback logged: a failed drain
            # (e.g. spill flush on a full disk) must not look clean to
            # the supervisor that sent the signal
            logger.exception("drain failed")
            raise SystemExit(1) from None
        raise SystemExit(0)

    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(sig, _handler)
        except (ValueError, OSError):  # non-main thread / exotic platform
            continue


def cmd_eventserver(args) -> int:
    import time as _time

    from predictionio_tpu.server import EventServer

    srv = EventServer(storage=_storage(), host=args.ip, port=args.port)
    if getattr(args, "native", False):
        # C++ continuous-batching frontend: concurrent single-event POSTs
        # aggregate into ONE group-committed insert per callback.
        from predictionio_tpu.native.frontend import NativeFrontend

        fe = NativeFrontend(None, host=args.ip, port=args.port,
                            fallback_batch=srv.native_fallback_batch,
                            plugin_hook=(srv.plugins.header_block
                                         if srv.plugins else None))
        fe.start()

        def _drain_native():
            fe.stop()
            srv.drain()

        _install_drain_handlers(_drain_native)
        print(f"Event Server (native frontend) listening on "
              f"{args.ip}:{fe.port} (Ctrl-C to stop)")
        try:
            while True:
                _time.sleep(3600)
        except KeyboardInterrupt:
            _drain_native()
        return 0
    _install_drain_handlers(srv.drain)
    srv.start(block=False)
    print(f"Event Server listening on {args.ip}:{srv.port} "
          "(Ctrl-C to stop)")
    try:
        srv._thread.join()
    except KeyboardInterrupt:
        srv.drain()
    return 0


def cmd_deploy(args) -> int:
    from predictionio_tpu.controller import EngineVariant, load_engine_factory
    from predictionio_tpu.parallel.distributed import initialize_distributed
    from predictionio_tpu.server import EngineServer
    from predictionio_tpu.serving import SchedulerConfig

    initialize_distributed()
    variant_path = Path(args.engine_json)
    if not variant_path.exists():
        _die(f"{variant_path} not found (expected an engine.json).")
    variant = EngineVariant.from_file(variant_path)
    engine = load_engine_factory(variant.engine_factory)()
    # Serving-scheduler knobs: flags override the PIO_BATCH_*/PIO_QUEUE_*
    # env (SchedulerConfig.from_env ignores None overrides).
    sched_cfg = SchedulerConfig.from_env(
        enabled=False if args.no_batcher else None,
        window_ms=args.batch_window_ms,
        queue_depth=args.queue_depth,
        max_batch=args.max_batch,
        p99_target_ms=args.batch_p99_target_ms,
    )
    srv = EngineServer(
        engine, variant, _storage(), host=args.ip, port=args.port,
        instance_id=args.engine_instance_id, mesh_spec=args.mesh,
        scheduler_config=sched_cfg,
    )
    if args.native:
        from predictionio_tpu.native.frontend import NativeFrontend

        import threading as _threading

        stop_event = _threading.Event()

        def engine_fallback(method, path_with_qs, body):
            # Non-query routes (/reload and friends) keep working behind
            # the native frontend — the reference's deploy server
            # supports hot-reload after retrain (SURVEY §3.2).  GET /
            # and GET /metrics stay C++-answered in deploy mode
            # (frontend liveness + batching counters).  /stop
            # must stop the FRONTEND, and not from inside its own
            # callback thread (pio_frontend_stop joins the batchers):
            # answer first, signal the main loop to tear down.
            path = path_with_qs.split("?", 1)[0]
            if path == "/stop" and method == "POST":
                stop_event.set()
                return 200, {"status": "stopping"}
            return srv.handle(method, path, body)

        # Same batch ceiling as the scheduler config (flag beats
        # PIO_BATCH_MAX beats 64) — one knob, both batching stacks.
        fe = NativeFrontend(srv.query_batch, host=args.ip, port=args.port,
                            max_batch=sched_cfg.max_batch,
                            max_wait_us=args.max_wait_us,
                            fallback=engine_fallback,
                            plugin_hook=(srv.plugins.header_block
                                         if srv.plugins else None))
        def _drain_native_deploy():
            fe.stop()
            srv.plugins.stop()

        _install_drain_handlers(_drain_native_deploy)
        port = fe.start()
        print(f"Native engine frontend on {args.ip}:{port} "
              f"(instance {srv._instance.id}; continuous batching "
              f"≤{sched_cfg.max_batch}; Ctrl-C to stop)")
        try:
            stop_event.wait()
        except KeyboardInterrupt:
            pass
        fe.stop()
        srv.plugins.stop()
        return 0
    _install_drain_handlers(srv.stop)
    srv.start(block=False)
    print(f"Engine Server listening on {args.ip}:{srv.port} "
          f"(instance {srv._instance.id}; Ctrl-C to stop)")
    try:
        srv._thread.join()
    except KeyboardInterrupt:
        srv.stop()
    return 0


def cmd_batchpredict(args) -> int:
    """Reference: `pio batchpredict` (0.13+) — bulk queries from NDJSON.

    Uses the EngineServer's batched path so the whole file is answered in
    vectorized XLA chunks, not per-line predicts.
    """
    from predictionio_tpu.controller import EngineVariant, load_engine_factory
    from predictionio_tpu.parallel.distributed import initialize_distributed
    from predictionio_tpu.server import EngineServer

    initialize_distributed()
    variant_path = Path(args.engine_json)
    if not variant_path.exists():
        _die(f"{variant_path} not found (expected an engine.json).")
    variant = EngineVariant.from_file(variant_path)
    engine = load_engine_factory(variant.engine_factory)()
    srv = EngineServer(engine, variant, _storage(),
                       instance_id=args.engine_instance_id,
                       mesh_spec=args.mesh)
    queries = []
    with open(args.input) as f:
        for line_no, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                queries.append(json.loads(line))
            except json.JSONDecodeError as e:
                _die(f"{args.input}:{line_no}: {e}")
    n = 0
    with open(args.output, "w") as out:
        for start in range(0, len(queries), args.query_partitions):
            chunk = queries[start:start + args.query_partitions]
            for q, r in zip(chunk, srv.query_batch(chunk)):
                out.write(json.dumps({"query": q, "prediction": r}) + "\n")
                n += 1
    print(f"Wrote {n} predictions to {args.output}.")
    return 0


def cmd_adminserver(args) -> int:
    from predictionio_tpu.server.admin import AdminServer

    srv = AdminServer(storage=_storage(), host=args.ip, port=args.port)
    srv.start(block=False)
    print(f"Admin server listening on {args.ip}:{srv.port} (Ctrl-C to stop)")
    try:
        srv._thread.join()
    except KeyboardInterrupt:
        srv.stop()
    return 0


def cmd_template_get(args) -> int:
    """Reference: `pio template get <gallery-repo> <dir>` scaffolds a new
    engine from the template gallery.  The rebuild's gallery is the
    source checkout's examples/<name>; this copies the engine.json +
    quickstart into the target directory, ready for `pio build` /
    `pio train`."""
    import shutil

    gallery = Path(__file__).resolve().parents[2] / "examples"
    if not gallery.is_dir():
        # pip wheels ship only predictionio_tpu/*; the scaffold gallery
        # lives in the source checkout.
        _die("No template gallery in this installation (pip wheels ship "
             "only the package) — run from a source checkout, which has "
             "examples/<template>/engine.json scaffolds.")
    name = args.template.rstrip("/").split("/")[-1]  # accept repo-ish paths
    src = gallery / name
    if not src.is_dir():
        avail = sorted(d.name for d in gallery.iterdir()
                       if d.is_dir() and not d.name.startswith("_"))
        _die(f"Unknown template {name!r}. Available: {', '.join(avail)}")
    dst = Path(args.directory)
    if dst.exists() and (not dst.is_dir() or any(dst.iterdir())):
        _die(f"{dst} exists and is not empty.")
    shutil.copytree(src, dst, dirs_exist_ok=True)
    print(f"Template {name!r} copied to {dst}/")
    for f in sorted(p.name for p in dst.iterdir()):
        print(f"  {f}")
    print("Next: edit engine.json (appName), then `pio train` there.")
    return 0


def cmd_shell(args) -> int:
    """Reference: `pio-shell` (a spark-shell with the pio jars).  Here: a
    Python REPL with the storage, config, and template modules preloaded."""
    import code

    from predictionio_tpu import config as pio_config
    from predictionio_tpu.data.storage import get_storage

    storage = get_storage()
    banner = (
        f"predictionio_tpu shell\n"
        f"  storage  -> {type(storage).__name__} "
        f"({storage.config.repositories['METADATA'].source} metadata)\n"
        f"  apps     -> storage.get_apps().get_all()\n"
        f"  events   -> storage.get_events()\n"
        f"Modules: predictionio_tpu (pio), numpy (np), jax, jax.numpy (jnp)"
    )
    import jax
    import jax.numpy as jnp
    import numpy as np

    import predictionio_tpu as pio

    code.interact(banner=banner, local={
        "storage": storage, "pio": pio, "np": np, "jax": jax, "jnp": jnp,
        "config": pio_config,
    })
    return 0


def cmd_storageserver(args) -> int:
    """Host this process's configured storage over TCP (data/storage/remote.py)
    so OTHER processes can select it with type=pioserver — the reference's
    network-storage deployment shape (JDBC/HBase/ES) without their servers."""
    from predictionio_tpu.data.storage.remote import StorageServer

    secret = args.secret or os.environ.get("PIO_STORAGE_SERVER_SECRET")
    srv = StorageServer(_storage(), host=args.ip, port=args.port,
                        secret=secret)
    srv.start()
    print(f"Storage server listening on {args.ip}:{srv.port} (Ctrl-C to stop)")
    print("Clients: PIO_STORAGE_SOURCES_REMOTE_TYPE=pioserver "
          f"PIO_STORAGE_SOURCES_REMOTE_HOSTS={args.ip} "
          f"PIO_STORAGE_SOURCES_REMOTE_PORTS={srv.port} "
          "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE=REMOTE")
    try:
        srv._thread.join()
    except KeyboardInterrupt:
        srv.stop()
    return 0


def cmd_profile(args) -> int:
    """On-demand profiler capture (obs.profiler).

    With --url, arms the capture on a RUNNING admin server
    (POST /admin/profile) and returns immediately — the artifact lands on
    the server's disk.  Without it, captures THIS process for the window
    (mostly useful under `pio shell` or to smoke-test the platform)."""
    duration_ms = args.duration_ms
    if args.url:
        from urllib.error import HTTPError
        from urllib.request import Request, urlopen

        base = args.url.rstrip("/")
        url = base + f"/admin/profile?duration_ms={duration_ms:g}"
        try:
            with urlopen(Request(url, method="POST"), timeout=30) as resp:
                body = json.loads(resp.read() or b"{}")
        except HTTPError as e:
            payload = e.read()
            try:
                msg = json.loads(payload).get("message", "")
            except Exception:
                msg = payload.decode(errors="replace")[:200]
            _die(f"profile request failed: HTTP {e.code}: {msg}")
        except OSError as e:
            _die(f"cannot reach {args.url}: {e}")
        print(f"Profiling for {body.get('durationMs', duration_ms):g} ms; "
              f"artifacts: {body.get('path')}")
        if not args.out:
            print("(view in TensorBoard/XProf or chrome://tracing once "
                  "the window closes; --out FILE downloads the archive)")
            return 0
        # ISSUE 9 satellite: the capture path above is SERVER-local —
        # wait the window out, then pull the archive down over HTTP so
        # remote/fleet operation never needs box access.
        import time as _time

        _time.sleep(float(body.get("durationMs", duration_ms)) / 1e3)
        deadline = _time.monotonic() + 30
        while _time.monotonic() < deadline:
            try:
                with urlopen(base + "/admin/profile", timeout=10) as resp:
                    if not json.loads(resp.read() or b"{}").get("active"):
                        break
            except OSError:
                pass
            _time.sleep(0.25)
        try:
            with urlopen(base + "/admin/profile/artifact",
                         timeout=60) as resp:
                data = resp.read()
                disposition = resp.headers.get("Content-Disposition", "")
        except HTTPError as e:
            _die(f"artifact download failed: HTTP {e.code}")
        except OSError as e:
            _die(f"artifact download failed: {e}")
        out = Path(args.out)
        if out.is_dir():
            # The server names the archive after its capture dir
            # (Content-Disposition); fall back to a stable default.
            m = re.search(r'filename="([^"/\\]+)"', disposition)
            out = out / (m.group(1) if m else "pio_profile.tar.gz")
        out.write_bytes(data)
        print(f"Profile archive downloaded: {out} ({len(data):,} bytes)")
        return 0
    from predictionio_tpu.obs.profiler import ProfilerUnavailable, capture

    try:
        path = capture(duration_ms, args.out)
    except ValueError as e:  # bad --duration-ms: same clean error as --url
        _die(str(e))
    except ProfilerUnavailable as e:
        _die(f"this platform cannot capture a profile: {e}")
    print(f"Profile captured: {path}")
    return 0


def cmd_dashboard(args) -> int:
    from predictionio_tpu.server.dashboard import DashboardServer

    fleet = ([u.strip() for u in args.fleet.split(",") if u.strip()]
             if getattr(args, "fleet", None) else None)
    srv = DashboardServer(storage=_storage(), host=args.ip, port=args.port,
                          fleet=fleet)
    srv.start(block=False)
    print(f"Dashboard listening on {args.ip}:{srv.port} (Ctrl-C to stop)")
    try:
        srv._thread.join()
    except KeyboardInterrupt:
        srv.stop()
    return 0


# --------------------------------------------------------------------------
# pio rollout — coordinated wave promotion across a fleet (ISSUE 15)
# --------------------------------------------------------------------------

def cmd_rollout(args) -> int:
    from predictionio_tpu.fleet import RolloutConfig, RolloutController
    from predictionio_tpu.obs.fleet import fleet_instances_from_env

    urls = ([u.strip().rstrip("/") for u in args.instances.split(",")
             if u.strip()] if args.instances
            else fleet_instances_from_env())
    if not urls:
        _die("no instances (--instances URL,URL or PIO_FLEET_INSTANCES)")
    cfg = RolloutConfig.from_env(
        waves=args.waves, bake_s=args.bake_s, poll_s=args.poll_s,
        state_path=args.state)
    ctl = RolloutController(urls, cfg)
    if args.resume or args.unwind:
        try:
            state = ctl.resume(unwind=args.unwind)
        except RuntimeError as e:
            _die(str(e))
    else:
        prior = ctl.load_state()
        if prior and prior.get("status") in ("in_progress",
                                             "rolling_back"):
            _die(f"rollout {prior.get('rolloutId')} is journaled "
                 f"{prior.get('status')!r} at {ctl.state_path} — finish "
                 "it first (--resume to continue, --unwind to roll it "
                 "back)")
        state = ctl.run(args.engine_instance_id)
    print(f"rollout {state.get('rolloutId')}: {state['status']} "
          f"(target instance {state.get('target')})")
    print(f"  promoted: {len(state.get('promoted', []))}/"
          f"{len(state.get('instances', []))} instance(s)"
          + (f" through wave {state.get('wave')}"
             if state.get('wave') is not None else ""))
    for url, why in (state.get("skipped") or {}).items():
        print(f"  skipped {url}: {why}")
    if state.get("haltReason"):
        print(f"  halt: {state['haltReason']}")
    for url in state.get("rolledBack", []):
        print(f"  rolled back {url}")
    for url, why in (state.get("unwindFailures") or {}).items():
        print(f"  UNWIND FAILED {url}: {why} — roll this instance back "
              "by hand (POST /admin/rollback)")
    print(f"  state journal: {ctl.state_path}")
    # An explicitly requested unwind that rolled every instance back IS
    # the success case; a rollout (or resumed rollout) that got halted
    # and rolled back is not.
    ok = state["status"] == "promoted" or (
        args.unwind and state["status"] == "rolled_back"
        and not state.get("unwindFailures"))
    return 0 if ok else 1


# --------------------------------------------------------------------------
# pio spill — manual spill-journal operations (ISSUE 4 satellite: the
# stopgap for ROADMAP resilience follow-on (b) until shared-queue spill)
# --------------------------------------------------------------------------

def _spill_dir(args) -> "Path":
    from predictionio_tpu.config import load_config
    from predictionio_tpu.resilience.spill import resolve_spill_dir

    d = resolve_spill_dir(getattr(args, "dir", None), load_config().home)
    if d is None:
        _die("spilling is disabled (PIO_SPILL_DIR=off and no --dir given).")
    return d


def _spill_cli_backend(args) -> str:
    """local|shared for the spill verbs: --backend > PIO_SPILL_BACKEND >
    auto (shared only on a pioserver EVENTDATA source)."""
    from predictionio_tpu.resilience.shared_spill import (
        resolve_spill_backend,
    )

    try:
        ev_type = _storage().config.source_for("EVENTDATA").type
    except Exception:
        ev_type = None
    return resolve_spill_backend(getattr(args, "backend", None), ev_type)


def _shared_spill(args):
    from predictionio_tpu.data.storage import StorageError
    from predictionio_tpu.resilience.shared_spill import SharedSpillQueue

    try:
        storage = _storage()
        storage.get_spill_queues()  # probe support
    except StorageError as e:
        _die(f"shared spill queue unavailable on this storage: {e}")
    return SharedSpillQueue(storage)


def cmd_spill_inspect(args) -> int:
    from predictionio_tpu.resilience.spill import journal_summary

    if _spill_cli_backend(args) == "shared":
        q = _shared_spill(args)
        st = q.stats()
        print(f"shared spill queue [{q.queue}] "
              f"(storage-backed, fleet-wide):")
        print(f"  pending: {st.get('pending', 0)} record(s) / "
              f"{st.get('pendingEvents', 0)} event(s)")
        print(f"  leased: {st.get('leased', 0)} record(s) "
              f"({st.get('expired', 0)} with expired leases awaiting "
              "takeover)")
        print(f"  dead-lettered: {st.get('dead', 0)} record(s) / "
              f"{st.get('deadEvents', 0)} event(s)")
        if args.json:
            print(json.dumps(st))
        return 0
    s = journal_summary(_spill_dir(args))
    print(f"spill journal: {s['dir']}")
    print(f"  pending: {s['pendingRecords']} record(s) / "
          f"{s['pendingEvents']} event(s) "
          f"(offset {s['replayedOffset']}/{s['records']})")
    if s["pendingTokens"]:
        print(f"  next tokens: {', '.join(t or '-' for t in s['pendingTokens'])}")
    print(f"  dead-lettered: {s['deadRecords']} record(s) / "
          f"{s['deadEvents']} event(s)")
    for inst in s["privateInstanceDirs"]:
        print(f"  private instance dir (locked-journal divert): {inst}")
    if args.json:
        print(json.dumps(s))
    return 0


def _open_spill_exclusive(args):
    """The mutating verbs need THE journal, not a diverted private one."""
    from predictionio_tpu.resilience.spill import SpillJournal

    try:
        return SpillJournal(_spill_dir(args), divert_if_locked=False)
    except RuntimeError as e:
        _die(str(e))


def _spill_insert_fn(storage):
    """One journal/queue record → storage, token-pinned (shared by both
    drain backends)."""
    from predictionio_tpu.data.json_support import event_from_json
    from predictionio_tpu.resilience import idempotency_key

    def insert(record):
        evs = [event_from_json(e) for e in record["events"]]
        with idempotency_key(record["token"]):
            storage.get_events().insert_batch(evs, record["appId"],
                                              record.get("channelId"))
    return insert


def cmd_spill_drain(args) -> int:
    """Foreground replay into storage — the same record-at-a-time,
    token-pinned insert the event server's background worker does, for
    when that server is gone (crashed box, decommission) but its spill
    must not be.  Against the shared queue this is just another lease
    drainer (safe next to live instances — leases serialize the work);
    against the local journal it takes the exclusive flock."""
    from predictionio_tpu.resilience.spill import ReplayWorker

    storage = _storage()
    if _spill_cli_backend(args) == "shared":
        from predictionio_tpu.resilience.shared_spill import LeaseDrainer

        q = _shared_spill(args)
        # owner=None → LeaseDrainer mints a pid+uuid identity: two
        # operators draining concurrently must never share an owner, or
        # one's dead_letter could park a record the other just landed.
        drainer = LeaseDrainer(q, _spill_insert_fn(storage),
                               batch=args.batch)
        landed = drainer.drain_once()
        remaining = q.depth()
        print(f"Replayed {landed} event(s); {remaining} still pending "
              "in the shared queue"
              + (" (storage unavailable or leased elsewhere — re-run "
                 "after recovery)." if remaining else "."))
        return 0 if remaining == 0 else 1
    journal = _open_spill_exclusive(args)
    worker = ReplayWorker(journal, _spill_insert_fn(storage),
                          batch=args.batch)
    try:
        landed = worker.drain_once()
        remaining = journal.depth()
    finally:
        journal.close()
    print(f"Replayed {landed} event(s); {remaining} still pending"
          + (" (storage unavailable — re-run after recovery)."
             if remaining else "."))
    return 0 if remaining == 0 else 1


def cmd_spill_requeue_dead(args) -> int:
    if _spill_cli_backend(args) == "shared":
        n = _shared_spill(args).requeue_dead()
        if n == 0:
            print("No dead-lettered records in the shared queue.")
        else:
            print(f"Requeued {n} dead-lettered event(s) — any instance's "
                  "drainer (or `pio spill drain`) replays them.")
        return 0
    journal = _open_spill_exclusive(args)
    try:
        n = journal.requeue_dead()
    finally:
        journal.close()
    if n == 0:
        print("No dead-lettered records.")
    else:
        print(f"Requeued {n} dead-lettered event(s) for replay "
              "(drain with `pio spill drain` or restart the event server).")
    return 0


# --------------------------------------------------------------------------
# pio import / export
# --------------------------------------------------------------------------

# Streamed-import commit granularity (module-level so tests can shrink
# it to exercise the chunk-boundary resume path).
IMPORT_CHUNK = 50_000


def cmd_import(args) -> int:
    """Streamed import: parse + insert in bounded chunks so a 25M-event
    file never materializes as one Python list (reference: FileToEvents;
    VERDICT r4 item 1a).  Each chunk is one group-committed insert_batch.

    Chunks committed before a parse error STAY committed (event ids are
    store-assigned, so a naive full re-run would duplicate them); the
    error message reports the exact resume point and ``--from-line``
    skips the already-imported prefix on retry."""
    from predictionio_tpu.data.json_support import event_from_json

    s = _storage()
    channel_id = _resolve_channel(s, args.appid, args.channel)
    ev = s.get_events()
    ev.init(args.appid, channel_id)
    start_line = max(1, getattr(args, "from_line", 1) or 1)
    total = 0
    chunk = []
    last_committed_line = start_line - 1
    with open(args.input) as f:
        for line_no, line in enumerate(f, 1):
            if line_no < start_line:
                continue
            line = line.strip()
            if not line:
                continue
            try:
                chunk.append(event_from_json(json.loads(line)))
            except Exception as e:
                _die(
                    f"{args.input}:{line_no}: {e}\n"
                    f"{total} event(s) up to line {last_committed_line} "
                    f"were already imported and remain stored; fix the "
                    f"line and re-run with --from-line "
                    f"{last_committed_line + 1} to avoid duplicates.")
            if len(chunk) >= IMPORT_CHUNK:
                total += len(ev.insert_batch(chunk, args.appid, channel_id))
                chunk = []
                last_committed_line = line_no
    if chunk:
        total += len(ev.insert_batch(chunk, args.appid, channel_id))
    print(f"Imported {total} events to app {args.appid}.")
    return 0


def cmd_export(args) -> int:
    from predictionio_tpu.data.json_support import event_to_json

    s = _storage()
    channel_id = _resolve_channel(s, args.appid, args.channel)
    n = 0
    with open(args.output, "w") as f:
        for ev in s.get_events().find(args.appid, channel_id):
            f.write(json.dumps(event_to_json(ev)) + "\n")
            n += 1
    print(f"Exported {n} events from app {args.appid} to {args.output}.")
    return 0


def _resolve_channel(s, app_id: int, channel_name: Optional[str]) -> Optional[int]:
    if not channel_name:
        return None
    ch = next((c for c in s.get_channels().get_by_app_id(app_id)
               if c.name == channel_name), None)
    if ch is None:
        _die(f"Channel {channel_name!r} does not exist in app {app_id}.")
    return ch.id


# --------------------------------------------------------------------------
# parser
# --------------------------------------------------------------------------

def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="pio", description="predictionio_tpu console"
    )
    p.add_argument("--version", action="version", version=__version__)
    p.add_argument("-v", "--verbose", action="store_true")
    sub = p.add_subparsers(dest="verb", required=True)

    st = sub.add_parser("status", help="storage + device sanity check "
                                       "+ metrics snapshot")
    st.add_argument("--metrics-url", dest="metrics_url", default=None,
                    metavar="URL",
                    help="scrape a running server's /metrics into the "
                         "status report (e.g. http://127.0.0.1:7070)")
    st.add_argument("--fleet", dest="fleet", default=None,
                    metavar="URLS",
                    help="comma-separated instance base URLs (or unset: "
                         "PIO_FLEET_INSTANCES) — scrape and merge "
                         "/metrics + SLO state across the fleet instead "
                         "of one process")
    st.set_defaults(fn=cmd_status)

    app = sub.add_parser("app", help="app management").add_subparsers(
        dest="app_verb", required=True
    )
    a = app.add_parser("new")
    a.add_argument("name")
    a.add_argument("--description")
    a.add_argument("--access-key", dest="access_key")
    a.set_defaults(fn=cmd_app_new)
    app.add_parser("list").set_defaults(fn=cmd_app_list)
    a = app.add_parser("delete")
    a.add_argument("name")
    a.add_argument("-f", "--force", action="store_true")
    a.set_defaults(fn=cmd_app_delete)
    a = app.add_parser("data-delete")
    a.add_argument("name")
    a.add_argument("--channel")
    a.add_argument("-f", "--force", action="store_true")
    a.set_defaults(fn=cmd_app_data_delete)
    a = app.add_parser("channel-new")
    a.add_argument("app")
    a.add_argument("channel")
    a.set_defaults(fn=cmd_app_channel_new)
    a = app.add_parser("channel-delete")
    a.add_argument("app")
    a.add_argument("channel")
    a.set_defaults(fn=cmd_app_channel_delete)

    ak = sub.add_parser("accesskey", help="access key management").add_subparsers(
        dest="ak_verb", required=True
    )
    a = ak.add_parser("new")
    a.add_argument("app")
    a.add_argument("events", nargs="*")
    a.set_defaults(fn=cmd_accesskey_new)
    a = ak.add_parser("list")
    a.add_argument("app", nargs="?")
    a.set_defaults(fn=cmd_accesskey_list)
    a = ak.add_parser("delete")
    a.add_argument("key")
    a.set_defaults(fn=cmd_accesskey_delete)

    b = sub.add_parser("build", help="validate an engine variant")
    b.add_argument("--engine-json", default="engine.json")
    b.set_defaults(fn=cmd_build)

    t = sub.add_parser("train", help="train an engine variant")
    t.add_argument("--engine-json", default="engine.json")
    t.add_argument("--seed", type=int, default=0)
    t.add_argument("--checkpoint-dir", dest="checkpoint_dir", default=None,
                   help="orbax checkpoint root; with --checkpoint-every, a "
                        "killed train resumes from the last complete step")
    t.add_argument("--checkpoint-every", dest="checkpoint_every", type=int,
                   default=0, metavar="N",
                   help="save every N sweeps/steps (0 = off)")
    t.add_argument("--mesh", default=None, metavar="SPEC",
                   help="device mesh, e.g. 'data=8,model=2' or 'auto' "
                        "(default: env PIO_MESH, else single device)")
    t.add_argument("--prefetch-depth", dest="prefetch_depth", type=int,
                   default=0, metavar="N",
                   help="staged batches the input pipeline keeps ahead "
                        "of the device (default: env PIO_PREFETCH_DEPTH, "
                        "else 2; raise on fast-feeder/slow-step "
                        "workloads, lower if HBM headroom warns)")
    t.add_argument("--fuse-steps", dest="fuse_steps", default=None,
                   metavar="K|auto",
                   help="optimizer steps fused into one XLA dispatch "
                        "(lax.scan over a K-batch superbatch; "
                        "bitwise-equal to K=1).  'auto' grows depth "
                        "until the HBM headroom guardrail pushes back, "
                        "then backs off one notch and pins (default: "
                        "env PIO_FUSE_STEPS, else 1)")
    t.add_argument("--batch-autoscale", dest="batch_autoscale",
                   action="store_true",
                   help="let the fusion autotuner also widen the "
                        "effective batch (concatenate prepped batches) "
                        "once fusion depth caps out — fewer, wider "
                        "optimizer steps: a semantics change, opt-in "
                        "(env PIO_BATCH_AUTOSCALE=on)")
    t.add_argument("--pq", dest="pq", default=None, metavar="auto|on|off",
                   help="quantized-corpus build policy: serialize "
                        "residual PQ codes (1+M bytes/item) next to the "
                        "IVF index so serving LUT-scans the packed "
                        "codes and re-ranks a shortlist exactly "
                        "(default: env PIO_PQ, else auto — builds above "
                        "PIO_PQ_MIN_ITEMS)")
    t.add_argument("--pq-m", dest="pq_m", type=int, default=0,
                   metavar="M",
                   help="PQ subspace count (bytes/item = 1+M; default: "
                        "env PIO_PQ_M, else ~dim/4)")
    t.add_argument("--follow", action="store_true",
                   help="continuous refresh: retrain on a cadence "
                        "(delta warm-start when possible), promote "
                        "through the serving server's staged-reload "
                        "canary gate (--promote-url), roll back on SLO "
                        "burn; Ctrl-C/SIGTERM stops")
    t.add_argument("--refresh-interval", dest="refresh_interval",
                   type=float, default=None, metavar="S",
                   help="follow-mode cadence in seconds (default env "
                        "PIO_REFRESH_INTERVAL_S, else 300)")
    t.add_argument("--promote-url", dest="promote_url", default=None,
                   metavar="URL",
                   help="engine-server base URL each refreshed "
                        "generation is promoted through (POST /reload → "
                        "staged canary gate; default env "
                        "PIO_REFRESH_PROMOTE_URL; unset = train only)")
    t.add_argument("--canary-window", dest="canary_window", type=float,
                   default=None, metavar="S",
                   help="post-promotion SLO-burn watch window; a trip "
                        "rolls the promotion back (default env "
                        "PIO_REFRESH_CANARY_WINDOW_S, else 60; 0 = off)")
    t.add_argument("--trigger-staleness", dest="trigger_staleness",
                   type=float, default=None, metavar="S",
                   help="follow-mode trigger: fire a refresh cycle when "
                        "event→servable staleness crosses S seconds "
                        "(default env PIO_REFRESH_TRIGGER_STALENESS_S; "
                        "the --refresh-interval cadence becomes a "
                        "backstop ceiling)")
    t.add_argument("--trigger-delta-count", dest="trigger_delta_count",
                   type=int, default=None, metavar="N",
                   help="follow-mode trigger: fire a refresh cycle when "
                        "N events have landed past the served watermark "
                        "(default env PIO_REFRESH_TRIGGER_DELTA_COUNT)")
    t.set_defaults(fn=cmd_train)

    e = sub.add_parser("eval", help="evaluate engine-params candidates")
    e.add_argument("evaluation_class")
    e.add_argument("params_generator_class")
    e.add_argument("--seed", type=int, default=0)
    e.add_argument("--mesh", default=None, metavar="SPEC")
    e.add_argument("--output-json", dest="output_json")
    e.add_argument("--checkpoint-dir", dest="checkpoint_dir", default=None,
                   help="persist each completed (candidate, fold) unit "
                        "here so a SIGTERM'd sweep resumes instead of "
                        "restarting (default env PIO_EVAL_CHECKPOINT_DIR;"
                        " cleared when the sweep completes)")
    e.set_defaults(fn=cmd_eval)

    es = sub.add_parser("eventserver", help="start the event ingestion server")
    es.add_argument("--ip", default="0.0.0.0")
    es.add_argument("--port", type=int, default=7070)
    es.add_argument("--native", action="store_true",
                    help="serve through the C++ continuous-batching "
                         "frontend (group-committed ingest)")
    es.set_defaults(fn=cmd_eventserver)

    d = sub.add_parser("deploy", help="serve a trained engine over HTTP")
    d.add_argument("--engine-json", default="engine.json")
    d.add_argument("--ip", default="0.0.0.0")
    d.add_argument("--port", type=int, default=8000)
    d.add_argument("--engine-instance-id", dest="engine_instance_id")
    d.add_argument("--mesh", default=None, metavar="SPEC",
                   help="device mesh for model re-load/serve sharding")
    d.add_argument("--native", action="store_true",
                   help="serve via the C++ continuous-batching frontend")
    d.add_argument("--max-batch", type=int, default=None,
                   help="max queries per batched dispatch — applies to "
                        "the serving scheduler AND the native frontend "
                        "(default env PIO_BATCH_MAX, else 64)")
    d.add_argument("--max-wait-us", type=int, default=2000)
    d.add_argument("--no-batcher", action="store_true",
                   help="disable the serving micro-batcher (per-request "
                        "dispatch; admission control stays on)")
    d.add_argument("--batch-window-ms", dest="batch_window_ms", type=float,
                   default=None,
                   help="initial batch gather window (default env "
                        "PIO_BATCH_WINDOW_MS, else 2.0; autotuned live)")
    d.add_argument("--queue-depth", dest="queue_depth", type=int,
                   default=None,
                   help="admission queue depth; full queue answers 429 "
                        "(default env PIO_QUEUE_DEPTH, else 128)")
    d.add_argument("--batch-p99-target-ms", dest="batch_p99_target_ms",
                   type=float, default=None,
                   help="autotuner served-latency p99 target (default env "
                        "PIO_BATCH_P99_TARGET_MS, else 100)")
    d.set_defaults(fn=cmd_deploy)

    bp = sub.add_parser("batchpredict", help="bulk predict from NDJSON queries")
    bp.add_argument("--engine-json", default="engine.json")
    bp.add_argument("--input", required=True)
    bp.add_argument("--output", required=True)
    bp.add_argument("--engine-instance-id", dest="engine_instance_id")
    bp.add_argument("--mesh", default=None, metavar="SPEC")
    bp.add_argument("--query-partitions", type=int, default=256,
                    help="queries per vectorized predict chunk")
    bp.set_defaults(fn=cmd_batchpredict)

    adm = sub.add_parser("adminserver", help="app-management REST API server")
    adm.add_argument("--ip", default="127.0.0.1")
    adm.add_argument("--port", type=int, default=7071)
    adm.set_defaults(fn=cmd_adminserver)

    tpl = sub.add_parser("template", help="engine template gallery")
    tplsub = tpl.add_subparsers(dest="template_cmd", required=True)
    tg = tplsub.add_parser("get", help="scaffold an engine from a template")
    tg.add_argument("template", help="template name (e.g. recommendation)")
    tg.add_argument("directory", help="target directory")
    tg.set_defaults(fn=cmd_template_get)

    sh = sub.add_parser("shell", help="interactive shell with storage "
                                      "preloaded (reference: pio-shell)")
    sh.set_defaults(fn=cmd_shell)

    ss = sub.add_parser("storageserver",
                        help="serve this PIO_HOME's storage over TCP "
                             "(clients use type=pioserver)")
    ss.add_argument("--ip", default="127.0.0.1")
    ss.add_argument("--port", type=int, default=7077)
    ss.add_argument("--secret", default=None,
                    help="shared auth secret clients must present "
                         "(default: env PIO_STORAGE_SERVER_SECRET); "
                         "strongly recommended when binding non-loopback")
    ss.set_defaults(fn=cmd_storageserver)

    db = sub.add_parser("dashboard", help="engine/evaluation instance dashboard")
    db.add_argument("--ip", default="127.0.0.1")
    db.add_argument("--port", type=int, default=9000)
    db.add_argument("--fleet", default=None, metavar="URLS",
                    help="comma-separated instance base URLs to aggregate "
                         "at GET /fleet.json (default: "
                         "PIO_FLEET_INSTANCES)")
    db.set_defaults(fn=cmd_dashboard)

    pf = sub.add_parser("profile", help="on-demand JAX profiler capture "
                                        "(local, or a running admin "
                                        "server via --url)")
    pf.add_argument("--duration-ms", dest="duration_ms", type=float,
                    default=2000.0, help="capture window (default 2000)")
    pf.add_argument("--url", default=None,
                    help="admin server base URL (e.g. "
                         "http://127.0.0.1:7071) — capture happens there")
    pf.add_argument("--out", default=None,
                    help="local capture: artifact directory (default: "
                         "fresh temp dir; env PIO_PROFILE_OUT). With "
                         "--url: LOCAL file/dir the capture archive is "
                         "downloaded to after the window closes "
                         "(GET /admin/profile/artifact)")
    pf.set_defaults(fn=cmd_profile)

    ro = sub.add_parser("rollout",
                        help="promote a generation across a fleet in "
                             "gated waves, rolling the WHOLE fleet back "
                             "on degradation")
    ro.add_argument("--instances", default=None, metavar="URLS",
                    help="comma-separated engine-server base URLs "
                         "(default: PIO_FLEET_INSTANCES)")
    ro.add_argument("--engine-instance-id", dest="engine_instance_id",
                    default=None,
                    help="candidate engine instance id (default: the "
                         "first promoted server's latest COMPLETED, "
                         "then pinned fleet-wide)")
    ro.add_argument("--waves", default=None, metavar="SPEC",
                    help="wave tranches, counts or percentages "
                         "(default env PIO_ROLLOUT_WAVES, else "
                         "'1,25%%,100%%')")
    ro.add_argument("--bake-s", dest="bake_s", type=float, default=None,
                    help="per-wave observation window watching the "
                         "fleet-merged SLO burn + quality gate "
                         "(default env PIO_ROLLOUT_BAKE_S, else 10)")
    ro.add_argument("--poll-s", dest="poll_s", type=float, default=None,
                    help="gate poll cadence inside the bake (default "
                         "env PIO_ROLLOUT_POLL_S, else 1)")
    ro.add_argument("--state", default=None, metavar="FILE",
                    help="wave-state journal (default env "
                         "PIO_ROLLOUT_STATE, else "
                         "$PIO_HOME/rollout/state.json)")
    ro.add_argument("--resume", action="store_true",
                    help="continue a preempted rollout from its journal "
                         "(re-verifies what each instance serves first)")
    ro.add_argument("--unwind", action="store_true",
                    help="roll back everything the journaled rollout "
                         "already promoted, instead of continuing")
    ro.set_defaults(fn=cmd_rollout)

    sp = sub.add_parser("spill", help="inspect/drain the storage-outage "
                                      "spill journal")
    spsub = sp.add_subparsers(dest="spill_verb", required=True)
    si = spsub.add_parser("inspect", help="pending/dead-letter counts "
                                          "(read-only; safe while the "
                                          "event server runs)")
    _backend_help = ("spill home to operate on: 'shared' = the "
                     "storage-backed fleet queue, 'local' = this box's "
                     "JSONL journal (default: PIO_SPILL_BACKEND, else "
                     "auto — shared on a pioserver EVENTDATA source)")
    si.add_argument("--dir", default=None,
                    help="journal directory (default: PIO_SPILL_DIR, "
                         "else $PIO_HOME/spill)")
    si.add_argument("--backend", default=None,
                    choices=("auto", "local", "shared"),
                    help=_backend_help)
    si.add_argument("--json", action="store_true",
                    help="also print the summary as one JSON line")
    si.set_defaults(fn=cmd_spill_inspect)
    sd = spsub.add_parser("drain", help="foreground replay into storage "
                                        "(local: event server must be "
                                        "stopped; shared: safe anytime — "
                                        "leases serialize)")
    sd.add_argument("--dir", default=None)
    sd.add_argument("--backend", default=None,
                    choices=("auto", "local", "shared"),
                    help=_backend_help)
    sd.add_argument("--batch", type=int, default=100,
                    help="records per replay batch")
    sd.set_defaults(fn=cmd_spill_drain)
    sq = spsub.add_parser("requeue-dead",
                          help="move dead-lettered records back into the "
                               "queue/journal for replay")
    sq.add_argument("--dir", default=None)
    sq.add_argument("--backend", default=None,
                    choices=("auto", "local", "shared"),
                    help=_backend_help)
    sq.set_defaults(fn=cmd_spill_requeue_dead)

    imp = sub.add_parser("import", help="import NDJSON events")
    imp.add_argument("--appid", type=int, required=True)
    imp.add_argument("--channel")
    imp.add_argument("--input", required=True)
    imp.add_argument("--from-line", type=int, default=1, dest="from_line",
                     help="resume a partially-committed import at this "
                          "1-based line (printed by a failed run)")
    imp.set_defaults(fn=cmd_import)

    exp = sub.add_parser("export", help="export events as NDJSON")
    exp.add_argument("--appid", type=int, required=True)
    exp.add_argument("--channel")
    exp.add_argument("--output", required=True)
    exp.set_defaults(fn=cmd_export)

    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format="[%(levelname)s] [%(name)s] %(message)s",
    )
    from predictionio_tpu.controller import ParamsBindingError
    from predictionio_tpu.data.storage import StorageError
    from predictionio_tpu.workflow import WorkflowError

    try:
        return args.fn(args)
    except SystemExit:
        raise
    except KeyboardInterrupt:
        return 130
    except (ParamsBindingError, StorageError, WorkflowError) as e:
        # User-input errors get a clean message; unexpected ones traceback.
        print(f"[error] {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
