"""Device-side ragged→dense bucketing for the ALS data path.

The host-numpy bucketing in :mod:`predictionio_tpu.ops.ragged` is exact but
became the wall-clock story of ``pio train`` at ML-25M (SURVEY §2.3 /
round-2 verdict item 3): ~30 s of single-threaded numpy plus a ~1 GB
padded-block H2D upload.  The TPU-native answer: ship the COMPACT COO
triplets once (12 B/rating instead of ~18 B/padded-slot) and run the
entire layout transform — degree counting, bucket assignment, stable
grouping, padded-block scatter, zipf-head splitting — as ONE jitted XLA
program on the accelerator, where a 25M-element sort is milliseconds.

Two pieces:

- :func:`plan_buckets` (host): turns the degree histogram into a static
  :class:`BucketPlan` — bucket bounds, padded row counts, flat-buffer
  offsets.  Everything shape-like is decided here so the device program
  is fully static.
- :func:`build_buckets` (device): one jit per plan; scatters every entry
  into a flat [total_slots] buffer at a computed destination, then views
  per-bucket [R, L] blocks out of it.

Semantics match ``bucket_by_length(...)`` exactly (same bucket bounds
policy, same split-bucket segment layout, same within-row event order);
``tests/test_device_prep.py`` pins host-vs-device equivalence.
Truncation (``max_len``) is NOT supported here — callers with
``max_degree`` set fall back to the host path.
"""

from __future__ import annotations

import dataclasses
import functools
import time
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from predictionio_tpu.obs import get_registry
from predictionio_tpu.ops.ragged import LEN_ALIGN, _round_up, fit_bounds

__all__ = ["BucketPlan", "plan_buckets", "build_buckets", "degree_histogram"]


@dataclasses.dataclass(frozen=True)
class BucketPlan:
    """Static layout for one side's buckets (hashable: jit static arg)."""

    bounds: Tuple[int, ...]          # plain-bucket bounds, ascending
    rows: Tuple[int, ...]            # real rows per plain bucket
    rows_padded: Tuple[int, ...]     # rows rounded to pad_rows_to
    # Split bucket (zipf head), or None:
    split_len: Optional[int]         # seg_len (= split_above)
    split_rows: int                  # partial rows (padded)
    split_segs: int                  # entity slots (padded)
    n_rows: int                      # entities on this side
    pad_rows_to: int
    # HBM chunking, decided at plan time so the whole chunked layout is
    # emitted by ONE jitted program.  (Round-2's eager per-chunk slicing
    # compiled ~100 distinct tiny XLA programs — with no persistent
    # compile cache on this backend that alone cost minutes of cold prep.)
    # plain_chunks[i] = ((row_start, n_rows), ...) within bucket i;
    # split_chunks  = ((e0, e1, r0, r1), ...) at entity granularity.
    plain_chunks: Tuple[Tuple[Tuple[int, int], ...], ...] = ()
    split_chunks: Tuple[Tuple[int, int, int, int], ...] = ()

    @property
    def row_starts(self) -> Tuple[int, ...]:
        out, acc = [], 0
        for r in self.rows:
            out.append(acc)
            acc += r
        return tuple(out)

    @property
    def slot_starts(self) -> Tuple[int, ...]:
        out, acc = [], 0
        for rp, b in zip(self.rows_padded, self.bounds):
            out.append(acc)
            acc += rp * b
        return tuple(out)

    @property
    def total_plain_slots(self) -> int:
        return sum(rp * b for rp, b in zip(self.rows_padded, self.bounds))

    @property
    def total_plain_rows(self) -> int:
        return sum(self.rows_padded)


def degree_histogram(counts: jax.Array, cap: int) -> Tuple[np.ndarray, int, int]:
    """Pull (clipped histogram, n_over, n_partials) off-device.

    One tiny D2H instead of the full per-row count vector: the histogram
    of degrees clipped at ``cap`` (cap+1 bins), the number of rows above
    cap, and the total ceil(d/cap) partial rows they need.
    """
    clipped = jnp.minimum(counts, cap)
    hist = jnp.zeros(cap + 1, jnp.int32).at[clipped].add(1)
    over = counts > cap
    n_over = jnp.sum(over.astype(jnp.int32))
    n_part = jnp.sum(jnp.where(over, (counts + cap - 1) // cap, 0))
    return np.asarray(hist), int(n_over), int(n_part)


def plan_buckets(
    hist: np.ndarray,
    n_over: int,
    n_part: int,
    n_rows: int,
    *,
    split_above: int,
    pad_rows_to: int = 1,
    bucket_bounds="auto",
    max_block_floats: Optional[int] = None,
    rank: int = 64,
    over_degrees: Optional[np.ndarray] = None,
) -> BucketPlan:
    """Degree histogram → static bucket layout (host-side, cheap).

    ``max_block_floats`` (with ``rank``) turns on HBM chunking: buckets
    whose gathered [R, L, K] block would exceed the budget are emitted as
    several row chunks by the device program.  Chunking the split bucket
    additionally needs ``over_degrees`` — the degrees of the over-cap
    entities in entity-id order (a tiny D2H).
    """
    _t0 = time.perf_counter()
    pad_to = max(pad_rows_to, LEN_ALIGN)  # batch dim also sublane-aligned
    degrees = np.arange(len(hist))
    present = degrees[(hist > 0) & (degrees < len(hist))]
    counts_rep = np.repeat(present, hist[present])  # ≤ n_rows ints
    if isinstance(bucket_bounds, str):
        bounds = fit_bounds(counts_rep, cap=split_above)
    else:
        bounds = sorted(set(min(b, split_above) for b in bucket_bounds
                            if b > 0))
        top = int(counts_rep.max()) if len(counts_rep) else 1
        if not bounds or bounds[-1] < top:
            bounds.append(_round_up(top, LEN_ALIGN))
    rows_per = []
    prev = -1  # first bucket includes degree-0 rows
    for b in bounds:
        hi = min(b, len(hist) - 1)
        lo = prev + 1
        n = int(hist[lo:hi + 1].sum())
        if hi >= split_above:
            # cap-bin rows that are genuinely over go to the split bucket
            n -= n_over
        rows_per.append(n)
        prev = b
    # Drop empty buckets (keep at least one).
    kept = [(b, r) for b, r in zip(bounds, rows_per) if r > 0] or \
        [(bounds[0], 0)]
    bounds = tuple(b for b, _ in kept)
    rows = tuple(r for _, r in kept)
    rows_padded = tuple(max(_round_up(r, pad_to), pad_to) for r in rows)
    if n_over > 0:
        split_rows = max(_round_up(n_part, pad_to), pad_to)
        split_segs = max(_round_up(n_over, pad_to), pad_to)
        split_len = split_above
    else:
        split_rows = split_segs = 0
        split_len = None

    def rows_max_for(length: int) -> int:
        return max(LEN_ALIGN,
                   (max_block_floats // max(length * rank, 1))
                   // LEN_ALIGN * LEN_ALIGN)

    plain_chunks: Tuple = ()
    split_chunks: Tuple = ()
    if max_block_floats is not None:
        pc_list = []
        for b, rp in zip(bounds, rows_padded):
            rm = rows_max_for(b)
            ch = []
            s = 0
            while s < rp:
                ch.append((s, min(rm, rp - s)))  # rp, rm multiples of 8
                s += rm
            pc_list.append(tuple(ch))
        plain_chunks = tuple(pc_list)
        if split_len is not None:
            assert over_degrees is not None and len(over_degrees) == n_over
            parts = (np.asarray(over_degrees, np.int64) + split_len - 1) \
                // split_len
            starts = np.zeros(n_over + 1, np.int64)
            np.cumsum(parts, out=starts[1:])
            rm = rows_max_for(split_len)
            sc = []
            e0 = 0
            while e0 < n_over:
                e1 = e0 + 1
                while e1 < n_over and starts[e1 + 1] - starts[e0] <= rm:
                    e1 += 1
                sc.append((e0, e1, int(starts[e0]), int(starts[e1])))
                e0 = e1
            split_chunks = tuple(sc) if len(sc) > 1 else ()
    plan = BucketPlan(bounds=bounds, rows=rows, rows_padded=rows_padded,
                      split_len=split_len, split_rows=split_rows,
                      split_segs=split_segs, n_rows=n_rows,
                      pad_rows_to=pad_to, plain_chunks=plain_chunks,
                      split_chunks=split_chunks)
    # Pipeline observability: planning cost + how much padded HBM the
    # device program will touch (ISSUE: make ALS prep attributable next
    # to the feeder/training gauges).
    reg = get_registry()
    reg.histogram("pio_device_prep_plan_ms",
                  "Host time planning the bucket layout.").observe(
        (time.perf_counter() - _t0) * 1e3)
    total_slots = plan.total_plain_slots + plan.split_rows * (plan.split_len
                                                              or 0)
    reg.gauge("pio_device_prep_total_slots",
              "Padded entry slots the device layout allocates.").set(
        total_slots)
    reg.gauge("pio_device_prep_padded_rows",
              "Padded rows across plain + split buckets.").set(
        plan.total_plain_rows + plan.split_rows)
    reg.gauge("pio_device_prep_buckets",
              "Plain bucket count of the current plan.").set(
        len(plan.bounds))
    return plan


@functools.partial(jax.jit, static_argnames=("plan",))
def build_buckets(
    rows: jax.Array,     # [N] int32 entity ids (this side)
    cols: jax.Array,     # [N] int32 other-side ids
    vals: jax.Array,     # [N] f32
    plan: BucketPlan,
) -> Tuple:
    """One XLA program: COO → per-bucket padded blocks.

    Returns ``(plain, split)`` where ``plain`` is a list of
    ``(indices [R,L], values, mask, row_ids)`` per plan bucket and
    ``split`` is ``(indices, values, mask, seg_ids, ent_ids)`` or None.
    """
    n = rows.shape[0]
    n_rows = plan.n_rows
    counts = jnp.zeros(n_rows, jnp.int32).at[rows].add(1)

    # --- bucket of each entity ---------------------------------------
    bounds_arr = jnp.asarray(plan.bounds, jnp.int32)
    bucket_of = jnp.searchsorted(bounds_arr, counts, side="left"
                                 ).astype(jnp.int32)
    n_plain = len(plan.bounds)
    is_split_row = counts > (plan.split_len or jnp.int32(2 ** 30))
    bucket_of = jnp.where(is_split_row, n_plain, bucket_of)

    # --- slot of each entity within its bucket (stable by id) --------
    order = jnp.argsort(bucket_of, stable=True)
    rank = jnp.zeros(n_rows, jnp.int32).at[order].set(
        jnp.arange(n_rows, dtype=jnp.int32))
    row_start = jnp.asarray(plan.row_starts + (sum(plan.rows),), jnp.int32)
    slot_of = rank - row_start[jnp.minimum(bucket_of, n_plain)]

    # row_ids: flat over plain buckets (padded rows stay -1)
    row_starts_pad = []
    acc = 0
    for rp in plan.rows_padded:
        row_starts_pad.append(acc)
        acc += rp
    row_starts_pad_arr = jnp.asarray(row_starts_pad + [acc], jnp.int32)
    total_rows = acc
    ent = jnp.arange(n_rows, dtype=jnp.int32)
    dest_row = jnp.where(
        bucket_of < n_plain,
        row_starts_pad_arr[jnp.minimum(bucket_of, n_plain)] + slot_of,
        total_rows)  # split rows dropped here
    flat_row_ids = jnp.full(total_rows, -1, jnp.int32
                            ).at[dest_row].set(ent, mode="drop")

    # --- entry positions within rows (stable = event order) ----------
    e_order = jnp.argsort(rows, stable=True)
    r_sorted = rows[e_order]
    starts = jnp.concatenate([jnp.zeros(1, jnp.int32),
                              jnp.cumsum(counts).astype(jnp.int32)])
    pos_sorted = jnp.arange(n, dtype=jnp.int32) - starts[r_sorted]
    pos = jnp.zeros(n, jnp.int32).at[e_order].set(pos_sorted)

    # --- flat destination per entry ----------------------------------
    slot_starts = jnp.asarray(plan.slot_starts + (plan.total_plain_slots,),
                              jnp.int32)
    bounds_full = jnp.asarray(plan.bounds + (1,), jnp.int32)
    b_of_e = bucket_of[rows]
    b_clip = jnp.minimum(b_of_e, n_plain)
    plain_dest = (slot_starts[b_clip]
                  + slot_of[rows] * bounds_full[b_clip] + pos)
    total_plain = plan.total_plain_slots

    if plan.split_len is not None:
        seg_len = plan.split_len
        # entity slot e (0..n_over) within split bucket = slot_of; its
        # partial-row base = exclusive cumsum of ceil(count/seg_len) over
        # entities ordered by slot.
        n_seg = plan.split_segs
        ent_of_slot = jnp.full(n_seg, -1, jnp.int32).at[
            jnp.where(is_split_row, slot_of, n_seg)].set(ent, mode="drop")
        cnt_of_slot = jnp.where(ent_of_slot >= 0,
                                counts[jnp.maximum(ent_of_slot, 0)], 0)
        parts_of_slot = (cnt_of_slot + seg_len - 1) // seg_len
        part_base = jnp.concatenate(
            [jnp.zeros(1, jnp.int32),
             jnp.cumsum(parts_of_slot).astype(jnp.int32)])[:-1]
        # per-entry split destination
        eslot = slot_of[rows]
        prow = part_base[jnp.minimum(eslot, n_seg - 1)] + pos // seg_len
        split_dest = total_plain + prow * seg_len + pos % seg_len
        dest = jnp.where(b_of_e < n_plain, plain_dest, split_dest)
        total_slots = total_plain + plan.split_rows * seg_len
        # split row_ids / seg_ids
        part_rows = plan.split_rows
        prow_iota = jnp.arange(part_rows, dtype=jnp.int32)
        seg_ids = jnp.searchsorted(
            part_base + parts_of_slot,  # cumulative end per slot
            prow_iota, side="right").astype(jnp.int32)
        valid_part = seg_ids < n_seg
        valid_part = valid_part & (prow_iota <
                                   (part_base + parts_of_slot)[
                                       jnp.minimum(seg_ids, n_seg - 1)])
        seg_ids = jnp.where(valid_part, seg_ids, n_seg)  # padding → OOB slot
    else:
        dest = plain_dest
        total_slots = total_plain

    flat_idx = jnp.zeros(total_slots, jnp.int32).at[dest].set(
        cols, mode="drop")
    flat_val = jnp.zeros(total_slots, jnp.float32).at[dest].set(
        vals, mode="drop")
    flat_msk = jnp.zeros(total_slots, jnp.bool_).at[dest].set(
        True, mode="drop")

    plain = []
    for i, (b, rp) in enumerate(zip(plan.bounds, plan.rows_padded)):
        s0 = plan.slot_starts[i]
        r0 = row_starts_pad[i]
        chunks = plan.plain_chunks[i] if plan.plain_chunks else ((0, rp),)
        for cs, cn in chunks:
            plain.append((
                flat_idx[s0 + cs * b:s0 + (cs + cn) * b].reshape(cn, b),
                flat_val[s0 + cs * b:s0 + (cs + cn) * b].reshape(cn, b),
                flat_msk[s0 + cs * b:s0 + (cs + cn) * b].reshape(cn, b),
                flat_row_ids[r0 + cs:r0 + cs + cn],
            ))
    split = None
    if plan.split_len is not None:
        s0 = total_plain
        sl = plan.split_len
        pr = plan.split_rows
        if not plan.split_chunks:
            split = [(
                flat_idx[s0:s0 + pr * sl].reshape(pr, sl),
                flat_val[s0:s0 + pr * sl].reshape(pr, sl),
                flat_msk[s0:s0 + pr * sl].reshape(pr, sl),
                seg_ids,
                ent_of_slot,
            )]
        else:
            split = []
            for e0, e1, r0c, r1c in plan.split_chunks:
                n_chunk = e1 - e0
                seg_pad = (-n_chunk) % plan.pad_rows_to
                row_pad = (-(r1c - r0c)) % plan.pad_rows_to
                oob = n_chunk + seg_pad  # padding rows → dropped slot
                seg_c = seg_ids[r0c:r1c]
                seg_c = jnp.where((seg_c >= e0) & (seg_c < e1),
                                  seg_c - e0, oob)

                def padrows(a):
                    return jnp.pad(a, ((0, row_pad),) + ((0, 0),)
                                   * (a.ndim - 1))

                split.append((
                    padrows(flat_idx[s0 + r0c * sl:s0 + r1c * sl]
                            .reshape(r1c - r0c, sl)),
                    padrows(flat_val[s0 + r0c * sl:s0 + r1c * sl]
                            .reshape(r1c - r0c, sl)),
                    padrows(flat_msk[s0 + r0c * sl:s0 + r1c * sl]
                            .reshape(r1c - r0c, sl)),
                    jnp.pad(seg_c, (0, row_pad), constant_values=oob),
                    jnp.pad(ent_of_slot[e0:e1], (0, seg_pad),
                            constant_values=-1),
                ))
        split = tuple(split)
    return tuple(plain), split
