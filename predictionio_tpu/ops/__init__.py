"""TPU-numeric building blocks shared by the model layer.

These are the rebuild's replacement for the reference's reliance on Spark
MLlib + netlib BLAS (SURVEY.md §2.3): dense, batched, statically-shaped
primitives that XLA tiles onto the MXU.

- :mod:`ragged`  — ragged event streams → fixed-shape padded blocks
  (the recompilation-discipline layer, SURVEY.md §7 hard parts)
- :mod:`linalg`  — batched ridge/Cholesky solves (ALS normal equations)
- :mod:`topk`    — chunked dot-product top-K retrieval (serving hot path)
"""

from predictionio_tpu.ops.linalg import batched_ridge_solve, gram
from predictionio_tpu.ops.ragged import Padded, pad_ragged, bucket_by_length
from predictionio_tpu.ops.topk import top_k_scores, chunked_top_k

__all__ = [
    "batched_ridge_solve",
    "gram",
    "Padded",
    "pad_ragged",
    "bucket_by_length",
    "top_k_scores",
    "chunked_top_k",
]
