"""Batched dense linear algebra for the MXU.

The reference's ALS leans on netlib/OpenBLAS JNI for per-block normal
equations (SURVEY.md §2.3).  Here the same math is a single batched XLA
program: Gram matrices via einsum (MXU) and positive-definite solves via
batched Cholesky.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["gram", "batched_ridge_solve", "masked_gram"]


def gram(y: jax.Array) -> jax.Array:
    """``YᵀY`` for ``[n, k]`` factors — one MXU matmul, f32 accumulation."""
    return jnp.einsum("nk,nl->kl", y, y, preferred_element_type=jnp.float32)


def masked_gram(f: jax.Array, w: jax.Array) -> jax.Array:
    """Weighted per-row Gram: ``[B, L, K] × [B, L] → [B, K, K]``.

    Computes ``Σ_l w[b,l] · f[b,l,:] ⊗ f[b,l,:]`` — the per-entity portion of
    the ALS normal-equation matrix.  Batched einsum → MXU-tiled by XLA.
    """
    return jnp.einsum(
        "blk,bl,blm->bkm", f, w, f, preferred_element_type=jnp.float32
    )


def batched_ridge_solve(a: jax.Array, b: jax.Array, reg: jax.Array | float) -> jax.Array:
    """Solve ``(A + reg·I) x = b`` for a batch of PSD ``A``: ``[B,K,K],[B,K]``.

    Uses Cholesky — A is PSD by construction in ALS (Gram + λI).  Falls back
    on well-posedness from the ridge term; callers guarantee ``reg > 0``.
    """
    k = a.shape[-1]
    eye = jnp.eye(k, dtype=a.dtype)
    a_reg = a + reg * eye
    chol = jnp.linalg.cholesky(a_reg)
    # Two triangular solves; batched over leading dims.
    y = jax.scipy.linalg.solve_triangular(chol, b[..., None], lower=True)
    x = jax.scipy.linalg.solve_triangular(
        chol, y, lower=True, trans="T"
    )
    return x[..., 0]
