"""Ragged → dense layout transforms.

The reference's data plane is ragged by construction (RDD of per-user rating
lists; Spark shuffles them between ALS blocks).  XLA wants static shapes, so
every ragged stream is converted host-side into padded ``[rows, L]`` index /
value blocks with a validity mask, optionally bucketed by row length so that
short rows don't pay the max-degree padding cost (SURVEY.md §7 "hard parts":
the ragged→dense gather layout).

All functions here are host-side numpy (they run once per training run,
before device_put); the outputs are what gets sharded onto the mesh.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

__all__ = ["Padded", "pad_ragged", "bucket_by_length", "segment_counts",
           "fit_bounds"]

# Padded row lengths are rounded up to this so the lane/sublane layout of
# the [rows, L] blocks (and the gathered [rows, L, K] blocks downstream)
# stays tiled.  Measured on v5e: an L=206 bucket runs the fused
# gather+gram at 0.08 Gnnz/s vs 0.27 Gnnz/s at L=208 — a 3.3x cliff for
# a misaligned sublane dimension.  8 = f32 sublane granule.
LEN_ALIGN = 8


@dataclasses.dataclass
class Padded:
    """A padded ragged batch.

    - ``indices``: int32 ``[rows, L]`` — column ids, 0 where padded
    - ``values``:  float32 ``[rows, L]`` — entry values, 0 where padded
    - ``mask``:    bool ``[rows, L]`` — True on real entries
    - ``row_ids``: int32 ``[rows]`` — original row id of each padded row

    Split buckets (``split_above``) additionally carry:

    - ``seg_ids``: int32 ``[rows]`` — segment slot of each partial row
      (several partial rows of one over-long entity share a slot)
    - ``ent_ids``: int32 ``[n_segments]`` — entity id per slot, -1 padding

    For split buckets ``row_ids`` repeats the entity id per partial row;
    consumers must segment-sum partial results by ``seg_ids`` before any
    per-entity math (ALS does this for the normal-equation pieces).
    """

    indices: np.ndarray
    values: np.ndarray
    mask: np.ndarray
    row_ids: np.ndarray
    seg_ids: Optional[np.ndarray] = None
    ent_ids: Optional[np.ndarray] = None

    @property
    def split(self) -> bool:
        return self.seg_ids is not None

    @property
    def shape(self) -> Tuple[int, int]:
        return self.indices.shape  # type: ignore[return-value]


def segment_counts(rows: np.ndarray, n_rows: int) -> np.ndarray:
    """Entries per row (rows need not be sorted)."""
    return np.bincount(rows, minlength=n_rows).astype(np.int32)


def _round_up(n: int, multiple: int) -> int:
    return ((n + multiple - 1) // multiple) * multiple


def pad_ragged(
    rows: np.ndarray,
    cols: np.ndarray,
    vals: Optional[np.ndarray],
    n_rows: int,
    *,
    max_len: Optional[int] = None,
    pad_rows_to: int = 1,
) -> Padded:
    """COO triplets → one padded block ``[n_rows_padded, L]``.

    ``L`` = max row length (or ``max_len`` cap — rows beyond it are truncated,
    keeping the *latest* entries, matching the reference's LEventStore
    ``reversed=true, limit=N`` semantics for "recent interactions").
    ``pad_rows_to`` rounds the row count up (mesh divisibility).
    """
    rows = np.asarray(rows, dtype=np.int64)
    cols = np.asarray(cols, dtype=np.int64)
    if vals is None:
        vals = np.ones(len(rows), dtype=np.float32)
    vals = np.asarray(vals, dtype=np.float32)
    counts = segment_counts(rows, n_rows)
    natural = int(counts.max()) if len(counts) and counts.max() > 0 else 1
    # Truncation honors max_len exactly; the ALLOCATED width is rounded up
    # to the sublane granule (the extra columns are masked padding).
    L = max(min(natural, max_len) if max_len else natural, 1)
    L_arr = _round_up(L, LEN_ALIGN)
    R = _round_up(max(n_rows, 1), pad_rows_to)

    # Stable sort by row so each row's entries are contiguous, preserving
    # insertion (event-time) order within a row.
    order = np.argsort(rows, kind="stable")
    r_sorted, c_sorted, v_sorted = rows[order], cols[order], vals[order]
    # Position of each entry within its row.
    starts = np.zeros(n_rows + 1, dtype=np.int64)
    np.cumsum(counts, out=starts[1:])
    pos = np.arange(len(r_sorted)) - starts[r_sorted]
    # Truncate: keep the LAST L entries of overlong rows.
    keep = pos >= (counts[r_sorted] - L)
    r_k, c_k, v_k = r_sorted[keep], c_sorted[keep], v_sorted[keep]
    pos_k = pos[keep] - np.maximum(counts[r_k] - L, 0)

    indices = np.zeros((R, L_arr), dtype=np.int32)
    values = np.zeros((R, L_arr), dtype=np.float32)
    mask = np.zeros((R, L_arr), dtype=bool)
    indices[r_k, pos_k] = c_k
    values[r_k, pos_k] = v_k
    mask[r_k, pos_k] = True
    return Padded(indices=indices, values=values, mask=mask,
                  row_ids=np.arange(R, dtype=np.int32))


def fit_bounds(
    counts: np.ndarray,
    *,
    max_buckets: int = 12,
    align: int = LEN_ALIGN,
    cap: Optional[int] = None,
) -> List[int]:
    """Choose bucket bounds that minimize total padded slots.

    Exact DP over candidate cut points (the aligned unique degrees,
    quantile-thinned to ≤256): ``D[j, b]`` = min padded slots covering all
    rows with degree ≤ candidate j using b buckets.  Candidates are
    multiples of ``align`` so every bucket keeps the tiled lane/sublane
    layout (see LEN_ALIGN).  ``cap`` bounds the largest candidate (rows
    above it are the caller's split bucket).  Replaces the fixed
    power-of-4-ish default bounds: at the ML-25M shape those pad 1.66x on
    the user side; the fitted bounds pad ≤~1.1x.
    """
    counts = np.asarray(counts)
    counts = counts[counts > 0]
    if cap is not None:
        counts = np.minimum(counts, cap)
    if len(counts) == 0:
        return [align]
    aligned = (np.ceil(counts / align) * align).astype(np.int64)
    cands = np.unique(aligned)  # always covers every (clipped) degree
    if len(cands) > 256:  # thin by quantile, keep the extremes
        qs = np.quantile(cands, np.linspace(0, 1, 256))
        cands = np.unique((np.ceil(qs / align) * align).astype(np.int64))
    # rows_le[j] = #rows with aligned degree ≤ cands[j]
    rows_le = np.searchsorted(np.sort(aligned), cands, side="right")
    D = len(cands)
    B = min(max_buckets, D)
    INF = np.inf
    dp = np.full((D, B), INF)
    choice = np.zeros((D, B), dtype=np.int64)
    dp[:, 0] = cands * rows_le
    for b in range(1, B):
        for j in range(D):
            # over i < j: dp[i, b-1] + cands[j] * (rows_le[j] - rows_le[i])
            prev = dp[:j, b - 1] + cands[j] * (rows_le[j] - rows_le[:j])
            if len(prev):
                i = int(np.argmin(prev))
                if prev[i] < dp[j, b]:
                    dp[j, b] = prev[i]
                    choice[j, b] = i
            if dp[j, b - 1] < dp[j, b]:  # fewer buckets is allowed
                dp[j, b] = dp[j, b - 1]
                choice[j, b] = -1
    bounds = []
    j, b = D - 1, B - 1
    while True:
        c = choice[j, b]
        if b == 0:
            bounds.append(int(cands[j]))
            break
        if c == -1:
            b -= 1
            continue
        bounds.append(int(cands[j]))
        j, b = int(c), b - 1
    return sorted(set(bounds))


def bucket_by_length(
    rows: np.ndarray,
    cols: np.ndarray,
    vals: Optional[np.ndarray],
    n_rows: int,
    *,
    bucket_bounds: Union[Sequence[int], str] = "auto",
    max_len: Optional[int] = None,
    pad_rows_to: int = 1,
    split_above: Optional[int] = None,
) -> List[Padded]:
    """COO triplets → per-length-bucket padded blocks.

    Rows are grouped by degree into buckets with padded length equal to the
    bucket bound, so a 3-item user costs 16 slots, not max-degree slots.
    This is the TPU answer to Spark ALS's ragged shuffle blocks: a handful
    of static shapes (one compile each) instead of one worst-case shape.
    Returns blocks ordered short→long; ``row_ids`` maps back to real rows.

    ``split_above``: rows longer than this are *split* into partial rows of
    at most ``split_above`` entries instead of padding every such row to the
    global max degree.  Without it, one zipf-head entity forces a bucket of
    shape [few, max_degree] that is mostly padding (measured 3.7x padded
    waste on the item side of an ML-1M-shape workload).  The returned split
    bucket carries ``seg_ids``/``ent_ids`` so consumers can segment-sum the
    partial results — exact, not an approximation (unlike ``max_len``,
    which truncates).
    """
    rows = np.asarray(rows, dtype=np.int64)
    cols = np.asarray(cols, dtype=np.int64)
    if vals is None:
        vals = np.ones(len(rows), dtype=np.float32)
    vals = np.asarray(vals, dtype=np.float32)
    counts = segment_counts(rows, n_rows)
    cap = max_len or (int(counts.max()) if len(counts) else 1)
    split_at = split_above if (split_above and split_above < cap) else None
    top = split_at if split_at else cap
    if isinstance(bucket_bounds, str):  # "auto": fit to the degree histogram
        bounds = fit_bounds(counts, cap=top)
    else:
        bounds = sorted(set(min(b, top) for b in bucket_bounds if b > 0))
    if not bounds or bounds[-1] < top:
        bounds.append(top)

    out: List[Padded] = []
    all_rows = np.arange(n_rows, dtype=np.int64)
    prev = 0
    for b in bounds:
        sel = all_rows[(counts > prev) & (counts <= b)] if prev else \
            all_rows[counts <= b]
        prev = b
        if len(sel) == 0:
            continue
        # Remap selected rows to 0..len(sel)-1, pad within the bucket.
        remap = np.full(n_rows, -1, dtype=np.int64)
        remap[sel] = np.arange(len(sel))
        in_bucket = remap[rows] >= 0
        p = pad_ragged(
            remap[rows[in_bucket]], cols[in_bucket], vals[in_bucket],
            len(sel), max_len=b, pad_rows_to=pad_rows_to,
        )
        real = np.full(p.indices.shape[0], -1, dtype=np.int32)
        real[: len(sel)] = sel.astype(np.int32)
        p.row_ids = real
        out.append(p)

    if split_at:
        sel = all_rows[counts > split_at]
        if len(sel):
            out.append(_split_bucket(rows, cols, vals, counts, sel,
                                     split_at, max_len, pad_rows_to))
    return out


def _split_bucket(
    rows: np.ndarray,
    cols: np.ndarray,
    vals: np.ndarray,
    counts: np.ndarray,
    sel: np.ndarray,
    seg_len: int,
    max_len: Optional[int],
    pad_rows_to: int,
) -> Padded:
    """Entities in ``sel`` (degree > seg_len) → partial rows of ``seg_len``."""
    n_rows = len(counts)
    in_split = np.isin(rows, sel)
    r_s, c_s, v_s = rows[in_split], cols[in_split], vals[in_split]
    order = np.argsort(r_s, kind="stable")
    r_s, c_s, v_s = r_s[order], c_s[order], v_s[order]
    # Position of each entry within its entity (entries are entity-sorted).
    counts_sel = counts[sel]
    starts = np.zeros(len(sel) + 1, dtype=np.int64)
    np.cumsum(counts_sel, out=starts[1:])
    seg_of_entity = np.full(n_rows, -1, dtype=np.int64)
    seg_of_entity[sel] = np.arange(len(sel))
    ent_slot = seg_of_entity[r_s]
    pos = np.arange(len(r_s)) - starts[ent_slot]
    if max_len is not None:
        # Truncation semantics match pad_ragged: keep the LAST max_len.
        keep = pos >= (counts_sel[ent_slot] - max_len)
        r_s, c_s, v_s = r_s[keep], c_s[keep], v_s[keep]
        ent_slot, pos = ent_slot[keep], pos[keep]
        pos = pos - np.maximum(counts_sel[ent_slot] - max_len, 0)
        counts_sel = np.minimum(counts_sel, max_len)
    partials_per = (counts_sel + seg_len - 1) // seg_len
    part_start = np.zeros(len(sel) + 1, dtype=np.int64)
    np.cumsum(partials_per, out=part_start[1:])
    n_part = int(part_start[-1])
    part_row = part_start[ent_slot] + pos // seg_len
    within = pos % seg_len

    R = _round_up(max(n_part, 1), pad_rows_to)
    n_seg = _round_up(max(len(sel), 1), pad_rows_to)
    indices = np.zeros((R, seg_len), dtype=np.int32)
    values = np.zeros((R, seg_len), dtype=np.float32)
    mask = np.zeros((R, seg_len), dtype=bool)
    indices[part_row, within] = c_s
    values[part_row, within] = v_s
    mask[part_row, within] = True
    row_ids = np.full(R, -1, dtype=np.int32)
    seg_ids = np.full(R, n_seg, dtype=np.int32)  # padding rows → OOB slot
    for e in range(len(sel)):
        sl = slice(int(part_start[e]), int(part_start[e + 1]))
        row_ids[sl] = sel[e]
        seg_ids[sl] = e
    ent_ids = np.full(n_seg, -1, dtype=np.int32)
    ent_ids[: len(sel)] = sel.astype(np.int32)
    return Padded(indices=indices, values=values, mask=mask, row_ids=row_ids,
                  seg_ids=seg_ids, ent_ids=ent_ids)
