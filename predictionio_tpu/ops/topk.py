"""Dot-product top-K retrieval — the serving hot path.

Reference behavior: predict = user-factor · item-factorsᵀ, top-K (MLlib
ALS `recommendProducts`, SURVEY.md §2.2).  TPU shape: one [B, K] × [K, N]
matmul (MXU) + `jax.lax.top_k`; for sharded item factors each shard computes
a local top-K and the K·shards candidates are reduced — O(N/shards) memory
per device and a tiny all-gather instead of gathering N scores.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["top_k_scores", "chunked_top_k", "sharded_top_k", "host_top_k"]

NEG_INF = jnp.float32(-3.4e38)


@partial(jax.jit, static_argnames=("k",))
def top_k_scores(
    queries: jax.Array,   # [B, K] float
    items: jax.Array,     # [N, K] float
    k: int,
    *,
    exclude: Optional[jax.Array] = None,  # [B, N] bool — True = mask out
    biases: Optional[jax.Array] = None,   # [N] additive item biases
) -> Tuple[jax.Array, jax.Array]:
    """Scores+ids of the top-k items per query. Returns ([B,k], [B,k] int32).

    Jitted (k static): the serving hot path must be ONE dispatch, not
    eager op-by-op — on a tunneled TPU each eager op is a network RTT.
    """
    scores = jnp.einsum(
        "bk,nk->bn", queries, items, preferred_element_type=jnp.float32
    )
    if biases is not None:
        scores = scores + biases[None, :]
    if exclude is not None:
        scores = jnp.where(exclude, NEG_INF, scores)
    return jax.lax.top_k(scores, k)


def chunked_top_k(
    queries: jax.Array,
    items: jax.Array,
    k: int,
    *,
    chunk: int = 8192,
    biases: Optional[jax.Array] = None,
    exclude: Optional[jax.Array] = None,  # [B, N] bool — True = mask out
    n_valid: Optional[int] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Top-k with bounded [B, chunk] score materialization.

    `lax.scan` over item chunks keeps HBM flat for huge catalogs: each step
    scores one chunk and merges with the running top-k.  Any catalog size
    works — the tail chunk reads a clamped (overlapping) window via
    ``dynamic_slice`` and masks the rows it re-reads, so callers no longer
    pad the corpus to a chunk multiple (and no padded copy is ever
    materialized).  ``n_valid`` additionally masks trailing padding rows a
    blocked/sharded model carries; ``exclude`` is the per-query mask of
    :func:`top_k_scores`, sliced chunk-by-chunk.
    """
    n, dim = items.shape
    b = queries.shape[0]
    limit = n if n_valid is None else min(n_valid, n)
    if n <= chunk:
        # Single-dispatch small corpus: fold the n_valid tail mask into
        # exclude and take the one-matmul path.
        excl = exclude
        if limit < n:
            pad_rows = jnp.broadcast_to(
                (jnp.arange(n, dtype=jnp.int32) >= limit)[None, :], (b, n))
            excl = pad_rows if excl is None else (excl | pad_rows)
        return top_k_scores(queries, items, k, exclude=excl, biases=biases)
    steps = -(-n // chunk)
    init = (
        jnp.full((b, k), NEG_INF, dtype=jnp.float32),
        jnp.zeros((b, k), dtype=jnp.int32),
    )

    def step(carry, nominal):
        best_s, best_i = carry
        # The tail chunk's window clamps to [n - chunk, n): rows below the
        # nominal boundary were already scored by the previous chunk and
        # are masked out below — static shapes, no recompile per catalog
        # size, no duplicate candidates.
        start = jnp.minimum(nominal, n - chunk)
        tile = jax.lax.dynamic_slice(items, (start, 0), (chunk, dim))
        s = jnp.einsum("bk,nk->bn", queries, tile,
                       preferred_element_type=jnp.float32)
        ids = start + jnp.arange(chunk, dtype=jnp.int32)[None, :]
        if biases is not None:
            s = s + jax.lax.dynamic_slice(biases, (start,), (chunk,))[None, :]
        invalid = (ids < nominal) | (ids >= limit)
        if exclude is not None:
            invalid = invalid | jax.lax.dynamic_slice(
                exclude, (0, start), (b, chunk))
        s = jnp.where(invalid, NEG_INF, s)
        merged_s = jnp.concatenate([best_s, s], axis=1)
        merged_i = jnp.concatenate(
            [best_i, jnp.broadcast_to(ids, s.shape)], axis=1)
        top_s, pos = jax.lax.top_k(merged_s, k)
        top_i = jnp.take_along_axis(merged_i, pos, axis=1)
        return (top_s, top_i), None

    starts = (jnp.arange(steps, dtype=jnp.int32) * chunk)
    (best_s, best_i), _ = jax.lax.scan(step, init, starts)
    return best_s, best_i


def sharded_top_k(
    mesh: Mesh,
    axis: str,
    queries: jax.Array,   # [B, K] replicated
    items: jax.Array,     # [N, K] sharded on `axis` along dim 0
    k: int,
    n_valid: Optional[int] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Top-k over item factors row-sharded on a mesh axis.

    Each shard scores its N/shards slice and takes a local top-k; the
    k·shards candidates are all-gathered (tiny) and reduced — the ICI
    traffic is O(k·shards·B), never O(N·B).  ``n_valid`` masks the
    mesh-padding rows a blocked model carries at the tail (they are
    zero vectors and would outrank genuinely negative scores).
    """
    n = items.shape[0]
    n_shards = mesh.shape[axis]
    assert n % n_shards == 0, f"pad catalog ({n}) to a multiple of {n_shards}"
    per = n // n_shards

    def local(q, it):  # it: [N/shards, K]
        shard = jax.lax.axis_index(axis)
        excl = None
        if n_valid is not None and n_valid < n:
            gid = shard * per + jnp.arange(per, dtype=jnp.int32)
            excl = jnp.broadcast_to(gid[None, :] >= n_valid,
                                    (q.shape[0], per))
        s, i = top_k_scores(q, it, min(k, per), exclude=excl)
        i = i + shard * per
        # Gather every shard's candidates, then reduce to the global top-k.
        all_s = jax.lax.all_gather(s, axis, axis=1).reshape(q.shape[0], -1)
        all_i = jax.lax.all_gather(i, axis, axis=1).reshape(q.shape[0], -1)
        top_s, pos = jax.lax.top_k(all_s, k)
        return top_s, jnp.take_along_axis(all_i, pos, axis=1)

    from predictionio_tpu.parallel.compat import shard_map

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(P(), P(axis)),
        out_specs=(P(), P()),
        # Outputs ARE replicated (identical post-all_gather reduction on every
        # shard) but the static varying-axes check can't prove it.
        check_vma=False,
    )
    return fn(queries, items)


def host_top_k(
    queries,              # np [B, K]
    items,                # np [N, K]
    k: int,
    *,
    exclude=None,         # np [B, N] bool — True = mask out
    biases=None,          # np [N]
):
    """Numpy top-k for the host-resident serving fast path.

    A B=1 predict over even ML-25M-scale item factors is ~4M MACs — far
    below the cost of one device dispatch round-trip (milliseconds on a
    production host, ~100 ms through this harness's remote-TPU tunnel).
    Serving keeps a host copy of the factors and answers small batches
    here; large batches still go to the device (ops.topk.top_k_scores).
    Returns ([B, k], [B, k] int32) sorted descending like lax.top_k.
    """
    import numpy as np

    if k <= 0:  # lax.top_k parity: k=0 → empty, never the whole catalog
        return (np.empty((queries.shape[0], 0), np.float32),
                np.empty((queries.shape[0], 0), np.int32))
    scores = queries @ items.T                      # [B, N]
    if biases is not None:
        scores = scores + biases[None, :]
    if exclude is not None:
        scores = np.where(exclude, -3.4e38, scores)
    n = scores.shape[1]
    k = min(k, n)
    if k < n:
        part = np.argpartition(scores, -k, axis=1)[:, -k:]
    else:
        part = np.broadcast_to(np.arange(n), scores.shape).copy()
    part_scores = np.take_along_axis(scores, part, axis=1)
    order = np.argsort(-part_scores, axis=1, kind="stable")
    ids = np.take_along_axis(part, order, axis=1).astype(np.int32)
    return np.take_along_axis(part_scores, order, axis=1), ids
