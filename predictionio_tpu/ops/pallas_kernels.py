"""Pallas TPU kernels for the ALS hot loop.

SURVEY.md §7 flags the ragged→dense gather/gram layout as "likely the one
place a Pallas kernel pays off".  The XLA formulation of the per-entity
normal equations reads the gathered factor block ``F [R, L, K]`` from HBM
twice (once for ``A = Fᵀ·diag(w)·F``, once for ``b = Fᵀ·c``).  The fused
kernel below tiles rows into VMEM once and emits both outputs per pass —
halving HBM traffic on the training hot loop.

Grid: one program per solve row; per-program working set is
``L·K + K² + K`` floats (≤ ~0.6 MB at L=1024, K=128 — well inside VMEM).
Matmuls sit on the MXU via ``dot_general`` with f32 accumulation.

On CPU (tests) the kernel runs in interpret mode; ``fused_gram_vector``
dispatches to the plain einsum path unless Pallas is requested/available.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["fused_gram_vector", "fused_gram_vector_pallas",
           "fused_gram_vector_xla", "pallas_supported",
           "ridge_solve_gj_pallas", "ridge_solve_lu_pallas", "gj_fits_vmem"]


def pallas_supported() -> bool:
    """True when the default backend can run the compiled kernel."""
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


# Per-program VMEM budget: the double-buffered [TILE_R, L, K] f32 input
# tile plus w/c blocks, both outputs, and Mosaic's stack share ~16 MB —
# fits_vmem budgets the tile at ≤ 2 MB (L·K ≤ 65536 at TILE_R=8).
_VMEM_BUDGET_FLOATS = 1 << 20  # halved again inside fits_vmem


def fits_vmem(l: int, k: int) -> bool:
    """Whether a [TILE_R, l, k] f32 tile double-buffers within VMEM.

    Factor 2 on top of the tile itself: the w/c blocks, both outputs and
    Mosaic's stack allocation share the ~16 MB budget (an L=1776, K=64
    bucket passed the old guard and overflowed scoped vmem by 388 KB)."""
    return l * k <= _VMEM_BUDGET_FLOATS // (2 * TILE_R)


def fused_gram_vector_xla(f: jax.Array, w: jax.Array, c: jax.Array
                          ) -> Tuple[jax.Array, jax.Array]:
    """Reference path: ``A[r] = Σ_l w[r,l]·f[r,l]⊗f[r,l]``, ``b[r] = Σ_l
    c[r,l]·f[r,l]`` via two einsums (XLA fuses what it can)."""
    a = jnp.einsum("blk,bl,blm->bkm", f, w, f,
                   preferred_element_type=jnp.float32)
    b = jnp.einsum("blk,bl->bk", f, c, preferred_element_type=jnp.float32)
    return a, b


TILE_R = 8  # rows per program — TPU sublane granularity for f32


def _kernel(f_ref, w_ref, c_ref, a_ref, b_ref):
    # f: [TILE_R, L, K] in VMEM; w/c: [TILE_R, L].  Static 8-row unroll of
    # plain 2-D MXU dots — Mosaic lowers these directly (the batched 3-D
    # dot_general form does not lower).
    for r in range(TILE_R):
        f = f_ref[r]                              # [L, K]
        fw = f * w_ref[r][:, None]                # VPU
        a_ref[r] = jax.lax.dot_general(           # MXU: [K,L]·[L,K]
            fw, f, dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        b_ref[r] = jax.lax.dot_general(           # MXU: [1,L]·[L,K]
            c_ref[r][None, :], f,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)[0]


@functools.partial(jax.jit, static_argnames=("interpret",))
def fused_gram_vector_pallas(f: jax.Array, w: jax.Array, c: jax.Array,
                             *, interpret: bool = False
                             ) -> Tuple[jax.Array, jax.Array]:
    """Fused (A, b) build — one VMEM pass over the gathered factors.

    Rows are padded up to the TILE_R sublane granule; padding rows compute
    garbage that is sliced off (their weights are whatever padding holds —
    never read).
    """
    r, l, k = f.shape
    r_pad = (-r) % TILE_R
    if r_pad:
        f = jnp.pad(f, ((0, r_pad), (0, 0), (0, 0)))
        w = jnp.pad(w, ((0, r_pad), (0, 0)))
        c = jnp.pad(c, ((0, r_pad), (0, 0)))
    rp = r + r_pad
    grid = (rp // TILE_R,)
    a, b = pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((TILE_R, l, k), lambda i: (i, 0, 0)),
            pl.BlockSpec((TILE_R, l), lambda i: (i, 0)),
            pl.BlockSpec((TILE_R, l), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((TILE_R, k, k), lambda i: (i, 0, 0)),
            pl.BlockSpec((TILE_R, k), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rp, k, k), jnp.float32),
            jax.ShapeDtypeStruct((rp, k), jnp.float32),
        ],
        interpret=interpret,
    )(f.astype(jnp.float32), w.astype(jnp.float32), c.astype(jnp.float32))
    return a[:r], b[:r]


# ---------------------------------------------------------------------------
# Batched ridge solve via Gauss-Jordan elimination.
#
# XLA's batched Cholesky lowers to a K-step while-loop of small dynamic
# slices — measured ~50 ms for 6040 rank-64 systems on v5e, i.e. ~10 GF/s.
# Gauss-Jordan does ~9x the FLOPs of Cholesky but every step is a dense
# [B, K, K] VPU op with no data-dependent control flow, which is the shape
# the hardware actually likes.  No pivoting: A + lambda*diag is SPD with
# lambda > 0 (ALS-WR always scales reg by degree >= 1).
# ---------------------------------------------------------------------------

GJ_LANES = 128  # systems per program — one per vector lane


def gj_fits_vmem(k: int) -> bool:
    """Whether the GJ kernel's per-program working set fits VMEM.

    The kernel holds the [k, k, 128] input block plus a same-shape VMEM
    scratch (f32): 2·k²·128·4 bytes, with double-buffering on the input.
    Budget ~12 MB of the ~16 MB/core keeps headroom; above it (k ≳ 96)
    callers must take the Cholesky path — the kernel would fail to
    compile where XLA's solver still works (round-2 advisor finding).
    """
    return 3 * k * k * GJ_LANES * 4 <= 12 * 1024 * 1024


def _gj_kernel(a_ref, b_ref, x_ref, m_ref):
    """Solve A x = b for GJ_LANES pre-regularized SPD systems per program.

    Layout is the whole trick: systems live on the LANE dimension —
    ``m [K, K, 128]`` holds matrix element (r, c) of system t at
    ``m[r, c, t]``.  Row/column j of all 128 systems are then contiguous
    dynamic sublane slices (``m[pl.ds(j,1)]``, ``m[:, pl.ds(j,1)]``), the
    pivot is a plain [1,1,128] lane vector, and the rank-1 elimination
    update is a single lane-parallel FMA over [K,K,128] with no one-hot
    masks materialized.  (A prior batch-on-sublanes formulation spent ~94%
    of VPU issue on mask/select traffic — 18.7 ms for 6040 K=64 systems;
    this layout removes all of it.)

    The "set row j to the normalized row" step is folded into the update:
    ``m - (col - e_j) ⊗ row_n`` eliminates every other row and lands row j
    on ``row_n`` in one expression (col's pivot entry becomes p-1).
    """
    k = a_ref.shape[0]
    sub_iota = jax.lax.broadcasted_iota(jnp.int32, (k, 1, 1), 0)
    m_ref[:] = a_ref[:]
    x_ref[:] = b_ref[:]

    def step(j, _):
        row = m_ref[pl.ds(j, 1), :, :]                # [1, K, T] row j
        col = m_ref[:, pl.ds(j, 1), :]                # [K, 1, T] col j
        inv = 1.0 / m_ref[pl.ds(j, 1), pl.ds(j, 1), :]  # [1, 1, T] pivot
        row_n = row * inv                             # [1, K, T]
        bj = x_ref[pl.ds(j, 1), :, :] * inv           # [1, 1, T]
        ej = (sub_iota == j).astype(jnp.float32)      # [K, 1, 1]
        col_m = col - ej                              # pivot row → p-1
        m_ref[:] = m_ref[:] - col_m * row_n           # lane-parallel FMA
        x_ref[:] = x_ref[:] - col_m * bj
        return 0

    jax.lax.fori_loop(0, k, step, 0, unroll=False)


def _ridge_solve_lanes(kernel, a, b, reg, interpret: bool):
    """Shared host-side scaffolding for the systems-on-lanes solvers:
    ridge pre-add, GJ_LANES padding (identity-filled, solutions
    discarded), batch→lane transposes, pallas_call, inverse transpose."""
    bt, k = b.shape
    a = (a + reg[:, None, None] * jnp.eye(k, dtype=jnp.float32)).astype(jnp.float32)
    pad = (-bt) % GJ_LANES
    if pad:
        a = jnp.pad(a, ((0, pad), (0, 0), (0, 0)))
        a = a.at[bt:].set(jnp.eye(k, dtype=jnp.float32))
        b = jnp.pad(b, ((0, pad), (0, 0)))
    bp = bt + pad
    # Batch → lanes: [B,K,K] → [K,K,B], [B,K] → [K,1,B].
    at = jnp.transpose(a, (1, 2, 0))
    btr = jnp.transpose(b.astype(jnp.float32), (1, 0))[:, None, :]
    x = pl.pallas_call(
        kernel,
        grid=(bp // GJ_LANES,),
        in_specs=[
            pl.BlockSpec((k, k, GJ_LANES), lambda i: (0, 0, i)),
            pl.BlockSpec((k, 1, GJ_LANES), lambda i: (0, 0, i)),
        ],
        out_specs=pl.BlockSpec((k, 1, GJ_LANES), lambda i: (0, 0, i)),
        out_shape=jax.ShapeDtypeStruct((k, 1, bp), jnp.float32),
        scratch_shapes=[pltpu.VMEM((k, k, GJ_LANES), jnp.float32)],
        interpret=interpret,
    )(at, btr)
    return jnp.transpose(x[:, 0, :], (1, 0))[:bt]


@functools.partial(jax.jit, static_argnames=("interpret",))
def ridge_solve_gj_pallas(a, b, reg, *, interpret: bool = False):
    """Batched SPD solve ``(A + diag(reg)) x = b`` — [B,K,K],[B,K],[B]→[B,K]."""
    return _ridge_solve_lanes(_gj_kernel, a, b, reg, interpret)


def _lu_kernel(a_ref, b_ref, x_ref, m_ref):
    """Cholesky-free LDU solve for GJ_LANES SPD systems per program.

    Same systems-on-lanes layout as the GJ kernel, but the elimination
    SHRINKS: the Python-unrolled outer loop updates only the trailing
    rows, in 8-row (sublane-granule) quanta so every slice stays
    aligned — ~K³/3 FLOPs vs Gauss-Jordan's ~K³.  Back-substitution
    runs K cheap [1, ·, T] steps on the upper-triangular remainder.
    No pivoting: A + diag(reg) is SPD (ALS-WR reg ≥ λ).
    """
    k = a_ref.shape[0]
    m_ref[:] = a_ref[:]
    x_ref[:] = b_ref[:]
    blk = 8  # sublane granule — update starts stay aligned

    # Forward elimination, block-quantized shrinkage.
    for j in range(k):
        start = (j + 1) // blk * blk  # aligned block containing row j+1
        rows = k - start
        if rows <= 0:
            continue  # last row: nothing below to eliminate
        inv = 1.0 / m_ref[pl.ds(j, 1), pl.ds(j, 1), :]    # [1,1,T]
        row_n = m_ref[pl.ds(j, 1), :, :] * inv            # [1,K,T]
        bj = x_ref[pl.ds(j, 1), :, :] * inv               # [1,1,T]
        col = m_ref[pl.ds(start, rows), pl.ds(j, 1), :]   # [rows,1,T]
        # Rows < j+1 inside the aligned block must not change: zero their
        # multiplier (cheap [rows,1,1] iota mask, not a [K,K] mask).
        sub_iota = jax.lax.broadcasted_iota(jnp.int32, (rows, 1, 1), 0)
        col = jnp.where(sub_iota + start > j, col, 0.0)
        m_ref[pl.ds(start, rows)] = m_ref[pl.ds(start, rows)] - col * row_n
        x_ref[pl.ds(start, rows)] = x_ref[pl.ds(start, rows)] - col * bj

    # Back-substitution on the upper triangle (x_ref holds modified b).
    for j in range(k - 1, -1, -1):
        inv = 1.0 / m_ref[pl.ds(j, 1), pl.ds(j, 1), :]
        xj = x_ref[pl.ds(j, 1), :, :] * inv               # [1,1,T]
        x_ref[pl.ds(j, 1)] = xj
        if j:
            col = m_ref[pl.ds(0, j), pl.ds(j, 1), :]      # [j,1,T]
            x_ref[pl.ds(0, j)] = x_ref[pl.ds(0, j)] - col * xj


@functools.partial(jax.jit, static_argnames=("interpret",))
def ridge_solve_lu_pallas(a: jax.Array, b: jax.Array, reg: jax.Array,
                          *, interpret: bool = False) -> jax.Array:
    """Batched SPD solve via shrinking elimination — [B,K,K],[B,K],[B]→[B,K]."""
    return _ridge_solve_lanes(_lu_kernel, a, b, reg, interpret)


def fused_gram_vector(f: jax.Array, w: jax.Array, c: jax.Array,
                      *, use_pallas: Optional[bool] = None
                      ) -> Tuple[jax.Array, jax.Array]:
    """Dispatch: Pallas on TPU, einsum elsewhere (or force via flag)."""
    if use_pallas is None:
        use_pallas = pallas_supported()
    if use_pallas:
        return fused_gram_vector_pallas(f, w, c,
                                        interpret=not pallas_supported())
    return fused_gram_vector_xla(f, w, c)
