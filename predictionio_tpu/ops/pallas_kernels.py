"""Pallas TPU kernels for the ALS hot loop.

SURVEY.md §7 flags the ragged→dense gather/gram layout as "likely the one
place a Pallas kernel pays off".  The XLA formulation of the per-entity
normal equations reads the gathered factor block ``F [R, L, K]`` from HBM
twice (once for ``A = Fᵀ·diag(w)·F``, once for ``b = Fᵀ·c``).  The fused
kernel below tiles rows into VMEM once and emits both outputs per pass —
halving HBM traffic on the training hot loop.

Grid: one program per solve row; per-program working set is
``L·K + K² + K`` floats (≤ ~0.6 MB at L=1024, K=128 — well inside VMEM).
Matmuls sit on the MXU via ``dot_general`` with f32 accumulation.

On CPU (tests) the kernel runs in interpret mode; ``fused_gram_vector``
dispatches to the plain einsum path unless Pallas is requested/available.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["fused_gram_vector", "fused_gram_vector_pallas",
           "fused_gram_vector_xla", "pallas_supported",
           "ridge_solve_gj_pallas", "ridge_solve_lu_pallas", "gj_fits_vmem",
           "fused_topk", "fused_topk_pallas",
           "pq_scan", "pq_scan_pallas", "pq_scan_xla"]


def pallas_supported() -> bool:
    """True when the default backend can run the compiled kernel."""
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


# Per-program VMEM budget: the double-buffered [TILE_R, L, K] f32 input
# tile plus w/c blocks, both outputs, and Mosaic's stack share ~16 MB —
# fits_vmem budgets the tile at ≤ 2 MB (L·K ≤ 65536 at TILE_R=8).
_VMEM_BUDGET_FLOATS = 1 << 20  # halved again inside fits_vmem


def fits_vmem(l: int, k: int) -> bool:
    """Whether the fused gram kernel can handle a [*, l, k] bucket.

    Since the L-chunked grid (round 4), any bucket length fits — the
    staged tile is at most [TILE_R, _L_CHUNK·64/k, k].  Only the rank
    bounds the working set (the [TILE_R, k, k] f32 accumulator)."""
    del l
    return k <= 256


def fused_gram_vector_xla(f: jax.Array, w: jax.Array, c: jax.Array
                          ) -> Tuple[jax.Array, jax.Array]:
    """Reference path: ``A[r] = Σ_l w[r,l]·f[r,l]⊗f[r,l]``, ``b[r] = Σ_l
    c[r,l]·f[r,l]`` via two einsums (XLA fuses what it can)."""
    a = jnp.einsum("blk,bl,blm->bkm", f, w, f,
                   preferred_element_type=jnp.float32)
    b = jnp.einsum("blk,bl->bk", f, c, preferred_element_type=jnp.float32)
    return a, b


TILE_R = 8     # rows per program — TPU sublane granularity for f32
_L_CHUNK = 1024  # max slots staged per grid step (VMEM tile bound)


def _gram_kernel(f_ref, w_ref, c_ref, a_ref, b_ref, *, l_real: int,
                 l_chunk: int):
    """One (row-tile, L-chunk) grid step of the fused (A, b) build.

    ``f`` arrives in the gather's NATURAL layout and dtype — bf16,
    K-minor — so XLA inserts NO relayout copy between the gather and this
    kernel (round-3's 47 ms/iter copy phase was exactly that relayout).
    The kernel accumulates both outputs in f32 across L-chunks; the final
    chunk of a non-multiple L masks the over-read tail (Pallas pads OOB
    block loads with unspecified values — a NaN there would poison the
    accumulation through 0·NaN).
    """
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        a_ref[:] = jnp.zeros_like(a_ref)
        b_ref[:] = jnp.zeros_like(b_ref)

    n_chunks = pl.num_programs(1)
    partial_tail = l_real % l_chunk != 0

    def accumulate(masked: bool):
        for r in range(TILE_R):
            f = f_ref[r]                              # [LC, K] bf16
            w = w_ref[r]                              # [LC] f32
            c = c_ref[r]
            if masked:
                # Masks built at their target ranks: Mosaic cannot insert
                # a minor dim on an i1 vector.
                off = j * l_chunk
                valid1 = (jax.lax.broadcasted_iota(
                    jnp.int32, (l_chunk,), 0) + off) < l_real
                valid2 = (jax.lax.broadcasted_iota(
                    jnp.int32, (l_chunk, 1), 0) + off) < l_real
                w = jnp.where(valid1, w, 0.0)
                c = jnp.where(valid1, c, 0.0)
                f = jnp.where(valid2, f, jnp.zeros((), f.dtype))
            # Reshape to 2-D in f32 BEFORE the dtype cast: Mosaic only
            # supports minor-dim insertion on 32-bit vectors.
            fw = f * w[:, None].astype(f.dtype)       # VPU
            a_ref[r] += jax.lax.dot_general(          # MXU: [K,L]·[L,K]
                fw, f, dimension_numbers=(((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            b_ref[r] += jax.lax.dot_general(          # MXU: [1,L]·[L,K]
                c[None, :].astype(f.dtype), f,
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)[0]

    if partial_tail:
        @pl.when(j == n_chunks - 1)
        def _tail():
            accumulate(masked=True)

        @pl.when(j < n_chunks - 1)
        def _body():
            accumulate(masked=False)
    else:
        accumulate(masked=False)


@functools.partial(jax.jit, static_argnames=("interpret",))
def fused_gram_vector_pallas(f: jax.Array, w: jax.Array, c: jax.Array,
                             *, interpret: bool = False
                             ) -> Tuple[jax.Array, jax.Array]:
    """Fused (A, b) build — one VMEM pass over the gathered factors.

    Accepts ``f`` in any float dtype (bf16 keeps the gather at its
    measured row-rate AND avoids a materialized f32 convert); rows are
    padded up to the TILE_R sublane granule (padding rows compute garbage
    that is sliced off), L is chunked so any bucket length fits VMEM.
    """
    r, l, k = f.shape
    r_pad = (-r) % TILE_R
    if r_pad:
        f = jnp.pad(f, ((0, r_pad), (0, 0), (0, 0)))
        w = jnp.pad(w, ((0, r_pad), (0, 0)))
        c = jnp.pad(c, ((0, r_pad), (0, 0)))
    rp = r + r_pad
    # Chunk length scales inversely with rank to hold the staged tile at
    # ~[TILE_R, 1024, 64]-equivalent bytes.
    lc = min(l, max(128, _L_CHUNK * 64 // max(k, 1)))
    n_chunks = -(-l // lc)
    kernel = functools.partial(_gram_kernel, l_real=l, l_chunk=lc)
    a, b = pl.pallas_call(
        kernel,
        grid=(rp // TILE_R, n_chunks),
        in_specs=[
            pl.BlockSpec((TILE_R, lc, k), lambda i, j: (i, j, 0)),
            pl.BlockSpec((TILE_R, lc), lambda i, j: (i, j)),
            pl.BlockSpec((TILE_R, lc), lambda i, j: (i, j)),
        ],
        out_specs=[
            pl.BlockSpec((TILE_R, k, k), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((TILE_R, k), lambda i, j: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rp, k, k), jnp.float32),
            jax.ShapeDtypeStruct((rp, k), jnp.float32),
        ],
        interpret=interpret,
    )(f, w.astype(jnp.float32), c.astype(jnp.float32))
    return a[:r], b[:r]


# ---------------------------------------------------------------------------
# Batched ridge solve via Gauss-Jordan elimination.
#
# XLA's batched Cholesky lowers to a K-step while-loop of small dynamic
# slices — measured ~50 ms for 6040 rank-64 systems on v5e, i.e. ~10 GF/s.
# Gauss-Jordan does ~9x the FLOPs of Cholesky but every step is a dense
# [B, K, K] VPU op with no data-dependent control flow, which is the shape
# the hardware actually likes.  No pivoting: A + lambda*diag is SPD with
# lambda > 0 (ALS-WR always scales reg by degree >= 1).
# ---------------------------------------------------------------------------

GJ_LANES = 128  # systems per program — one per vector lane


def gj_fits_vmem(k: int) -> bool:
    """Whether the lanes-solve kernels' per-program working set fits VMEM.

    The kernel holds the natural [128, k, k] input block (double-buffered)
    plus the lane-major [k, k, 128] scratch, all f32.  Budget ~12 MB of
    the ~16 MB/core keeps headroom; above it (k ≳ 72) callers must take
    the Cholesky path — the kernel would fail to compile where XLA's
    solver still works (round-2 advisor finding).
    """
    return 5 * k * k * GJ_LANES * 4 <= 12 * 1024 * 1024


def _load_lane_major(a_ref, b_ref, reg_ref, m_ref, v_ref):
    """In-kernel batch→lane staging: natural [T,K,K]/[T,K] blocks →
    lane-major ``m [K,K,T]`` / ``v [K,1,T]`` VMEM scratch, ridge added.

    Doing the transpose HERE (a 2-D [T, K·K] ↔ [K·K, T] VMEM shuffle)
    instead of host-side removes the [B,K,K] relayout copy + transpose XLA
    emitted between the gram dots and the solve — measured ~20 ms of the
    round-3 iteration at the ML-25M shape.
    """
    t, k, _ = a_ref.shape
    regv = reg_ref[:].reshape(1, 1, t)
    ci = jax.lax.broadcasted_iota(jnp.int32, (1, k, 1), 1)
    # K two-dimensional [T,K]→[K,T] transposes: Mosaic has no 3-D
    # minor-collapsing reshape, but 2-D f32 transposes lower cleanly.
    for r in range(k):
        sl = a_ref[:, pl.ds(r, 1), :].reshape(t, k)
        tr = jnp.transpose(sl, (1, 0)).reshape(1, k, t)
        m_ref[pl.ds(r, 1)] = tr + (ci == r).astype(jnp.float32) * regv
    v_ref[:] = jnp.transpose(b_ref[:], (1, 0)).reshape(k, 1, t)


def _store_lane_major(x_ref, v_ref):
    t, k = x_ref.shape
    x_ref[:] = jnp.transpose(v_ref[:].reshape(k, t), (1, 0))


def _gj_kernel(a_ref, b_ref, reg_ref, x_ref, m_ref, v_ref):
    """Solve (A + diag(reg)) x = b for GJ_LANES systems per program.

    Layout is the whole trick: systems live on the LANE dimension —
    ``m [K, K, 128]`` holds matrix element (r, c) of system t at
    ``m[r, c, t]``.  Row/column j of all 128 systems are then contiguous
    dynamic sublane slices (``m[pl.ds(j,1)]``, ``m[:, pl.ds(j,1)]``), the
    pivot is a plain [1,1,128] lane vector, and the rank-1 elimination
    update is a single lane-parallel FMA over [K,K,128] with no one-hot
    masks materialized.  (A prior batch-on-sublanes formulation spent ~94%
    of VPU issue on mask/select traffic — 18.7 ms for 6040 K=64 systems;
    this layout removes all of it.)

    The "set row j to the normalized row" step is folded into the update:
    ``m - (col - e_j) ⊗ row_n`` eliminates every other row and lands row j
    on ``row_n`` in one expression (col's pivot entry becomes p-1).

    Because every system is confined to its own lane, a boundary block
    whose tail lanes are Pallas OOB padding solves garbage there without
    touching real lanes — the padded x rows are simply never written back.
    """
    k = a_ref.shape[1]
    _load_lane_major(a_ref, b_ref, reg_ref, m_ref, v_ref)
    sub_iota = jax.lax.broadcasted_iota(jnp.int32, (k, 1, 1), 0)

    def step(j, _):
        row = m_ref[pl.ds(j, 1), :, :]                # [1, K, T] row j
        col = m_ref[:, pl.ds(j, 1), :]                # [K, 1, T] col j
        inv = 1.0 / m_ref[pl.ds(j, 1), pl.ds(j, 1), :]  # [1, 1, T] pivot
        row_n = row * inv                             # [1, K, T]
        bj = v_ref[pl.ds(j, 1), :, :] * inv           # [1, 1, T]
        ej = (sub_iota == j).astype(jnp.float32)      # [K, 1, 1]
        col_m = col - ej                              # pivot row → p-1
        m_ref[:] = m_ref[:] - col_m * row_n           # lane-parallel FMA
        v_ref[:] = v_ref[:] - col_m * bj
        return 0

    jax.lax.fori_loop(0, k, step, 0, unroll=False)
    _store_lane_major(x_ref, v_ref)


def _ridge_solve_lanes(kernel, a, b, reg, interpret: bool):
    """Shared scaffolding for the systems-on-lanes solvers.

    Inputs stay in their NATURAL layouts ([B,K,K], [B,K], [B]) — the
    lane-major staging happens inside the kernel, so no relayout copies
    are emitted between the gram build, this solve, and the factor
    scatter.  A non-multiple-of-128 batch rides Pallas's auto-padded
    boundary block (lane-isolated systems make the padding harmless).
    """
    bt, k = b.shape
    x = pl.pallas_call(
        kernel,
        grid=(-(-bt // GJ_LANES),),
        in_specs=[
            pl.BlockSpec((GJ_LANES, k, k), lambda i: (i, 0, 0)),
            pl.BlockSpec((GJ_LANES, k), lambda i: (i, 0)),
            pl.BlockSpec((1, GJ_LANES), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((GJ_LANES, k), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((bt, k), jnp.float32),
        scratch_shapes=[pltpu.VMEM((k, k, GJ_LANES), jnp.float32),
                        pltpu.VMEM((k, 1, GJ_LANES), jnp.float32)],
        interpret=interpret,
    )(a.astype(jnp.float32), b.astype(jnp.float32),
      reg.astype(jnp.float32).reshape(1, bt))
    return x


@functools.partial(jax.jit, static_argnames=("interpret",))
def ridge_solve_gj_pallas(a, b, reg, *, interpret: bool = False):
    """Batched SPD solve ``(A + diag(reg)) x = b`` — [B,K,K],[B,K],[B]→[B,K]."""
    return _ridge_solve_lanes(_gj_kernel, a, b, reg, interpret)


def _lu_kernel(a_ref, b_ref, reg_ref, x_ref, m_ref, v_ref):
    """Cholesky-free LDU solve for GJ_LANES SPD systems per program.

    Same systems-on-lanes layout as the GJ kernel, but the elimination
    SHRINKS: the Python-unrolled outer loop updates only the trailing
    rows, in 8-row (sublane-granule) quanta so every slice stays
    aligned — ~K³/3 FLOPs vs Gauss-Jordan's ~K³.  Back-substitution
    runs K cheap [1, ·, T] steps on the upper-triangular remainder.
    No pivoting: A + diag(reg) is SPD (ALS-WR reg ≥ λ).
    """
    k = a_ref.shape[1]
    _load_lane_major(a_ref, b_ref, reg_ref, m_ref, v_ref)
    blk = 8  # sublane granule — update starts stay aligned

    # Forward elimination, block-quantized shrinkage.  Unrolled at BLOCK
    # granularity with a fori_loop over the 8 pivots inside: each pivot's
    # update spans the aligned sub-matrix from its own block down (rows
    # above the pivot inside the block are masked out of the multiplier).
    # The fully-unrolled form emitted ~6 Mosaic ops per pivot and cost
    # 0.83 s of kernel lowering PER DISTINCT BATCH SIZE — with ~34 chunk
    # batch sizes in the fused ALS loop that was most of its 37 s
    # lowering wall; this form lowers ~2x faster with execution equal
    # within measurement noise (21-34 ms at 131k systems either way).
    for jb in range(0, k, blk):
        rows = k - jb

        def fwd(j, _):
            inv = 1.0 / m_ref[pl.ds(j, 1), pl.ds(j, 1), :]    # [1,1,T]
            row_n = m_ref[pl.ds(j, 1), :, :] * inv            # [1,K,T]
            bj = v_ref[pl.ds(j, 1), :, :] * inv               # [1,1,T]
            col = m_ref[pl.ds(jb, rows), pl.ds(j, 1), :]      # [rows,1,T]
            # Rows <= j inside the block must not change: zero their
            # multiplier (cheap [rows,1,1] iota mask, not a [K,K] mask).
            sub_iota = jax.lax.broadcasted_iota(jnp.int32, (rows, 1, 1), 0)
            col = jnp.where(sub_iota + jb > j, col, 0.0)
            m_ref[pl.ds(jb, rows)] = m_ref[pl.ds(jb, rows)] - col * row_n
            v_ref[pl.ds(jb, rows)] = v_ref[pl.ds(jb, rows)] - col * bj
            return 0

        jax.lax.fori_loop(jb, min(jb + blk, k), fwd, 0)

    # Back-substitution on the upper triangle (v_ref holds modified b).
    for j in range(k - 1, -1, -1):
        inv = 1.0 / m_ref[pl.ds(j, 1), pl.ds(j, 1), :]
        xj = v_ref[pl.ds(j, 1), :, :] * inv               # [1,1,T]
        v_ref[pl.ds(j, 1)] = xj
        if j:
            col = m_ref[pl.ds(0, j), pl.ds(j, 1), :]      # [j,1,T]
            v_ref[pl.ds(0, j)] = v_ref[pl.ds(0, j)] - col * xj
    _store_lane_major(x_ref, v_ref)


@functools.partial(jax.jit, static_argnames=("interpret",))
def ridge_solve_lu_pallas(a: jax.Array, b: jax.Array, reg: jax.Array,
                          *, interpret: bool = False) -> jax.Array:
    """Batched SPD solve via shrinking elimination — [B,K,K],[B,K],[B]→[B,K]."""
    return _ridge_solve_lanes(_lu_kernel, a, b, reg, interpret)


def fused_gram_vector(f: jax.Array, w: jax.Array, c: jax.Array,
                      *, use_pallas: Optional[bool] = None
                      ) -> Tuple[jax.Array, jax.Array]:
    """Dispatch: Pallas on TPU, einsum elsewhere (or force via flag)."""
    if use_pallas is None:
        use_pallas = pallas_supported()
    if use_pallas:
        return fused_gram_vector_pallas(f, w, c,
                                        interpret=not pallas_supported())
    return fused_gram_vector_xla(f, w, c)


# ---------------------------------------------------------------------------
# Fused corpus-score + running top-K (ISSUE 8: million-item retrieval).
#
# The XLA retrieval path (ops.topk) either materializes the full [B, N]
# score block (top_k_scores) or scans [B, chunk] slabs through HBM
# (chunked_top_k).  This kernel streams corpus tiles into VMEM, scores a
# tile on the MXU, and folds it into a running top-K held in VMEM — the
# [B, N] scores never exist anywhere, and HBM traffic is one read of the
# corpus plus O(B·k) output.  The merge is a k-step extract-max built
# ONLY from Mosaic-supported primitives (axis reductions, where,
# broadcasted_iota, pl.ds stores) — no in-kernel sort/top_k dependence —
# so the selection costs k·(k+T)·B VPU ops per tile: the kernel targets
# large-N / menu-k serving shapes where the MXU tile score dominates.
# ---------------------------------------------------------------------------

_TOPK_TILE = 1024        # corpus rows per grid step (lane-aligned)
_TOPK_NEG_INF = -3.4e38  # matches ops.topk.NEG_INF


def _topk_kernel(q_ref, items_ref, out_s_ref, out_i_ref, m_ref, mi_ref,
                 *, tile: int, k: int, n_real: int):
    """One corpus tile folded into the running top-k.

    ``m_ref``/``mi_ref`` are [B, k+T] merged-candidate scratch: the first
    k lanes hold the running best (read back from the output refs, which
    persist across the sequential TPU grid), the remaining T lanes this
    tile's scores.  Tail tiles read an OOB-padded block — the garbage
    columns are overwritten with NEG_INF via the global-id mask before
    any of them can win a slot (`where` selects, never propagates a NaN).
    """
    j = pl.program_id(0)
    b = q_ref.shape[0]

    @pl.when(j == 0)
    def _init():
        out_s_ref[:] = jnp.full_like(out_s_ref, _TOPK_NEG_INF)
        out_i_ref[:] = jnp.zeros_like(out_i_ref)

    m_ref[:, :k] = out_s_ref[:]
    mi_ref[:, :k] = out_i_ref[:]
    s = jax.lax.dot_general(                     # MXU: [B,D]·[T,D]ᵀ
        q_ref[:], items_ref[:],
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    gid = j * tile + jax.lax.broadcasted_iota(jnp.int32, (b, tile), 1)
    m_ref[:, k:] = jnp.where(gid < n_real, s, _TOPK_NEG_INF)
    mi_ref[:, k:] = gid
    cols = jax.lax.broadcasted_iota(jnp.int32, (b, k + tile), 1)

    def extract(slot, _):
        m = m_ref[:]
        v = jnp.max(m, axis=1, keepdims=True)            # [B, 1]
        # Lowest column among the ties = exactly one winner per row; its
        # id is recovered with a sum-select (no gather needed).
        amax = jnp.min(jnp.where(m == v, cols, k + tile),
                       axis=1, keepdims=True)
        sel = cols == amax
        cid = jnp.sum(jnp.where(sel, mi_ref[:], 0), axis=1, keepdims=True)
        out_s_ref[:, pl.ds(slot, 1)] = v
        out_i_ref[:, pl.ds(slot, 1)] = cid
        m_ref[:] = jnp.where(sel, _TOPK_NEG_INF, m)
        return 0

    jax.lax.fori_loop(0, k, extract, 0, unroll=False)


@functools.partial(jax.jit,
                   static_argnames=("k", "tile", "n_valid", "interpret"))
def fused_topk_pallas(queries: jax.Array, items: jax.Array, k: int, *,
                      tile: int = _TOPK_TILE,
                      n_valid: Optional[int] = None,
                      interpret: bool = False
                      ) -> Tuple[jax.Array, jax.Array]:
    """Scores+ids of the top-k items per query — [B,D]·[N,D]ᵀ without
    ever materializing the [B, N] score block.

    Returns ([B, k] f32, [B, k] int32) sorted descending.  ``n_valid``
    masks trailing corpus-padding rows.  Tie order is lowest-running-slot
    first, which can differ from ``lax.top_k``'s lowest-global-id order
    on exactly-equal scores — callers compare id SETS, not sequences,
    when scores tie.
    """
    b, d = queries.shape
    n = items.shape[0]
    assert 1 <= k <= n, f"k={k} outside [1, {n}]"
    n_real = n if n_valid is None else min(n_valid, n)
    b_pad = (-b) % TILE_R
    if b_pad:
        queries = jnp.pad(queries, ((0, b_pad), (0, 0)))
    bp = b + b_pad
    kernel = functools.partial(_topk_kernel, tile=tile, k=k, n_real=n_real)
    out_s, out_i = pl.pallas_call(
        kernel,
        grid=(-(-n // tile),),
        in_specs=[
            pl.BlockSpec((bp, d), lambda j: (0, 0)),
            pl.BlockSpec((tile, d), lambda j: (j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bp, k), lambda j: (0, 0)),
            pl.BlockSpec((bp, k), lambda j: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bp, k), jnp.float32),
            jax.ShapeDtypeStruct((bp, k), jnp.int32),
        ],
        scratch_shapes=[pltpu.VMEM((bp, k + tile), jnp.float32),
                        pltpu.VMEM((bp, k + tile), jnp.int32)],
        interpret=interpret,
    )(queries.astype(jnp.float32), items.astype(jnp.float32))
    return out_s[:b], out_i[:b]


def fused_topk(queries: jax.Array, items: jax.Array, k: int, *,
               n_valid: Optional[int] = None,
               use_pallas: Optional[bool] = None,
               chunk: Optional[int] = None
               ) -> Tuple[jax.Array, jax.Array]:
    """Dispatch: fused Pallas kernel on TPU, XLA top-k elsewhere.

    The XLA fallback rides :func:`ops.topk.chunked_top_k` (which folds
    small corpora into one ``top_k_scores`` dispatch), so callers get
    bounded score-block memory either way.  ``chunk`` sizes the
    fallback's scan slab only — the Pallas kernel's VMEM tile is fixed.
    """
    from predictionio_tpu.ops.topk import chunked_top_k

    b = queries.shape[0]
    if k <= 0:
        return (jnp.zeros((b, 0), jnp.float32),
                jnp.zeros((b, 0), jnp.int32))
    k = min(k, items.shape[0])
    if use_pallas is None:
        use_pallas = pallas_supported()
    if use_pallas:
        return fused_topk_pallas(queries, items, k, n_valid=n_valid,
                                 interpret=not pallas_supported())
    if chunk:
        return chunked_top_k(queries, items, k, chunk=chunk,
                             n_valid=n_valid)
    return chunked_top_k(queries, items, k, n_valid=n_valid)


# ---------------------------------------------------------------------------
# Asymmetric PQ LUT scan + running top-K (ISSUE 13: quantized corpora).
#
# The quantized corpus is a packed [S, N] uint8 code matrix (S = coarse
# table + M residual subspaces); a query's per-table distance LUTs
# ([B, S, 256] f32) are computed ONCE per dispatch and held whole in
# VMEM.  Each grid step stages one code tile, expands table t's codes to
# a one-hot [256, T] block and accumulates lut_t · one_hot on the MXU —
# a [B, 256]×[256, T] matmul per table, which is exactly the gather
# "lut[t, code]" expressed as the small-integer arithmetic the MXU eats
# (Mosaic has no vector gather; the one-hot contraction is the
# supported spelling).  Tile scores fold into the same running-top-K
# VMEM scratch pattern as fused_topk — the [B, N] score block never
# materializes, and HBM traffic is ONE read of the (1+M)-byte-per-item
# codes instead of 4·D bytes of fp32 corpus.
# ---------------------------------------------------------------------------

_PQ_TILE = 512  # code rows per grid step (lane-aligned)


def _pq_scan_kernel(luts_ref, codes_ref, out_s_ref, out_i_ref, m_ref,
                    mi_ref, *, tile: int, k: int, n_real: int,
                    n_tables: int):
    """One code tile LUT-scored and folded into the running top-k.

    ``luts_ref`` is the flattened [B, S·256] table stack (lane slices
    ``pl.ds(t·256, 256)`` address table t); ``codes_ref`` the [S, T]
    uint8 tile.  Tail tiles read OOB-padded garbage codes — their
    columns are overwritten with NEG_INF via the global-id mask before
    any can win a slot (same discipline as ``_topk_kernel``).
    """
    j = pl.program_id(0)
    b = luts_ref.shape[0]

    @pl.when(j == 0)
    def _init():
        out_s_ref[:] = jnp.full_like(out_s_ref, _TOPK_NEG_INF)
        out_i_ref[:] = jnp.zeros_like(out_i_ref)

    m_ref[:, :k] = out_s_ref[:]
    mi_ref[:, :k] = out_i_ref[:]
    codes = codes_ref[:].astype(jnp.int32)               # [S, T]
    cc = jax.lax.broadcasted_iota(jnp.int32, (256, tile), 0)
    s = jnp.zeros((b, tile), jnp.float32)
    for t in range(n_tables):
        # One-hot of table t's codes: [256, T] with a single 1 per lane.
        oh = (codes[t:t + 1, :] == cc).astype(jnp.float32)
        s = s + jax.lax.dot_general(                     # MXU
            luts_ref[:, pl.ds(t * 256, 256)], oh,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
    gid = j * tile + jax.lax.broadcasted_iota(jnp.int32, (b, tile), 1)
    m_ref[:, k:] = jnp.where(gid < n_real, s, _TOPK_NEG_INF)
    mi_ref[:, k:] = gid
    cols = jax.lax.broadcasted_iota(jnp.int32, (b, k + tile), 1)

    def extract(slot, _):
        m = m_ref[:]
        v = jnp.max(m, axis=1, keepdims=True)
        amax = jnp.min(jnp.where(m == v, cols, k + tile),
                       axis=1, keepdims=True)
        sel = cols == amax
        cid = jnp.sum(jnp.where(sel, mi_ref[:], 0), axis=1, keepdims=True)
        out_s_ref[:, pl.ds(slot, 1)] = v
        out_i_ref[:, pl.ds(slot, 1)] = cid
        m_ref[:] = jnp.where(sel, _TOPK_NEG_INF, m)
        return 0

    jax.lax.fori_loop(0, k, extract, 0, unroll=False)


@functools.partial(jax.jit,
                   static_argnames=("k", "tile", "n_valid", "interpret"))
def pq_scan_pallas(luts: jax.Array, codes: jax.Array, k: int, *,
                   tile: int = _PQ_TILE, n_valid: Optional[int] = None,
                   interpret: bool = False
                   ) -> Tuple[jax.Array, jax.Array]:
    """Top-k LUT scores over a packed code matrix — [B, S, 256] tables ×
    [S, N] uint8 codes without ever materializing the [B, N] block.

    Returns ([B, k] f32, [B, k] int32) sorted descending; ``n_valid``
    masks trailing padding columns.  Same tie-order caveat as
    ``fused_topk_pallas``: compare id SETS on exactly-equal scores.
    """
    b, s, width = luts.shape
    assert width == 256, f"LUT width {width} != 256"
    assert codes.shape[0] == s, (codes.shape, s)
    n = codes.shape[1]
    assert 1 <= k <= n, f"k={k} outside [1, {n}]"
    n_real = n if n_valid is None else min(n_valid, n)
    b_pad = (-b) % TILE_R
    if b_pad:
        luts = jnp.pad(luts, ((0, b_pad), (0, 0), (0, 0)))
    bp = b + b_pad
    kernel = functools.partial(_pq_scan_kernel, tile=tile, k=k,
                               n_real=n_real, n_tables=s)
    out_s, out_i = pl.pallas_call(
        kernel,
        grid=(-(-n // tile),),
        in_specs=[
            pl.BlockSpec((bp, s * 256), lambda j: (0, 0)),
            pl.BlockSpec((s, tile), lambda j: (0, j)),
        ],
        out_specs=[
            pl.BlockSpec((bp, k), lambda j: (0, 0)),
            pl.BlockSpec((bp, k), lambda j: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bp, k), jnp.float32),
            jax.ShapeDtypeStruct((bp, k), jnp.int32),
        ],
        scratch_shapes=[pltpu.VMEM((bp, k + tile), jnp.float32),
                        pltpu.VMEM((bp, k + tile), jnp.int32)],
        interpret=interpret,
    )(luts.astype(jnp.float32).reshape(bp, s * 256), codes)
    return out_s[:b], out_i[:b]


def pq_scan_xla(luts: jax.Array, codes: jax.Array, k: int, *,
                chunk: int = 262_144, n_valid: Optional[int] = None
                ) -> Tuple[jax.Array, jax.Array]:
    """XLA gather fallback: `lax.scan` over code chunks, per-table
    ``jnp.take`` into the LUTs, running top-k merge — bounded [B, chunk]
    score memory, any N (clamped overlapping tail window, masked
    re-reads, same trick as ``ops.topk.chunked_top_k``)."""
    s, n = codes.shape
    b = luts.shape[0]
    limit = n if n_valid is None else min(n_valid, n)

    def score(cslab):                                    # [S, C] uint8
        ci = cslab.astype(jnp.int32)
        acc = jnp.take(luts[:, 0, :], ci[0], axis=1)
        for t in range(1, s):
            acc = acc + jnp.take(luts[:, t, :], ci[t], axis=1)
        return acc                                       # [B, C]

    if n <= chunk:
        sc = score(codes)
        if limit < n:
            pad = (jnp.arange(n, dtype=jnp.int32) >= limit)[None, :]
            sc = jnp.where(pad, _TOPK_NEG_INF, sc)
        return jax.lax.top_k(sc, k)
    steps = -(-n // chunk)
    init = (jnp.full((b, k), _TOPK_NEG_INF, dtype=jnp.float32),
            jnp.zeros((b, k), dtype=jnp.int32))

    def step(carry, nominal):
        best_s, best_i = carry
        start = jnp.minimum(nominal, n - chunk)
        cslab = jax.lax.dynamic_slice(codes, (0, start), (s, chunk))
        sc = score(cslab)
        ids = start + jnp.arange(chunk, dtype=jnp.int32)[None, :]
        invalid = (ids < nominal) | (ids >= limit)
        sc = jnp.where(invalid, _TOPK_NEG_INF, sc)
        merged_s = jnp.concatenate([best_s, sc], axis=1)
        merged_i = jnp.concatenate(
            [best_i, jnp.broadcast_to(ids, sc.shape)], axis=1)
        top_s, pos = jax.lax.top_k(merged_s, k)
        return (top_s, jnp.take_along_axis(merged_i, pos, axis=1)), None

    starts = jnp.arange(steps, dtype=jnp.int32) * chunk
    (best_s, best_i), _ = jax.lax.scan(step, init, starts)
    return best_s, best_i


def pq_scan(luts: jax.Array, codes: jax.Array, k: int, *,
            n_valid: Optional[int] = None,
            use_pallas: Optional[bool] = None
            ) -> Tuple[jax.Array, jax.Array]:
    """Dispatch: fused Pallas LUT kernel on TPU, chunked XLA gather scan
    elsewhere — bounded score memory either way."""
    b = luts.shape[0]
    n = codes.shape[1]
    if k <= 0:
        return (jnp.zeros((b, 0), jnp.float32),
                jnp.zeros((b, 0), jnp.int32))
    k = min(k, n)
    if use_pallas is None:
        use_pallas = pallas_supported()
    if use_pallas:
        return pq_scan_pallas(luts, codes, k, n_valid=n_valid,
                              interpret=not pallas_supported())
    return pq_scan_xla(luts, codes, k, n_valid=n_valid)
