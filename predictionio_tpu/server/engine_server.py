"""Engine Server — the `pio deploy` target.

Reference: core/.../workflow/CreateServer.scala (SURVEY.md §3.2): resolve
the latest COMPLETED engine instance, load its models, answer
``POST /queries.json`` through Algorithm.predict → Serving.serve, support
hot-reload after retrain (``POST /reload``), and a status page at ``GET /``.

The per-request path binds the query JSON to the engine's ``query_class``
dataclass (reference: JsonExtractor), runs every algorithm, and serializes
the served result back to JSON.  ``GET /metrics`` adds the rebuild's
latency histogram (SURVEY.md §5.5).
"""

from __future__ import annotations

import dataclasses
import datetime as _dt
import json
import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple
from urllib.parse import urlparse

from predictionio_tpu.controller import Engine, EngineVariant, RuntimeContext
from predictionio_tpu.controller.params import bind_params
from predictionio_tpu.data.storage import (
    Storage,
    StorageUnavailable,
    get_storage,
)
from predictionio_tpu.obs import (
    get_registry,
    publish_event,
    span,
    start_runtime_introspection,
)
from predictionio_tpu.obs import waterfall as _waterfall
from predictionio_tpu.obs.quality import SERVE_ID_HEADER, QualityMonitor
from predictionio_tpu.obs.recall import RecallMonitor
from predictionio_tpu.obs.slo import SLOConfig, SLOEngine
from predictionio_tpu.resilience import deadline as _deadline
from predictionio_tpu.resilience.deadline import DeadlineExceeded
from predictionio_tpu.resilience.faults import fault_point
from predictionio_tpu.resilience.policy import CircuitBreaker, CircuitOpenError
from predictionio_tpu.resilience.supervision import (
    ModelValidationError,
    validate_model_finite,
)
from predictionio_tpu.server.http import (
    BaseHandler,
    ThreadingHTTPServer,
    timeline_payload,
    traces_payload,
    param_bool,
)
from predictionio_tpu.config import env_bool
from predictionio_tpu.serving import (
    QueueFull,
    ResultCache,
    ResultCacheConfig,
    SchedulerClosed,
    SchedulerConfig,
    SchedulerStalled,
    ServingScheduler,
    canonical_query,
)
from predictionio_tpu.version import __version__
from predictionio_tpu.workflow.core_workflow import (
    WorkflowError,
    data_watermark,
    instance_engine_params,
    load_models,
)

logger = logging.getLogger(__name__)

__all__ = ["EngineServer", "QueryError"]

_DC_FIELDS: Dict[type, Tuple[str, ...]] = {}


def _dc_to_json(obj: Any) -> Any:
    """Shallow-recursive dataclass→dict for predicted results.

    ``dataclasses.asdict`` was 32% of the serving hot path — its generic
    deep-copy walks every value through ``_asdict_inner``.  This cached-
    field walk keeps asdict's JSON-visible contract (dataclasses nested
    in lists/tuples/dict values convert; tuples serialize as arrays)
    without the deep copies of leaf values.
    """
    fields = _DC_FIELDS.get(type(obj))
    if fields is None:
        fields = tuple(f.name for f in dataclasses.fields(obj))
        _DC_FIELDS[type(obj)] = fields
    return {name: _val_to_json(getattr(obj, name)) for name in fields}


def _val_to_json(v: Any) -> Any:
    if dataclasses.is_dataclass(v) and not isinstance(v, type):
        return _dc_to_json(v)
    if isinstance(v, (list, tuple)):
        return [_val_to_json(x) for x in v]
    if isinstance(v, dict):
        return {k: _val_to_json(x) for k, x in v.items()}
    return v


class QueryError(ValueError):
    pass


class _QueryMetrics:
    """Serving instruments over the shared registry; ``/metrics`` and
    ``/stats.json`` are views of these series."""

    def __init__(self, registry=None):
        self.registry = registry or get_registry()
        self.requests = self.registry.counter(
            "pio_query_requests_total", "Predict requests served.")
        self.errors = self.registry.counter(
            "pio_query_errors_total", "Predict requests that failed.")
        self.latency = self.registry.histogram(
            "pio_query_latency_ms", "Predict request latency.")
        self.shed = self.registry.counter(
            "pio_deadline_shed_total",
            "Requests shed with 504 because their deadline expired.",
            ("server",))

    def record(self, ms: float, ok: bool) -> None:
        self.requests.inc()
        if not ok:
            self.errors.inc()
        self.latency.observe(ms)

    def snapshot(self) -> Dict[str, Any]:
        return {"requestCount": int(self.requests.value()),
                "errorCount": int(self.errors.value()),
                "latencyMs": {"p50": self.latency.quantile(0.5),
                              "p95": self.latency.quantile(0.95),
                              "p99": self.latency.quantile(0.99)}}


class _Generation:
    """One immutable loaded-model generation (instance + built serving
    stack).  The server swaps whole generations under the lock and keeps
    the previous one for instant ``POST /admin/rollback``."""

    __slots__ = ("instance", "models", "algorithms", "serving", "loaded_at",
                 "number")

    def __init__(self, instance, models, algorithms, serving, loaded_at,
                 number):
        self.instance = instance
        self.models = models
        self.algorithms = algorithms
        self.serving = serving
        self.loaded_at = loaded_at
        self.number = number


class EngineServer:
    """Loads a trained engine instance and serves queries over HTTP.

    Reference roles: MasterActor (lifecycle/reload supervision) and
    ServerActor (request handling) collapse into this class.  The reload
    path is STAGED (the rebuild's answer to actor supervision — ISSUE 4):
    breaker-guarded storage reads, candidate built off to the side,
    validated (finite params + optional ``PIO_CANARY_QUERIES`` golden
    queries), then atomically swapped under the lock with the previous
    generation retained for ``POST /admin/rollback``.  A failed reload
    keeps serving the last-good model — ``pio_model_reload_total{result}``
    and ``pio_model_generation`` make the outcome observable.
    """

    def __init__(
        self,
        engine: Engine,
        variant: EngineVariant,
        storage: Optional[Storage] = None,
        host: str = "0.0.0.0",
        port: int = 8000,
        *,
        engine_id: Optional[str] = None,
        engine_version: str = __version__,
        instance_id: Optional[str] = None,
        mesh_spec: Optional[str] = None,
        plugins=None,
        breaker: Optional[CircuitBreaker] = None,
        scheduler_config: Optional[SchedulerConfig] = None,
    ):
        from predictionio_tpu.server.plugins import PluginManager

        self.engine = engine
        self.variant = variant
        self.storage = storage or get_storage()
        self.ctx = RuntimeContext.create(storage=self.storage, mesh_spec=mesh_spec)
        self.host = host
        self.port = port
        self.engine_id = engine_id or variant.engine_factory
        self.engine_version = engine_version
        self.requested_instance_id = instance_id
        self.stats = _QueryMetrics()
        # Runtime introspection: registers pio_xla_compile_* /
        # pio_device_mem_* so /metrics exposes them from t=0, and starts
        # the memory-sampler thread (jax is loaded here — models are).
        start_runtime_introspection()
        self._swap_lock = threading.Lock()
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._instance = None
        self._algorithms: List[Any] = []
        self._models: List[Any] = []
        self._serving = None
        self._loaded_at: Optional[_dt.datetime] = None
        self._init_lifecycle_state(breaker, scheduler_config)
        self.reload()
        # Server plugin seam (reference: EngineServerPlugin, SURVEY §5.1).
        # Started LAST — after reload() — so plugins see a fully
        # constructed server with a loaded instance.
        self.plugins = (plugins if plugins is not None
                        else PluginManager.from_env("PIO_ENGINESERVER_PLUGINS"))
        self.plugins.start(self)

    # -- model lifecycle ----------------------------------------------------

    def _init_lifecycle_state(
            self,
            breaker: Optional[CircuitBreaker] = None,
            scheduler_config: Optional[SchedulerConfig] = None) -> None:
        """Staged-reload state + the serving scheduler: lock, generations,
        breaker, instruments.  Factored out of ``__init__`` so test
        skeletons built with ``__new__`` (tests/test_resilience.py) stay
        in lock-step — their ``/queries.json`` calls ride the same
        admission queue + micro-batcher as production."""
        self._reload_lock = threading.Lock()  # serialize staged reloads
        self._generation = 0
        self._previous: Optional[_Generation] = None
        self._last_reload: Dict[str, Any] = {}
        # Breaker around reload()'s storage reads (ROADMAP resilience
        # follow-on (a)): a dead model store must shed fast with
        # Retry-After, not hang every /reload until TCP gives up.
        self._breaker = breaker or CircuitBreaker(
            "modeldata",
            failure_threshold=int(os.environ.get(
                "PIO_BREAKER_THRESHOLD", "5")),
            recovery_time_s=float(os.environ.get(
                "PIO_BREAKER_RECOVERY_S", "10")),
            failure_types=(StorageUnavailable, ConnectionError))
        self.retry_after_s = int(os.environ.get("PIO_RETRY_AFTER_S", "5"))
        # Retained-previous policy (ROADMAP carry-forward: the rollback
        # generation doubles model memory while it lives).  off = never
        # retain; TTL > 0 = drop it after the canary window.
        self._retain_previous = env_bool(
            os.environ.get("PIO_RETAIN_PREVIOUS"), True)
        try:
            self._retain_ttl_s = float(
                os.environ.get("PIO_RETAIN_PREVIOUS_TTL_S", "0") or 0)
        except ValueError:
            self._retain_ttl_s = 0.0
        self._evict_timer: Optional[threading.Timer] = None
        reg = self.stats.registry
        self._reload_total = reg.counter(
            "pio_model_reload_total",
            "Staged model reloads by outcome.", ("result",))
        self._gen_gauge = reg.gauge(
            "pio_model_generation",
            "Monotonic generation of the model currently serving "
            "(bumped by every successful reload or rollback).")
        self._prev_retained = reg.gauge(
            "pio_model_previous_retained",
            "1 while a rollback generation is held in memory.")
        self._prev_evicted = reg.counter(
            "pio_model_previous_evicted_total",
            "Rollback generations dropped by the PIO_RETAIN_PREVIOUS_TTL_S "
            "eviction timer.")
        # Serving scheduler (ISSUE 6): every /queries.json rides the
        # admission queue + micro-batcher; handlers never reach the
        # model directly (tools/lint_dispatch.py pins this).
        self.scheduler = ServingScheduler(
            config=scheduler_config or SchedulerConfig.from_env())
        self.scheduler.register("default", self._dispatch_batch)
        # SLO engine (ISSUE 9): multi-window burn rates over the serving
        # instruments + the autotuner's persistent-floor saturation
        # detector, combined into the /ready degradation verdict
        # (PIO_READY_SLO=off disables the flip, never the gauges).
        self.slo = SLOEngine(SLOConfig.from_env(),
                             registry=reg,
                             saturation_fn=self.scheduler.saturated)
        # Model-quality layer (ISSUE 11): sampled prediction stream +
        # drift detection + shadow-scored canary + feedback join, all
        # behind the PIO_QUALITY kill switch (off = inert no-op hooks).
        self.quality = QualityMonitor(registry=reg)
        # Retrieval-recall layer (ISSUE 16): sampled exact re-rank of
        # approximate-rung answers vs each generation's own baked recall
        # scorecard, folded into /quality.json's gate as a third
        # verdict.  PIO_RECALL=off registers zero instruments and can
        # never block a promotion.
        self.recall = RecallMonitor(registry=reg)
        # Serve-side result cache (ISSUE 20): the FIRST stop on the query
        # path, keyed by (generation fingerprint, canonical query) so every
        # reload/rollback invalidates by construction.  The optional fleet
        # tier rides the PR-13 shared KV; a missing/broken KV degrades to
        # the per-instance LRU, never fails construction.
        cache_cfg = ResultCacheConfig.from_env()
        cache_kv = None
        if cache_cfg.shared and getattr(self, "storage", None) is not None:
            try:
                cache_kv = self.storage.get_kv()
            except Exception:
                logger.warning("result cache: shared tier unavailable; "
                               "running local-only", exc_info=True)
        self.result_cache = ResultCache(cache_cfg, registry=reg,
                                        kv=cache_kv)

    def _load_candidate(self, target_instance_id: Optional[str] = None):
        """Storage-read phase of the staged reload (runs under the
        breaker): resolve the target instance and load its models.

        ``target_instance_id`` (ISSUE 15) pins the candidate explicitly
        — the fleet rollout controller names ONE instance id on every
        ``POST /reload`` so a newer COMPLETED train landing mid-wave can
        never split the fleet across generations."""
        instances = self.storage.get_engine_instances()
        requested = target_instance_id or self.requested_instance_id
        if requested:
            instance = instances.get(requested)
            if instance is None or instance.status != "COMPLETED":
                raise WorkflowError(
                    f"Engine instance {requested!r} not found "
                    "or not COMPLETED.")
        else:
            instance = instances.get_latest_completed(
                self.engine_id, self.engine_version, self.variant.variant_id)
            if instance is None:
                raise WorkflowError(
                    f"No COMPLETED engine instance for engine id "
                    f"{self.engine_id!r} variant {self.variant.variant_id!r} — "
                    "run `pio train` first.")
        models = load_models(self.engine, instance, self.ctx)
        return instance, models

    @staticmethod
    def _canary_queries() -> List[Any]:
        """Golden queries from ``PIO_CANARY_QUERIES``: inline JSON array,
        or a path to a JSON-array / NDJSON file.  Empty/unset disables
        the canary stage."""
        raw = os.environ.get("PIO_CANARY_QUERIES", "").strip()
        if not raw:
            return []
        if raw.startswith("["):
            return json.loads(raw)
        with open(raw, encoding="utf-8") as f:
            text = f.read().strip()
        if text.startswith("["):
            return json.loads(text)
        return [json.loads(line) for line in text.splitlines() if line.strip()]

    def _validate_candidate(self, instance, models, algorithms,
                            serving) -> None:
        """Validation stage: a candidate that cannot be trusted never
        reaches the swap.  Finite-params sanity over every array the
        models carry, then the optional golden-query canary — each
        PIO_CANARY_QUERIES entry must predict without raising."""
        for i, model in enumerate(models):
            validate_model_finite(model, name=f"models[{i}]")
        for qi, query_json in enumerate(self._canary_queries()):
            try:
                self._predict_with(algorithms, models, serving, query_json)
            except Exception as e:
                raise ModelValidationError(
                    f"candidate instance {instance.id} failed canary "
                    f"query #{qi} ({query_json!r}): "
                    f"{type(e).__name__}: {e}") from e

    def _record_reload(self, result: str, error: Optional[str] = None,
                       **extra) -> None:
        self._reload_total.inc(result=result)
        self._last_reload = {
            "result": result,
            "at": _dt.datetime.now(_dt.timezone.utc).isoformat(),
            **({"error": error} if error else {}),
        }
        publish_event("model.reload", result=result,
                      **({"error": error[:200]} if error else {}), **extra)

    def reload(self, target_instance_id: Optional[str] = None) -> str:
        """Staged reload of the latest COMPLETED instance — or, with
        ``target_instance_id``, of exactly THAT instance (the rollout
        controller's generation-atomic wave contract).

        read (breaker-guarded) → build → validate → swap; any failure
        keeps the last-good generation serving and raises.  The previous
        generation is retained for :meth:`rollback`."""
        with self._reload_lock:
            try:
                instance, models = self._breaker.call(
                    self._load_candidate, target_instance_id)
                engine_params = instance_engine_params(self.engine, instance)
                algorithms = self.engine.make_algorithms(engine_params)
                serving = self.engine.make_serving(engine_params)
                self._validate_candidate(instance, models, algorithms,
                                         serving)
            except Exception as e:
                self._record_reload("failed", error=str(e))
                logger.error("model reload failed (%s); %s", e,
                             "serving continues on the last-good model"
                             if self._instance is not None else
                             "no model is loaded yet")
                raise
            now = _dt.datetime.now(_dt.timezone.utc)
            with self._swap_lock:
                # PIO_RETAIN_PREVIOUS=off: never hold a second generation
                # in memory (large corpora double their footprint while a
                # rollback generation lives).
                self._previous = _Generation(
                    self._instance, self._models, self._algorithms,
                    self._serving, self._loaded_at, self._generation) \
                    if self._instance is not None and self._retain_previous \
                    else None
                self._instance = instance
                self._models = models
                self._algorithms = algorithms
                self._serving = serving
                self._loaded_at = now
                self._generation += 1
                gen = self._generation
                prev = self._previous
                retained = prev is not None
            self._gen_gauge.set(gen)
            self._prev_retained.set(1 if retained else 0)
            # Quality re-anchor (ISSUE 11): the new generation's
            # scorecard becomes the drift baseline, and — while a
            # previous generation is retained for rollback — its predict
            # stack shadow-scores a sampled slice of live queries so the
            # canary window can judge old-vs-new divergence.  The
            # closure is dropped on rollback/eviction so the retained
            # generation's memory is actually freed.
            shadow_fn = None
            if retained and self.quality.enabled:
                def shadow_fn(q, _gen=prev):
                    return self._shadow_predict(_gen, q)
            self.quality.on_generation(
                gen, models, shadow_fn=shadow_fn,
                prev_generation=prev.number if retained else None)
            # Recall re-anchor (ISSUE 16): arm the NEW generation's
            # retriever hook and judge it against its own baked recall
            # scorecard (never the predecessor's).
            self.recall.on_generation(gen, models)
            # Result cache (ISSUE 20): the new instance id becomes the key
            # fingerprint — every pre-swap entry misses by construction.
            self.result_cache.on_generation(gen, instance.id)
            self._arm_eviction(gen)
            self._record_reload("ok", instance=instance.id, generation=gen)
            logger.info("Engine server loaded instance %s (generation %d)",
                        instance.id, gen)
            return instance.id

    def rollback(self) -> str:
        """Instant swap back to the retained previous generation
        (``POST /admin/rollback``).  The generations exchange places, so
        a second rollback returns; raises when none is retained."""
        with self._reload_lock:
            with self._swap_lock:
                prev = self._previous
                if prev is None:
                    raise WorkflowError(
                        "No previous model generation retained — nothing "
                        "to roll back to.")
                self._previous = _Generation(
                    self._instance, self._models, self._algorithms,
                    self._serving, self._loaded_at, self._generation)
                self._instance = prev.instance
                self._models = prev.models
                self._algorithms = prev.algorithms
                self._serving = prev.serving
                self._loaded_at = prev.loaded_at
                self._generation += 1
                gen = self._generation
                instance_id = prev.instance.id
                restored_models = prev.models
            self._gen_gauge.set(gen)
            self._prev_retained.set(1)
            # Quality: the rollback ends any shadow session (the "new"
            # generation it was judging is out) and re-anchors drift on
            # the RESTORED generation's own scorecard.
            self.quality.on_generation(gen, restored_models)
            self.recall.on_generation(gen, restored_models)
            # Restoring the previous instance id revalidates its surviving
            # cache entries for free — the fingerprint IS the key.
            self.result_cache.on_generation(gen, instance_id)
            # The rolled-from generation now sits in the previous slot;
            # it ages out on the same TTL as any other retained one.
            self._arm_eviction(gen)
            self._record_reload("rollback", instance=instance_id,
                                generation=gen)
            logger.warning("Engine server rolled back to instance %s "
                           "(generation %d)", instance_id, gen)
            return instance_id

    def _arm_eviction(self, generation: int) -> None:
        """(Re)start the retained-previous TTL timer for ``generation``.

        The timer carries the generation it was armed for: if a newer
        reload/rollback swapped again before it fires, the stale timer's
        eviction is a no-op (the new swap armed its own)."""
        timer, self._evict_timer = self._evict_timer, None
        if timer is not None:
            timer.cancel()
        if self._retain_ttl_s <= 0:
            return
        with self._swap_lock:
            if self._previous is None:
                return
        timer = threading.Timer(self._retain_ttl_s, self._evict_previous,
                                args=(generation,))
        timer.daemon = True
        timer.start()
        self._evict_timer = timer

    def _evict_previous(self, expected_generation: int) -> bool:
        """Drop the retained rollback generation (frees its model memory)
        — called by the TTL timer after the canary window, or directly.
        Returns False when a newer swap already owns the previous slot."""
        with self._swap_lock:
            if (self._generation != expected_generation
                    or self._previous is None):
                return False
            dropped = self._previous
            self._previous = None
        self._prev_retained.set(0)
        self._prev_evicted.inc()
        # The shadow session holds the evicted generation's predict
        # closure — drop it with the generation, or the eviction frees
        # nothing.
        self.quality.end_shadow("previous generation evicted")
        publish_event("model.previous_evicted",
                      generation=expected_generation,
                      evicted_generation=dropped.number)
        logger.info("Evicted retained previous model generation %d after "
                    "%.0fs TTL (rollback no longer available)",
                    dropped.number, self._retain_ttl_s)
        return True

    # -- query path ---------------------------------------------------------

    def _bind_query(self, obj: Any):
        if self.engine.query_class is None:
            return obj
        if dataclasses.is_dataclass(self.engine.query_class):
            try:
                return bind_params(self.engine.query_class, obj, _path="query")
            except TypeError as e:
                raise QueryError(str(e)) from e
        return self.engine.query_class(**obj)

    @staticmethod
    def _result_to_json(result: Any) -> Any:
        if dataclasses.is_dataclass(result) and not isinstance(result, type):
            return _dc_to_json(result)
        return result

    def _predict_with(self, algorithms, models, serving,
                      query_json: Any) -> Any:
        """bind → supplement → per-algorithm predict → serve against an
        EXPLICIT model set — the live generation (``query``) and the
        reload canary both ride this path."""
        with span("predict.bind"):
            q = self._bind_query(query_json)
        with span("predict.supplement"):
            q = serving.supplement(q)
        predictions = []
        for a, m in zip(algorithms, models):
            with span("predict.algorithm", algo=type(a).__name__):
                predictions.append(a.predict(m, q))
        with span("predict.serve"):
            return self._result_to_json(serving.serve(q, predictions))

    def _shadow_predict(self, gen: _Generation, q: Any) -> Any:
        """Score one BOUND query against a retained (non-serving)
        generation's full predict stack — the shadow-scoring canary's
        reference answer (ISSUE 11).  Runs on the shadow worker thread,
        never a handler thread."""
        q2 = gen.serving.supplement(q)
        preds = [a.predict(m, q2)
                 for a, m in zip(gen.algorithms, gen.models)]
        return self._result_to_json(gen.serving.serve(q2, preds))

    def query(self, query_json: Any) -> Any:
        """One predict round-trip (reference §3.2 hot path).

        Span-per-phase under an active trace: bind → supplement →
        per-algorithm predict → serve.  Outside a trace each ``span`` is
        two perf_counter calls — the hot path stays hot.
        """
        with self._swap_lock:
            algorithms, models, serving = (
                self._algorithms, self._models, self._serving)
        return self._predict_with(algorithms, models, serving, query_json)

    def _dispatch_batch(self, bound_queries: List[Any]
                        ) -> Tuple[List[Any], int]:
        """THE batched dispatch the serving scheduler drives: one
        ``batch_predict`` (vectorized XLA) call per algorithm for the
        whole cohort, against ONE generation snapshot taken under a
        single swap-lock acquisition — a reload/rollback landing
        mid-batch flips the next batch, never splits this one.

        Takes BOUND queries: binding is per-member, client-controlled
        failure, so it happens at admission (handler thread → its own
        400) and can never fail a cohort.  ``supplement`` stays here —
        it belongs to the generation's serving instance."""
        with self._swap_lock:
            algorithms, models, serving, generation = (
                self._algorithms, self._models, self._serving,
                self._generation)
        queries = [serving.supplement(q) for q in bound_queries]
        indexed = list(enumerate(queries))
        per_algo = [dict(a.batch_predict(m, indexed))
                    for a, m in zip(algorithms, models)]
        return [
            self._result_to_json(
                serving.serve(q, [pa[i] for pa in per_algo]))
            for i, q in indexed
        ], generation

    def query_batch(self, query_jsons: List[Any]) -> List[Any]:
        """Batched predict (native frontend, ``pio batchpredict``): the
        scheduler's dispatch path without the generation tag."""
        return self._dispatch_batch(
            [self._bind_query(qj) for qj in query_jsons])[0]

    # -- HTTP ---------------------------------------------------------------

    def handle(self, method: str, path: str, body: bytes,
               params: Optional[Dict[str, List[str]]] = None
               ) -> Tuple[int, Any]:
        params = params or {}
        try:
            fault_point("http.engine")
            if path == "/" and method == "GET":
                with self._swap_lock:
                    inst = self._instance
                    loaded = self._loaded_at
                    gen = self._generation
                    prev = self._previous
                wm = data_watermark(inst) if inst else None
                return 200, {
                    "status": "alive",
                    "engineFactory": self.variant.engine_factory,
                    "variant": self.variant.variant_id,
                    "engineInstanceId": inst.id if inst else None,
                    "modelLoadedAt": loaded.isoformat() if loaded else None,
                    "modelGeneration": gen,
                    # ISSUE 10: the served generation's data high-
                    # watermark — events before this instant are in the
                    # model; the gap to pio_events_latest_ts is the
                    # event→servable staleness.
                    "dataWatermark": wm.isoformat() if wm else None,
                    "refreshMode": (inst.env or {}).get("refreshMode")
                    if inst else None,
                    "lastReload": self._last_reload or None,
                    "rollbackAvailable": prev is not None,
                    "retainPreviousTtlS": self._retain_ttl_s or None,
                    "breaker": self._breaker.state,
                    "batcher": self.scheduler.snapshot(),
                    "resultCache": self.result_cache.snapshot(),
                    "slo": self.slo.snapshot(),
                    "version": __version__,
                }
            if path == "/ready" and method == "GET":
                # Readiness (vs "/" liveness): a model is loaded AND the
                # SLO/saturation signal is healthy — 503 rotates the
                # instance out of the LB pool (ISSUE 9: persistent-floor
                # saturation + burn rate flip this; PIO_READY_SLO=off is
                # the operator escape hatch; hysteresis in the engine).
                with self._swap_lock:
                    inst = self._instance
                    serving = self._serving
                loaded = inst is not None and serving is not None
                slo_ok, slo_state = self.slo.ready()
                ok = loaded and slo_ok
                status = "ready" if ok else (
                    "degraded" if loaded else "unavailable")
                return (200 if ok else 503), {
                    "status": status,
                    "engineInstanceId": inst.id if inst else None,
                    "slo": slo_state,
                }
            if path == "/metrics" and method == "GET":
                # THE process-wide exposition (shared registry render).
                # ?exemplars=1 appends the OpenMetrics trace-id suffixes
                # to waterfall buckets — opt-in, classic scrapers choke.
                return 200, self.stats.registry.render(
                    exemplars=param_bool(params, "exemplars"))
            if path == "/stats.json" and method == "GET":
                with self._swap_lock:
                    inst = self._instance
                wm = data_watermark(inst) if inst else None
                return 200, {**self.stats.snapshot(),
                             "batcher": self.scheduler.snapshot(),
                             "resultCache": self.result_cache.snapshot(),
                             "slo": self.slo.snapshot(),
                             "quality": self.quality.summary(),
                             "dataWatermark": wm.isoformat() if wm
                             else None}
            if path == "/quality.json" and method == "GET":
                # Model-quality document (ISSUE 11): drift vs the
                # training scorecard, shadow-canary divergence, online
                # hit-rate, and the promotion-gate verdict the refresh
                # daemon polls during the canary window.  The recall
                # layer (ISSUE 16) folds its verdict into the same gate
                # — the daemon/rollout read only gate.rollback, so a
                # recall regression rolls back through the existing path.
                return 200, self.recall.augment_quality(
                    self.quality.payload())
            if path == "/traces.json" and method == "GET":
                # ?request_id= resolves waterfall exemplars to ONE trace;
                # ?min_ms=/?limit= bound the view (shared helper).
                return 200, traces_payload(params)
            if path == "/timeline.json" and method == "GET":
                # Step-timeline ring: ?model=/?n=/?format=chrome for the
                # chrome://tracing / Perfetto export.
                return 200, timeline_payload(params)
            if path == "/reload" and method == "POST":
                # Optional target pin (ISSUE 15): the rollout controller
                # posts {"engineInstanceId": ...} so every instance in a
                # wave loads the SAME candidate.
                target = None
                if body:
                    try:
                        target = (json.loads(body.decode("utf-8"))
                                  or {}).get("engineInstanceId")
                    except (ValueError, AttributeError):
                        return 400, {"message": "reload body must be "
                                                "JSON"}
                try:
                    instance_id = self.reload(target)
                except ModelValidationError as e:
                    # Candidate rejected by the validation stage: the
                    # last-good model keeps serving — a client fault
                    # (bad train), not an availability failure.
                    return 409, {"message": str(e),
                                 "status": "rejected"}
                except WorkflowError as e:
                    if target:
                        # An explicitly named candidate this server
                        # cannot load (not COMPLETED / unknown): reject
                        # like a validation failure — the wave skips and
                        # reports, last-good keeps serving.
                        return 409, {"message": str(e),
                                     "status": "rejected"}
                    raise
                return 200, {"status": "reloaded",
                             "engineInstanceId": instance_id,
                             "generation": self._generation}
            if path == "/admin/rollback" and method == "POST":
                try:
                    instance_id = self.rollback()
                except WorkflowError as e:
                    return 409, {"message": str(e)}
                return 200, {"status": "rolled_back",
                             "engineInstanceId": instance_id,
                             "generation": self._generation}
            if path == "/queries.json" and method == "POST":
                t0 = time.perf_counter()
                # Arm the latency waterfall (ISSUE 9): stages stamped
                # here (bind), by the batcher (queue/batch/dispatch/
                # retrieval), and by the transport driver (serialize/
                # shed_check), which also finalizes + publishes it after
                # the response is written.
                _waterfall.activate()
                try:
                    # Shed BEFORE admission: a request whose budget is
                    # spent must not occupy a queue slot.
                    _deadline.check("predict")
                    # Bind BEFORE admission: a malformed query 400s on
                    # this thread and never occupies a queue slot or
                    # fails the batch it would have ridden in.
                    tb = time.perf_counter()
                    # ingress: transport receipt → here (socket body
                    # read, trace setup, routing, the deadline check) —
                    # real wall the attestation contains, so the
                    # waterfall must bill it.
                    t0t = _waterfall.transport_start()
                    if t0t is not None and tb > t0t:
                        _waterfall.record_stage("ingress",
                                                (tb - t0t) * 1e3)
                    q = self._bind_query(json.loads(body.decode("utf-8")))
                    _waterfall.record_stage(
                        "bind", (time.perf_counter() - tb) * 1e3)
                    # The ONLY route to the model: admission queue →
                    # micro-batcher → vectorized dispatch (ISSUE 6; the
                    # lint forbids calling query/query_batch from here).
                    wf = _waterfall.current_waterfall()
                    # ONE uniform draw per request (ISSUE 11): the
                    # prediction record stream, shadow sampling, and the
                    # PIO_REQUEST_LOG_SAMPLE wide-event sampler all
                    # compare this same u against their own rates.
                    u = self.quality.draw() if self.quality.enabled \
                        else (self.recall.draw()
                              if self.recall.enabled else None)
                    if wf is not None and u is not None:
                        wf.sample_u = u
                    # Result cache (ISSUE 20): the first stop after bind.
                    # A hit bypasses admission/batching entirely but
                    # stamps the `cache` stage with the FILL generation —
                    # attribution and the serve-id describe the answer
                    # actually served — and rides the same quality record
                    # stream as a dispatched request, so a 95%-hit-rate
                    # drive still feeds the drift windows.  The lookup
                    # cost is stamped on misses too: it is real wall the
                    # attestation contains.
                    canon = None
                    if self.result_cache.enabled:
                        tc = time.perf_counter()
                        try:
                            canon = canonical_query(q)
                        except TypeError:
                            canon = None  # uncacheable query shape
                        hit = (self.result_cache.lookup(canon)
                               if canon is not None else None)
                        _waterfall.record_stage(
                            "cache", (time.perf_counter() - tc) * 1e3,
                            cacheHit=hit is not None)
                        if hit is not None:
                            if wf is not None:
                                wf.note(generation=hit.generation,
                                        cacheTier=hit.tier,
                                        cacheAgeS=round(hit.age_s, 3))
                                wf.mark("handler_done")
                            # Same never-late-200 gate as the dispatch
                            # path: a hit found past the budget still
                            # sheds.
                            _deadline.check("respond")
                            # Parse the document only when this request
                            # is quality-sampled (same gate observe
                            # applies): an unsampled hit serves the
                            # cached bytes untouched.
                            if (u is not None and self.quality.enabled
                                    and u < self.quality.config.sample):
                                sid = self.quality.observe(
                                    q, hit.result, hit.generation, u)
                                if sid is not None and wf is not None:
                                    wf.note(serveId=sid)
                            self.stats.record(
                                (time.perf_counter() - t0) * 1e3, True)
                            return (200, hit.result_bytes,
                                    "application/json; charset=UTF-8")
                    try:
                        result = self.scheduler.submit_and_wait(
                            "default", q)
                    finally:
                        # shed_check opens here: the transport stamps it
                        # from this mark so the span-unwind/stats segment
                        # between scheduler hand-back and the respond
                        # write is accounted, not lost.
                        if wf is not None:
                            wf.mark("handler_done")
                    # Cache fill at the scheduler hand-back, under the
                    # generation the batcher STAMPED at dispatch — never
                    # "current" — so a mid-flight swap can't cache
                    # generation A's answer under B's key.  Before the
                    # respond gate: a result that arrives past its budget
                    # still warms the cache for the retry.
                    if canon is not None:
                        self.result_cache.fill(
                            canon, result,
                            wf.attr("generation") if wf is not None
                            else None)
                    # Final gate: a result that arrived past its own
                    # deadline is never served as a slow 200 — the
                    # client's budget is spent, so it gets the same 504
                    # the waiter would have raised a tick later.
                    _deadline.check("respond")
                    # Quality record stream, at the scheduler hand-back
                    # (the request side of the dispatch boundary): one
                    # sampled append, attributed to the generation the
                    # batcher stamped on the dispatch.
                    sid = self.quality.observe(
                        q, result,
                        wf.attr("generation") if wf is not None else None,
                        u)
                    if sid is not None and wf is not None:
                        # Rides the waterfall into the wide event AND to
                        # the transport hook that echoes it as
                        # X-PIO-Serve-Id — a client that sends the id
                        # back on its buy/rate event
                        # (properties.pioServeId) closes the feedback
                        # join.
                        wf.note(serveId=sid)
                    self.stats.record((time.perf_counter() - t0) * 1e3, True)
                    return 200, result
                except QueueFull as e:
                    # Admission rejected: 429 + Retry-After (the handler
                    # adds the hint via retry_after_statuses) — back off,
                    # the requests already admitted keep their latency.
                    self.stats.record((time.perf_counter() - t0) * 1e3, False)
                    return 429, {"message": str(e)}
                except DeadlineExceeded as e:
                    self.stats.shed.inc(server="engine")
                    self.stats.record((time.perf_counter() - t0) * 1e3, False)
                    return 504, {"message": str(e)}
                except (SchedulerStalled, SchedulerClosed) as e:
                    self.stats.record((time.perf_counter() - t0) * 1e3, False)
                    return 503, {"message": f"Temporarily unavailable: {e}"}
                except (QueryError, json.JSONDecodeError) as e:
                    self.stats.record((time.perf_counter() - t0) * 1e3, False)
                    return 400, {"message": str(e)}
                except Exception:
                    self.stats.record((time.perf_counter() - t0) * 1e3, False)
                    logger.exception("query failed")
                    return 500, {"message": "Internal server error."}
            if path == "/stop" and method == "POST":
                threading.Thread(target=self.stop, daemon=True).start()
                return 200, {"status": "stopping"}
            return 404, {"message": "Not Found"}
        except DeadlineExceeded as e:
            self.stats.shed.inc(server="engine")
            return 504, {"message": str(e)}
        except (ConnectionError, StorageUnavailable, CircuitOpenError) as e:
            # Injected faults, dead backends, and the reload breaker
            # shedding (CircuitOpenError) are availability failures: 503
            # + Retry-After, not a 500 bug report.  The last-good model
            # keeps serving throughout.
            return 503, {"message": f"Temporarily unavailable: {e}"}
        except Exception:
            logger.exception("engine server internal error")
            return 500, {"message": "Internal server error."}

    def _make_handler(server_self):
        class Handler(BaseHandler):
            server_log_name = "engine-server"
            trace_server_name = "engine"
            # Predicts are read-only: a 200 computed past its budget is
            # safely rewritten to 504 at the transport (never-late-200).
            shed_late_responses = True

            def pio_handle(self, method, path, params, body):
                return server_self.handle(method, path, body, params)

            def pio_shed(self):
                server_self.stats.shed.inc(server="engine")

            def pio_on_complete(self, method, path, status, ms, body,
                                params):
                extra = dict(server_self.plugins.on_request(
                    f"{method} {path}", status, ms) or {}) \
                    if server_self.plugins else {}
                # Serve-id echo (ISSUE 11): the quality layer noted the
                # sampled serve on the request's waterfall; surface it
                # as a response header so the client can echo it on its
                # feedback event.
                wf = _waterfall.current_waterfall()
                sid = wf.attr("serveId") if wf is not None else None
                if sid:
                    extra[SERVE_ID_HEADER] = str(sid)
                return extra or None

            def pio_retry_after_s(self):
                # Breaker-open reload shed carries the breaker's actual
                # recovery hint; other degraded answers the env default.
                open_in = server_self._breaker.retry_after_s()
                return max(1, int(open_in)) if open_in > 0 \
                    else server_self.retry_after_s

            def do_GET(self):  # noqa: N802
                self.dispatch("GET")

            def do_POST(self):  # noqa: N802
                self.dispatch("POST")

        return Handler

    def start(self, block: bool = False) -> None:
        self._httpd = ThreadingHTTPServer((self.host, self.port),
                                          self._make_handler())
        self.port = self._httpd.server_address[1]
        logger.info("Engine Server listening on %s:%d", self.host, self.port)
        if block:
            self._httpd.serve_forever()
        else:
            self._thread = threading.Thread(target=self._httpd.serve_forever,
                                            daemon=True)
            self._thread.start()

    def stop(self) -> None:
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._evict_timer is not None:
            self._evict_timer.cancel()
            self._evict_timer = None
        self.scheduler.close()
        self.quality.close()
        self.recall.close()
        self.plugins.stop()
