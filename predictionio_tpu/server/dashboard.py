"""Dashboard — read-only web UI over engine/evaluation instances.

Reference: tools/.../tools/dashboard/Dashboard.scala (SURVEY.md §2.1): an
HTML listing of engine instances (status, times, params) and completed
evaluations with their metric scores.  JSON endpoints added for tooling:
``GET /engine_instances.json``, ``GET /evaluation_instances.json``, plus
the shared observability views ``GET /metrics`` / ``GET /traces.json`` /
``GET /timeline.json``.

ISSUE 9 (fleet telemetry): ``GET /fleet.json`` scrapes a configured list
of instance base URLs (``PIO_FLEET_INSTANCES`` or the ``fleet``
constructor arg), merges ``/metrics`` type-correctly (counters sum,
histogram buckets add, gauges keep an ``instance`` label), and carries
each instance's SLO/batcher state and timeline summary — the dashboard
finally sees N processes, not one.
"""

from __future__ import annotations

import html
import json
import logging
import threading
from typing import List, Optional, Tuple

from predictionio_tpu.data.storage import Storage, get_storage
from predictionio_tpu.obs import get_registry
from predictionio_tpu.obs.fleet import (
    FleetAggregator,
    fleet_instances_from_env,
)
from predictionio_tpu.server.http import (
    BaseHandler,
    PROMETHEUS_CTYPE,
    ThreadingHTTPServer,
    timeline_payload,
    traces_payload,
    param_bool,
)
from predictionio_tpu.version import __version__

logger = logging.getLogger(__name__)

__all__ = ["DashboardServer"]


def _fmt_time(t) -> str:
    return t.isoformat(timespec="seconds") if t else "-"


class DashboardServer:
    def __init__(self, storage: Optional[Storage] = None, host: str = "127.0.0.1",
                 port: int = 9000, fleet: Optional[List[str]] = None):
        self.storage = storage or get_storage()
        self.host = host
        self.port = port
        self.registry = get_registry()
        self._requests = self.registry.counter(
            "pio_dashboard_requests_total",
            "Dashboard requests by HTTP status.", ("status",))
        self._latency = self.registry.histogram(
            "pio_dashboard_request_latency_ms",
            "Dashboard request handling latency.")
        instances = fleet if fleet is not None else fleet_instances_from_env()
        self.fleet: Optional[FleetAggregator] = (
            FleetAggregator(instances) if instances else None)
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # -- payloads -----------------------------------------------------------

    def _engine_rows(self):
        rows = self.storage.get_engine_instances().get_all()
        return sorted(rows, key=lambda r: r.start_time or 0, reverse=True)

    def _eval_rows(self):
        rows = self.storage.get_evaluation_instances().get_all()
        return sorted(rows, key=lambda r: r.start_time or 0, reverse=True)

    def _index_html(self) -> str:
        eng = "".join(
            f"<tr><td>{html.escape(r.id or '')}</td>"
            f"<td>{html.escape(r.engine_factory)}</td>"
            f"<td>{html.escape(r.engine_variant)}</td>"
            f"<td>{html.escape(r.status)}</td>"
            f"<td>{_fmt_time(r.start_time)}</td><td>{_fmt_time(r.end_time)}</td></tr>"
            for r in self._engine_rows()
        )
        ev = "".join(
            f"<tr><td>{html.escape(r.id or '')}</td>"
            f"<td>{html.escape(r.evaluation_class)}</td>"
            f"<td>{html.escape(r.status)}</td>"
            f"<td>{_fmt_time(r.start_time)}</td>"
            f"<td><pre>{html.escape(r.evaluator_results or '-')}</pre></td></tr>"
            for r in self._eval_rows()
        )
        return f"""<!doctype html><html><head><title>PredictionIO-TPU Dashboard</title>
<style>body{{font-family:sans-serif;margin:2em}}table{{border-collapse:collapse}}
td,th{{border:1px solid #ccc;padding:4px 8px;text-align:left}}</style></head>
<body><h1>PredictionIO-TPU Dashboard <small>v{__version__}</small></h1>
<h2>Engine instances</h2>
<table><tr><th>ID</th><th>Factory</th><th>Variant</th><th>Status</th>
<th>Start</th><th>End</th></tr>{eng}</table>
<h2>Evaluation instances</h2>
<table><tr><th>ID</th><th>Evaluation</th><th>Status</th><th>Start</th>
<th>Results</th></tr>{ev}</table></body></html>"""

    def handle(self, method: str, path: str,
               params: Optional[dict] = None) -> Tuple[int, str, str]:
        if method != "GET":
            return 404, "application/json", json.dumps({"message": "Not Found"})
        if path == "/":
            return 200, "text/html; charset=UTF-8", self._index_html()
        if path == "/metrics":
            return 200, PROMETHEUS_CTYPE, self.registry.render(
                exemplars=param_bool(params, "exemplars"))
        if path == "/traces.json":
            return 200, "application/json", json.dumps(
                traces_payload(params or {}))
        if path == "/timeline.json":
            return 200, "application/json", json.dumps(
                timeline_payload(params or {}))
        if path == "/fleet.json":
            if self.fleet is None:
                return 200, "application/json", json.dumps({
                    "instances": [],
                    "message": "no fleet configured — set "
                               "PIO_FLEET_INSTANCES or `pio dashboard "
                               "--fleet URL,URL`"})
            return 200, "application/json", json.dumps(self.fleet.scrape())
        if path == "/quality.json":
            # Fleet-merged model-quality view (ISSUE 11): per-instance
            # /quality.json docs + the union-of-keys merge.
            if self.fleet is None:
                return 200, "application/json", json.dumps({
                    "enabled": False,
                    "message": "no fleet configured — set "
                               "PIO_FLEET_INSTANCES or `pio dashboard "
                               "--fleet URL,URL`"})
            doc = self.fleet.scrape()
            return 200, "application/json", json.dumps({
                "merged": doc["merged"].get("quality"),
                "instances": [
                    {"instance": row["instance"], "stale": row["stale"],
                     "quality": row.get("quality")}
                    for row in doc["instances"]],
            })
        if path == "/engine_instances.json":
            rows = [
                {"id": r.id, "status": r.status,
                 "engineFactory": r.engine_factory,
                 "variant": r.engine_variant,
                 "startTime": _fmt_time(r.start_time),
                 "endTime": _fmt_time(r.end_time)}
                for r in self._engine_rows()
            ]
            return 200, "application/json", json.dumps(rows)
        if path == "/evaluation_instances.json":
            rows = [
                {"id": r.id, "status": r.status,
                 "evaluationClass": r.evaluation_class,
                 "startTime": _fmt_time(r.start_time),
                 "results": r.evaluator_results,
                 "resultsJson": r.evaluator_results_json}
                for r in self._eval_rows()
            ]
            return 200, "application/json", json.dumps(rows)
        return 404, "application/json", json.dumps({"message": "Not Found"})

    # -- HTTP ---------------------------------------------------------------

    def _make_handler(server_self):
        class Handler(BaseHandler):
            server_log_name = "dashboard"
            trace_server_name = "dashboard"

            def pio_handle(self, method, path, params, body):
                status, ctype, payload = server_self.handle(method, path,
                                                            params)
                return status, payload, ctype

            def pio_on_complete(self, method, path, status, ms, body,
                                params):
                server_self._requests.inc(status=str(status))
                server_self._latency.observe(ms)
                return None

            def do_GET(self):  # noqa: N802
                self.dispatch("GET")

        return Handler

    def start(self, block: bool = False) -> None:
        self._httpd = ThreadingHTTPServer((self.host, self.port),
                                          self._make_handler())
        self.port = self._httpd.server_address[1]
        logger.info("Dashboard listening on %s:%d", self.host, self.port)
        if block:
            self._httpd.serve_forever()
        else:
            self._thread = threading.Thread(target=self._httpd.serve_forever,
                                            daemon=True)
            self._thread.start()

    def stop(self) -> None:
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
