"""Server plugin seam — injected request-level instrumentation.

Reference: ``EngineServerPlugin`` (core/.../workflow/) and
``EventServerPlugin`` (data/.../data/api/) per SURVEY.md §5.1: the
reference's engine and event servers discover plugin implementations at
startup (ServiceLoader-style) and invoke them around requests.  Here
discovery is env-driven (matching this rebuild's storage-registry
convention): a comma-separated list of ``module:factory`` specs in

- ``PIO_EVENTSERVER_PLUGINS``  — loaded by every EventServer
- ``PIO_ENGINESERVER_PLUGINS`` — loaded by every EngineServer

Each factory is imported and called with no arguments and must return a
:class:`ServerPlugin`.  Plugins see every request on BOTH transports —
the python HTTP frontends and the C++ native frontend (whose responses
carry plugin-injected headers through ``pio_batch_respond_ex``).

A plugin must never take the server down: exceptions from plugin hooks
are logged and swallowed, and header names/values are sanitized against
CRLF header injection before they reach a response.
"""

from __future__ import annotations

import importlib
import logging
import os
import threading
from typing import Dict, Iterable, List, Optional, Sequence

logger = logging.getLogger(__name__)

__all__ = ["ServerPlugin", "PluginManager", "MetricsPlugin",
           "make_metrics_plugin"]


class ServerPlugin:
    """Base class for server plugins (subclassing is optional — any
    object with these methods works).

    - :meth:`start` runs once at server startup with the server object.
    - :meth:`on_request` runs per request with the route
      (``"METHOD /path"``), response status, and handling time; it may
      return a dict of response headers to inject.
    - :meth:`stop` runs at server shutdown.
    """

    name = "plugin"

    def start(self, server) -> None:  # pragma: no cover - default no-op
        pass

    def on_request(self, route: str, status: int,
                   ms: float) -> Optional[Dict[str, str]]:
        return None

    def stop(self) -> None:  # pragma: no cover - default no-op
        pass


def _sanitize(s: str) -> str:
    """Strip CR/LF so a plugin-supplied value cannot inject headers."""
    return str(s).replace("\r", " ").replace("\n", " ")


class MetricsPlugin(ServerPlugin):
    """Exemplar plugin: feed ``on_request`` into the shared obs registry.

    Proves the plugin seam and the built-in server instrumentation report
    through the SAME pipeline: this plugin's
    ``pio_plugin_requests_total{route,status}`` series and the server's
    built-in ``pio_*_requests_total`` counters land in one registry and
    one ``/metrics`` exposition, and must agree on totals (pinned by
    tests/test_servers.py).  Enable with::

        PIO_EVENTSERVER_PLUGINS=predictionio_tpu.server.plugins:make_metrics_plugin

    Note the ``route`` label carries the raw request path, so its
    cardinality is client-controlled (e.g. ``/events/<id>.json``) — fine
    for a trusted deployment, something to aggregate for a public one.
    """

    name = "metrics"

    def __init__(self, registry=None):
        from predictionio_tpu.obs import get_registry

        reg = registry or get_registry()
        self.requests = reg.counter(
            "pio_plugin_requests_total",
            "Requests seen by the metrics plugin, by route and status.",
            ("route", "status"))
        self.latency = reg.histogram(
            "pio_plugin_request_latency_ms",
            "Request latency as seen by the metrics plugin.")

    def on_request(self, route: str, status: int,
                   ms: float) -> Optional[Dict[str, str]]:
        self.requests.inc(route=route, status=str(status))
        self.latency.observe(ms)
        return None


def make_metrics_plugin() -> MetricsPlugin:
    """Env-spec factory (``module:factory`` discovery contract)."""
    return MetricsPlugin()


class PluginManager:
    """Loads, starts, and fans requests out to the server's plugins."""

    def __init__(self, plugins: Iterable[ServerPlugin] = ()):
        self.plugins: List[ServerPlugin] = list(plugins)
        self._lock = threading.Lock()
        self._started = False

    @classmethod
    def from_env(cls, env_var: str,
                 extra_specs: Sequence[str] = ()) -> "PluginManager":
        """``module:factory[,module:factory...]`` from ``env_var`` plus
        any explicit ``extra_specs`` (e.g. an engine.json list)."""
        specs = [s.strip() for s in os.environ.get(env_var, "").split(",")
                 if s.strip()]
        specs.extend(extra_specs)
        plugins = []
        for spec in specs:
            try:
                mod_name, _, factory_name = spec.partition(":")
                if not factory_name:
                    raise ValueError(
                        f"plugin spec {spec!r} must be module:factory")
                factory = getattr(importlib.import_module(mod_name),
                                  factory_name)
                plugins.append(factory())
            except Exception:
                logger.exception("failed to load server plugin %r", spec)
        return cls(plugins)

    def start(self, server) -> None:
        with self._lock:
            if self._started:
                return
            self._started = True
        for p in self.plugins:
            try:
                p.start(server)
            except Exception:
                logger.exception("plugin %s start failed",
                                 getattr(p, "name", p))

    def on_request(self, route: str, status: int, ms: float) -> Dict[str, str]:
        """Fan out one request notification; merge injected headers."""
        headers: Dict[str, str] = {}
        for p in self.plugins:
            try:
                h = p.on_request(route, status, ms)
                if h:
                    headers.update({_sanitize(k): _sanitize(v)
                                    for k, v in h.items()})
            except Exception:
                logger.exception("plugin %s on_request failed",
                                 getattr(p, "name", p))
        return headers

    def header_block(self, route: str, status: int, ms: float) -> str:
        """CRLF-joined header lines for the native frontend's
        ``pio_batch_respond_ex``; empty string when nothing to inject."""
        headers = self.on_request(route, status, ms)
        if not headers:
            return ""
        return "".join(f"{k}: {v}\r\n" for k, v in headers.items())

    def stop(self) -> None:
        for p in self.plugins:
            try:
                p.stop()
            except Exception:
                logger.exception("plugin %s stop failed",
                                 getattr(p, "name", p))

    def __bool__(self) -> bool:
        return bool(self.plugins)
