"""Event Server — always-on ingestion REST service.

Reference: data/.../data/api/EventServer.scala + EventServiceActor routes
(SURVEY.md §3.3).  API parity (Appendix A):

- ``POST /events.json?accessKey=K[&channel=C]`` → 201 ``{"eventId": ...}``
- ``POST /batch/events.json`` → 200 ``[{"status":201,"eventId":...}, ...]``
  (per-item status; malformed items get their error inline, max 50/batch)
- ``GET /events.json?accessKey=K&...`` filters: startTime, untilTime,
  entityType, entityId, event, targetEntityType, targetEntityId, limit,
  reversed
- ``GET /events/<id>.json`` / ``DELETE /events/<id>.json``
- ``GET /`` → ``{"status": "alive"}``; ``GET /stats.json`` ingest counters
  (reference keeps these behind a flag; always on here)
- ``GET /metrics`` — rebuild addition (SURVEY.md §5.5): Prometheus-style
  text exposition of request counters/latency

Auth: accessKey query param or ``Authorization`` header (the reference
accepts basic-auth with the key as username).  Per-key event allowlists
enforced on write.
"""

from __future__ import annotations

import base64
import datetime as _dt
import json
import logging
import os
import re
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from predictionio_tpu.data.columnar import SegmentDiskPressure, SegmentStore
from predictionio_tpu.data.event import EventValidationError
from predictionio_tpu.data.json_support import (
    event_from_json,
    event_to_json,
    parse_iso8601,
)
from predictionio_tpu.data.storage import (
    Storage,
    StorageError,
    StorageUnavailable,
    get_storage,
)
from predictionio_tpu.obs import (
    get_registry,
    start_runtime_introspection,
)
from predictionio_tpu.resilience import idempotency_key
from predictionio_tpu.resilience.deadline import DeadlineExceeded
from predictionio_tpu.resilience.faults import fault_point
from predictionio_tpu.resilience.policy import CircuitBreaker, CircuitOpenError
from predictionio_tpu.resilience.shared_spill import (
    LeaseDrainer,
    SharedSpillQueue,
    resolve_spill_backend,
)
from predictionio_tpu.resilience.spill import (
    ReplayWorker,
    SpillJournal,
    resolve_spill_dir,
)
from predictionio_tpu.server.http import (
    BaseHandler,
    ThreadingHTTPServer,
    traces_payload,
    param_bool,
)

logger = logging.getLogger(__name__)

__all__ = ["EventServer", "MAX_BATCH_SIZE", "max_batch_size"]

MAX_BATCH_SIZE = 50  # reference: EventServer batch cap


def max_batch_size() -> int:
    """Batch cap for /batch/events.json — reference parity default (50),
    raisable via PIO_MAX_BATCH_SIZE for bulk-load clients (the server's
    group commit and segment tee are O(batch), so a larger cap costs
    memory, not correctness)."""
    raw = os.environ.get("PIO_MAX_BATCH_SIZE")
    if not raw:
        return MAX_BATCH_SIZE
    try:
        return max(1, int(raw))
    except ValueError:
        logger.warning("bad PIO_MAX_BATCH_SIZE=%r; using %d", raw,
                       MAX_BATCH_SIZE)
        return MAX_BATCH_SIZE

# Availability failures (vs client faults): these trip the breaker and
# route to spill/503, never to a 400.
_UNAVAILABLE = (CircuitOpenError, StorageUnavailable, ConnectionError)

# Client-supplied batch idempotency tokens become event-id material —
# keep them filesystem/URL-safe.
_TOKEN_RE = re.compile(r"[A-Za-z0-9._-]+")


class _EventMetrics:
    """Ingest instruments over the shared registry (reference:
    Stats/StatsActor).  ``/stats.json`` and ``/metrics`` are both views
    of these series — there is no second set of counters."""

    def __init__(self, registry=None):
        self.registry = registry or get_registry()
        self.start_time = _dt.datetime.now(_dt.timezone.utc)
        self.requests = self.registry.counter(
            "pio_event_requests_total",
            "Event API requests by HTTP status.", ("status",))
        self.events = self.registry.counter(
            "pio_event_events_total",
            "Accepted events by event name.", ("event",))
        self.latency = self.registry.histogram(
            "pio_event_request_latency_ms",
            "Event API request handling latency.")
        # Ingest high-watermark (ISSUE 10): the newest event_time STORED
        # per app, epoch seconds — the freshness anchor the refresh
        # daemon/`pio status` compare against the serving generation's
        # data watermark.  Spilled (202) events do not advance it until
        # replay lands them: the gauge tracks what is *servable from the
        # store*, not what was accepted.
        self.latest_ts = self.registry.gauge(
            "pio_events_latest_ts",
            "Newest stored event_time per app (ingest high-watermark), "
            "epoch seconds.", ("app",))

    def record(self, status: int, event_name: Optional[str], ms: float) -> None:
        self.requests.inc(status=str(status))
        if event_name:
            self.events.inc(event=event_name)
        self.latency.observe(ms)

    def snapshot(self) -> Dict[str, Any]:
        """The legacy /stats.json shape, read back off the registry
        (quantiles are bucket-interpolated estimates)."""
        return {
            "startTime": self.start_time.isoformat(),
            "statusCounts": {k[0]: int(v)
                             for k, v in self.requests.series().items()},
            "eventCounts": {k[0]: int(v)
                            for k, v in self.events.series().items()},
            "latencyMs": {"p50": self.latency.quantile(0.5),
                          "p95": self.latency.quantile(0.95),
                          "p99": self.latency.quantile(0.99)},
        }


class EventServer:
    """Owns the HTTP server; one instance per process (reference: main)."""

    def __init__(self, storage: Optional[Storage] = None, host: str = "0.0.0.0",
                 port: int = 7070, plugins=None, *,
                 breaker: Optional[CircuitBreaker] = None,
                 spill_dir: Optional[str] = None,
                 spill_backend: Optional[str] = None,
                 replay_interval_s: Optional[float] = None,
                 replay_wait=None,
                 drain_wait=None):
        from predictionio_tpu.server.plugins import PluginManager

        self.storage = storage or get_storage()
        self.host = host
        self.port = port
        self.stats = _EventMetrics()
        # Runtime introspection (compile/device-mem instruments + the
        # memory-sampler thread); jax-free here — the sampler only polls
        # once some other code in the process has imported jax.
        start_runtime_introspection()
        # Positive accessKey cache (5 s TTL): the ingest hot path otherwise
        # pays a metadata SELECT per request.  Key revocation propagates
        # within the TTL; auth FAILURES are never cached.
        self._auth_cache: Dict[str, Tuple[float, Any]] = {}
        self._auth_ttl = 5.0
        # Stale-if-error window: how old a cached key may be and still
        # authenticate while the metadata store is unreachable.
        self._auth_stale_max_s = float(
            os.environ.get("PIO_AUTH_STALE_MAX_S", "300"))
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        # Resilience layer: breaker around every event-store touch, spill
        # journal + replay worker for write outages, Retry-After hint on
        # 202/503 answers.  PIO_BREAKER_* / PIO_SPILL_DIR / PIO_RETRY_AFTER_S
        # document the knobs (README "Resilience").
        self._breaker = breaker or CircuitBreaker(
            "eventdata",
            failure_threshold=int(os.environ.get(
                "PIO_BREAKER_THRESHOLD", "5")),
            recovery_time_s=float(os.environ.get(
                "PIO_BREAKER_RECOVERY_S", "10")),
            failure_types=(StorageUnavailable, ConnectionError))
        self.retry_after_s = int(os.environ.get("PIO_RETRY_AFTER_S", "5"))
        self._shed = self.stats.registry.counter(
            "pio_deadline_shed_total",
            "Requests shed with 504 because their deadline expired.",
            ("server",))
        # Per-app ingest high-watermark cache behind pio_events_latest_ts
        # (seeded from the store on an app's first insert, then advanced
        # in memory — one MAX query per app per process, not per event).
        self._latest_ts: Dict[int, int] = {}
        self._latest_lock = threading.Lock()
        # Columnar segment tee (ISSUE 17): landed writes are appended to
        # per-(app, channel) segment files so warm-refresh delta reads
        # become window-sized columnar slices.  Segments are DERIVED data:
        # a tee failure degrades (counted, /ready-visible), never fails
        # the ingest that already committed to the primary store.
        try:
            self.segments = SegmentStore.open_default()
        except Exception:
            logger.exception("segment store unavailable — tee disabled")
            self.segments = None
        self._segment_degraded = False
        self._segment_errors = self.stats.registry.counter(
            "pio_segment_tee_errors_total",
            "Segment tee failures (ingest unaffected).", ("kind",))
        # Write-path admission (ISSUE 17): one shared budget over events
        # queued anywhere on the write plane (local journal + shared
        # backplane + in-flight requests).  When the backlog exceeds it,
        # new writes answer 429 + Retry-After instead of growing the
        # spill without bound — bounded memory/disk beats a stalled
        # /events.json.  0 disables (default).
        self.ingest_budget = int(os.environ.get(
            "PIO_INGEST_QUEUE_BUDGET", "0") or 0)
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        self._overload = self.stats.registry.counter(
            "pio_ingest_overload_total",
            "Writes rejected 429 by the ingest admission budget.")
        self.spill: Optional[SpillJournal] = None
        self._replay: Optional[ReplayWorker] = None
        self.shared_spill: Optional[SharedSpillQueue] = None
        self._lease_drainer: Optional[LeaseDrainer] = None
        replay_interval = (replay_interval_s if replay_interval_s is not None
                           else float(os.environ.get(
                               "PIO_SPILL_REPLAY_INTERVAL_S", "0.5")))
        # Shared spill backplane (ISSUE 15): failed writes enqueue into
        # the storage-backed fleet queue; this instance also runs a lease
        # drainer so ANY instance (including a freshly restarted one) can
        # replay a crashed peer's batch.  The local journal below stays
        # as the last-resort spill-of-the-spill — when storage itself is
        # the outage, the shared enqueue fails too.
        try:
            ev_type = self.storage.config.source_for("EVENTDATA").type
        except Exception:
            ev_type = None
        self.spill_backend = resolve_spill_backend(spill_backend, ev_type)
        if self.spill_backend == "shared":
            try:
                self.storage.get_spill_queues()  # probe support
                self.shared_spill = SharedSpillQueue(self.storage)
            except Exception as e:
                logger.warning("shared spill backend unavailable (%s) — "
                               "falling back to the local journal", e)
                self.spill_backend = "local"
            else:
                # Owner must be globally unique — ack/dead_letter use it
                # to detect lease steals, and host:port collides when
                # port=0 is not yet resolved or two servers share a pid.
                self._lease_drainer = LeaseDrainer(
                    self.shared_spill, self._replay_insert,
                    owner=f"{host}:{os.getpid()}-{uuid.uuid4().hex[:6]}",
                    interval_s=replay_interval,
                    transient_types=_UNAVAILABLE + (OSError,),
                    wait=drain_wait)
                self._lease_drainer.start()
        spill_path = resolve_spill_dir(
            spill_dir, getattr(self.storage.config, "home", None))
        if spill_path is not None:
            self.spill = SpillJournal(spill_path)
            self._replay = ReplayWorker(
                self.spill, self._replay_insert,
                interval_s=replay_interval,
                transient_types=_UNAVAILABLE + (OSError,),
                # Injectable tick wait (tests drive replay with a fake
                # clock / direct drain instead of wall-clock polling).
                wait=replay_wait)
            self._replay.start()
        # Server plugin seam (reference: EventServerPlugin, SURVEY §5.1):
        # env-discovered request instrumentation, active on the python
        # HTTP path AND the native fallback path.  Started LAST so
        # plugins see a fully constructed server.
        self.plugins = (plugins if plugins is not None
                        else PluginManager.from_env("PIO_EVENTSERVER_PLUGINS"))
        self.plugins.start(self)

    # -- spill / replay -----------------------------------------------------

    def _spill_events(self, events_json: List[Any], app_id: int,
                      channel_id: Optional[int], token: str,
                      tokens: Optional[List[str]] = None) -> Optional[str]:
        """Durably queue one failed write (single event or whole batch)
        under the SAME idempotency token the write was issued with — if
        the "outage" was really a lost reply, the backend committed and
        replay must dedup against it, not re-insert.

        Shared backend first (the fleet queue: any instance's drainer
        replays it, a crash here strands nothing); the local journal is
        the fallback for when storage itself is the outage — the shared
        enqueue rides the same storage that just failed the write, so it
        usually fails too and the record degrades to the local file.
        Returns the token, or None when no home accepted it (caller
        503s)."""
        # Breaker-open = storage is KNOWN down, and the shared queue
        # rides that same storage: skip the doomed enqueue (which would
        # stack one RPC timeout onto every degraded request) and go
        # straight to the local journal; the drainer replays it into the
        # shared path's store once the breaker recloses.
        if self.shared_spill is not None and self._breaker.state != "open":
            try:
                return self.shared_spill.append(events_json, app_id,
                                                channel_id, token=token,
                                                tokens=tokens)
            except Exception:
                logger.warning("shared spill enqueue failed — degrading "
                               "to the local journal", exc_info=True)
        if self.spill is None:
            return None
        try:
            return self.spill.append(events_json, app_id, channel_id,
                                     token=token, tokens=tokens)
        except (OSError, ValueError):  # ValueError: journal closed itself
            logger.exception("spill journal write failed")
            return None

    def _replay_insert(self, record: Dict[str, Any]) -> None:
        """One journal record → storage, through the breaker (this worker
        is the half-open prober), re-issuing the ORIGINAL write: same
        token, same event set, so a dedup-capable backend answers from
        its window if the original actually committed.

        A record carrying per-item sub-``tokens`` (a bulk-ingest batch,
        ISSUE 17) replays through ``create_batch``: event ids derive from
        the sub-tokens, so a batch the crashed attempt PARTIALLY landed
        dedups row-by-row — each event lands exactly once even when the
        original commit split down the middle."""
        evs = [event_from_json(e) for e in record["events"]]
        events = self.storage.get_events()
        tokens = record.get("tokens")
        with idempotency_key(record["token"]):
            if tokens is not None:
                self._breaker.call(events.create_batch, evs,
                                   record["appId"], record.get("channelId"),
                                   tokens=tokens)
            else:
                self._breaker.call(events.insert_batch, evs,
                                   record["appId"], record.get("channelId"))
        # Replayed events are now servable — advance the watermark they
        # could not advance while journaled.
        self._note_ingest(record["appId"], evs)
        self._segment_tee(record["appId"], record.get("channelId"), evs)

    # -- segment tee / write-plane admission (ISSUE 17) ---------------------

    def _segment_tee(self, app_id: int, channel_id, evs) -> None:
        """Append LANDED events to the columnar segment store.  Disk
        pressure flips the degraded flag (and stops segment writes — the
        journal-spill/primary path keeps ingesting); any other failure is
        counted and swallowed: a derived file must never fail an ingest
        that already committed."""
        if self.segments is None or not evs:
            return
        try:
            self.segments.append_events(app_id, channel_id, evs)
            if self._segment_degraded:
                logger.info("segment tee recovered (disk pressure cleared)")
            self._segment_degraded = False
        except SegmentDiskPressure as e:
            if not self._segment_degraded:
                logger.warning("segment tee degraded: %s — ingest "
                               "continues without segment coverage", e)
            self._segment_degraded = True
            self._segment_errors.inc(kind="disk_pressure")
        except Exception:
            logger.exception("segment tee failed (ingest unaffected)")
            self._segment_errors.inc(kind="error")

    def _backlog_depth(self) -> int:
        depth = self.spill.depth() if self.spill is not None else 0
        if self.shared_spill is not None:
            # cached: admission must never pay a storage RPC per request
            depth += self.shared_spill.cached_depth()
        return depth

    def _admit(self, n: int) -> Optional[Tuple[int, Any]]:
        """Reserve ``n`` events of write-plane budget, or answer the 429
        (the transport adds Retry-After).  Pair with :meth:`_release` in
        a finally.  Returns None on admission."""
        fault_point("ingest.admit")
        if self.ingest_budget <= 0:
            with self._inflight_lock:
                self._inflight += n
            return None
        depth = self._backlog_depth()
        with self._inflight_lock:
            if depth + self._inflight + n > self.ingest_budget:
                self._overload.inc()
                return 429, {"message":
                             "Ingest backlog exceeds "
                             f"PIO_INGEST_QUEUE_BUDGET={self.ingest_budget} "
                             f"({depth} queued, {self._inflight} in "
                             "flight); retry later."}
            self._inflight += n
        return None

    def _release(self, n: int) -> None:
        with self._inflight_lock:
            self._inflight -= n

    def _note_ingest(self, app_id: int, evs) -> None:
        """Advance the per-app ingest high-watermark gauge after events
        LANDED in the store.  First touch of an app seeds the floor from
        the backend's own MAX so a restarted server reports the true
        store-wide watermark, not just this process's ingest.

        Also the feedback-join hook (ISSUE 11): a landed buy/rate event
        echoing a served recommendation's id (``properties.pioServeId``)
        joins back to the served item set → online hit-rate per model
        generation.  Joining here — not at accept time — means spilled
        (202) events count only when replay lands them, same contract as
        the watermark."""
        from predictionio_tpu.data.storage.base import epoch_us
        from predictionio_tpu.obs.quality import note_feedback_events

        try:
            note_feedback_events(evs)
        except Exception:
            logger.exception("feedback join failed (ingest unaffected)")

        newest = None
        for ev in evs:
            us = epoch_us(ev.event_time)
            if us is not None and (newest is None or us > newest):
                newest = us
        if newest is None:
            return
        with self._latest_lock:
            cur = self._latest_ts.get(app_id)
            if cur is None:
                # The gauge is APP-level; the store's MAX is per channel,
                # so the seed must cover the default channel AND every
                # named channel — else a restart under channel traffic
                # would republish a regressed watermark.
                try:
                    events = self.storage.get_events()
                    maxes = [epoch_us(events.latest_event_time(app_id))]
                    for ch in self.storage.get_channels() \
                            .get_by_app_id(app_id):
                        maxes.append(epoch_us(
                            events.latest_event_time(app_id, ch.id)))
                    known = [m for m in maxes if m is not None]
                    cur = max(known) if known else newest
                except Exception:
                    # Seeding is best-effort; the in-process max is still
                    # a valid (conservative) watermark.
                    cur = newest
            val = max(cur, newest)
            self._latest_ts[app_id] = val
            # set under the lock: two concurrent ingests must publish in
            # watermark order, never let a smaller max land last
            self.stats.latest_ts.set(val / 1e6, app=str(app_id))

    # -- request-handling core (transport-independent, used by tests) ------

    def _auth(self, params: Dict[str, List[str]], headers) -> Tuple[Optional[Any], Optional[int]]:
        """Resolve accessKey → AccessKey row; (None, status) on failure."""
        key = None
        if "accessKey" in params:
            key = params["accessKey"][0]
        else:
            auth = headers.get("Authorization", "") if headers else ""
            if auth.startswith("Basic "):
                try:
                    key = base64.b64decode(auth[6:]).decode().split(":")[0]
                except Exception:
                    key = None
        if not key:
            return None, 401
        now = time.monotonic()
        hit = self._auth_cache.get(key)
        if hit is not None and now - hit[0] < self._auth_ttl:
            return hit[1], None
        try:
            row = self.storage.get_access_keys().get(key)
        except _UNAVAILABLE:
            if hit is not None and now - hit[0] < self._auth_stale_max_s:
                # Stale-if-error: metadata store down but this key was
                # RECENTLY valid (bounded by PIO_AUTH_STALE_MAX_S so a
                # long-revoked key cannot ride every future blip) —
                # degraded ingest (spill) beats turning a metadata
                # outage into rejected events.
                return hit[1], None
            raise
        if row is None:
            return None, 401
        self._auth_cache[key] = (now, row)
        return row, None

    def _resolve_channel(self, app_id: int, params) -> Tuple[Optional[int], Optional[str]]:
        if "channel" not in params:
            return None, None
        name = params["channel"][0]
        chans = self.storage.get_channels().get_by_app_id(app_id)
        match = next((c for c in chans if c.name == name), None)
        if match is None:
            return None, f"Invalid channel: {name}"
        return match.id, None

    def handle(self, method: str, path: str, params: Dict[str, List[str]],
               body: bytes, headers=None) -> Tuple[int, Any]:
        """Dispatch one request; returns (status, JSON-able payload)."""
        try:
            fault_point("http.event")
            return self._handle(method, path, params, body, headers)
        except DeadlineExceeded as e:
            self._shed.inc(server="event")
            return 504, {"message": str(e)}
        except _UNAVAILABLE as e:
            # Availability failure, NOT a client fault: 503 + Retry-After
            # (the transport adds the header) so well-behaved clients back
            # off instead of hammering a dying backend.
            return 503, {"message": f"Storage temporarily unavailable: {e}"}
        except (EventValidationError, StorageError) as e:
            return 400, {"message": str(e)}
        except (json.JSONDecodeError, UnicodeDecodeError) as e:
            # bad JSON or a bad-UTF-8 body — a client fault, per-item 400
            # (NOT bare ValueError: that would mislabel e.g. limit=abc)
            return 400, {"message": f"Invalid JSON: {e}"}
        except Exception:
            logger.exception("Event server internal error")
            return 500, {"message": "Internal server error."}

    def _insert_one(self, ev, key_row, channel_id) -> Tuple[int, Any]:
        """Single-event ingest through the breaker; degrades to the spill
        journal (202 + token) when the store is unavailable.  The token
        is pinned BEFORE the attempt so the spilled record replays the
        identical write (dedup'd if the original secretly committed).
        What spills is event_to_json(ev) — the PARSED event, with
        eventTime/creationTime frozen at ingest — not the raw client
        body, so replay after a long outage cannot re-stamp times."""
        events = self.storage.get_events()
        token = uuid.uuid4().hex
        denied = self._admit(1)
        if denied is not None:
            return denied
        try:
            with idempotency_key(token):
                event_id = self._breaker.call(
                    events.insert, ev, key_row.app_id, channel_id)
            self._note_ingest(key_row.app_id, [ev])
            self._segment_tee(key_row.app_id, channel_id, [ev])
            return 201, {"eventId": event_id}
        except _UNAVAILABLE:
            spilled = self._spill_events([event_to_json(ev)],
                                         key_row.app_id, channel_id,
                                         token)
            if spilled is None:
                raise  # no journal → handle() maps to 503
            return 202, {"message": "Storage unavailable; event journaled "
                                    "for replay.", "token": spilled}
        finally:
            self._release(1)

    def _handle(self, method, path, params, body, headers) -> Tuple[int, Any]:
        if path == "/" and method == "GET":
            return 200, {"status": "alive"}
        if path == "/ready" and method == "GET":
            # Readiness (vs the "/" liveness ping): storage reachable —
            # breaker closed.  503 tells the load balancer to rotate this
            # instance out while it probes recovery.
            st = self._breaker.state
            disk_degraded = self._segment_degraded or (
                self.segments is not None and self.segments.disk_pressure())
            status_word = ("unavailable" if st != "closed"
                           else "degraded" if disk_degraded else "ready")
            body_ = {"status": status_word,
                     "breaker": st,
                     "spillBackend": self.spill_backend,
                     "spillQueueDepth": self.spill.depth() if self.spill
                     else 0,
                     "ingestInflight": self._inflight,
                     "ingestBudget": self.ingest_budget,
                     "diskDegraded": disk_degraded}
            if self.shared_spill is not None:
                # cached: a readiness probe must never block on a
                # storage RPC while storage is the thing that is down
                body_["sharedSpillDepth"] = \
                    self.shared_spill.cached_depth()
            if self.segments is not None:
                segs = self.segments.status()
                body_["segmentDirs"] = len(segs)
                body_["segmentCount"] = sum(s["segments"] for s in segs)
            # Disk-degraded is still READY (200): the primary store and
            # the spill journal keep accepting — only segment coverage
            # stopped growing.  Operators see it; LBs keep routing.
            return (200 if st == "closed" else 503), body_
        if path == "/stats.json" and method == "GET":
            return 200, self.stats.snapshot()
        if path == "/metrics" and method == "GET":
            # THE process-wide exposition: every subsystem's instruments
            # (ingest, serving, training, plugins) in one scrape.
            # ?exemplars=1 opts into the OpenMetrics exemplar suffixes.
            return 200, self.stats.registry.render(
                exemplars=param_bool(params, "exemplars"))

        key_row, err = self._auth(params, headers)
        if err:
            return err, {"message": "Invalid accessKey."}

        if path == "/traces.json" and method == "GET":
            # Behind accessKey, unlike the aggregate /metrics//stats.json
            # views: traces carry PER-REQUEST paths/timings/request ids.
            return 200, traces_payload(params)
        channel_id, cerr = self._resolve_channel(key_row.app_id, params)
        if cerr:
            return 400, {"message": cerr}
        events = self.storage.get_events()

        if path == "/events.json" and method == "POST":
            obj = json.loads(body.decode("utf-8"))
            ev = event_from_json(obj)
            if key_row.events and ev.event not in key_row.events:
                return 403, {"message": f"Event {ev.event!r} not allowed by this key."}
            return self._insert_one(ev, key_row, channel_id)

        if path == "/batch/events.json" and method == "POST":
            arr = self._parse_batch_body(body, headers)
            if not isinstance(arr, list):
                return 400, {"message": "Batch body must be a JSON array "
                                        "or NDJSON lines."}
            cap = max_batch_size()
            if len(arr) > cap:
                return 400, {"message":
                             f"Batch size exceeds the limit of {cap}."}
            # Client-supplied batch idempotency token (?batchToken=):
            # sub-tokens derive deterministically from it, so a client
            # RETRY of the whole batch produces the same event ids and
            # dedups row-by-row — exactly-once from the SDK on down.
            bt = params.get("batchToken", [None])[0]
            if bt is not None and (len(bt) > 120
                                   or not _TOKEN_RE.fullmatch(bt)):
                return 400, {"message": "batchToken must be 1-120 chars "
                                        "of [A-Za-z0-9._-]."}
            # Validate per item, then ONE group-committed insert for the
            # valid ones — per-item inserts each paid a transaction commit
            # (48 µs apiece measured), capping batch ingest at ~10k ev/s.
            folded = self._fold_insert(key_row, channel_id, arr,
                                       batch_token=bt)
            if folded and all(s == 429 for s, _, _ in folded):
                # whole batch refused at admission: answer at the HTTP
                # layer so the transport attaches Retry-After
                return 429, folded[0][1]
            return 200, [{"status": s, **p} for s, p, _ in folded]

        if path == "/events.json" and method == "GET":
            q = {}
            if "startTime" in params:
                q["start_time"] = parse_iso8601(params["startTime"][0])
            if "untilTime" in params:
                q["until_time"] = parse_iso8601(params["untilTime"][0])
            for http_name, kw in (("entityType", "entity_type"),
                                  ("entityId", "entity_id"),
                                  ("targetEntityType", "target_entity_type"),
                                  ("targetEntityId", "target_entity_id")):
                if http_name in params:
                    q[kw] = params[http_name][0]
            if "event" in params:
                q["event_names"] = params["event"]
            limit = int(params.get("limit", ["20"])[0])
            if limit < -1:
                return 400, {"message": "limit must be >= -1."}
            q["limit"] = None if limit == -1 else limit
            q["reversed"] = params.get("reversed", ["false"])[0].lower() == "true"
            found = self._breaker.call(
                lambda: list(events.find(key_row.app_id, channel_id, **q)))
            # Deliberate divergence from upstream (documented in
            # PARITY.md): upstream's event server answers an empty list
            # query with 404 {"message":"Not Found"}; here an empty match
            # is a valid result — 200 [].  Only the single-event
            # GET /events/<id> 404s.
            return 200, [event_to_json(e) for e in found]

        if path.startswith("/webhooks/") and method == "POST":
            # Reference: webhooks routes (SURVEY.md §2.1) — JSON connectors
            # at /webhooks/<name>.json, form connectors at /webhooks/<name>.
            from urllib.parse import parse_qsl

            from predictionio_tpu.data.webhooks import (
                ConnectorError,
                get_connector,
            )

            name = path[len("/webhooks/"):]
            is_json = name.endswith(".json")
            if is_json:
                name = name[:-len(".json")]
            try:
                connector = get_connector(name)
                if is_json:
                    payload = json.loads(body.decode("utf-8"))
                else:
                    payload = dict(parse_qsl(body.decode("utf-8")))
                # Burst coalescing (ISSUE 17): one provider delivery may
                # carry N messages (segment.io batches) — ALL of them ride
                # the batched-ingest fold as one group commit, never a
                # per-row create_event loop.  Malformed messages inside a
                # burst come back as Exception placeholders → per-item
                # 400, the rest of the delivery lands.
                items = connector.to_events_json(payload)
            except ConnectorError as e:
                return 400, {"message": str(e)}
            if not items:
                return 200, []
            folded = self._fold_insert(key_row, channel_id, items)
            if len(folded) == 1:
                # single-event deliveries keep the historical one-object
                # response shape (201 {"eventId": ...})
                s, p, _ = folded[0]
                return s, p
            return 200, [{"status": s, **p} for s, p, _ in folded]

        if path.startswith("/events/") and path.endswith(".json"):
            event_id = path[len("/events/"):-len(".json")]
            if method == "GET":
                ev = self._breaker.call(
                    events.get, event_id, key_row.app_id, channel_id)
                if ev is None:
                    return 404, {"message": "Not Found"}
                return 200, event_to_json(ev)
            if method == "DELETE":
                ok = self._breaker.call(
                    events.delete, event_id, key_row.app_id, channel_id)
                return (200, {"message": "Found"}) if ok else (404, {"message": "Not Found"})

        return 404, {"message": "Not Found"}

    # -- HTTP plumbing ------------------------------------------------------

    def _make_handler(server_self):
        class Handler(BaseHandler):
            server_log_name = "event-server"
            trace_server_name = "event"
            shed_pre_handle = True  # shed BEFORE auth/storage

            def pio_handle(self, method, path, params, body):
                return server_self.handle(method, path, params, body,
                                          self.headers)

            def pio_shed(self):
                server_self._shed.inc(server="event")

            def pio_retry_after_s(self):
                return server_self.retry_after_s

            def pio_on_complete(self, method, path, status, ms, body,
                                params):
                name = None
                if method == "POST" and path == "/events.json" \
                        and status == 201:
                    try:
                        name = json.loads(body).get("event")
                    except Exception:
                        name = None
                server_self.stats.record(status, name, ms)
                return server_self.plugins.on_request(
                    f"{method} {path}", status, ms) \
                    if server_self.plugins else None

            def do_GET(self):  # noqa: N802
                self.dispatch("GET")

            def do_POST(self):  # noqa: N802
                self.dispatch("POST")

            def do_DELETE(self):  # noqa: N802
                self.dispatch("DELETE")

        return Handler

    # -- native frontend entry ---------------------------------------------

    def native_fallback_batch(self, method: str, path_with_qs: str,
                              bodies: List[bytes]):
        """Batch entry for the C++ frontend: a run of same-route requests.

        A run of concurrent single-event POSTs becomes ONE
        group-committed ``insert_batch`` — the per-request transaction
        commit (48 µs measured) was the single-event ingest ceiling.
        Auth is query-param accessKey only (the native layer does not
        forward headers, so basic-auth clients must use the python
        frontend).
        """
        t0 = time.perf_counter()
        parsed = urlparse(path_with_qs)
        params = parse_qs(parsed.query)
        path = parsed.path
        if method == "POST" and path == "/events.json" and len(bodies) > 1:
            outs_named = self._ingest_group(params, bodies)
        else:
            outs_named = []
            for b in bodies:
                # handle() is total today (catches decode errors → 400,
                # everything else → 500); this guard is belt-and-suspenders
                # for the answered-every-item invariant — a future handle()
                # regression must not 500 peers whose inserts already
                # committed (that invites client-retry duplicates).
                try:
                    status, payload = self.handle(method, path, params, b)
                except Exception:
                    logger.exception("native fallback item failed")
                    status, payload = 500, {"message":
                                            "Internal server error."}
                name = None
                if method == "POST" and path == "/events.json" \
                        and status == 201:
                    try:  # single body, cold path — one extra parse is fine
                        name = json.loads(b).get("event")
                    except Exception:
                        name = None
                outs_named.append((status, payload, name))
        dt = (time.perf_counter() - t0) * 1e3 / max(len(bodies), 1)
        for status, _, name in outs_named:
            self.stats.record(status, name, dt)
        if method == "GET" and path == "/metrics":
            # Explicit Prometheus exposition content type on the wire —
            # the native layer would otherwise label the text plain UTF-8.
            return [(s, p, "text/plain; version=0.0.4")
                    if isinstance(p, str) else (s, p)
                    for s, p, _ in outs_named]
        return [(s, p) for s, p, _ in outs_named]

    def _ingest_group(self, params, bodies: List[bytes]):
        """Decode each body, then the shared validate+group-insert fold."""
        key_row, err = self._auth(params, None)
        if err:
            return [(err, {"message": "Invalid accessKey."}, None)] \
                * len(bodies)
        channel_id, cerr = self._resolve_channel(key_row.app_id, params)
        if cerr:
            return [(400, {"message": cerr}, None)] * len(bodies)
        items: List[Any] = []
        for body in bodies:
            try:
                items.append(json.loads(body.decode("utf-8")))
            except ValueError as e:  # JSONDecodeError + UnicodeDecodeError
                items.append(ValueError(f"Invalid JSON: {e}"))
        return self._fold_insert(key_row, channel_id, items)

    # (fold results carry the event name so the stats recorder does not
    # re-parse every body on the hot grouped-ingest path)

    @staticmethod
    def _parse_batch_body(body: bytes, headers) -> Any:
        """Decode a /batch/events.json body: a JSON array, or NDJSON —
        one event object per line (Content-Type ``application/x-ndjson``
        or any body whose first non-space byte is not ``[``).  A
        malformed NDJSON line becomes an Exception placeholder so
        ``_fold_insert`` answers it 400 PER-ITEM: one bad line never
        fails its cohort.  (A malformed JSON *array* is still a
        whole-request 400 — there are no item boundaries to salvage.)"""
        ctype = (headers.get("Content-Type", "") if headers else "") or ""
        text = body.decode("utf-8")
        ndjson = "ndjson" in ctype.lower() or "jsonlines" in ctype.lower()
        if not ndjson:
            head = text.lstrip()[:1]
            ndjson = bool(head) and head != "["
        if not ndjson:
            return json.loads(text)
        items: List[Any] = []
        for n, line in enumerate(text.splitlines(), start=1):
            if not line.strip():
                continue
            try:
                items.append(json.loads(line))
            except ValueError as e:
                items.append(ValueError(f"Invalid JSON on line {n}: {e}"))
        return items

    def _fold_insert(self, key_row, channel_id, items: List[Any],
                     batch_token: Optional[str] = None):
        """THE batched-ingest fold, shared by /batch/events.json, the
        webhook burst path and the native frontend's grouped singles:
        per-item validation against the key's event allowlist, then ONE
        group-committed ``create_batch`` for the valid events.  ``items``
        are parsed event JSON objects; an Exception instance stands for a
        body that failed to decode (reported per-item as 400).  Returns
        ``(status, payload, event_name)`` triples.

        Exactly-once (ISSUE 17): the whole batch is covered by ONE
        idempotency token (whole-call dedup at a hosted backend) plus a
        per-item sub-token each — event ids derive from the sub-tokens,
        so a replay after a crashed reply or a partial landing dedups
        row-by-row instead of all-or-nothing.  On storage outage the
        batch spills as ONE journal record carrying both token layers."""
        denied = self._admit(len(items))
        if denied is not None:
            st, payload = denied
            return [(st, payload, None)] * len(items)
        try:
            fault_point("ingest.batch")
            events = self.storage.get_events()
            outs: List[Any] = [None] * len(items)
            valid: List[Tuple[int, Any]] = []
            for i, item in enumerate(items):
                if isinstance(item, Exception):
                    outs[i] = (400, {"message": str(item)}, None)
                    continue
                try:
                    ev = event_from_json(item)
                    if key_row.events and ev.event not in key_row.events:
                        outs[i] = (403, {"message":
                                         f"Event {ev.event!r} not allowed "
                                         "by this key."}, None)
                        continue
                    valid.append((i, ev))
                except (EventValidationError, StorageError) as e:
                    outs[i] = (400, {"message": str(e)}, None)
                except Exception:
                    logger.exception("ingest item failed")
                    outs[i] = (500, {"message": "Internal server error."},
                               None)
            if valid:
                if batch_token is not None:
                    # Deterministic sub-tokens from the CLIENT's token,
                    # keyed by item position: a client retry of the same
                    # batch re-derives the same event ids → per-item
                    # dedup even when the first reply was lost.
                    token = batch_token
                    subtoks = [f"{batch_token}.{i}" for i, _ in valid]
                else:
                    token = uuid.uuid4().hex  # pinned BEFORE the attempt
                    subtoks = [uuid.uuid4().hex for _ in valid]
                try:
                    with idempotency_key(token):
                        ids = self._breaker.call(
                            events.create_batch, [ev for _, ev in valid],
                            key_row.app_id, channel_id, tokens=subtoks)
                    for (i, ev), eid in zip(valid, ids):
                        outs[i] = (201, {"eventId": eid}, ev.event)
                    self._note_ingest(key_row.app_id,
                                      [ev for _, ev in valid])
                    self._segment_tee(key_row.app_id, channel_id,
                                      [ev for _, ev in valid])
                except _UNAVAILABLE as e:
                    # Mid-batch storage outage: EVERY valid item gets an
                    # explicit answer — spilled (202 + the batch's token)
                    # when the journal is on, 503 when it is not.  Never
                    # a partial silent drop.  The whole batch journals as
                    # ONE record under the token it was attempted with
                    # PLUS its per-item sub-tokens, so the replay
                    # re-issues the identical create_batch and any rows
                    # the crashed attempt already committed dedup away.
                    spilled = self._spill_events(
                        [event_to_json(ev) for _, ev in valid],
                        key_row.app_id, channel_id, token, tokens=subtoks)
                    for i, _ in valid:
                        outs[i] = ((202, {"message":
                                          "Storage unavailable; event "
                                          "journaled for replay.",
                                          "token": spilled}, None)
                                   if spilled is not None else
                                   (503, {"message":
                                          "Storage temporarily "
                                          f"unavailable: {e}"}, None))
                except StorageError as e:
                    for i, _ in valid:
                        outs[i] = (400, {"message": str(e)}, None)
            return outs
        finally:
            self._release(len(items))

    def start(self, block: bool = False) -> None:
        self._httpd = ThreadingHTTPServer((self.host, self.port),
                                          self._make_handler())
        self.port = self._httpd.server_address[1]  # resolve port 0
        logger.info("Event Server listening on %s:%d", self.host, self.port)
        if block:
            self._httpd.serve_forever()
        else:
            self._thread = threading.Thread(target=self._httpd.serve_forever,
                                            daemon=True)
            self._thread.start()

    def stop(self) -> None:
        if self._httpd:
            # shutdown() stops accepting; server_close() joins in-flight
            # handler threads (socketserver block_on_close), so responses
            # already being written complete before we tear down.
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._lease_drainer is not None:
            self._lease_drainer.stop()
        if self._replay is not None:
            self._replay.stop()
        elif self.spill is not None:
            self.spill.close()
        if self.segments is not None:
            try:
                # seal open windows so a clean shutdown leaves the full
                # ingest history claimable by the next refresh read
                self.segments.seal_all()
            except Exception:
                logger.exception("segment seal on shutdown failed")
        self.plugins.stop()

    def drain(self) -> None:
        """Graceful SIGTERM/SIGINT path: stop accepting, finish in-flight
        requests, flush the spill journal to disk (it replays on next
        boot or when storage recovers)."""
        shared = (self.shared_spill.cached_depth()
                  if self.shared_spill is not None else None)
        logger.info("Event server draining (local spill depth=%d, shared "
                    "queue depth=%s)",
                    self.spill.depth() if self.spill else 0, shared)
        self.stop()
