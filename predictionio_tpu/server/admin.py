"""Admin API server — app/key management + runtime ops over REST.

Reference: tools/.../tools/admin/ (SURVEY.md §2.1 Tools/CLI row) — the
experimental `pio adminserver` (default :7071) exposing the console's app
commands as JSON endpoints:

- ``GET  /``                     → status
- ``GET  /v1/cmd/app``           → list apps (with access keys)
- ``POST /v1/cmd/app``           → create app  ``{"name": ..., "description"?}``
- ``DELETE /v1/cmd/app/<name>``  → delete app and all its data
- ``DELETE /v1/cmd/app/<name>/data`` → wipe event data only

Rebuild additions (runtime introspection):

- ``POST /admin/profile?duration_ms=`` → arm a bounded on-demand
  ``jax.profiler`` capture; answers the artifact path immediately, 409
  while a capture runs, and a clear **501** when the platform cannot
  capture (instead of crashing).  ``GET /admin/profile`` → status.
- ``GET /timeline.json`` → the per-step pipeline timeline ring
  (``?format=chrome`` for chrome://tracing).
"""

from __future__ import annotations

import json
import logging
import threading
from typing import Dict, List, Optional, Tuple

from predictionio_tpu.data.storage import AccessKey, App, Storage, get_storage
from predictionio_tpu.obs.profiler import (
    ProfilerBusy,
    ProfilerUnavailable,
    get_profiler,
)
from predictionio_tpu.server.http import (
    BaseHandler,
    ThreadingHTTPServer,
    timeline_payload,
)
from predictionio_tpu.version import __version__

logger = logging.getLogger(__name__)

__all__ = ["AdminServer"]


class AdminServer:
    # Binds loopback by default: this surface lists every access key and
    # performs unconfirmed destructive deletes (the reference's experimental
    # adminserver is localhost-only too).  Exposing it externally requires
    # an explicit --ip.
    def __init__(self, storage: Optional[Storage] = None, host: str = "127.0.0.1",
                 port: int = 7071):
        self.storage = storage or get_storage()
        self.host = host
        self.port = port
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def handle(self, method: str, path: str, body: bytes,
               params: Optional[Dict[str, List[str]]] = None
               ) -> tuple:
        """(status, payload) or (status, bytes, ctype) for the binary
        artifact download."""
        params = params or {}
        try:
            if path == "/" and method == "GET":
                return 200, {"status": "alive", "version": __version__}
            if path == "/admin/profile":
                return self._handle_profile(method, params)
            if path == "/admin/profile/artifact" and method == "GET":
                # Download the finished capture as a tar.gz (ISSUE 9
                # satellite): remote/fleet operators no longer need box
                # access to pick up the server-local artifact dir.
                try:
                    art = get_profiler().artifact()
                except ProfilerBusy as e:
                    return 409, {"message": str(e)}
                if art is None:
                    return 404, {"message": "no finished profiler capture "
                                            "in this process"}
                data, filename = art
                return 200, data, "application/gzip", {
                    "Content-Disposition":
                        f'attachment; filename="{filename}"'}
            if path == "/timeline.json" and method == "GET":
                return 200, timeline_payload(params)
            if path == "/v1/cmd/app" and method == "GET":
                apps = self.storage.get_apps().get_all()
                keys = self.storage.get_access_keys()
                return 200, [
                    {"name": a.name, "id": a.id,
                     "accessKeys": [k.key for k in keys.get_by_app_id(a.id)]}
                    for a in apps
                ]
            if path == "/v1/cmd/app" and method == "POST":
                obj = json.loads(body.decode() or "{}")
                name = obj.get("name")
                if not name:
                    return 400, {"message": "name is required."}
                app_id = self.storage.get_apps().insert(
                    App(id=None, name=name, description=obj.get("description")))
                if app_id is None:
                    return 409, {"message": f"App {name!r} already exists."}
                self.storage.get_events().init(app_id)
                key = self.storage.get_access_keys().insert(
                    AccessKey(key="", app_id=app_id))
                return 201, {"name": name, "id": app_id, "accessKey": key}
            if path.startswith("/v1/cmd/app/") and method == "DELETE":
                rest = path[len("/v1/cmd/app/"):]
                wipe_only = rest.endswith("/data")
                name = rest[:-len("/data")] if wipe_only else rest
                app = self.storage.get_apps().get_by_name(name)
                if app is None:
                    return 404, {"message": f"App {name!r} does not exist."}
                events = self.storage.get_events()
                if wipe_only:
                    events.remove(app.id)
                    events.init(app.id)
                    return 200, {"message": f"Data of app {name!r} deleted."}
                for ch in self.storage.get_channels().get_by_app_id(app.id):
                    events.remove(app.id, ch.id)
                    self.storage.get_channels().delete(ch.id)
                events.remove(app.id)
                for k in self.storage.get_access_keys().get_by_app_id(app.id):
                    self.storage.get_access_keys().delete(k.key)
                self.storage.get_apps().delete(app.id)
                return 200, {"message": f"App {name!r} deleted."}
            return 404, {"message": "Not Found"}
        except json.JSONDecodeError as e:
            return 400, {"message": f"Invalid JSON: {e}"}
        except Exception:
            logger.exception("admin server error")
            return 500, {"message": "Internal server error."}

    def _handle_profile(self, method: str,
                        params: Dict[str, List[str]]) -> Tuple[int, dict]:
        """On-demand profiler capture (ISSUE 3 tentpole part 3)."""
        profiler = get_profiler()
        if method == "GET":
            return 200, profiler.status()
        if method != "POST":
            return 404, {"message": "Not Found"}
        raw = params.get("duration_ms", ["2000"])[0]
        try:
            duration_ms = float(raw)
            if not duration_ms > 0:
                raise ValueError
        except ValueError:
            return 400, {"message": f"bad duration_ms: {raw!r}"}
        out_dir = params.get("out", [None])[0]
        try:
            info = profiler.start(duration_ms, out_dir)
        except ProfilerBusy as e:
            return 409, {"message": str(e)}
        except ProfilerUnavailable as e:
            # The clear degrade: this platform/process cannot capture
            # (no jax, no profiler plugin, remote-tunnel backend) — a
            # 501 the caller can act on, never a crash/500.
            return 501, {"message": f"profiler capture unavailable: {e}"}
        return 200, {"status": "profiling", **info}

    def _make_handler(server_self):
        class Handler(BaseHandler):
            server_log_name = "admin"
            trace_server_name = "admin"

            def pio_handle(self, method, path, params, body):
                return server_self.handle(method, path, body, params)

            def do_GET(self):  # noqa: N802
                self.dispatch("GET")

            def do_POST(self):  # noqa: N802
                self.dispatch("POST")

            def do_DELETE(self):  # noqa: N802
                self.dispatch("DELETE")

        return Handler

    def start(self, block: bool = False) -> None:
        self._httpd = ThreadingHTTPServer((self.host, self.port),
                                          self._make_handler())
        self.port = self._httpd.server_address[1]
        logger.info("Admin server listening on %s:%d", self.host, self.port)
        if block:
            self._httpd.serve_forever()
        else:
            self._thread = threading.Thread(target=self._httpd.serve_forever,
                                            daemon=True)
            self._thread.start()

    def stop(self) -> None:
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
