"""HTTP server layer: Event Server (ingest) and Engine Server (deploy).

Reference: data/.../data/api/EventServer.scala (akka-http, :7070) and
core/.../workflow/CreateServer.scala (:8000) — SURVEY.md §3.2/§3.3.
The REST surfaces (Appendix A) are preserved byte-for-byte where clients
could depend on them: paths, query params, status codes, JSON shapes.

Python's ``ThreadingHTTPServer`` stands in for akka-http: ingestion is
storage-bound, not compute-bound, and the serving hot path delegates to a
compiled XLA executable either way.  The C++ continuous-batching frontend
(SURVEY.md §7 step 9) replaces the engine server's request loop when p50
latency matters.

Transport plumbing shared by every server (backlog-tuned
``ThreadingHTTPServer``, handler base, ``X-Request-ID`` glue) lives in
:mod:`predictionio_tpu.server.http`; the metrics/tracing layer behind
each server's ``/metrics`` and ``/traces.json`` is
:mod:`predictionio_tpu.obs`.
"""

from predictionio_tpu.server.event_server import EventServer
from predictionio_tpu.server.engine_server import EngineServer

__all__ = ["EventServer", "EngineServer"]
