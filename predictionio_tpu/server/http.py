"""Shared HTTP plumbing for every predictionio_tpu server.

One place for the transport knobs the Event Server, Engine Server,
Dashboard, and Admin server previously each copy-pasted, plus the
request-id glue every frontend speaks:

- :class:`ThreadingHTTPServer` — stdlib ``ThreadingHTTPServer`` with a
  128-deep accept backlog (the default of 5 resets connections under
  load bursts; measured on the event server).
- ``X-Request-ID`` handling: :func:`incoming_request_id` pulls and
  sanitizes the client-supplied id (or None → the tracer generates one);
  every response carries the effective id back, so a client (or an
  upstream proxy) can join its logs to the server's trace/JSONL records.
- :class:`BaseHandler` — the per-request handler skeleton: HTTP/1.1
  keep-alive, Nagle off (Nagle + delayed-ACK between our multi-write
  responses and a keep-alive client stalls every request ~40 ms —
  measured: 44 ms/req persistent vs 0.9 ms without), debug-level access
  logs, and a :meth:`BaseHandler.respond` helper that writes a JSON or
  Prometheus-text payload with Content-Length and the request-id header.
"""

from __future__ import annotations

import json
import logging
from http.server import (
    BaseHTTPRequestHandler,
    ThreadingHTTPServer as _ThreadingHTTPServer,
)
from typing import Any, Dict, Optional, Tuple

from predictionio_tpu.obs.trace import sanitize_trace_id
from predictionio_tpu.resilience.deadline import DEADLINE_HEADER

logger = logging.getLogger(__name__)

__all__ = [
    "ThreadingHTTPServer",
    "BaseHandler",
    "REQUEST_ID_HEADER",
    "DEADLINE_HEADER",
    "PROMETHEUS_CTYPE",
    "incoming_request_id",
    "incoming_deadline_ms",
    "payload_bytes",
]

REQUEST_ID_HEADER = "X-Request-ID"
PROMETHEUS_CTYPE = "text/plain; version=0.0.4"


class ThreadingHTTPServer(_ThreadingHTTPServer):
    # Default accept backlog (5) resets connections under load bursts.
    request_queue_size = 128


def incoming_request_id(headers) -> Optional[str]:
    """Sanitized client-supplied ``X-Request-ID`` (None → generate one)."""
    if headers is None:
        return None
    return sanitize_trace_id(headers.get(REQUEST_ID_HEADER))


def incoming_deadline_ms(headers) -> Optional[float]:
    """Client-declared time budget (``X-PIO-Deadline-Ms``); None when
    absent or unparseable — a garbage header must not 500 the request."""
    if headers is None:
        return None
    raw = headers.get(DEADLINE_HEADER)
    if not raw:
        return None
    try:
        budget = float(raw)
    except ValueError:
        return None
    return budget if budget >= 0 else None


def payload_bytes(payload: Any) -> Tuple[bytes, str]:
    """(body, content-type) for a handler payload: ``str`` means
    Prometheus text exposition, anything else is JSON."""
    if isinstance(payload, str):
        return payload.encode(), PROMETHEUS_CTYPE
    return json.dumps(payload).encode(), "application/json; charset=UTF-8"


class BaseHandler(BaseHTTPRequestHandler):
    """Shared request-handler skeleton; subclasses implement do_* via
    their server's dispatch and reply through :meth:`respond`."""

    protocol_version = "HTTP/1.1"
    # See module docstring: keep-alive + Nagle stalls every request ~40 ms.
    disable_nagle_algorithm = True
    server_log_name = "server"

    def respond(self, status: int, data: bytes, ctype: str,
                extra_headers: Optional[Dict[str, str]] = None,
                request_id: Optional[str] = None) -> None:
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        if request_id:
            self.send_header(REQUEST_ID_HEADER, request_id)
        for k, v in (extra_headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(data)

    def log_message(self, fmt, *args):
        logger.debug("%s %s", self.server_log_name, fmt % args)
