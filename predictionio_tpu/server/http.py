"""Shared HTTP plumbing for every predictionio_tpu server.

One place for the transport knobs the Event Server, Engine Server,
Dashboard, and Admin server previously each copy-pasted, plus the
request-id glue every frontend speaks:

- :class:`ThreadingHTTPServer` — stdlib ``ThreadingHTTPServer`` with a
  128-deep accept backlog (the default of 5 resets connections under
  load bursts; measured on the event server).
- ``X-Request-ID`` handling: :func:`incoming_request_id` pulls and
  sanitizes the client-supplied id (or None → the tracer generates one);
  every response carries the effective id back, so a client (or an
  upstream proxy) can join its logs to the server's trace/JSONL records.
- :class:`BaseHandler` — the per-request handler skeleton: HTTP/1.1
  keep-alive, Nagle off (Nagle + delayed-ACK between our multi-write
  responses and a keep-alive client stalls every request ~40 ms —
  measured: 44 ms/req persistent vs 0.9 ms without), debug-level access
  logs, and a :meth:`BaseHandler.respond` helper that writes a JSON or
  Prometheus-text payload with Content-Length and the request-id header.
- :meth:`BaseHandler.dispatch` — THE request driver every frontend used
  to copy-paste (with intentional-but-drifting differences; ROADMAP
  resilience follow-on (d)): trace root + ``http.read`` /
  ``http.handle`` / ``http.respond`` spans, deadline scope with optional
  pre-handle shedding, per-server completion hook (stats + plugins), and
  the ``Retry-After`` hint on degraded answers.  Subclasses implement
  :meth:`BaseHandler.pio_handle` and override the small hooks below it.
"""

from __future__ import annotations

import json
import logging
import time
from http.server import (
    BaseHTTPRequestHandler,
    ThreadingHTTPServer as _ThreadingHTTPServer,
)
from typing import Any, Dict, List, Optional, Tuple, Union
from urllib.parse import parse_qs, urlparse

from predictionio_tpu.obs import waterfall as _waterfall
from predictionio_tpu.obs.trace import (
    attach_event,
    current_trace_id,
    get_recorder,
    sanitize_trace_id,
    slow_request_ms,
    span,
    trace,
)
from predictionio_tpu.resilience import deadline as _deadline
from predictionio_tpu.resilience.deadline import DEADLINE_HEADER

logger = logging.getLogger(__name__)

__all__ = [
    "ThreadingHTTPServer",
    "BaseHandler",
    "REQUEST_ID_HEADER",
    "DEADLINE_HEADER",
    "PROMETHEUS_CTYPE",
    "incoming_request_id",
    "incoming_deadline_ms",
    "payload_bytes",
    "timeline_payload",
    "traces_payload",
]

REQUEST_ID_HEADER = "X-Request-ID"
PROMETHEUS_CTYPE = "text/plain; version=0.0.4"


class ThreadingHTTPServer(_ThreadingHTTPServer):
    # Default accept backlog (5) resets connections under load bursts.
    request_queue_size = 128


def incoming_request_id(headers) -> Optional[str]:
    """Sanitized client-supplied ``X-Request-ID`` (None → generate one)."""
    if headers is None:
        return None
    return sanitize_trace_id(headers.get(REQUEST_ID_HEADER))


def incoming_deadline_ms(headers) -> Optional[float]:
    """Client-declared time budget (``X-PIO-Deadline-Ms``); None when
    absent or unparseable — a garbage header must not 500 the request."""
    if headers is None:
        return None
    raw = headers.get(DEADLINE_HEADER)
    if not raw:
        return None
    try:
        budget = float(raw)
    except ValueError:
        return None
    return budget if budget >= 0 else None


def payload_bytes(payload: Any) -> Tuple[bytes, str]:
    """(body, content-type) for a handler payload: ``str`` means
    Prometheus text exposition, anything else is JSON."""
    if isinstance(payload, str):
        return payload.encode(), PROMETHEUS_CTYPE
    return json.dumps(payload).encode(), "application/json; charset=UTF-8"


def timeline_payload(params: Dict[str, List[str]]) -> Dict[str, Any]:
    """The shared ``GET /timeline.json`` view over the process step
    timeline.  ``?model=`` filters, ``?n=`` bounds the record count, and
    ``?format=chrome`` returns Chrome-trace JSON (chrome://tracing /
    Perfetto); ``?format=summary`` returns only the per-model phase
    aggregation that ``tools/attribute_gap.py`` consumes."""
    from predictionio_tpu.obs.runtime import get_timeline

    tl = get_timeline()
    model = params.get("model", [None])[0]
    try:
        n = int(params.get("n", ["256"])[0])
    except ValueError:
        n = 256
    fmt = params.get("format", ["raw"])[0]
    if fmt == "chrome":
        return tl.to_chrome_trace(max(n, 1), model=model)
    models = tl.models() if model is None else [model]
    summaries = {m: tl.summary(m) for m in models}
    if fmt == "summary":
        return {"models": summaries}
    return {"steps": tl.recent(n, model=model), "models": summaries}


def param_bool(params: Optional[Dict[str, List[str]]], key: str,
               default: bool = False) -> bool:
    """Boolean query param in the same dialect as env_bool — so
    ``?exemplars=0`` / ``?exemplars=off`` actually means OFF (a bare
    presence check would read an explicit opt-out as opt-in)."""
    from predictionio_tpu.config import env_bool

    vals = (params or {}).get(key) or [""]
    return env_bool(vals[0], default)


def traces_payload(params: Dict[str, List[str]]) -> Dict[str, Any]:
    """The shared ``GET /traces.json`` view (every frontend).

    ``?request_id=`` resolves one exact trace (exemplar links from the
    ``pio_serve_stage_ms`` waterfall buckets land here), ``?min_ms=``
    keeps only traces at least that slow, ``?limit=`` bounds the count
    (default 50, clamped to the ring)."""
    request_id = sanitize_trace_id(params.get("request_id", [None])[0])
    try:
        limit = int(params.get("limit", ["50"])[0])
    except ValueError:
        limit = 50
    min_ms: Optional[float] = None
    raw = params.get("min_ms", [None])[0]
    if raw:
        try:
            min_ms = float(raw)
        except ValueError:
            min_ms = None
    return {"traces": get_recorder().recent(
        limit, request_id=request_id, min_ms=min_ms)}


# A handler hook's result: (status, payload) with the content type
# inferred by payload_bytes, (status, payload, ctype) when the frontend
# picks its own (the dashboard's HTML pages), or
# (status, payload, ctype, headers) when it also sets response headers
# (the profiler artifact's Content-Disposition).
HandlerResult = Union[Tuple[int, Any], Tuple[int, Any, str],
                      Tuple[int, Any, str, Dict[str, str]]]


class BaseHandler(BaseHTTPRequestHandler):
    """Shared request-handler skeleton; subclasses implement
    :meth:`pio_handle` and route their do_* methods through
    :meth:`dispatch` (or keep replying directly through :meth:`respond`).
    """

    protocol_version = "HTTP/1.1"
    # See module docstring: keep-alive + Nagle stalls every request ~40 ms.
    disable_nagle_algorithm = True
    server_log_name = "server"
    # Short server tag used in traces and the shed counter ("event", ...).
    trace_server_name = "server"
    # Shed with 504 BEFORE pio_handle when the deadline is already spent
    # (the event server's pre-auth shed; the engine server sheds inside
    # its handler, right before the expensive predict, instead).
    shed_pre_handle = False
    # Rewrite a 2xx whose budget ran out DURING handling into a 504
    # (ISSUE 6: an expired request gets 504, never a slow 200).  The
    # verdict and the X-PIO-Deadline-Remaining-Ms attestation are ONE
    # measurement, so a 200 always attests positive remaining budget.
    # Only safe on non-mutating frontends — the engine server opts in;
    # an event-server write that SUCCEEDED must report its success.
    shed_late_responses = False
    # Degraded answers that carry the Retry-After backoff hint: spill
    # accepts (202), admission rejections (429), and unavailability
    # (503) all want the client to come back, just later.
    retry_after_statuses = (202, 429, 503)

    # -- per-frontend hooks --------------------------------------------------

    def pio_handle(self, method: str, path: str,
                   params: Dict[str, List[str]], body: bytes) -> HandlerResult:
        """Handle one request; runs inside the trace + deadline scope."""
        raise NotImplementedError

    def pio_on_complete(self, method: str, path: str, status: int,
                        ms: float, body: bytes,
                        params: Dict[str, List[str]]
                        ) -> Optional[Dict[str, str]]:
        """Post-handle hook (stats recording, plugins); runs BEFORE the
        response is written — a client reading /stats.json right after
        its own request completes must see it counted.  May return extra
        response headers."""
        return None

    def pio_retry_after_s(self) -> Optional[int]:
        """Backoff hint attached to ``retry_after_statuses`` answers."""
        return None

    def pio_shed(self) -> None:
        """Count a transport-level deadline shed (pre-handle 504)."""

    # -- THE request driver --------------------------------------------------

    def dispatch(self, method: str) -> None:
        t0 = time.perf_counter()
        # Receipt wall for the waterfall's ingress stage (the engine
        # handler arms the collector mid-handle, after body read+routing
        # already happened — it reads this to bill them).
        _waterfall.note_transport_start(t0)
        with trace("http.request",
                   trace_id=incoming_request_id(self.headers),
                   slow_ms=slow_request_ms(),
                   server=self.trace_server_name, method=method) as troot:
            parsed = urlparse(self.path)
            troot.set(path=parsed.path)
            params = parse_qs(parsed.query)
            with span("http.read"):
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length) if length else b""
            remaining: Optional[float] = None
            with _deadline.deadline_scope(
                    incoming_deadline_ms(self.headers)):
                if self.shed_pre_handle and _deadline.exceeded():
                    # A request whose budget is already gone must not
                    # queue behind auth/storage.
                    self.pio_shed()
                    out: HandlerResult = (504, {"message":
                                                "Deadline exceeded."})
                else:
                    with span("http.handle"):
                        out = self.pio_handle(method, parsed.path, params,
                                              body)
                    remaining = _deadline.remaining_ms()
            t_shed = time.perf_counter()
            handler_headers: Dict[str, str] = {}
            if len(out) == 4:
                status, payload, ctype, handler_headers = out  # type: ignore[misc]
            elif len(out) == 3:
                status, payload, ctype = out  # type: ignore[misc]
            else:
                status, payload = out  # type: ignore[misc]
                ctype = None
            if (self.shed_late_responses and remaining is not None
                    and remaining <= 0 and 200 <= status < 300):
                # The handler answered, but past its budget: the client
                # stopped waiting — 504, not a slow 2xx (see class attr).
                self.pio_shed()
                status, payload, ctype = 504, {
                    "message": "Deadline exceeded before response."}, None
            troot.set(status=status)
            ms = (time.perf_counter() - t0) * 1e3
            extra = dict(self.pio_on_complete(method, parsed.path, status,
                                              ms, body, params) or {})
            for k, v in handler_headers.items():
                extra.setdefault(k, v)
            # The server's own read+handle wall time: clients (and the
            # serving bench) use it to attribute client-vs-server latency
            # drift and to ATTEST deadline compliance — a 200 whose
            # X-PIO-Server-Ms is inside the sent budget was served in
            # time by the server's clock, whatever transport queueing
            # added around it.
            extra.setdefault("X-PIO-Server-Ms", f"{ms:.1f}")
            if remaining is not None:
                # Deadline attestation: the SAME reading the late-shed
                # verdict used — a 200 always carries remaining > 0
                # (though formatting may floor a sliver to 0.00, so
                # verifiers must treat only NEGATIVE values as late).
                extra.setdefault("X-PIO-Deadline-Remaining-Ms",
                                 f"{remaining:.2f}")
            retry_after = self.pio_retry_after_s()
            if retry_after is not None and status in self.retry_after_statuses:
                extra.setdefault("Retry-After", str(retry_after))
            wf = _waterfall.current_waterfall()
            if wf is not None:
                # shed_check: scheduler hand-back → the respond write —
                # the handler's span unwind + stats hooks (from the
                # handler_done mark when the engine set one), the
                # late-shed verdict, and response-header assembly.  Small,
                # but the waterfall must account for it so the stage sum
                # reconciles with X-PIO-Server-Ms.
                t_fin = wf.take_mark("handler_done") or t_shed
                wf.stamp("shed_check",
                         (time.perf_counter() - t_fin) * 1e3)
            with span("http.respond") as rspan:
                if ctype is None:
                    data, ctype = payload_bytes(payload)
                else:
                    data = (payload.encode() if isinstance(payload, str)
                            else payload)
                self.respond(status, data, ctype, extra,
                             request_id=current_trace_id())
            if wf is not None:
                # serialize: result → JSON bytes + the socket write.
                wf.stamp("serialize", rspan.duration_ms or 0.0)
                doc = wf.finalize(
                    trace_id=current_trace_id(), status=status,
                    total_ms=(time.perf_counter() - t0) * 1e3,
                    attested_ms=ms)
                if doc:
                    attach_event(troot, "waterfall",
                                 **{k: v for k, v in doc.items()
                                    if k not in ("ts", "traceId")})
                _waterfall.deactivate()

    def respond(self, status: int, data: bytes, ctype: str,
                extra_headers: Optional[Dict[str, str]] = None,
                request_id: Optional[str] = None) -> None:
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        if request_id:
            self.send_header(REQUEST_ID_HEADER, request_id)
        for k, v in (extra_headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(data)

    def log_message(self, fmt, *args):
        logger.debug("%s %s", self.server_log_name, fmt % args)
