"""Serving scheduler: continuous micro-batching + admission control.

The serving half of "millions of users" (ROADMAP).  The engine server's
``/queries.json`` handlers no longer reach the model directly — every
query is ADMITTED into a bounded per-model queue (full → 429 +
``Retry-After``), COALESCED by a deadline-aware micro-batcher into one
vectorized ``batch_predict`` dispatch per window, and its window is
AUTOTUNED against a served-latency p99 target.  A lint rule
(``tools/lint_dispatch.py``) keeps the invariant: handlers go through
this scheduler, never straight to ``engine.query``/``query_batch``.

Layout:

- :mod:`predictionio_tpu.serving.queue` — admission queue, request
  lifecycle, injectable clock.
- :mod:`predictionio_tpu.serving.batcher` — the micro-batcher loop
  (window policy, deadline sheds, generation-atomic dispatch).
- :mod:`predictionio_tpu.serving.autotune` — the p99-targeted AIMD
  window/batch-size controller.
- :class:`ServingScheduler` (here) — the facade the engine server talks
  to: ``register`` a model's dispatch fn, ``submit_and_wait`` per
  request, ``snapshot`` for the status page, ``close`` on shutdown.

Env knobs (all read at server construction; deploy flags override):

====================================  =====================================
``PIO_BATCH_ENABLED``                 batcher on/off (default on; off =
                                      inline per-request dispatch, still
                                      admission-controlled)
``PIO_QUEUE_DEPTH``                   per-model admission limit (128)
``PIO_BATCH_WINDOW_MS``               initial gather window (2.0)
``PIO_BATCH_WINDOW_MAX_MS``           autotuner window cap (20.0)
``PIO_BATCH_MAX``                     max queries per dispatch (64)
``PIO_BATCH_AUTOTUNE``                autotuner on/off (default on)
``PIO_BATCH_P99_TARGET_MS``           served-latency p99 target (100)
``PIO_QUEUE_WAIT_MAX_S``              stall backstop for a pending
                                      request with no deadline (30)
====================================  =====================================
"""

from __future__ import annotations

import dataclasses
import os
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

from predictionio_tpu.config import env_bool as _truthy
from predictionio_tpu.obs import get_registry
from predictionio_tpu.obs.trace import current_span
from predictionio_tpu.obs.waterfall import current_waterfall
from predictionio_tpu.resilience import deadline as _deadline
from predictionio_tpu.resilience.deadline import DeadlineExceeded
from predictionio_tpu.serving.autotune import WindowAutotuner
from predictionio_tpu.serving.batcher import MicroBatcher
from predictionio_tpu.serving.queue import (
    Clock,
    ModelQueue,
    MonotonicClock,
    Pending,
    QueueFull,
    SchedulerClosed,
    SchedulerStalled,
)
from predictionio_tpu.serving.result_cache import (
    CacheHit,
    ResultCache,
    ResultCacheConfig,
    canonical_query,
)

__all__ = [
    "SchedulerConfig",
    "ServingScheduler",
    "ResultCache",
    "ResultCacheConfig",
    "CacheHit",
    "canonical_query",
    "MicroBatcher",
    "WindowAutotuner",
    "ModelQueue",
    "Pending",
    "Clock",
    "MonotonicClock",
    "QueueFull",
    "SchedulerClosed",
    "SchedulerStalled",
]


@dataclasses.dataclass
class SchedulerConfig:
    """Scheduler knobs; :meth:`from_env` is the production constructor."""

    enabled: bool = True
    queue_depth: int = 128
    window_ms: float = 2.0
    window_max_ms: float = 20.0
    max_batch: int = 64
    autotune: bool = True
    p99_target_ms: float = 100.0
    stall_s: float = 30.0

    @classmethod
    def from_env(cls, env=None, **overrides) -> "SchedulerConfig":
        env = os.environ if env is None else env

        def _f(key, cast, default):
            raw = env.get(key)
            if raw is None or str(raw).strip() == "":
                return default
            try:
                return cast(raw)
            except (TypeError, ValueError):
                return default

        cfg = cls(
            enabled=_truthy(env.get("PIO_BATCH_ENABLED"), True),
            queue_depth=_f("PIO_QUEUE_DEPTH", int, 128),
            window_ms=_f("PIO_BATCH_WINDOW_MS", float, 2.0),
            window_max_ms=_f("PIO_BATCH_WINDOW_MAX_MS", float, 20.0),
            max_batch=_f("PIO_BATCH_MAX", int, 64),
            autotune=_truthy(env.get("PIO_BATCH_AUTOTUNE"), True),
            p99_target_ms=_f("PIO_BATCH_P99_TARGET_MS", float, 100.0),
            stall_s=_f("PIO_QUEUE_WAIT_MAX_S", float, 30.0),
        )
        for k, v in overrides.items():
            if v is not None:
                setattr(cfg, k, v)
        return cfg


class _ModelLane:
    """One registered model's queue + batcher + (optional) autotuner."""

    __slots__ = ("queue", "batcher", "autotuner", "inline_inflight",
                 "inline_lock")

    def __init__(self, queue: ModelQueue, batcher: MicroBatcher,
                 autotuner: Optional[WindowAutotuner]):
        self.queue = queue
        self.batcher = batcher
        self.autotuner = autotuner
        # Inline (batching-off) admission: concurrent in-flight count
        # against the same queue_depth limit.
        self.inline_inflight = 0
        self.inline_lock = threading.Lock()


class ServingScheduler:
    """Facade: admission → micro-batch → dispatch, per registered model.

    ``register(name, dispatch_fn)`` wires one model lane;
    ``dispatch_fn(queries) -> (results, generation)`` must snapshot its
    model set atomically (the engine server grabs everything under ONE
    swap-lock acquisition) so a batch can never span generations.
    """

    def __init__(self, config: Optional[SchedulerConfig] = None,
                 clock: Optional[Clock] = None, registry=None):
        self.config = config or SchedulerConfig.from_env()
        self.clock = clock or MonotonicClock()
        self._registry = registry or get_registry()
        self._lanes: Dict[str, _ModelLane] = {}
        self._closed = False
        self._m_depth = self._registry.gauge(
            "pio_queue_depth", "Queued (admitted, undispatched) requests.",
            ("model",))
        self._m_rejected = self._registry.counter(
            "pio_queue_rejected_total",
            "Requests rejected at admission (HTTP 429).", ("model",))

    # -- wiring -------------------------------------------------------------

    def register(
        self,
        model: str,
        dispatch_fn: Callable[[List[Any]], Tuple[List[Any], int]],
    ) -> MicroBatcher:
        if model in self._lanes:
            raise ValueError(f"model {model!r} already registered")
        cfg = self.config
        queue = ModelQueue(
            model, cfg.queue_depth,
            on_depth=lambda n, _m=model: self._m_depth.set(n, model=_m))
        autotuner = None
        if cfg.autotune and cfg.enabled:
            autotuner = WindowAutotuner(
                model, cfg.p99_target_ms,
                window_max_s=cfg.window_max_ms / 1e3,
                max_size_cap=cfg.max_batch,
                registry=self._registry)
        batcher = MicroBatcher(
            model, queue, dispatch_fn,
            window_s=cfg.window_ms / 1e3,
            max_size=cfg.max_batch if cfg.enabled else 1,
            clock=self.clock, autotuner=autotuner,
            registry=self._registry)
        lane = _ModelLane(queue, batcher, autotuner)
        self._lanes[model] = lane
        if cfg.enabled:
            batcher.start()
        return batcher

    def models(self) -> List[str]:
        return sorted(self._lanes)

    # -- the per-request path ----------------------------------------------

    def submit_and_wait(self, model: str, query: Any) -> Any:
        """Admit one query and block until its batch answers (or sheds).

        Raises :class:`QueueFull` (→429), :class:`DeadlineExceeded`
        (→504), :class:`SchedulerStalled`/:class:`SchedulerClosed`
        (→503), or whatever the dispatch itself raised for this member
        (bind errors → 400 upstream).
        """
        if self._closed:
            raise SchedulerClosed("serving scheduler is shut down")
        try:
            lane = self._lanes[model]
        except KeyError:
            raise ValueError(f"unknown model {model!r}") from None
        now = self.clock.now()
        rem = _deadline.remaining_ms()
        deadline_s = now + rem / 1e3 if rem is not None else None
        # The request's stage collector rides the Pending hand-off so the
        # batcher thread can stamp queue_wait/batch_wait/dispatch/
        # retrieval onto it (ISSUE 9 waterfall).
        pending = Pending(query, now, deadline_s, span=current_span(),
                          waterfall=current_waterfall())
        if not self.config.enabled:
            return self._submit_inline(model, lane, pending)
        try:
            lane.queue.put(pending)
        except QueueFull:
            self._m_rejected.inc(model=model)
            raise
        budget_s = None
        if deadline_s is not None:
            budget_s = max(deadline_s - self.clock.now(), 0.0)
        stall_s = self.config.stall_s
        timeout = stall_s if budget_s is None else min(budget_s, stall_s)
        if not pending.wait_done(timeout):
            pending.abandon()  # best effort; a claimed entry's result is
            # discarded — its deadline has passed either way.
            if budget_s is not None and budget_s <= stall_s:
                raise DeadlineExceeded(
                    "deadline expired awaiting batch dispatch "
                    f"({timeout * 1e3:.0f}ms budget)")
            raise SchedulerStalled(
                f"no dispatch within {stall_s:.0f}s — batcher wedged?")
        if pending.waterfall is not None:
            # resume: dispatch done → this thread actually running again
            # (event wake-up + GIL/thread contention).  Computed as the
            # admission→result wall minus the batcher-attributed stages,
            # ON THE SAME CLOCK the batcher stamped with — without it the
            # waterfall's stage sum undershoots the server-attested wall
            # under concurrency and misattributes scheduling overhead.
            done = pending.waterfall.snapshot()
            resid = (self.clock.now() - now) * 1e3 - sum(
                done.get(s, 0.0)
                for s in ("queue_wait", "batch_wait", "dispatch"))
            if resid > 0:
                pending.waterfall.stamp("resume", resid)
        if pending.error is not None:
            raise pending.error
        return pending.result

    def _submit_inline(self, model: str, lane: _ModelLane,
                       pending: Pending) -> Any:
        """Batching disabled: dispatch on the caller thread through the
        SAME batcher machinery (metrics, deadline shed, trace event),
        with the queue-depth limit enforced as an in-flight cap."""
        with lane.inline_lock:
            if lane.inline_inflight >= lane.queue.depth:
                self._m_rejected.inc(model=model)
                raise QueueFull(
                    f"model {model!r} at inline concurrency limit "
                    f"({lane.inline_inflight}/{lane.queue.depth})")
            lane.inline_inflight += 1
        try:
            lane.batcher.dispatch([pending])
        finally:
            with lane.inline_lock:
                lane.inline_inflight -= 1
        if pending.error is not None:
            raise pending.error
        return pending.result

    # -- introspection / lifecycle ------------------------------------------

    def saturated(self) -> bool:
        """Any model lane's autotuner reporting persistent-floor
        saturation (offered load > capacity) — the serving half of the
        SLO engine's /ready degradation signal."""
        return any(lane.autotuner is not None and lane.autotuner.saturated()
                   for lane in self._lanes.values())

    def snapshot(self) -> Dict[str, Any]:
        """Status-page view (``GET /`` / ``/stats.json`` /
        ``pio status``): per-model knobs, flow counters, shed reasons."""
        out: Dict[str, Any] = {}
        for name, lane in sorted(self._lanes.items()):
            b = lane.batcher
            dispatches = b._m_dispatches.value(model=name)
            requests = b._m_requests.value(model=name)
            shed = {k[1]: int(v) for k, v in b._m_shed.series().items()
                    if k[0] == name and v}
            out[name] = {
                "batching": self.config.enabled,
                "queueDepth": len(lane.queue),
                "queueLimit": lane.queue.depth,
                "windowMs": round(b.window_s * 1e3, 3),
                "maxBatch": b.max_size,
                "dispatches": int(dispatches),
                "requests": int(requests),
                "meanBatch": (round(requests / dispatches, 2)
                              if dispatches else None),
                "rejected": int(self._m_rejected.value(model=name)),
                "shed": shed,
                "p99TargetMs": (lane.autotuner.target_p99_ms
                                if lane.autotuner else None),
                "servedP99Ms": (round(lane.autotuner.last_p99_ms, 2)
                                if lane.autotuner
                                and lane.autotuner.last_p99_ms is not None
                                else None),
                "saturated": (lane.autotuner.saturated()
                              if lane.autotuner else False),
            }
        return out

    def close(self) -> None:
        self._closed = True
        for lane in self._lanes.values():
            lane.batcher.close()
