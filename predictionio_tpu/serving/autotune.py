"""p99-targeted batch-window autotuner.

The batch window is a latency/throughput dial with no single right
setting: too short and concurrent requests stop coalescing (throughput
collapses to one dispatch per request), too long and every request pays
the window as queueing delay.  The right setting moves with load, model
cost, and hardware — so it is tuned FROM THE SERVED LATENCY HISTOGRAM,
not configured.

Control law (AIMD, the same shape TCP uses for the same reason —
stability under feedback delay):

- observed p99 over the target → multiplicative back-off: halve the
  window; if the window is already at its floor, halve ``max_size``
  instead (a huge batch can blow the budget all by itself).
- observed p99 comfortably under the target (< ``grow_fraction`` of it)
  → additive growth: restore ``max_size`` first (doubling toward its
  configured cap — batching is nearly free when latency is healthy),
  then widen the window by ``window_step_s`` toward its cap.
- in the hysteresis band between: leave the knobs alone.

Retune runs every ``interval`` dispatches over a sliding sample ring, so
the estimate reflects the current load, not the process's whole life.
All decisions are visible: ``pio_batch_autotune_total{model,action}``
counts them and the batcher republishes its knob gauges on every change.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Deque, Optional

from predictionio_tpu.obs import get_registry

__all__ = ["WindowAutotuner"]


def _quantile(sorted_vals, q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(int(q * len(sorted_vals)), len(sorted_vals) - 1)
    return sorted_vals[idx]


class WindowAutotuner:
    """Adapts a :class:`~predictionio_tpu.serving.batcher.MicroBatcher`'s
    ``window_s``/``max_size`` to hold served p99 at ``target_p99_ms``.

    ``observe`` is fed each member request's full served latency
    (admission → result, the number the client experiences); retuning
    happens on the batcher thread in ``after_dispatch`` so there is no
    extra timer thread to manage.
    """

    def __init__(
        self,
        model: str,
        target_p99_ms: float,
        *,
        window_min_s: float = 0.0,
        window_max_s: float = 0.020,
        window_step_s: float = 0.0005,
        max_size_cap: int = 64,
        interval: int = 32,
        sample_size: int = 512,
        grow_fraction: float = 0.6,
        saturation_streak: int = 3,
        registry=None,
    ):
        self.model = model
        self.target_p99_ms = float(target_p99_ms)
        self.window_min_s = float(window_min_s)
        self.window_max_s = float(window_max_s)
        self.window_step_s = float(window_step_s)
        self.max_size_cap = int(max_size_cap)
        self.interval = max(int(interval), 1)
        self.grow_fraction = float(grow_fraction)
        self._lock = threading.Lock()
        self._samples: Deque[float] = deque(maxlen=sample_size)
        self._since_retune = 0
        self.last_p99_ms: Optional[float] = None
        # Persistent-floor saturation detector (ISSUE 9 / ROADMAP rung):
        # the "floor" action means p99 is over target with the window
        # already at its minimum AND the dispatch itself fast — i.e. the
        # backlog, not the batching, is the latency.  A streak of them is
        # the controller saying "offered load > capacity"; the SLO engine
        # combines this with burn rate to flip /ready.
        self.saturation_streak = max(int(saturation_streak), 1)
        self._floor_streak = 0
        reg = registry or get_registry()
        self._m_actions = reg.counter(
            "pio_batch_autotune_total",
            "Autotuner decisions by action.", ("model", "action"))
        self._m_p99 = reg.gauge(
            "pio_batch_served_p99_ms",
            "Autotuner's sliding-window served-latency p99 estimate.",
            ("model",))
        self._m_saturated = reg.gauge(
            "pio_batch_saturated",
            "1 while the autotuner's persistent-floor detector reports "
            "offered load > capacity for this model lane.", ("model",))
        self._m_saturated.set(0, model=model)

    def observe(self, served_latency_ms: float) -> None:
        with self._lock:
            self._samples.append(float(served_latency_ms))

    def after_dispatch(self, batcher) -> None:
        with self._lock:
            self._since_retune += 1
            if self._since_retune < self.interval:
                return
            self._since_retune = 0
            samples = sorted(self._samples)
        if len(samples) < self.interval:
            return
        self.retune(batcher, _quantile(samples, 0.99))

    def saturated(self) -> bool:
        """Persistent-floor verdict: ≥ ``saturation_streak`` consecutive
        retunes ended in the ``floor`` action (nothing left to shrink,
        p99 still over target).  Any other action clears the streak —
        capacity returned or a knob still had room."""
        return self._floor_streak >= self.saturation_streak

    def _track_floor(self, action: str) -> None:
        self._floor_streak = (self._floor_streak + 1
                              if action == "floor" else 0)
        self._m_saturated.set(1 if self.saturated() else 0,
                              model=self.model)

    def retune(self, batcher, p99_ms: float) -> None:
        """One control step against an explicit p99 reading (tests call
        this directly; production arrives via :meth:`after_dispatch`)."""
        self.last_p99_ms = p99_ms
        self._m_p99.set(p99_ms, model=self.model)
        if p99_ms > self.target_p99_ms:
            if batcher.window_s > self.window_min_s:
                # Snap to the floor once halving drops below a tenth of
                # a millisecond — pure multiplicative decay would only
                # converge asymptotically, leaving the shrink_batch /
                # floor branches unreachable forever.
                new_w = batcher.window_s * 0.5
                if new_w < max(self.window_min_s, 1e-4):
                    new_w = self.window_min_s
                batcher.set_knobs(window_s=new_w)
                action = "shrink_window"
            elif (batcher.max_size > 1
                    and batcher._est_dispatch_s * 1e3
                    > 0.25 * self.target_p99_ms):
                # Shrink the batch only when the DISPATCH ITSELF is a
                # real slice of the budget.  Over-target with a fast
                # dispatch means backlog (offered load > capacity) —
                # shrinking the batch there cuts throughput and makes
                # the backlog, and the p99, strictly worse.
                batcher.set_knobs(max_size=max(batcher.max_size // 2, 1))
                action = "shrink_batch"
            else:
                action = "floor"
        elif p99_ms < self.grow_fraction * self.target_p99_ms:
            if batcher.max_size < self.max_size_cap:
                batcher.set_knobs(max_size=min(
                    batcher.max_size * 2, self.max_size_cap))
                action = "grow_batch"
            elif batcher.window_s < self.window_max_s:
                batcher.set_knobs(window_s=min(
                    batcher.window_s + self.window_step_s,
                    self.window_max_s))
                action = "grow_window"
            else:
                action = "ceiling"
        else:
            action = "hold"
        self._m_actions.inc(model=self.model, action=action)
        self._track_floor(action)
