"""Deadline-aware micro-batcher: queue → one vectorized dispatch per window.

The continuous-batching core.  One dispatcher thread per model drains the
admission queue into batches and hands each batch to the engine's
vectorized ``batch_predict`` path in a SINGLE call — concurrent requests
share one XLA dispatch instead of paying one each (the paper's engine
server already exposes ``query_batch``; until this module nothing ever
handed it more than one request's worth).

Window policy — the part that keeps tail latency honest:

- a batch OPENS when the first request arrives and CLOSES after
  ``window_s`` (autotuner-owned), when it reaches ``max_size``, or — the
  deadline-aware clause — at the latest instant the most-constrained
  member could still be dispatched and answered within its
  ``X-PIO-Deadline-Ms`` budget (estimated from an EWMA of recent
  dispatch times).  Batching must never convert an in-budget request
  into a deadline miss.
- entries whose deadline already expired are shed with
  ``DeadlineExceeded`` (HTTP 504 upstream) BEFORE the dispatch — a dead
  request must not occupy device work.

Generation safety: the whole batch goes through ONE ``dispatch_fn`` call,
and the engine server's dispatch snapshots (models, generation) once
under its swap lock — a staged reload or rollback that lands mid-gather
flips the NEXT batch, never splits this one across model generations.
``dispatch_fn(queries) -> (results, generation)`` returns the generation
it served so traces and tests can pin that invariant.

Failure isolation: when a batch dispatch raises, the batcher retries the
members individually so one malformed query (bind error) 400s itself
instead of failing its whole cohort.
"""

from __future__ import annotations

import logging
import threading
import uuid
from typing import Any, Callable, List, Optional, Sequence, Tuple

from predictionio_tpu.obs import get_registry
from predictionio_tpu.obs.trace import attach_event, trace as _trace
from predictionio_tpu.obs.waterfall import Waterfall, dispatch_sink
from predictionio_tpu.resilience.deadline import DeadlineExceeded
from predictionio_tpu.serving.queue import (
    Clock,
    ModelQueue,
    MonotonicClock,
    Pending,
    SchedulerClosed,
)

logger = logging.getLogger(__name__)

__all__ = ["MicroBatcher", "BATCH_SIZE_BUCKETS"]

# Batch-size histogram buckets: powers of two up to the native frontend's
# ceiling — the distribution, not just the mean, shows coalescing health.
BATCH_SIZE_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256)

# Coalescing-ratio buckets (dispatches per request = 1/batch_size):
# 1.0 = no coalescing, 1/64 = perfect 64-way sharing.
COALESCE_BUCKETS = (0.015625, 0.03125, 0.0625, 0.125, 0.25, 0.5, 1.0)

_FAR_FUTURE = float("inf")


class MicroBatcher:
    """Drains one :class:`ModelQueue` into windowed vectorized dispatches.

    ``dispatch_fn(queries) -> (results, generation)`` runs the whole
    batch against ONE atomically-snapshotted model generation.
    ``window_s`` and ``max_size`` are attributes (not constructor-frozen)
    because the autotuner retunes them live.
    """

    def __init__(
        self,
        model: str,
        queue: ModelQueue,
        dispatch_fn: Callable[[List[Any]], Tuple[List[Any], int]],
        *,
        window_s: float = 0.002,
        max_size: int = 64,
        clock: Optional[Clock] = None,
        autotuner=None,
        registry=None,
    ):
        self.model = model
        self.queue = queue
        self.dispatch_fn = dispatch_fn
        self.window_s = float(window_s)
        self.max_size = int(max_size)
        self.clock = clock or MonotonicClock()
        self.autotuner = autotuner
        # EWMA of recent dispatch wall times — the service-time estimate
        # the deadline-aware window close uses.  Seeded at 0 ("dispatch
        # is instant") so the first requests are never shed on a guess;
        # it converges within a few batches.
        self._est_dispatch_s = 0.0
        # Consecutive gathers that ended as singletons: after 2, the
        # stream is a lone client and the window wait is pure latency
        # tax — skip it until companions reappear (the backlog scoop
        # re-forms batches the moment concurrency returns, which resets
        # the streak).
        self._lone_streak = 0
        self._thread: Optional[threading.Thread] = None
        reg = registry or get_registry()
        self._m_batch_size = reg.histogram(
            "pio_batch_size", "Queries coalesced per dispatch.",
            ("model",), buckets=BATCH_SIZE_BUCKETS)
        self._m_coalesce = reg.histogram(
            "pio_batch_dispatches_per_request",
            "1/batch_size observed per member request — mean < 1 means "
            "the scheduler is coalescing.",
            ("model",), buckets=COALESCE_BUCKETS)
        self._m_dispatch_ms = reg.histogram(
            "pio_batch_dispatch_ms", "Wall time of one batched dispatch.",
            ("model",))
        self._m_wait_ms = reg.histogram(
            "pio_queue_wait_ms",
            "Queue wait from admission to dispatch start.", ("model",))
        self._m_dispatches = reg.counter(
            "pio_batch_dispatch_total", "Batched dispatches.", ("model",))
        self._m_requests = reg.counter(
            "pio_batch_requests_total",
            "Requests served through the batcher.", ("model",))
        self._m_shed = reg.counter(
            "pio_queue_shed_total",
            "Queue entries shed before dispatch.", ("model", "reason"))
        self._m_window = reg.gauge(
            "pio_batch_window_ms", "Current batch gather window.",
            ("model",))
        self._m_max = reg.gauge(
            "pio_batch_max_size", "Current max batch size.", ("model",))
        self._publish_knobs()

    # -- knobs (autotuner writes through these) -----------------------------

    def _publish_knobs(self) -> None:
        self._m_window.set(self.window_s * 1e3, model=self.model)
        self._m_max.set(self.max_size, model=self.model)

    def set_knobs(self, window_s: Optional[float] = None,
                  max_size: Optional[int] = None) -> None:
        if window_s is not None:
            self.window_s = max(float(window_s), 0.0)
        if max_size is not None:
            self.max_size = max(int(max_size), 1)
        self._publish_knobs()

    # -- gather -------------------------------------------------------------

    def _latest_dispatch_s(self, entry: Pending) -> float:
        """Latest clock time this entry could still be dispatched and
        (per the EWMA estimate) answered inside its deadline."""
        if entry.deadline_s is None:
            return _FAR_FUTURE
        return entry.deadline_s - self._est_dispatch_s

    def gather(self, first: Optional[Pending] = None) -> List[Pending]:
        """Form one batch: block for the first entry, then fill until the
        window closes, the most-constrained member's slack runs out, or
        ``max_size`` is reached.  Returns [] only when the queue closed.
        """
        if first is None:
            first = self.queue.take(self.clock, timeout=None)
            if first is None:
                return []
        batch = [first]
        opened = self.clock.now()
        # Waterfall: gather pickup splits the member's admission→dispatch
        # wait into queue_wait (before pickup) and batch_wait (window).
        first.gathered_s = opened
        window_s = self.window_s if self._lone_streak < 2 else 0.0
        close = opened + window_s
        close = min(close, self._latest_dispatch_s(first))
        # Scoop the backlog FIRST: entries already queued coalesce for
        # free (no added latency), so even a zero window batches under
        # load — the window only governs waiting for FUTURE arrivals.
        while len(batch) < self.max_size:
            entry = self.queue.take(self.clock, timeout=0)
            if entry is None:
                break
            entry.gathered_s = self.clock.now()
            batch.append(entry)
            close = min(close, self._latest_dispatch_s(entry))
        while len(batch) < self.max_size:
            now = self.clock.now()
            if now >= close:
                break
            entry = self.queue.take(self.clock, timeout=close - now)
            if entry is None:
                if self.queue.closed() or self.clock.now() >= close:
                    break
                continue
            entry.gathered_s = self.clock.now()
            batch.append(entry)
            close = min(close, self._latest_dispatch_s(entry))
        self._lone_streak = self._lone_streak + 1 if len(batch) == 1 else 0
        return batch

    # -- dispatch -----------------------------------------------------------

    def _stamp_waits(self, e: Pending, end_s: float) -> None:
        """queue_wait (admission → gather pickup) + batch_wait (pickup →
        ``end_s``) onto a member's waterfall.  Called on EVERY finish
        path — dispatch, pre-dispatch shed, failed batch — so a 504's
        wall is attributed to queueing, never mistaken for the waiter's
        post-dispatch resume residual."""
        if e.waterfall is None:
            return
        gathered = e.gathered_s if e.gathered_s is not None else end_s
        e.waterfall.stamp("queue_wait",
                          max(gathered - e.enqueued_s, 0.0) * 1e3)
        e.waterfall.stamp("batch_wait", max(end_s - gathered, 0.0) * 1e3)

    def dispatch(self, batch: Sequence[Pending]) -> int:
        """Claim, shed expired, run ONE vectorized dispatch, finish all.

        Returns the number of entries actually dispatched (after sheds
        and abandons) — 0 means the whole batch evaporated.
        """
        now = self.clock.now()
        live: List[Pending] = []
        for e in batch:
            if not e.claim():
                continue  # waiter already walked (deadline) — silent drop
            if e.deadline_s is not None and now >= e.deadline_s:
                # Expired in the queue: 504 upstream, no device work.
                # Stamp the waits first so the 504's wide event bills
                # this wall to queue_wait/batch_wait — NOT to the
                # waiter's resume residual, which would misread pure
                # overload as thread contention.
                self._stamp_waits(e, now)
                self._m_shed.inc(model=self.model, reason="expired")
                e.finish(error=DeadlineExceeded(
                    "deadline expired while queued for batch dispatch "
                    f"({(now - e.deadline_s) * 1e3:.0f}ms over budget)"))
                continue
            live.append(e)
        if not live:
            return 0
        batch_id = uuid.uuid4().hex[:12]
        # Per-dispatch stage sink: library code under the dispatch (the
        # retrieval facade) records stages here; the result is fanned out
        # to every member's waterfall below — one corpus scan, one shared
        # "retrieval" reading per cohort.
        sink = Waterfall()
        # The cohort shares ONE retrieval scan, so it shares one recall
        # sampling decision: carry the first member's per-request draw
        # (ISSUE 11 shared-u contract) onto the dispatch sink, where the
        # retrieval facade's recall capture reads it.
        for e in live:
            wf = e.waterfall
            if wf is not None and wf.sample_u is not None:
                sink.sample_u = wf.sample_u
                break
        t0 = self.clock.now()
        # queue_wait/batch_wait are fully determined at dispatch start —
        # stamp them NOW, on every outcome path (success, failure, retry),
        # so no finish path leaks its wait into the resume residual.
        for e in live:
            self._stamp_waits(e, t0)
        try:
            # The dispatch is its own root trace (the batcher thread has
            # no request context): the ring shows every coalesced device
            # dispatch, and member requests join it by batch_id via the
            # zero-duration event attached to their spans below.
            with _trace("batcher.dispatch", model=self.model,
                        batch_id=batch_id, batch_size=len(live)) as troot:
                with dispatch_sink(sink):
                    results, generation = self.dispatch_fn(
                        [e.query for e in live])
                if len(results) != len(live):
                    raise ValueError(
                        f"dispatch returned {len(results)} results for "
                        f"{len(live)} queries")
                troot.set(generation=generation)
        except Exception as exc:
            # The failed attempt's device time is real wall the members
            # waited through — bill it (stamps accumulate by design: a
            # retried dispatch bills both attempts).
            dt_fail = (self.clock.now() - t0) * 1e3
            for e in live:
                if e.waterfall is not None:
                    e.waterfall.stamp("dispatch", dt_fail,
                                      batchSize=len(live), failed=True,
                                      model=self.model)
            if len(live) == 1:
                # Retrying a singleton would replay the IDENTICAL call —
                # pure double work for the same error.
                live[0].finish(error=exc)
            else:
                self._finish_individually(live, batch_id)
            return len(live)
        dt = self.clock.now() - t0
        # EWMA (alpha .25): reactive enough to track a model swap,
        # smooth enough that one slow dispatch doesn't shed the queue.
        self._est_dispatch_s = (0.75 * self._est_dispatch_s + 0.25 * dt
                                if self._est_dispatch_s else dt)
        n = len(live)
        self._m_dispatches.inc(model=self.model)
        self._m_requests.inc(n, model=self.model)
        self._m_batch_size.observe(n, model=self.model)
        self._m_dispatch_ms.observe(dt * 1e3, model=self.model)
        sink_stages, sink_attrs = sink.export()
        for e, r in zip(live, results):
            wait_ms = (t0 - e.enqueued_s) * 1e3
            self._m_wait_ms.observe(wait_ms, model=self.model)
            self._m_coalesce.observe(1.0 / n, model=self.model)
            if e.waterfall is not None:
                # queue_wait/batch_wait already stamped at dispatch start.
                e.waterfall.stamp("dispatch", dt * 1e3,
                                  batchSize=n, generation=generation,
                                  model=self.model)
                e.waterfall.merge(sink_stages, **sink_attrs)
            # Join the dispatch to the member request's own span tree:
            # its trace now shows which batch carried it, how big the
            # cohort was, and which model generation answered.  Routed
            # through Pending.annotate — a waiter that already walked
            # (deadline) may be serializing that tree concurrently.
            e.annotate(attach_event, "batcher.dispatch", batch_id=batch_id,
                       model=self.model, batch_size=n,
                       queue_wait_ms=round(wait_ms, 3),
                       dispatch_ms=round(dt * 1e3, 3),
                       generation=generation)
            if self.autotuner is not None:
                self.autotuner.observe((self.clock.now() - e.enqueued_s)
                                       * 1e3)
            e.finish(result=r)
        if self.autotuner is not None:
            self.autotuner.after_dispatch(self)
        return n

    def _finish_individually(self, live: List[Pending],
                             batch_id: str) -> None:
        """Batch dispatch raised: isolate the failure per member so one
        poisoned query cannot 500 its cohort."""
        for e in live:
            # Re-check each member's budget: deadlines keep expiring
            # during the failed attempt and these serial retries, and a
            # systemic failure (dead backend) must not be amplified
            # N-fold with device work whose 200s get discarded anyway.
            now = self.clock.now()
            if e.deadline_s is not None and now >= e.deadline_s:
                self._m_shed.inc(model=self.model, reason="expired")
                e.finish(error=DeadlineExceeded(
                    "deadline expired during batch retry "
                    f"({(now - e.deadline_s) * 1e3:.0f}ms over budget)"))
                continue
            t1 = self.clock.now()
            try:
                sink = Waterfall()
                if e.waterfall is not None:
                    sink.sample_u = e.waterfall.sample_u
                with dispatch_sink(sink):
                    results, generation = self.dispatch_fn([e.query])
                if e.waterfall is not None:
                    # Waits already stamped at the failed batch's start;
                    # this retry's dispatch accumulates onto the failed
                    # attempt's — a retried dispatch bills both.
                    e.waterfall.stamp(
                        "dispatch", (self.clock.now() - t1) * 1e3,
                        batchSize=1, isolated=True,
                        generation=generation, model=self.model)
                    stages, attrs = sink.export()
                    e.waterfall.merge(stages, **attrs)
                e.annotate(attach_event, "batcher.dispatch",
                           batch_id=batch_id, model=self.model,
                           batch_size=1, isolated=True,
                           generation=generation)
                self._m_dispatches.inc(model=self.model)
                self._m_requests.inc(model=self.model)
                self._m_batch_size.observe(1, model=self.model)
                self._m_coalesce.observe(1.0, model=self.model)
                e.finish(result=results[0])
            except Exception as exc:  # noqa: BLE001 - per-item verdict
                if e.waterfall is not None:
                    e.waterfall.stamp(
                        "dispatch", (self.clock.now() - t1) * 1e3,
                        batchSize=1, isolated=True, failed=True,
                        model=self.model)
                e.finish(error=exc)

    # -- loop / lifecycle ---------------------------------------------------

    def run_once(self) -> int:
        """One gather+dispatch cycle (the unit tests' entry point)."""
        batch = self.gather()
        if not batch:
            return 0
        return self.dispatch(batch)

    def _loop(self) -> None:
        while not self.queue.closed():
            try:
                self.run_once()
            except Exception:
                # The dispatcher thread must survive anything — a dead
                # batcher turns every request into a stall timeout.
                logger.exception("micro-batcher loop error (model %s)",
                                 self.model)

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name=f"pio-batcher-{self.model}",
            daemon=True)
        self._thread.start()

    def close(self, timeout_s: float = 5.0) -> None:
        """Stop the loop and fail whatever is still queued (503)."""
        self.queue.close()
        if self._thread is not None:
            self._thread.join(timeout=timeout_s)
            self._thread = None
        for e in self.queue.drain():
            if e.claim():
                e.finish(error=SchedulerClosed(
                    "serving scheduler shut down before dispatch"))
