"""Serve-side result cache keyed by (generation fingerprint, canonical query).

The dominant production request shape is a repeat: Zipf-skewed user traffic
means the same hot user asks the same query seconds apart, and until this
module every repeat paid the full scheduler dispatch — admission, micro-batch
wait, retrieval rung, serialize.  This cache turns that repeat into a
dictionary read, and sidesteps the classic cache-invalidation problem the
same way the PR-13 durable fold-in cache did at the fleet tier: the
**generation fingerprint is part of the key**.  Promotion, rollback, and
refresh each swap to a different engine-instance id, so every entry filled
under the old generation misses *by construction* — no invalidation
protocol, no stale-read window.  Rollback restores the previous instance id,
so the pre-promotion entries become valid again for free.

Layout
------
- :func:`canonical_query` — ONE serialization for the query half of the key:
  sorted keys, fields equal to the query dataclass defaults stripped (so an
  explicit ``num=10`` and an omitted ``num`` share an entry), integral
  floats normalized (``10.0`` == ``10``), compact separators.  Queries that
  carry per-request state (``exclude`` lists etc.) serialize it verbatim and
  therefore key *distinctly* — correct, but a cache-hit-rate tax documented
  in the README ("when NOT to cache").
- :class:`ResultCache` — per-instance LRU bounded by entries AND bytes, an
  optional fleet tier riding the PR-13 shared ``KV`` trait (write-through on
  positive fill, read-through on local miss, blips degrade to LRU-only with
  a cooldown so a dead KV costs one timeout per cooldown window, not one per
  request), and short-TTL negative caching so an unknown-entity query storm
  doesn't punch through to the fold-in path on every request.

Mid-flight swap safety: the handler fills under the generation the PR-6
batcher *stamped on the waterfall at dispatch*, not under "whatever is
current at hand-back".  :meth:`ResultCache.fill` resolves that stamped
generation through a bounded generation→fingerprint map maintained by
:meth:`on_generation`; a generation the map no longer knows drops the fill
(counted, never mis-keyed).

Knobs (prefix ``PIO_RESULT_CACHE``; kill switch registers ZERO instruments):

======================================  =====================================
``PIO_RESULT_CACHE``                    master switch (default on)
``PIO_RESULT_CACHE_SIZE``               max entries per instance (10000)
``PIO_RESULT_CACHE_BYTES``              max serialized bytes (64 MiB)
``PIO_RESULT_CACHE_NEG_TTL_S``          empty-result TTL seconds (5.0)
``PIO_RESULT_CACHE_SHARED``             fleet tier over the shared KV (off)
======================================  =====================================

All ``pio_result_cache_*`` instruments register in THIS module and nowhere
else — ``tools/lint_cache.py`` enforces it, same single-owner rule the
quality and recall families live under.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, Optional, Tuple

from predictionio_tpu.config import env_bool
from predictionio_tpu.obs.metrics import MetricsRegistry, get_registry

__all__ = [
    "CacheHit",
    "RESULT_CACHE_METRICS",
    "ResultCache",
    "ResultCacheConfig",
    "canonical_query",
    "query_defaults",
]

logger = logging.getLogger("predictionio_tpu.serving.result_cache")

#: every instrument this module owns (kill-switch tests assert ZERO of these
#: exist when ``PIO_RESULT_CACHE=off``).
RESULT_CACHE_METRICS = (
    "pio_result_cache_hits_total",
    "pio_result_cache_misses_total",
    "pio_result_cache_fills_total",
    "pio_result_cache_evictions_total",
    "pio_result_cache_entries",
    "pio_result_cache_bytes",
    "pio_result_cache_hit_rate",
    "pio_result_cache_hit_age_s",
    "pio_result_cache_shared_errors_total",
)

#: age-at-hit buckets (seconds).  The interesting question is "how stale is
#: the fast path" — sub-second through the half-hour an LRU-resident entry
#: can plausibly live between promotions.
HIT_AGE_BUCKETS_S = (0.1, 0.5, 1.0, 5.0, 15.0, 60.0, 300.0, 1800.0)

#: generations remembered for fill attribution.  Requests outlive at most a
#: handful of swaps (deadline-bounded), so a short tail is plenty; anything
#: older is dropped rather than risked against a recycled id.
_GEN_MAP_KEEP = 8

#: after a shared-KV error, stay local-only this long (seconds) so a dead
#: backplane costs one failed round-trip per window, not one per request.
_SHARED_COOLDOWN_S = 30.0

#: write-throughs between shared-tier prunes (mirrors the fold-in cache's
#: every-256th-put cadence).
_SHARED_PRUNE_EVERY = 256


# --------------------------------------------------------------------------
# canonical query serialization
# --------------------------------------------------------------------------

_defaults_cache: Dict[type, Dict[str, Any]] = {}
_defaults_lock = threading.Lock()


def query_defaults(query_class: type) -> Dict[str, Any]:
    """Field-name → default for a query dataclass (memoized per class).

    ``default_factory`` fields are materialized ONCE; factories on query
    dataclasses produce empty containers, which compare by value, so a
    single materialization is safe to reuse for equality checks.
    """
    with _defaults_lock:
        d = _defaults_cache.get(query_class)
        if d is not None:
            return d
    out: Dict[str, Any] = {}
    if dataclasses.is_dataclass(query_class):
        for f in dataclasses.fields(query_class):
            if f.default is not dataclasses.MISSING:
                out[f.name] = f.default
            elif f.default_factory is not dataclasses.MISSING:
                out[f.name] = f.default_factory()
    with _defaults_lock:
        _defaults_cache[query_class] = out
    return out


def _canon_value(v: Any) -> Any:
    """Normalize one value: integral floats become ints (``10.0`` and ``10``
    are the same query), containers recurse.  Anything json.dumps can't
    handle surfaces as TypeError at serialization time — the caller treats
    that query as uncacheable."""
    if isinstance(v, float) and v.is_integer():
        return int(v)
    if isinstance(v, dict):
        return {k: _canon_value(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_canon_value(x) for x in v]
    if isinstance(v, (set, frozenset)):
        return sorted(_canon_value(x) for x in v)
    return v


def canonical_query(query: Any,
                    defaults: Optional[Dict[str, Any]] = None) -> str:
    """THE canonical serialization of a query for cache keying.

    Accepts the bound query dataclass (the normal server path) or a plain
    dict (tests, tools).  Fields whose value equals the query class default
    are stripped, so ``{"user": "u1"}`` and ``{"user": "u1", "num": 10}``
    share an entry when 10 is the default; key order never matters (sorted
    keys); integral floats normalize (JSON clients that send ``num: 10.0``).

    Raises TypeError for values JSON can't represent — callers bypass the
    cache for such queries rather than guessing at a key.
    """
    if dataclasses.is_dataclass(query) and not isinstance(query, type):
        if defaults is None:
            defaults = query_defaults(type(query))
        doc = {f.name: getattr(query, f.name)
               for f in dataclasses.fields(query)}
    elif isinstance(query, dict):
        doc = dict(query)
        defaults = defaults or {}
    else:
        raise TypeError(f"uncacheable query type {type(query).__name__}")
    canon = {}
    for k, v in doc.items():
        cv = _canon_value(v)
        if k in defaults and cv == _canon_value(defaults[k]):
            continue
        canon[k] = cv
    return json.dumps(canon, sort_keys=True, separators=(",", ":"))


# --------------------------------------------------------------------------
# config
# --------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class ResultCacheConfig:
    enabled: bool = True
    max_entries: int = 10000
    max_bytes: int = 64 * 1024 * 1024
    neg_ttl_s: float = 5.0
    shared: bool = False

    @classmethod
    def from_env(cls, env=None) -> "ResultCacheConfig":
        import os

        env = os.environ if env is None else env

        def _i(key: str, default: int) -> int:
            raw = env.get(key)
            if raw is None or not str(raw).strip():
                return default
            try:
                return max(0, int(str(raw).strip()))
            except ValueError:
                logger.warning("bad %s=%r; using %s", key, raw, default)
                return default

        def _f(key: str, default: float) -> float:
            raw = env.get(key)
            if raw is None or not str(raw).strip():
                return default
            try:
                return max(0.0, float(str(raw).strip()))
            except ValueError:
                logger.warning("bad %s=%r; using %s", key, raw, default)
                return default

        return cls(
            enabled=env_bool(env.get("PIO_RESULT_CACHE"), True),
            max_entries=_i("PIO_RESULT_CACHE_SIZE", 10000),
            max_bytes=_i("PIO_RESULT_CACHE_BYTES", 64 * 1024 * 1024),
            neg_ttl_s=_f("PIO_RESULT_CACHE_NEG_TTL_S", 5.0),
            shared=env_bool(env.get("PIO_RESULT_CACHE_SHARED"), False),
        )


# --------------------------------------------------------------------------
# cache proper
# --------------------------------------------------------------------------

@dataclasses.dataclass
class CacheHit:
    """What :meth:`ResultCache.lookup` hands the request handler.

    ``result_json`` is the cached serialization itself: the hit path hands
    ``result_bytes`` straight to the transport, so a hit never pays a
    parse + re-dump of a document that is already exactly the response
    body.  ``result`` deserializes FRESH per access — handlers and plugins
    that do want the document (the sampled quality record) may annotate it
    without corrupting the cached entry.  ``generation`` is the generation
    the entry was *filled* under: the hit path stamps it on the waterfall
    so attribution and the quality layer's serve-id semantics describe the
    answer actually served.
    """

    result_json: str
    generation: int
    fingerprint: str
    age_s: float
    tier: str            # "local" | "shared"
    negative: bool

    @property
    def result(self) -> Any:
        return json.loads(self.result_json)

    @property
    def result_bytes(self) -> bytes:
        return self.result_json.encode("utf-8")


class _Entry:
    __slots__ = ("value_json", "generation", "filled_at", "filled_wall",
                 "negative", "nbytes")

    def __init__(self, value_json: str, generation: int, filled_at: float,
                 filled_wall: float, negative: bool):
        self.value_json = value_json
        self.generation = generation
        self.filled_at = filled_at
        self.filled_wall = filled_wall
        self.negative = negative
        self.nbytes = len(value_json)


class ResultCache:
    """Per-instance LRU + optional shared fleet tier, generation-keyed.

    Thread-safe; the LRU lock is held only for dict work, never across KV
    I/O.  A KV blip never fails a request: the shared tier degrades to
    LRU-only and retries after a cooldown.
    """

    def __init__(self, config: Optional[ResultCacheConfig] = None, *,
                 registry: Optional[MetricsRegistry] = None,
                 kv: Any = None,
                 clock: Callable[[], float] = time.monotonic,
                 wall_clock: Callable[[], float] = time.time):
        self.config = config or ResultCacheConfig.from_env()
        self._registry = registry or get_registry()
        self._kv = kv
        self._clock = clock
        self._wall = wall_clock
        self._lock = threading.Lock()
        self._entries: "OrderedDict[Tuple[str, str], _Entry]" = OrderedDict()
        self._bytes = 0
        self._enabled = self.config.enabled
        self._generation: Optional[int] = None
        self._fingerprint: Optional[str] = None
        self._gen_fp: "OrderedDict[int, str]" = OrderedDict()
        self._hits = 0
        self._misses = 0
        self._puts = 0
        self._shared_down_until = 0.0
        self._metrics_ready = False
        if self._enabled:
            self._ensure_metrics()

    # -- instruments (single-owner family; zero with the kill switch) ------

    def _ensure_metrics(self) -> None:
        if self._metrics_ready:
            return
        r = self._registry
        self._m_hits = r.counter(
            "pio_result_cache_hits_total",
            "result-cache hits by tier", ("tier",))
        self._m_misses = r.counter(
            "pio_result_cache_misses_total", "result-cache misses")
        self._m_fills = r.counter(
            "pio_result_cache_fills_total",
            "result-cache fills by kind", ("kind",))
        self._m_evict = r.counter(
            "pio_result_cache_evictions_total", "entries evicted (LRU)")
        self._m_entries = r.gauge(
            "pio_result_cache_entries", "resident entries")
        self._m_bytes = r.gauge(
            "pio_result_cache_bytes", "resident serialized bytes")
        self._m_rate = r.gauge(
            "pio_result_cache_hit_rate", "hits / lookups since start")
        self._m_age = r.histogram(
            "pio_result_cache_hit_age_s", "entry age at hit (seconds)",
            buckets=HIT_AGE_BUCKETS_S)
        self._m_shared_err = r.counter(
            "pio_result_cache_shared_errors_total",
            "shared-tier KV errors (degraded to local)")
        self._metrics_ready = True

    # -- lifecycle ---------------------------------------------------------

    @property
    def enabled(self) -> bool:
        return self._enabled

    def set_enabled(self, flag: bool) -> None:
        """Runtime toggle (bench A/B).  Enabling late registers the
        instrument family on first use."""
        self._enabled = bool(flag)
        if self._enabled:
            self._ensure_metrics()

    def on_generation(self, generation: int, fingerprint: str) -> None:
        """Swap the active (generation, fingerprint) pair.

        Called under the server's swap lock at reload/rollback.  Old
        entries stay resident keyed by their own fingerprint — a rollback
        that restores a previous instance id revalidates them for free;
        otherwise LRU churn retires them.
        """
        with self._lock:
            self._generation = int(generation)
            self._fingerprint = str(fingerprint)
            self._gen_fp[self._generation] = self._fingerprint
            while len(self._gen_fp) > _GEN_MAP_KEEP:
                self._gen_fp.popitem(last=False)

    def clear(self) -> None:
        """Drop every resident entry (counters keep their history)."""
        with self._lock:
            self._entries.clear()
            self._bytes = 0
        if self._metrics_ready:
            self._m_entries.set(0)
            self._m_bytes.set(0)

    # -- read path ---------------------------------------------------------

    def lookup(self, canon: str) -> Optional[CacheHit]:
        """Local LRU first, then (on miss) the shared tier.  Negative
        entries past their TTL are retired inline and count as misses."""
        if not self._enabled:
            return None
        now = self._clock()
        fp = self._fingerprint
        if fp is None:
            return None
        key = (fp, canon)
        with self._lock:
            e = self._entries.get(key)
            if e is not None:
                if e.negative and now - e.filled_at > self.config.neg_ttl_s:
                    self._entries.pop(key, None)
                    self._bytes -= e.nbytes + len(canon)
                    e = None
                else:
                    self._entries.move_to_end(key)
        if e is not None:
            self._hits += 1
            age = max(0.0, now - e.filled_at)
            self._m_hits.inc(tier="local")
            self._m_age.observe(age)
            self._note_rate()
            return CacheHit(result_json=e.value_json,
                            generation=e.generation, fingerprint=fp,
                            age_s=age, tier="local", negative=e.negative)
        hit = self._shared_get(fp, canon, now)
        if hit is not None:
            self._hits += 1
            self._m_hits.inc(tier="shared")
            self._m_age.observe(hit.age_s)
            self._note_rate()
            return hit
        self._misses += 1
        self._m_misses.inc()
        self._note_rate()
        return None

    # -- write path --------------------------------------------------------

    def fill(self, canon: str, result: Any, generation: Optional[int],
             ) -> str:
        """Insert a scheduler hand-back under the generation the batcher
        STAMPED at dispatch — never "current".  Returns the fill kind:
        ``positive`` | ``negative`` | ``dropped`` | ``disabled``.

        A generation the map no longer knows (ancient in-flight request
        racing many swaps) is dropped: mis-keying generation A's answer
        under B's fingerprint is the one corruption this design must never
        allow.
        """
        if not self._enabled:
            return "disabled"
        if generation is None:
            self._m_fills.inc(kind="dropped")
            return "dropped"
        with self._lock:
            fp = self._gen_fp.get(int(generation))
        if fp is None:
            self._m_fills.inc(kind="dropped")
            return "dropped"
        try:
            value_json = json.dumps(result, separators=(",", ":"))
        except (TypeError, ValueError):
            self._m_fills.inc(kind="dropped")
            return "dropped"
        negative = self._is_negative(result)
        now = self._clock()
        wall = self._wall()
        e = _Entry(value_json, int(generation), now, wall, negative)
        key = (fp, canon)
        evicted = 0
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.nbytes + len(canon)
            self._entries[key] = e
            self._bytes += e.nbytes + len(canon)
            while self._entries and (
                    len(self._entries) > self.config.max_entries
                    or self._bytes > self.config.max_bytes):
                k, v = self._entries.popitem(last=False)
                self._bytes -= v.nbytes + len(k[1])
                evicted += 1
            n, b = len(self._entries), self._bytes
        if evicted:
            self._m_evict.inc(evicted)
        self._m_entries.set(n)
        self._m_bytes.set(max(0, b))
        kind = "negative" if negative else "positive"
        self._m_fills.inc(kind=kind)
        if not negative:
            # negatives are NEVER shared: one instance's fold-in gap is not
            # fleet truth, and a 5 s local TTL does not survive a KV hop.
            self._shared_put(fp, canon, value_json, int(generation), wall)
        return kind

    # -- shared tier (PR-13 KV trait; blips degrade to LRU-only) -----------

    @staticmethod
    def _ns(fingerprint: str) -> str:
        return f"resultcache:{fingerprint}"

    @staticmethod
    def _shared_key(canon: str) -> str:
        return hashlib.sha1(canon.encode("utf-8")).hexdigest()

    def _shared_ok(self, now: float) -> bool:
        return (self.config.shared and self._kv is not None
                and now >= self._shared_down_until)

    def _shared_trip(self, now: float, what: str) -> None:
        self._m_shared_err.inc()
        self._shared_down_until = now + _SHARED_COOLDOWN_S
        logger.warning("result-cache shared tier %s failed; local-only for "
                       "%.0fs", what, _SHARED_COOLDOWN_S, exc_info=True)

    def _shared_get(self, fp: str, canon: str, now: float,
                    ) -> Optional[CacheHit]:
        if not self._shared_ok(now):
            return None
        try:
            raw = self._kv.get(self._ns(fp), self._shared_key(canon))
        except Exception:
            self._shared_trip(now, "get")
            return None
        if raw is None:
            return None
        try:
            doc = json.loads(raw.decode("utf-8"))
            value_json = json.dumps(doc["r"], separators=(",", ":"))
            gen = int(doc["g"])
            age = max(0.0, self._wall() - float(doc["t"]))
        except Exception:
            return None  # foreign bytes in the namespace: treat as miss
        # adopt into the local LRU so the next hit skips the KV round-trip;
        # filled_at is back-dated so age-at-hit stays honest.
        e = _Entry(value_json, gen, now - age, float(doc["t"]), False)
        key = (fp, canon)
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.nbytes + len(canon)
            self._entries[key] = e
            self._bytes += e.nbytes + len(canon)
            n, b = len(self._entries), self._bytes
        self._m_entries.set(n)
        self._m_bytes.set(max(0, b))
        return CacheHit(result_json=value_json, generation=gen,
                        fingerprint=fp, age_s=age, tier="shared",
                        negative=False)

    def _shared_put(self, fp: str, canon: str, value_json: str,
                    generation: int, wall: float) -> None:
        now = self._clock()
        if not self._shared_ok(now):
            return
        payload = json.dumps(
            {"r": json.loads(value_json), "g": generation, "t": wall},
            separators=(",", ":")).encode("utf-8")
        try:
            self._kv.put(self._ns(fp), self._shared_key(canon), payload)
            self._puts += 1
            if self._puts % _SHARED_PRUNE_EVERY == 0:
                self._kv.prune(self._ns(fp), keep=self.config.max_entries)
        except Exception:
            self._shared_trip(now, "put")

    # -- views -------------------------------------------------------------

    def _note_rate(self) -> None:
        total = self._hits + self._misses
        if total:
            self._m_rate.set(self._hits / total)

    def _is_negative(self, result: Any) -> bool:
        from predictionio_tpu.obs.quality import extract_result_items

        return extract_result_items(result) == []

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            n, b = len(self._entries), self._bytes
            gen, fp = self._generation, self._fingerprint
        total = self._hits + self._misses
        return {
            "enabled": self._enabled,
            "entries": n,
            "bytes": max(0, b),
            "maxEntries": self.config.max_entries,
            "maxBytes": self.config.max_bytes,
            "hits": self._hits,
            "misses": self._misses,
            "hitRate": (self._hits / total) if total else None,
            "negTtlS": self.config.neg_ttl_s,
            "shared": bool(self.config.shared and self._kv is not None),
            "generation": gen,
            "fingerprint": fp,
        }
