"""Admission-controlled request queue for the serving scheduler.

The bounded per-model queue is the admission-control half of continuous
batching: a server that accepts every connection and lets requests pile
up behind a saturated device turns overload into unbounded latency for
EVERYONE (the classic "accept-queue death spiral").  A bounded queue
rejects the marginal request fast — HTTP 429 + ``Retry-After`` — so the
requests already admitted keep their latency and the client knows to
back off (this composes with the deadline/shedding transport in
``server/http.py`` rather than replacing it).

Pieces:

- :class:`Clock` / :class:`MonotonicClock` — the scheduler's time source.
  ``wait`` is ON the clock so tests drive the batcher with a fake clock
  and zero wall sleeps (the same injectable-clock discipline as
  ``resilience.supervision``).
- :class:`Pending` — one submitted query's lifecycle: ``queued`` →
  ``claimed`` (a batcher owns it) → ``done``; or ``queued`` →
  ``abandoned`` when the submitting thread gave up (deadline) before any
  batch took it.  The claim/abandon race is settled by one lock so a
  request is never both answered and re-dispatched.
- :class:`ModelQueue` — bounded FIFO + condition variable, one per
  registered model, with depth gauges and shed counters.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, List, Optional

__all__ = [
    "Clock",
    "MonotonicClock",
    "Pending",
    "ModelQueue",
    "QueueFull",
    "SchedulerClosed",
    "SchedulerStalled",
]


class QueueFull(RuntimeError):
    """Admission rejected: the model's queue is at capacity (HTTP 429)."""

    retriable = True


class SchedulerClosed(RuntimeError):
    """Submitted to a scheduler that is shutting down (HTTP 503)."""

    retriable = True


class SchedulerStalled(RuntimeError):
    """A pending query saw no dispatch within the stall budget — the
    batcher thread is wedged or the dispatch fn hung (HTTP 503)."""

    retriable = True


class Clock:
    """Time source + condition wait, both injectable.

    ``wait`` takes the condition variable so a fake clock can ADVANCE
    TIME instead of sleeping — the deadline-window tests run the full
    gather/dispatch logic with zero wall-clock waits.
    """

    def now(self) -> float:  # pragma: no cover - interface
        raise NotImplementedError

    def wait(self, cond: threading.Condition,
             timeout: Optional[float]) -> bool:  # pragma: no cover
        raise NotImplementedError


class MonotonicClock(Clock):
    def now(self) -> float:
        return time.monotonic()

    def wait(self, cond: threading.Condition,
             timeout: Optional[float]) -> bool:
        return cond.wait(timeout)


class Pending:
    """One admitted query awaiting its batch.

    The submitting (HTTP handler) thread blocks on :meth:`wait_done`;
    the batcher thread claims, dispatches, and :meth:`finish`\\ es it.
    ``span`` carries the submitting request's open trace span so the
    batcher can attach its ``batcher.dispatch`` event to the request's
    own tree (the handler thread is parked in ``wait_done`` while the
    batcher writes, so the append is race-free).
    """

    __slots__ = ("query", "enqueued_s", "deadline_s", "span", "state",
                 "result", "error", "walked", "_lock", "_done",
                 "gathered_s", "waterfall")

    QUEUED = "queued"
    CLAIMED = "claimed"
    ABANDONED = "abandoned"
    DONE = "done"

    def __init__(self, query: Any, enqueued_s: float,
                 deadline_s: Optional[float] = None, span: Any = None,
                 waterfall: Any = None):
        self.query = query
        self.enqueued_s = enqueued_s
        self.deadline_s = deadline_s
        self.span = span
        self.state = Pending.QUEUED
        self.result: Any = None
        self.error: Optional[BaseException] = None
        # True once the submitting thread stopped waiting (deadline) —
        # its span tree may be serializing, so no one may touch it.
        self.walked = False
        # Stamped by the batcher when a gather picks the entry up —
        # splits the admission→dispatch wait into queue_wait (admission →
        # pickup) and batch_wait (pickup → dispatch start).
        self.gathered_s: Optional[float] = None
        # The submitting request's stage collector (obs.waterfall); the
        # collector is internally locked and close-once, so cross-thread
        # stamps from the batcher are safe even against a walked waiter.
        self.waterfall = waterfall
        self._lock = threading.Lock()
        self._done = threading.Event()

    def claim(self) -> bool:
        """Batcher takes ownership; False if the waiter already walked."""
        with self._lock:
            if self.state != Pending.QUEUED:
                return False
            self.state = Pending.CLAIMED
            return True

    def abandon(self) -> bool:
        """Waiter gives up (deadline); False if a batch already owns it."""
        with self._lock:
            self.walked = True
            if self.state != Pending.QUEUED:
                return False
            self.state = Pending.ABANDONED
            return True

    def annotate(self, attach, name: str, **attrs) -> None:
        """Attach a trace event to the submitter's span — but ONLY while
        the submitter is still parked in :meth:`wait_done`.  A waiter
        that walked (deadline) may be serializing its span tree right
        now; the shared lock with :meth:`abandon` makes walk-vs-annotate
        atomic, so the tree is never mutated mid-record."""
        with self._lock:
            if not self.walked:
                attach(self.span, name, **attrs)

    def finish(self, result: Any = None,
               error: Optional[BaseException] = None) -> None:
        with self._lock:
            self.state = Pending.DONE
        self.result = result
        self.error = error
        self._done.set()

    def wait_done(self, timeout: Optional[float]) -> bool:
        return self._done.wait(timeout)


class ModelQueue:
    """Bounded FIFO of :class:`Pending` entries for ONE model.

    ``depth`` is the per-model concurrency limit: queued-but-undispatched
    requests.  Depth 0 is legal and means "no queueing at all" — every
    submit rejects, which the admission tests use to force deterministic
    429s.
    """

    def __init__(self, name: str, depth: int,
                 on_depth: Optional[Callable[[int], None]] = None):
        self.name = name
        self.depth = int(depth)
        self._items: List[Pending] = []
        self._cond = threading.Condition()
        self._closed = False
        # gauge hook (queue depth after every put/take), injected by the
        # scheduler so this module stays metrics-agnostic.
        self._on_depth = on_depth

    def __len__(self) -> int:
        with self._cond:
            return len(self._items)

    def put(self, entry: Pending) -> None:
        with self._cond:
            if self._closed:
                raise SchedulerClosed(
                    f"serving scheduler for model {self.name!r} is closed")
            if len(self._items) >= self.depth:
                # Before rejecting, sweep corpses: entries whose waiter
                # abandoned (deadline) still sit here until a gather
                # drains them — they must not hold admission slots
                # against live traffic during a long dispatch.
                self._items = [e for e in self._items
                               if e.state == Pending.QUEUED]
            if len(self._items) >= self.depth:
                raise QueueFull(
                    f"model {self.name!r} queue full "
                    f"({len(self._items)}/{self.depth} queued)")
            self._items.append(entry)
            if self._on_depth:
                self._on_depth(len(self._items))
            self._cond.notify()

    def take(self, clock: Clock,
             timeout: Optional[float] = None) -> Optional[Pending]:
        """Pop the oldest entry, waiting up to ``timeout`` (None = until
        an item or close).  Returns None on timeout or close — the caller
        distinguishes via :meth:`closed`."""
        with self._cond:
            while not self._items:
                if self._closed:
                    return None
                if timeout is not None and timeout <= 0:
                    return None
                if not clock.wait(self._cond, timeout) and timeout is not None:
                    # timed out; re-check once in case of a late notify
                    if not self._items:
                        return None
            entry = self._items.pop(0)
            # Gauge updates stay under the lock: put/take callbacks
            # interleaving after release would publish depths out of
            # order and freeze a stale reading on the status page.
            if self._on_depth:
                self._on_depth(len(self._items))
        return entry

    def drain(self) -> List[Pending]:
        """Remove and return everything queued (close path)."""
        with self._cond:
            items, self._items = self._items, []
            if self._on_depth:
                self._on_depth(0)
        return items

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def closed(self) -> bool:
        with self._cond:
            return self._closed
