"""Event model, data types, and storage abstraction.

Reference layer: data/src/main/scala/org/apache/predictionio/data/
(upstream Apache PredictionIO path; the reference mount was empty at survey
time — see SURVEY.md header).
"""

from predictionio_tpu.data.prefetch import DevicePrefetcher, PrefetchedBatch
from predictionio_tpu.data.event import (
    BiMap,
    DataMap,
    DataMapError,
    Event,
    EventValidationError,
    PropertyMap,
    aggregate_properties,
    is_reserved_event,
    validate_event,
)

__all__ = [
    "DevicePrefetcher",
    "PrefetchedBatch",
    "BiMap",
    "DataMap",
    "DataMapError",
    "Event",
    "EventValidationError",
    "PropertyMap",
    "aggregate_properties",
    "is_reserved_event",
    "validate_event",
]
