"""Append-only Parquet event log — the batch-training-optimized event store.

Reference analogue: storage/hbase/ (HBPEvents' full-scan RDD reads) —
SURVEY.md §2.1.  Where HBase serves Spark `newAPIHadoopRDD` scans, this
backend serves columnar `pyarrow` scans that feed host-sharded `jax.Array`
construction directly (zero row materialization on the training path).

Layout: ``<root>/app_<id>/<channel|default>/part-<uuid>.parquet``; one file
per flushed batch.  Deletion of single events rewrites the owning part file
(rare path); `remove` drops the directory.
"""

from __future__ import annotations

import datetime as _dt
import json
import threading
import uuid
from pathlib import Path
from typing import Dict, Iterator, List, Optional, Sequence

import pyarrow as pa
import pyarrow.compute as pc
import pyarrow.parquet as pq

from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage import base
from predictionio_tpu.data.storage.base import EVENT_ARROW_SCHEMA

__all__ = ["ParquetEvents"]


def _us(dt: _dt.datetime) -> int:
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=_dt.timezone.utc)
    return int(dt.timestamp() * 1_000_000)


class ParquetEvents(base.Events):
    """Single-event inserts are buffered in memory and flushed as one part
    file per :data:`FLUSH_THRESHOLD` events (or on any read/close) — an
    event-per-file layout would make every scan O(#events) file opens."""

    FLUSH_THRESHOLD = 256

    def __init__(self, root: str):
        self.root = Path(root)
        self._lock = threading.RLock()
        self._pending: Dict[tuple, List[Event]] = {}
        # Bulk-ingest dedup index (ISSUE 17): token-derived event ids
        # already on disk, per (app, channel).  Seeded lazily with ONE
        # projected scan of the event_id column, then maintained
        # incrementally — parquet has no primary key to conflict on, so
        # create_batch's per-item exactly-once lives here.
        self._batch_ids: Dict[tuple, set] = {}

    def _dir(self, app_id: int, channel_id: Optional[int]) -> Path:
        chan = "default" if channel_id is None else str(channel_id)
        return self.root / f"app_{app_id}" / chan

    def init(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        self._dir(app_id, channel_id).mkdir(parents=True, exist_ok=True)
        return True

    def remove(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        import shutil

        with self._lock:
            self._pending.pop((app_id, channel_id), None)
            self._batch_ids.pop((app_id, channel_id), None)
            d = self._dir(app_id, channel_id)
            if not d.exists():
                return False
            shutil.rmtree(d)
            return True

    def close(self) -> None:
        self.flush()

    def _check_init(self, app_id: int, channel_id: Optional[int]) -> Path:
        d = self._dir(app_id, channel_id)
        if not d.is_dir():
            raise base.StorageError(
                f"Events store for app {app_id} channel {channel_id} not initialized."
            )
        return d

    def insert(self, event: Event, app_id: int, channel_id: Optional[int] = None) -> str:
        self._check_init(app_id, channel_id)
        eid = uuid.uuid4().hex  # store-assigned, any client id ignored
        with self._lock:
            pending = self._pending.setdefault((app_id, channel_id), [])
            pending.append(event.with_event_id(eid))
            if len(pending) >= self.FLUSH_THRESHOLD:
                self._flush(app_id, channel_id)
        return eid

    def insert_batch(
        self, events: Sequence[Event], app_id: int, channel_id: Optional[int] = None
    ) -> List[str]:
        d = self._check_init(app_id, channel_id)
        stamped = []
        ids = []
        for ev in events:
            eid = uuid.uuid4().hex
            ids.append(eid)
            stamped.append(ev.with_event_id(eid))
        table = base.events_to_arrow(stamped)
        with self._lock:
            pq.write_table(table, d / f"part-{uuid.uuid4().hex}.parquet")
        return ids

    def _seen_batch_ids(self, d: Path, app_id: int,
                        channel_id: Optional[int]) -> set:
        """Token-derived ids already stored (caller holds the lock)."""
        key = (app_id, channel_id)
        seen = self._batch_ids.get(key)
        if seen is None:
            table = self._scan(d, app_id, channel_id, columns=["event_id"])
            seen = set()
            if table is not None:
                for eid in table["event_id"].to_pylist():
                    if eid and eid.startswith("bt"):
                        seen.add(eid)
            self._batch_ids[key] = seen
        return seen

    def create_batch(
        self, events: Sequence[Event], app_id: int,
        channel_id: Optional[int] = None,
        tokens: Optional[Sequence[str]] = None,
    ) -> List[str]:
        """One part file for the not-yet-landed rows; rows whose derived
        id is already on disk (prior partial landing) are skipped, so a
        replayed batch never duplicates."""
        d = self._check_init(app_id, channel_id)
        if tokens is None:
            # One uuid4 per BATCH, not per event (see sqlite.create_batch).
            pre = uuid.uuid4().hex
            tokens = [f"{pre}{i:x}" for i in range(len(events))]
        else:
            tokens = list(tokens)
        if len(tokens) != len(events):
            raise base.StorageError(
                f"create_batch: {len(events)} events but {len(tokens)} "
                "tokens")
        ids = [base.batch_event_id(t) for t in tokens]
        with self._lock:
            seen = self._seen_batch_ids(d, app_id, channel_id)
            fresh = [ev.with_event_id(eid)
                     for ev, eid in zip(events, ids) if eid not in seen]
            if fresh:
                pq.write_table(base.events_to_arrow(fresh),
                               d / f"part-{uuid.uuid4().hex}.parquet")
                seen.update(ev.event_id for ev in fresh)
        return ids

    def insert_columnar(
        self, table: pa.Table, app_id: int, channel_id: Optional[int] = None
    ) -> int:
        """Bulk columnar ingest: normalize, stamp ids with one Arrow
        kernel, write ONE part file — no per-event Python object is ever
        created.  This is the write half of the north-star data path
        (25M events land at parquet-writer speed, not event-loop speed).

        Dictionary encoding is parquet's default for strings, so
        low-cardinality columns (entity ids, the ~10 distinct rating
        property bags of ML-25M) compress to their index width on disk
        and come back dictionary-encoded on the training scan."""
        d = self._check_init(app_id, channel_id)
        table = base.stamp_event_ids(
            base.normalize_event_table(table),
            prefix=f"blk{uuid.uuid4().hex[:12]}-")
        with self._lock:
            pq.write_table(table, d / f"part-{uuid.uuid4().hex}.parquet")
        return table.num_rows

    def _flush(self, app_id: int, channel_id: Optional[int]) -> None:
        """Write buffered single-event inserts as one part file. Caller holds
        the lock (RLock: safe from both insert and the read paths)."""
        pending = self._pending.pop((app_id, channel_id), None)
        if not pending:
            return
        d = self._dir(app_id, channel_id)
        pq.write_table(base.events_to_arrow(pending),
                       d / f"part-{uuid.uuid4().hex}.parquet")

    def flush(self) -> None:
        with self._lock:
            for app_id, channel_id in list(self._pending):
                self._flush(app_id, channel_id)

    # Parquet stores low-cardinality strings dictionary-encoded anyway;
    # reading them back AS dictionary arrays keeps the training scan at
    # index width (int32 per row instead of a materialized string) and
    # hands `data.columnar.encode_ids` its O(unique) fast path.
    _DICT_COLS = ["event", "entity_type", "entity_id", "target_entity_type",
                  "target_entity_id", "properties_json", "pr_id"]

    def _scan(self, d: Path, app_id: int, channel_id: Optional[int],
              columns: Optional[Sequence[str]] = None) -> Optional[pa.Table]:
        """Caller holds the lock; flushes the write buffer first so reads
        always see every insert.  ``columns`` projects the read — parquet
        is columnar, unread columns cost nothing."""
        self._flush(app_id, channel_id)
        parts = sorted(d.glob("part-*.parquet"))
        if not parts:
            return None
        read_cols = list(columns) if columns is not None else None
        tabs = [pq.read_table(p, columns=read_cols,
                              read_dictionary=self._DICT_COLS)
                for p in parts]
        return tabs[0] if len(tabs) == 1 else pa.concat_tables(tabs)

    def _filtered(
        self, app_id, channel_id, start_time, until_time, entity_type, entity_id,
        event_names, target_entity_type, target_entity_id,
        ordered: bool = True, columns: Optional[Sequence[str]] = None,
    ) -> pa.Table:
        d = self._check_init(app_id, channel_id)
        read_cols = None
        if columns is not None:
            # filters need their columns read even when projected away
            need = set(columns)
            for col, active in (
                ("event_time_us", start_time is not None
                 or until_time is not None),
                ("creation_time_us", ordered),
                ("event_time_us", ordered),
                ("entity_type", entity_type is not None),
                ("entity_id", entity_id is not None),
                ("event", event_names is not None),
                ("target_entity_type", target_entity_type is not None),
                ("target_entity_id", target_entity_id is not None),
            ):
                if active:
                    need.add(col)
            read_cols = [f.name for f in EVENT_ARROW_SCHEMA
                         if f.name in need]
        with self._lock:
            table = self._scan(d, app_id, channel_id, columns=read_cols)
        if table is None:
            empty = EVENT_ARROW_SCHEMA.empty_table()
            return empty.select(list(columns)) if columns is not None \
                else empty
        mask = None

        def _and(m, cond):
            if cond is None:  # condition passes every row
                return m
            return cond if m is None else pc.and_(m, cond)

        def _value_mask(col, pred):
            """Row mask from a VALUE-level predicate.  For dictionary
            columns the predicate runs over the dictionary (O(unique))
            and fans out by index; ``None`` short-circuits "every row
            passes" so the common full-scan filter costs O(unique)."""
            arr = (col.combine_chunks()
                   if isinstance(col, pa.ChunkedArray) else col)
            if not pa.types.is_dictionary(arr.type):
                return pred(arr)
            if len(arr.dictionary) == 0:  # all-null column: no row matches
                import numpy as np

                return pa.array(np.zeros(len(arr), bool))
            from predictionio_tpu.data.columnar import dict_take

            vm = pred(arr.dictionary).to_numpy(zero_copy_only=False)
            if arr.null_count == 0 and vm.all():
                return None
            return pa.array(dict_take(vm, arr, False))

        if start_time is not None:
            mask = _and(mask, pc.greater_equal(table["event_time_us"], _us(start_time)))
        if until_time is not None:
            mask = _and(mask, pc.less(table["event_time_us"], _us(until_time)))
        if entity_type is not None:
            mask = _and(mask, _value_mask(
                table["entity_type"], lambda a: pc.equal(a, entity_type)))
        if entity_id is not None:
            mask = _and(mask, _value_mask(
                table["entity_id"], lambda a: pc.equal(a, entity_id)))
        if event_names is not None:
            vs = pa.array(list(event_names), type=pa.string())
            mask = _and(mask, _value_mask(
                table["event"], lambda a: pc.is_in(a, value_set=vs)))
        if target_entity_type is not None:
            mask = _and(mask, _value_mask(
                table["target_entity_type"],
                lambda a: pc.equal(a, target_entity_type)))
        if target_entity_id is not None:
            mask = _and(mask, _value_mask(
                table["target_entity_id"],
                lambda a: pc.equal(a, target_entity_id)))
        if mask is not None and not (
                mask.null_count == 0 and pc.all(mask).as_py()):
            # all-true masks (the common full-training scan) skip the
            # 25M-row copy a filter() would pay; a null in the mask means
            # "drop" (Arrow filter semantics), so it never skips
            table = table.filter(mask)
        if ordered:
            table = table.sort_by([("event_time_us", "ascending"),
                                   ("creation_time_us", "ascending")])
        if columns is not None:
            table = table.select(list(columns))
        return table

    def latest_event_time(
        self, app_id: int, channel_id: Optional[int] = None
    ) -> Optional[_dt.datetime]:
        """Ingest high-watermark: columnar MAX over the projected
        event_time_us column — no row materialization, no sort."""
        d = self._check_init(app_id, channel_id)
        with self._lock:
            table = self._scan(d, app_id, channel_id,
                               columns=["event_time_us"])
        if table is None or table.num_rows == 0:
            return None
        us = pc.max(table["event_time_us"]).as_py()
        if us is None:
            return None
        return _dt.datetime.fromtimestamp(us / 1_000_000,
                                          tz=_dt.timezone.utc)

    def get(self, event_id: str, app_id: int, channel_id: Optional[int] = None):
        d = self._check_init(app_id, channel_id)
        with self._lock:
            table = self._scan(d, app_id, channel_id)
        if table is None:
            return None
        hit = table.filter(pc.equal(table["event_id"], event_id))
        if hit.num_rows == 0:
            return None
        return base.arrow_to_events(hit)[0]

    def delete(self, event_id: str, app_id: int, channel_id: Optional[int] = None) -> bool:
        d = self._check_init(app_id, channel_id)
        with self._lock:
            self._flush(app_id, channel_id)
            for p in sorted(d.glob("part-*.parquet")):
                t = pq.read_table(p)
                mask = pc.equal(t["event_id"], event_id)
                if pc.any(mask).as_py():
                    kept = t.filter(pc.invert(mask))
                    if kept.num_rows:
                        pq.write_table(kept, p)
                    else:
                        p.unlink()
                    # keep the bulk-ingest dedup index truthful: a deleted
                    # token-derived row may legitimately be re-created
                    seen = self._batch_ids.get((app_id, channel_id))
                    if seen is not None:
                        seen.discard(event_id)
                    return True
        return False

    def find(
        self,
        app_id: int,
        channel_id: Optional[int] = None,
        *,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        entity_type: Optional[str] = None,
        entity_id: Optional[str] = None,
        event_names: Optional[Sequence[str]] = None,
        target_entity_type: Optional[str] = None,
        target_entity_id: Optional[str] = None,
        limit: Optional[int] = None,
        reversed: bool = False,
    ) -> Iterator[Event]:
        table = self._filtered(
            app_id, channel_id, start_time, until_time, entity_type, entity_id,
            event_names, target_entity_type, target_entity_id,
        )
        events = base.arrow_to_events(table)
        if reversed:
            events.reverse()
        if limit is not None and limit >= 0:
            events = events[:limit]
        return iter(events)

    def find_columnar(
        self,
        app_id: int,
        channel_id: Optional[int] = None,
        *,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        entity_type: Optional[str] = None,
        entity_id: Optional[str] = None,
        event_names: Optional[Sequence[str]] = None,
        target_entity_type: Optional[str] = None,
        target_entity_id: Optional[str] = None,
        ordered: bool = True,
        columns: Optional[Sequence[str]] = None,
    ) -> pa.Table:
        return self._filtered(
            app_id, channel_id, start_time, until_time, entity_type, entity_id,
            event_names, target_entity_type, target_entity_id,
            ordered=ordered, columns=columns,
        )
