"""Storage registry — env-configured backend instantiation.

Reference: data/.../data/storage/Storage.scala — reads ``PIO_STORAGE_*``
config, reflectively instantiates backend clients, and exposes typed
repository getters for the three logical stores (METADATA / EVENTDATA /
MODELDATA).  Here "reflection" is a registry of backend factory functions
keyed by source ``type``.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional

from predictionio_tpu.config import PioConfig, StorageSourceConfig, load_config
from predictionio_tpu.data.storage import base
from predictionio_tpu.data.storage.base import (  # re-export
    AccessKey,
    AccessKeys,
    App,
    Apps,
    Channel,
    Channels,
    EngineInstance,
    EngineInstances,
    EvaluationInstance,
    EvaluationInstances,
    Events,
    KV,
    Model,
    Models,
    QueueRecord,
    SpillQueues,
    StorageError,
    StorageUnavailable,
)
from predictionio_tpu.resilience.faults import (
    wrap_events as _wrap_events,
    wrap_instances as _wrap_instances,
    wrap_kv as _wrap_kv,
    wrap_models as _wrap_models,
    wrap_spill_queues as _wrap_spill_queues,
)

__all__ = [
    "Storage",
    "get_storage",
    "reset_storage",
    "register_backend",
    "App", "Apps", "AccessKey", "AccessKeys", "Channel", "Channels",
    "EngineInstance", "EngineInstances", "EvaluationInstance",
    "EvaluationInstances", "Model", "Models", "Events", "SpillQueues",
    "QueueRecord", "KV", "StorageError", "StorageUnavailable",
]


class _Backend:
    """A constructed storage client for one source; repo accessors per kind."""

    def __init__(self, source: StorageSourceConfig, namespace: str):
        self.source = source
        self.namespace = namespace

    def events(self) -> Events:
        raise StorageError(f"Source type {self.source.type} has no events support.")

    def apps(self) -> Apps:
        raise StorageError(f"Source type {self.source.type} has no metadata support.")

    def access_keys(self) -> AccessKeys:
        raise StorageError(f"Source type {self.source.type} has no metadata support.")

    def channels(self) -> Channels:
        raise StorageError(f"Source type {self.source.type} has no metadata support.")

    def engine_instances(self) -> EngineInstances:
        raise StorageError(f"Source type {self.source.type} has no metadata support.")

    def evaluation_instances(self) -> EvaluationInstances:
        raise StorageError(f"Source type {self.source.type} has no metadata support.")

    def models(self) -> Models:
        raise StorageError(f"Source type {self.source.type} has no models support.")

    def spill_queues(self) -> SpillQueues:
        raise StorageError(
            f"Source type {self.source.type} has no shared-queue support.")

    def kv(self) -> KV:
        raise StorageError(
            f"Source type {self.source.type} has no shared-KV support.")

    def close(self) -> None:
        pass


class _SQLiteBackend(_Backend):
    def __init__(self, source, namespace):
        super().__init__(source, namespace)
        from predictionio_tpu.data.storage.sqlite import SQLiteClient

        path = source.path
        if not path:
            raise StorageError(f"sqlite source {source.name} needs a PATH property.")
        self._client = SQLiteClient(path, namespace=namespace)

    def events(self): return self._client.events()
    def apps(self): return self._client.apps()
    def access_keys(self): return self._client.access_keys()
    def channels(self): return self._client.channels()
    def engine_instances(self): return self._client.engine_instances()
    def evaluation_instances(self): return self._client.evaluation_instances()
    def models(self): return self._client.models()
    def spill_queues(self): return self._client.spill_queues()
    def kv(self): return self._client.kv()
    def close(self): self._client.close()


class _ParquetBackend(_Backend):
    def __init__(self, source, namespace):
        super().__init__(source, namespace)
        from predictionio_tpu.data.storage.parquet_events import ParquetEvents

        path = source.path
        if not path:
            raise StorageError(f"parquetlog source {source.name} needs a PATH property.")
        self._events = ParquetEvents(path)

    def events(self): return self._events


class _LocalFSBackend(_Backend):
    def __init__(self, source, namespace):
        super().__init__(source, namespace)
        from predictionio_tpu.data.storage.localfs_models import LocalFSModels

        path = source.path
        if not path:
            raise StorageError(f"localfs source {source.name} needs a PATH property.")
        self._models = LocalFSModels(path)

    def models(self): return self._models


class _MemoryBackend(_Backend):
    def __init__(self, source, namespace):
        super().__init__(source, namespace)
        from predictionio_tpu.data.storage import memory as m

        self._events = m.MemoryEvents()
        self._apps = m.MemoryApps()
        self._keys = m.MemoryAccessKeys()
        self._channels = m.MemoryChannels()
        self._engine_instances = m.MemoryEngineInstances()
        self._evaluation_instances = m.MemoryEvaluationInstances()
        self._models = m.MemoryModels()
        self._spill_queues = m.MemorySpillQueues()
        self._kv = m.MemoryKV()

    def events(self): return self._events
    def apps(self): return self._apps
    def access_keys(self): return self._keys
    def channels(self): return self._channels
    def engine_instances(self): return self._engine_instances
    def evaluation_instances(self): return self._evaluation_instances
    def models(self): return self._models
    def spill_queues(self): return self._spill_queues
    def kv(self): return self._kv


class _PioServerBackend(_Backend):
    """Out-of-process backend: every repository call forwarded over TCP to
    a ``pio storageserver`` process (data/storage/remote.py) — the
    reference's JDBC/HBase/ES network-storage property, selected purely
    by PIO_STORAGE_* config (HOSTS/PORTS properties)."""

    def __init__(self, source, namespace):
        super().__init__(source, namespace)
        from predictionio_tpu.data.storage.remote import RemoteClient

        host = source.properties.get("HOSTS", "127.0.0.1").split(",")[0]
        port = source.properties.get("PORTS")
        if not port:
            raise StorageError(
                f"pioserver source {source.name} needs a PORTS property.")
        self._client = RemoteClient(
            host, int(port.split(",")[0]),
            secret=source.properties.get("SECRET"),
            pool_size=int(source.properties.get("CONNECTIONS", "2")),
            retries=int(source.properties.get("RETRIES", "2")))

    def events(self): return self._client.events()
    def apps(self): return self._client.apps()
    def access_keys(self): return self._client.access_keys()
    def channels(self): return self._client.channels()
    def engine_instances(self): return self._client.engine_instances()
    def evaluation_instances(self): return self._client.evaluation_instances()
    def models(self): return self._client.models()
    def spill_queues(self): return self._client.spill_queues()
    def kv(self): return self._client.kv()
    def close(self): self._client.close()


_BACKEND_TYPES: Dict[str, Callable[[StorageSourceConfig, str], _Backend]] = {
    "sqlite": _SQLiteBackend,
    "parquetlog": _ParquetBackend,
    "localfs": _LocalFSBackend,
    "memory": _MemoryBackend,
    "pioserver": _PioServerBackend,
}


def register_backend(type_name: str, factory: Callable[[StorageSourceConfig, str], _Backend]) -> None:
    """Plugin point for new storage types (reference: reflective client load)."""
    _BACKEND_TYPES[type_name] = factory


class Storage:
    """Typed repository getters over configured backends.

    Reference getters: ``Storage.getLEvents`` / ``getPEvents`` /
    ``getMetaDataApps`` / ``getModelDataModels`` etc.  The L/P split
    collapses into :meth:`get_events` (see base.Events docstring).
    """

    def __init__(self, config: Optional[PioConfig] = None):
        self.config = config or load_config()
        self._backends: Dict[str, _Backend] = {}
        self._lock = threading.Lock()

    def _backend_for(self, repo: str) -> _Backend:
        rc = self.config.repositories[repo.upper()]
        cache_key = f"{rc.source}:{rc.namespace}"
        with self._lock:
            if cache_key not in self._backends:
                source = self.config.source_for(repo)
                try:
                    factory = _BACKEND_TYPES[source.type]
                except KeyError:
                    raise StorageError(
                        f"Unknown storage source type {source.type!r} "
                        f"(registered: {sorted(_BACKEND_TYPES)})"
                    ) from None
                self._backends[cache_key] = factory(source, rc.namespace)
            return self._backends[cache_key]

    # EVENTDATA
    def get_events(self) -> Events:
        # Fault-injection seam (resilience/faults.py): a no-op passthrough
        # unless a PIO_FAULTS plan targets storage.* points.  Wrapped per
        # call so a plan installed mid-process takes effect immediately.
        return _wrap_events(self._backend_for("EVENTDATA").events())

    # METADATA
    def get_apps(self) -> Apps:
        return self._backend_for("METADATA").apps()

    def get_access_keys(self) -> AccessKeys:
        return self._backend_for("METADATA").access_keys()

    def get_channels(self) -> Channels:
        return self._backend_for("METADATA").channels()

    def get_engine_instances(self) -> EngineInstances:
        # Fault seam like get_events: lets PIO_FAULTS storage.* rules
        # break the engine server's reload reads (ISSUE 4 fail-closed).
        return _wrap_instances(
            self._backend_for("METADATA").engine_instances())

    def get_evaluation_instances(self) -> EvaluationInstances:
        return self._backend_for("METADATA").evaluation_instances()

    # MODELDATA
    def get_models(self) -> Models:
        return _wrap_models(self._backend_for("MODELDATA").models())

    # Fleet backplane (ISSUE 15) — rides the EVENTDATA source: the spill
    # queue holds event payloads and the fold-in cache derives from
    # events, and EVENTDATA is the repository a fleet already points at
    # shared storage.  Raises StorageError on sources without support
    # (parquetlog) — callers degrade to the local journal / LRU-only.
    def get_spill_queues(self) -> SpillQueues:
        return _wrap_spill_queues(
            self._backend_for("EVENTDATA").spill_queues())

    def get_kv(self) -> KV:
        return _wrap_kv(self._backend_for("EVENTDATA").kv())

    def close(self) -> None:
        with self._lock:
            for b in self._backends.values():
                b.close()
            self._backends.clear()

    def verify(self) -> Dict[str, str]:
        """Touch all three stores; returns repo→source-type map (pio status)."""
        out = {}
        for repo, getter in (
            ("METADATA", self.get_apps),
            ("EVENTDATA", self.get_events),
            ("MODELDATA", self.get_models),
        ):
            getter()
            out[repo] = self.config.source_for(repo).type
        return out


_global: Optional[Storage] = None
_global_lock = threading.Lock()


def get_storage(config: Optional[PioConfig] = None) -> Storage:
    """Process-wide storage singleton (reference: Storage object)."""
    global _global
    with _global_lock:
        if _global is None or config is not None:
            if _global is not None:
                _global.close()
            _global = Storage(config)
        return _global


def reset_storage() -> None:
    global _global
    with _global_lock:
        if _global is not None:
            _global.close()
        _global = None
