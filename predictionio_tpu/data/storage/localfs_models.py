"""Filesystem MODELDATA blob store.

Reference: storage/localfs/ — ``LocalFSModels`` (SURVEY.md §2.1); HDFS/S3
variants of the reference collapse to this one locally (object stores can be
added behind the same :class:`~predictionio_tpu.data.storage.base.Models`
trait).
"""

from __future__ import annotations

import urllib.parse
from pathlib import Path
from typing import Optional

from predictionio_tpu.data.storage import base
from predictionio_tpu.data.storage.base import Model

__all__ = ["LocalFSModels"]


class LocalFSModels(base.Models):
    def __init__(self, root: str):
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)

    def _path(self, model_id: str) -> Path:
        safe = urllib.parse.quote(model_id, safe="")  # collision-free encoding
        return self.root / f"pio_model_{safe}.bin"

    def insert(self, model: Model) -> None:
        tmp = self._path(model.id).with_suffix(".tmp")
        tmp.write_bytes(model.models)
        tmp.replace(self._path(model.id))

    def get(self, model_id: str) -> Optional[Model]:
        p = self._path(model_id)
        if not p.exists():
            return None
        return Model(id=model_id, models=p.read_bytes())

    def delete(self, model_id: str) -> bool:
        p = self._path(model_id)
        if not p.exists():
            return False
        p.unlink()
        return True
