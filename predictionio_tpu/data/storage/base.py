"""Storage abstraction: metadata records + repository traits.

Reference: data/src/main/scala/org/apache/predictionio/data/storage/ —
the ``LEvents`` / ``PEvents`` / ``Models`` / ``Apps`` / ``AccessKeys`` /
``Channels`` / ``EngineInstances`` / ``EvaluationInstances`` traits that every
backend plugin implements (SURVEY.md §1 L2).

Design departure from the reference (deliberate, TPU-first): the reference
splits event reads into ``LEvents`` (iterator, serving path) and ``PEvents``
(RDD, training path).  Here a single :class:`Events` trait carries both:
``find`` yields :class:`Event` objects (the L path) and ``find_columnar``
returns a ``pyarrow.Table`` (the P path) — columnar batches are what feeds
host-sharded ``jax.Array`` construction, replacing RDD partitions.
"""

from __future__ import annotations

import abc
import datetime as _dt
import secrets
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, Iterator, List, Optional, Sequence

import numpy as np
import pyarrow as pa

from predictionio_tpu.data.event import Event, PropertyMap

__all__ = [
    "App",
    "AccessKey",
    "Channel",
    "EngineInstance",
    "EvaluationInstance",
    "Model",
    "QueueRecord",
    "Apps",
    "AccessKeys",
    "Channels",
    "EngineInstances",
    "EvaluationInstances",
    "Models",
    "Events",
    "SpillQueues",
    "KV",
    "EVENT_ARROW_SCHEMA",
    "StorageError",
    "StorageUnavailable",
    "normalize_event_table",
    "stamp_event_ids",
    "batch_event_id",
]


def batch_event_id(token: str) -> str:
    """Deterministic event id for a bulk-ingest item from its idempotency
    sub-token.  The id IS the dedup key: every backend's ``create_batch``
    keys its conflict-ignoring insert on it, so a replayed batch (same
    tokens) lands each row at most once — even when a crash left the
    first attempt partially committed."""
    return f"bt{token}"


class StorageError(RuntimeError):
    pass


class StorageUnavailable(StorageError):
    """The backend is unreachable / timing out — an AVAILABILITY failure,
    distinct from a bad request: retriable, counted by circuit breakers,
    and mapped to 503 (or a spill-journal 202) by the servers instead of
    a client-fault 400."""

    retriable = True


# --------------------------------------------------------------------------
# Metadata records (reference: App.scala, AccessKey.scala, Channel.scala,
# EngineInstance.scala, EvaluationInstance.scala, Model.scala)
# --------------------------------------------------------------------------


@dataclass
class App:
    id: Optional[int]
    name: str
    description: Optional[str] = None


@dataclass
class AccessKey:
    key: str
    app_id: int
    events: Sequence[str] = ()          # allowlist; empty = all events permitted

    @staticmethod
    def generate(app_id: int, events: Sequence[str] = ()) -> "AccessKey":
        return AccessKey(key=secrets.token_urlsafe(48), app_id=app_id, events=tuple(events))


@dataclass
class Channel:
    id: Optional[int]
    name: str
    app_id: int

    NAME_MAX = 16

    @staticmethod
    def is_valid_name(name: str) -> bool:
        # Reference: Channel.isValidName — [a-zA-Z0-9-] and 1..16 chars.
        return (
            0 < len(name) <= Channel.NAME_MAX
            and all((c.isascii() and c.isalnum()) or c == "-" for c in name)
        )


@dataclass
class EngineInstance:
    """One row per train run (reference: EngineInstance.scala)."""

    id: Optional[str]
    status: str                                  # INIT | TRAINING | COMPLETED | FAILED
    start_time: _dt.datetime
    end_time: Optional[_dt.datetime]
    engine_id: str
    engine_version: str
    engine_variant: str
    engine_factory: str
    env: Dict[str, str] = field(default_factory=dict)
    runtime_conf: Dict[str, Any] = field(default_factory=dict)   # reference: sparkConf
    datasource_params: str = "{}"
    preparator_params: str = "{}"
    algorithms_params: str = "[]"
    serving_params: str = "{}"


@dataclass
class EvaluationInstance:
    """One row per `pio eval` run (reference: EvaluationInstance.scala)."""

    id: Optional[str]
    status: str
    start_time: _dt.datetime
    end_time: Optional[_dt.datetime]
    evaluation_class: str
    engine_params_generator_class: str
    env: Dict[str, str] = field(default_factory=dict)
    evaluator_results: str = ""                  # pretty text summary
    evaluator_results_html: str = ""
    evaluator_results_json: str = "{}"


@dataclass
class Model:
    """Binary model blob (reference: Model.scala / Models trait)."""

    id: str
    models: bytes


@dataclass
class QueueRecord:
    """One record of a shared spill queue (ISSUE 15).

    ``payload`` is the journal-record JSON object (token/appId/channelId/
    events); ``state`` walks pending → leased → (acked = deleted | dead).
    ``lease_expires_s`` is epoch seconds — lease math is done against a
    CALLER-supplied ``now_s`` so tests (and clock-skewed fleets) reason
    about expiry explicitly instead of trusting each backend's wall
    clock."""

    id: str
    payload: Dict[str, Any]
    token: Optional[str] = None
    events: int = 1
    attempts: int = 0
    state: str = "pending"            # pending | leased | dead
    lease_owner: Optional[str] = None
    lease_expires_s: Optional[float] = None
    reason: Optional[str] = None      # dead-letter reason
    enqueued_s: float = 0.0


# --------------------------------------------------------------------------
# Repository traits
# --------------------------------------------------------------------------


class Apps(abc.ABC):
    @abc.abstractmethod
    def insert(self, app: App) -> Optional[int]: ...

    @abc.abstractmethod
    def get(self, app_id: int) -> Optional[App]: ...

    @abc.abstractmethod
    def get_by_name(self, name: str) -> Optional[App]: ...

    @abc.abstractmethod
    def get_all(self) -> List[App]: ...

    @abc.abstractmethod
    def update(self, app: App) -> bool: ...

    @abc.abstractmethod
    def delete(self, app_id: int) -> bool: ...


class AccessKeys(abc.ABC):
    @abc.abstractmethod
    def insert(self, access_key: AccessKey) -> Optional[str]: ...

    @abc.abstractmethod
    def get(self, key: str) -> Optional[AccessKey]: ...

    @abc.abstractmethod
    def get_all(self) -> List[AccessKey]: ...

    @abc.abstractmethod
    def get_by_app_id(self, app_id: int) -> List[AccessKey]: ...

    @abc.abstractmethod
    def update(self, access_key: AccessKey) -> bool: ...

    @abc.abstractmethod
    def delete(self, key: str) -> bool: ...


class Channels(abc.ABC):
    def insert(self, channel: Channel) -> Optional[int]:
        """Validate then store; name rules enforced here so every backend —
        including ones registered via ``register_backend`` — gets them."""
        if not Channel.is_valid_name(channel.name):
            return None
        return self._insert(channel)

    @abc.abstractmethod
    def _insert(self, channel: Channel) -> Optional[int]: ...

    @abc.abstractmethod
    def get(self, channel_id: int) -> Optional[Channel]: ...

    @abc.abstractmethod
    def get_by_app_id(self, app_id: int) -> List[Channel]: ...

    @abc.abstractmethod
    def delete(self, channel_id: int) -> bool: ...


class EngineInstances(abc.ABC):
    @abc.abstractmethod
    def insert(self, instance: EngineInstance) -> str: ...

    @abc.abstractmethod
    def get(self, instance_id: str) -> Optional[EngineInstance]: ...

    @abc.abstractmethod
    def get_all(self) -> List[EngineInstance]: ...

    @abc.abstractmethod
    def get_latest_completed(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> Optional[EngineInstance]: ...

    @abc.abstractmethod
    def get_completed(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> List[EngineInstance]: ...

    @abc.abstractmethod
    def update(self, instance: EngineInstance) -> bool: ...

    @abc.abstractmethod
    def delete(self, instance_id: str) -> bool: ...


class EvaluationInstances(abc.ABC):
    @abc.abstractmethod
    def insert(self, instance: EvaluationInstance) -> str: ...

    @abc.abstractmethod
    def get(self, instance_id: str) -> Optional[EvaluationInstance]: ...

    @abc.abstractmethod
    def get_all(self) -> List[EvaluationInstance]: ...

    @abc.abstractmethod
    def get_completed(self) -> List[EvaluationInstance]: ...

    @abc.abstractmethod
    def update(self, instance: EvaluationInstance) -> bool: ...

    @abc.abstractmethod
    def delete(self, instance_id: str) -> bool: ...


class Models(abc.ABC):
    @abc.abstractmethod
    def insert(self, model: Model) -> None: ...

    @abc.abstractmethod
    def get(self, model_id: str) -> Optional[Model]: ...

    @abc.abstractmethod
    def delete(self, model_id: str) -> bool: ...


class SpillQueues(abc.ABC):
    """Shared durable work queue with lease/ack semantics (ISSUE 15).

    The fleet-scale replacement for the per-instance JSONL spill journal:
    N event servers enqueue failed writes into ONE storage-backed queue,
    and any instance's drainer may lease a batch, replay it, and ack.  A
    crashed drainer's lease expires (``lease_expires_s`` vs the caller's
    ``now_s``) and another instance re-leases the batch — replay stays
    idempotent because each record carries the ORIGINAL write's
    idempotency token, so the at-least-once redelivery dedups into
    exactly-once against dedup-capable backends (pioserver).

    Contract pinned by tests/test_fleet.py across sqlite/memory/remote:

    - :meth:`enqueue` is token-idempotent — re-enqueueing a token already
      queued (lost-reply retry) returns the existing record's id.
    - :meth:`lease` atomically claims up to ``n`` records that are
      pending OR whose lease expired before ``now_s``, oldest first,
      bumping ``attempts`` — two concurrent drainers never hold the same
      record under an unexpired lease.
    - :meth:`ack` deletes ONLY records still leased by ``owner`` — an
      acker whose lease was stolen learns it from the return count.
    - :meth:`nack` releases records back to pending (transient replay
      failure: storage still down, retry next tick).
    - :meth:`dead_letter` parks a permanently unreplayable record (state
      ``dead``) where :meth:`requeue_dead` can resurrect it after the
      operator fixes the cause.
    """

    @abc.abstractmethod
    def enqueue(self, queue: str, payload: Dict[str, Any],
                token: Optional[str] = None, events: int = 1,
                now_s: Optional[float] = None) -> str: ...

    @abc.abstractmethod
    def lease(self, queue: str, owner: str, n: int, ttl_s: float,
              now_s: Optional[float] = None) -> List["QueueRecord"]: ...

    @abc.abstractmethod
    def ack(self, queue: str, ids: Sequence[str], owner: str) -> int: ...

    @abc.abstractmethod
    def nack(self, queue: str, ids: Sequence[str], owner: str) -> int: ...

    @abc.abstractmethod
    def dead_letter(self, queue: str, record_id: str, owner: str,
                    reason: str) -> bool: ...

    @abc.abstractmethod
    def requeue_dead(self, queue: str) -> int:
        """Move every dead record back to pending; returns EVENTS
        requeued (the operator-facing unit, matching the journal)."""

    @abc.abstractmethod
    def stats(self, queue: str, now_s: Optional[float] = None
              ) -> Dict[str, Any]:
        """``{"pending","leased","expired","dead"}`` record counts plus
        ``*Events`` sums — ``expired`` counts leased records whose lease
        already lapsed at ``now_s`` (re-leasable work)."""

    @abc.abstractmethod
    def peek(self, queue: str, n: int = 5, state: str = "pending"
             ) -> List["QueueRecord"]:
        """Read-only oldest-first view for ``pio spill inspect`` — takes
        no lease, never mutates."""


class KV(abc.ABC):
    """Namespaced shared key-value store (ISSUE 15: the durable fold-in
    cache).  Values are opaque bytes; ``prune`` bounds a namespace by
    dropping the least-recently-written entries, so N instances can share
    a cache without any one of them owning an eviction thread."""

    @abc.abstractmethod
    def put(self, ns: str, key: str, value: bytes) -> None: ...

    @abc.abstractmethod
    def get(self, ns: str, key: str) -> Optional[bytes]: ...

    @abc.abstractmethod
    def delete(self, ns: str, key: str) -> bool: ...

    @abc.abstractmethod
    def count(self, ns: str) -> int: ...

    @abc.abstractmethod
    def prune(self, ns: str, keep: int) -> int:
        """Drop all but the ``keep`` most-recently-written entries of
        ``ns``; returns the number deleted."""


# --------------------------------------------------------------------------
# Events trait — unified L+P event store
# --------------------------------------------------------------------------

# Columnar schema for the P (training) read path; feeds host-sharded arrays.
EVENT_ARROW_SCHEMA = pa.schema(
    [
        pa.field("event_id", pa.string()),
        pa.field("event", pa.string()),
        pa.field("entity_type", pa.string()),
        pa.field("entity_id", pa.string()),
        pa.field("target_entity_type", pa.string()),
        pa.field("target_entity_id", pa.string()),
        pa.field("properties_json", pa.string()),
        pa.field("event_time_us", pa.int64()),      # epoch micros UTC
        pa.field("pr_id", pa.string()),
        pa.field("creation_time_us", pa.int64()),
    ]
)


class Events(abc.ABC):
    """Unified event store trait (reference: LEvents + PEvents).

    All methods take ``app_id`` and optional ``channel_id`` (None = default
    channel), matching the reference's partitioning.
    """

    @abc.abstractmethod
    def init(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        """Create per-app/channel structures (reference: LEvents.init)."""

    @abc.abstractmethod
    def remove(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        """Drop all events of the app/channel (reference: LEvents.remove)."""

    @abc.abstractmethod
    def close(self) -> None: ...

    @abc.abstractmethod
    def insert(self, event: Event, app_id: int, channel_id: Optional[int] = None) -> str:
        """Insert one event; the store ALWAYS assigns a fresh event id
        (any ``event.event_id`` present is ignored), matching the
        reference's server-generated ids.  Returns the assigned id."""

    def insert_batch(
        self, events: Sequence[Event], app_id: int, channel_id: Optional[int] = None
    ) -> List[str]:
        return [self.insert(e, app_id, channel_id) for e in events]

    def create_batch(
        self, events: Sequence[Event], app_id: int,
        channel_id: Optional[int] = None,
        tokens: Optional[Sequence[str]] = None,
    ) -> List[str]:
        """One multi-row write with PER-ITEM exactly-once semantics
        (ISSUE 17: the bulk-ingest data plane's storage contract).

        ``tokens`` are the batch's per-item idempotency sub-tokens; each
        item's event id is derived deterministically from its sub-token
        (:func:`batch_event_id`), so a replay of the same batch after a
        crashed reply — possibly after a PARTIAL landing — skips the rows
        that already committed and re-inserts only the missing ones,
        returning the same ids either way.  Without tokens a fresh set is
        minted, which degrades to plain at-least-once ``insert_batch``
        behavior.

        The base default delegates to :meth:`insert_batch` (store-assigned
        ids, at-least-once on replay — it cannot force ids on a backend it
        knows nothing about); sqlite/memory/parquet override with a
        genuinely single-round-trip conflict-ignoring write keyed on the
        derived ids, and the pioserver backend forwards the call (token
        set included) over one RPC.
        """
        if tokens is not None and len(tokens) != len(events):
            raise StorageError(
                f"create_batch: {len(events)} events but {len(tokens)} "
                "tokens")
        return self.insert_batch(events, app_id, channel_id)

    @abc.abstractmethod
    def get(
        self, event_id: str, app_id: int, channel_id: Optional[int] = None
    ) -> Optional[Event]: ...

    @abc.abstractmethod
    def delete(
        self, event_id: str, app_id: int, channel_id: Optional[int] = None
    ) -> bool: ...

    @abc.abstractmethod
    def find(
        self,
        app_id: int,
        channel_id: Optional[int] = None,
        *,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        entity_type: Optional[str] = None,
        entity_id: Optional[str] = None,
        event_names: Optional[Sequence[str]] = None,
        target_entity_type: Optional[str] = None,
        target_entity_id: Optional[str] = None,
        limit: Optional[int] = None,
        reversed: bool = False,
    ) -> Iterator[Event]:
        """Time/entity-filtered scan (reference: LEvents.find).

        ``limit=None`` means no limit; ``reversed=True`` returns newest first
        (only valid when filtering, per reference semantics — here always
        honored).  Results are ordered by event time.
        """

    def find_columnar(
        self,
        app_id: int,
        channel_id: Optional[int] = None,
        *,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        entity_type: Optional[str] = None,
        entity_id: Optional[str] = None,
        event_names: Optional[Sequence[str]] = None,
        target_entity_type: Optional[str] = None,
        target_entity_id: Optional[str] = None,
        ordered: bool = True,
        columns: Optional[Sequence[str]] = None,
    ) -> pa.Table:
        """Columnar scan for the training path (reference: PEvents.find).

        ``ordered=False`` lets the backend skip the event-time sort —
        training reads are order-independent (the reference's RDD scans
        come back in HBase rowkey-hash order, not time order), and at the
        ML-25M north star the sort alone costs seconds.  ``columns``
        projects the result to the named :data:`EVENT_ARROW_SCHEMA`
        fields; columnar backends then avoid materializing the others at
        all (the 32-char ``event_id`` strings are the widest column in
        the store and no trainer reads them).

        Default implementation converts the iterator; columnar backends
        override with a zero-copy path.
        """
        table = events_to_arrow(
            self.find(
                app_id,
                channel_id,
                start_time=start_time,
                until_time=until_time,
                entity_type=entity_type,
                entity_id=entity_id,
                event_names=event_names,
                target_entity_type=target_entity_type,
                target_entity_id=target_entity_id,
            )
        )
        if columns is not None:
            table = table.select(list(columns))
        return table

    def insert_columnar(
        self, table: pa.Table, app_id: int, channel_id: Optional[int] = None
    ) -> int:
        """Bulk columnar ingest (reference analogue: HBase bulk import /
        ``pio import`` at scale — SURVEY §2.1).

        ``table`` carries :data:`EVENT_ARROW_SCHEMA` columns (``event_id``
        is ignored — the store assigns ids, same rule as :meth:`insert`;
        missing nullable columns default to null, a missing
        ``creation_time_us`` defaults to now).  Returns the number of
        events ingested instead of per-row id strings: materializing 25M
        Python strings would defeat the point of the columnar path.

        Default implementation chunks through :meth:`insert_batch` so
        row-oriented backends stay correct without bulk-specific code.
        """
        table = normalize_event_table(table)
        n = 0
        for start in range(0, table.num_rows, 65536):
            chunk = table.slice(start, 65536)
            n += len(self.insert_batch(arrow_to_events(chunk),
                                       app_id, channel_id))
        return n

    def latest_event_time(
        self, app_id: int, channel_id: Optional[int] = None
    ) -> Optional[_dt.datetime]:
        """The ingest high-watermark: the newest ``event_time`` stored for
        the app/channel, or None when empty.

        This is THE freshness anchor of the online-learning loop
        (ISSUE 10): the event server exports it as
        ``pio_events_latest_ts{app}`` and the refresh daemon compares it
        against the serving generation's data watermark to compute
        event→servable staleness.  Default implementation reads one
        event via the reversed ordered scan; backends override with an
        O(1)/indexed query.
        """
        for ev in self.find(app_id, channel_id, limit=1, reversed=True):
            return ev.event_time
        return None

    def aggregate_properties(
        self,
        app_id: int,
        channel_id: Optional[int] = None,
        *,
        entity_type: str,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        required: Optional[Sequence[str]] = None,
    ) -> Dict[str, PropertyMap]:
        """Aggregate ``$set``/``$unset``/``$delete`` into per-entity state.

        Reference: PEventStore.aggregateProperties / LEventAggregator.
        """
        from predictionio_tpu.data.event import aggregate_properties as _agg

        by_entity: Dict[str, List[Event]] = {}
        for ev in self.find(
            app_id,
            channel_id,
            start_time=start_time,
            until_time=until_time,
            entity_type=entity_type,
            event_names=["$set", "$unset", "$delete"],
        ):
            by_entity.setdefault(ev.entity_id, []).append(ev)
        out: Dict[str, PropertyMap] = {}
        for eid, evs in by_entity.items():
            pm = _agg(evs)
            if pm is None:
                continue
            if required and not all(k in pm for k in required):
                continue
            out[eid] = pm
        return out


# --------------------------------------------------------------------------
# Timestamp + Arrow conversion helpers (shared by all backends — keep the
# naive-datetime-is-UTC rule in exactly one place)
# --------------------------------------------------------------------------


def epoch_us(dt: Optional[_dt.datetime]) -> Optional[int]:
    if dt is None:
        return None
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=_dt.timezone.utc)
    return int(dt.timestamp() * 1_000_000)


def from_epoch_us(us: Optional[int]) -> Optional[_dt.datetime]:
    if us is None:
        return None
    return _dt.datetime.fromtimestamp(us / 1_000_000, tz=_dt.timezone.utc)


# Backwards-compat private aliases used inside this module.
_epoch_us = epoch_us
_from_epoch_us = from_epoch_us


def events_to_arrow(events: Iterable[Event]) -> pa.Table:
    import json

    cols: Dict[str, list] = {f.name: [] for f in EVENT_ARROW_SCHEMA}
    for e in events:
        cols["event_id"].append(e.event_id)
        cols["event"].append(e.event)
        cols["entity_type"].append(e.entity_type)
        cols["entity_id"].append(e.entity_id)
        cols["target_entity_type"].append(e.target_entity_type)
        cols["target_entity_id"].append(e.target_entity_id)
        cols["properties_json"].append(json.dumps(e.properties.to_dict()))
        cols["event_time_us"].append(_epoch_us(e.event_time))
        cols["pr_id"].append(e.pr_id)
        cols["creation_time_us"].append(_epoch_us(e.creation_time))
    return pa.table(cols, schema=EVENT_ARROW_SCHEMA)


def normalize_event_table(table: pa.Table) -> pa.Table:
    """Validate/complete a caller-supplied columnar event batch against
    :data:`EVENT_ARROW_SCHEMA` for :meth:`Events.insert_columnar`.

    Required: ``event``, ``entity_type``, ``entity_id``.  ``event_id`` is
    dropped (store-assigned).  Missing nullable columns become null;
    a missing ``creation_time_us`` is stamped now; a missing
    ``event_time_us`` defaults to creation time (reference rule: an event
    without an explicit eventTime gets the server clock).
    """
    names = set(table.column_names)
    for req in ("event", "entity_type", "entity_id"):
        if req not in names:
            raise StorageError(f"insert_columnar: missing column {req!r}")
        nc = table.column(req).null_count
        if nc:
            raise StorageError(
                f"insert_columnar: column {req!r} has {nc} null value(s) "
                "— required per event (reference: EventJson4sSupport "
                "validation)")
    unknown = names - {f.name for f in EVENT_ARROW_SCHEMA}
    if unknown:
        raise StorageError(
            f"insert_columnar: unknown column(s) {sorted(unknown)}")
    n = table.num_rows
    now_us = epoch_us(_dt.datetime.now(_dt.timezone.utc))

    def _conform(col: "pa.ChunkedArray", typ: pa.DataType):
        # A dictionary column with the right value type passes through
        # untouched — casting it dense would materialize 25M strings and
        # defeat the columnar bulk path (parquet stores dictionary pages
        # either way; row backends densify per-chunk at insert).
        if pa.types.is_dictionary(col.type) and col.type.value_type == typ:
            return col
        return col.cast(typ)

    cols = []
    for field in EVENT_ARROW_SCHEMA:
        if field.name == "event_id":
            cols.append(pa.nulls(n, field.type))
        elif field.name == "properties_json":
            # the row path always serializes a DataMap ('{}' minimum);
            # null here would violate that invariant (and sqlite's schema)
            if field.name in names:
                col = _conform(table.column(field.name), field.type)
                if col.null_count:
                    import pyarrow.compute as pc

                    if pa.types.is_dictionary(col.type):
                        col = col.cast(field.type)  # rare: nulls in dict col
                    col = pc.fill_null(col, "{}")
                cols.append(col)
            else:
                cols.append(pa.repeat(pa.scalar("{}", field.type), n))
        elif field.name in names:
            col = _conform(table.column(field.name), field.type)
            if field.name in ("event_time_us", "creation_time_us") \
                    and col.null_count:
                # per-row default, same rule as the missing-column case:
                # an event without an explicit time gets the server clock
                import pyarrow.compute as pc

                col = pc.fill_null(col, now_us)
            cols.append(col)
        elif field.name == "creation_time_us":
            cols.append(pa.array(np.full(n, now_us, np.int64)))
        elif field.name == "event_time_us":
            # defaults to creation time, whether that column was given;
            # null creation rows take the server clock too (the null must
            # not leak into event_time_us — sqlite's eventtime is NOT
            # NULL and readers assume every Event has a time)
            if "creation_time_us" in names:
                import pyarrow.compute as pc

                ct = pc.fill_null(
                    table.column("creation_time_us").cast(pa.int64()),
                    now_us)
            else:
                ct = pa.array(np.full(n, now_us, np.int64))
            cols.append(ct)
        else:
            cols.append(pa.nulls(n, field.type))
    fields = [pa.field(f.name, col.type, nullable=True)
              for f, col in zip(EVENT_ARROW_SCHEMA, cols)]
    return pa.table(cols, schema=pa.schema(fields))


def stamp_event_ids(table: pa.Table, prefix: str) -> pa.Table:
    """Replace ``event_id`` with ``<prefix><row>`` — unique ids from one
    cast+concat Arrow kernel pair instead of 25M Python ``uuid4`` calls
    (measured ~1 µs each; the columnar bulk path cannot afford them)."""
    import pyarrow.compute as pc

    seq = pc.cast(pa.array(np.arange(table.num_rows, dtype=np.int64)),
                  pa.string())
    ids = pc.binary_join_element_wise(pa.scalar(prefix), seq, "")
    return table.set_column(
        table.schema.get_field_index("event_id"),
        EVENT_ARROW_SCHEMA.field("event_id"), ids)


def arrow_to_events(table: pa.Table) -> List[Event]:
    import json

    from predictionio_tpu.data.event import DataMap

    out: List[Event] = []
    d = table.to_pydict()
    n = table.num_rows
    for i in range(n):
        out.append(
            Event(
                event_id=d["event_id"][i],
                event=d["event"][i],
                entity_type=d["entity_type"][i],
                entity_id=d["entity_id"][i],
                target_entity_type=d["target_entity_type"][i],
                target_entity_id=d["target_entity_id"][i],
                properties=DataMap(json.loads(d["properties_json"][i] or "{}")),
                event_time=_from_epoch_us(d["event_time_us"][i]),
                pr_id=d["pr_id"][i],
                creation_time=_from_epoch_us(d["creation_time_us"][i]),
            )
        )
    return out
