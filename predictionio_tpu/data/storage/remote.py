"""Out-of-process storage backend — the pluggability proof.

Reference: the JDBC / HBase / Elasticsearch storage plugins (upstream
``storage/{jdbc,hbase,elasticsearch}/``) whose defining property is that
the event/metadata/model stores live in ANOTHER PROCESS reached over the
network, selected purely by ``PIO_STORAGE_*`` configuration.  This module
supplies both halves:

- :class:`StorageServer` — a TCP daemon hosting any configured local
  backend (sqlite by default) behind a length-prefixed JSON-RPC protocol.
  ``pio storageserver`` runs it as the "database process".
- ``type=pioserver`` backend — client adapters for all seven repository
  traits (events, apps, access keys, channels, engine/evaluation
  instances, models) that forward every call over the wire.  Selected
  with::

      PIO_STORAGE_SOURCES_REMOTE_TYPE=pioserver
      PIO_STORAGE_SOURCES_REMOTE_HOSTS=127.0.0.1
      PIO_STORAGE_SOURCES_REMOTE_PORTS=7077
      PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE=REMOTE

Wire format: 4-byte big-endian length + UTF-8 JSON per message.
Requests are ``{"m": "events.insert", "a": [...], "k": {...}}``; replies
``{"ok": ...}`` or ``{"err": "...", "storage_error": bool}``.  Values are
JSON with two tagged encodings: ``{"__dt__": iso8601}`` for datetimes and
``{"__b64__": ...}`` for byte blobs (model payloads).
"""

from __future__ import annotations

import base64
import dataclasses
import datetime as _dt
import json
import logging
import socket
import socketserver
import struct
import threading
from typing import Any, Dict, List, Optional

from predictionio_tpu.data.event import DataMap, Event
from predictionio_tpu.data.storage import base
from predictionio_tpu.data.storage.base import (
    AccessKey, AccessKeys, App, Apps, Channel, Channels, EngineInstance,
    EngineInstances, EvaluationInstance, EvaluationInstances, Events, Model,
    Models, StorageError,
)

logger = logging.getLogger(__name__)

__all__ = ["StorageServer", "RemoteClient", "RemoteBackendError"]


class RemoteBackendError(StorageError):
    pass


# -- value (de)serialization ------------------------------------------------

_DATACLASSES = {
    "Event": Event, "App": App, "AccessKey": AccessKey, "Channel": Channel,
    "EngineInstance": EngineInstance,
    "EvaluationInstance": EvaluationInstance, "Model": Model,
}


def _enc(v: Any) -> Any:
    if isinstance(v, _dt.datetime):
        return {"__dt__": v.isoformat()}
    if isinstance(v, (bytes, bytearray)):
        return {"__b64__": base64.b64encode(bytes(v)).decode()}
    if isinstance(v, DataMap):
        return {"__map__": v.to_dict()}
    if dataclasses.is_dataclass(v) and type(v).__name__ in _DATACLASSES:
        return {"__dc__": type(v).__name__,
                "f": {f.name: _enc(getattr(v, f.name))
                      for f in dataclasses.fields(v)}}
    if isinstance(v, (list, tuple)):
        return [_enc(x) for x in v]
    if isinstance(v, dict):
        return {k: _enc(x) for k, x in v.items()}
    return v


def _dec(v: Any) -> Any:
    if isinstance(v, dict):
        if "__dt__" in v:
            return _dt.datetime.fromisoformat(v["__dt__"])
        if "__b64__" in v:
            return base64.b64decode(v["__b64__"])
        if "__map__" in v:
            return DataMap(v["__map__"])
        if "__dc__" in v:
            cls = _DATACLASSES[v["__dc__"]]
            fields = {k: _dec(x) for k, x in v["f"].items()}
            if cls is AccessKey and isinstance(fields.get("events"), list):
                fields["events"] = tuple(fields["events"])  # JSON drops tuples
            return cls(**fields)
        return {k: _dec(x) for k, x in v.items()}
    if isinstance(v, list):
        return [_dec(x) for x in v]
    return v


def _send(sock: socket.socket, obj: Any) -> None:
    payload = json.dumps(obj).encode()
    sock.sendall(struct.pack(">I", len(payload)) + payload)


def _recv(sock: socket.socket) -> Any:
    head = b""
    while len(head) < 4:
        chunk = sock.recv(4 - len(head))
        if not chunk:
            raise ConnectionError("storage server closed the connection")
        head += chunk
    (n,) = struct.unpack(">I", head)
    if n > (256 << 20):
        raise RemoteBackendError("oversized storage reply")
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError("storage server closed mid-reply")
        buf += chunk
    return json.loads(bytes(buf))


# -- server -----------------------------------------------------------------

# Exact RPC surface per repository — nothing outside this table is
# callable over the wire (getattr dispatch would otherwise expose
# private/backing methods).
_ALLOWED = {
    "events": {"init", "remove", "insert", "insert_batch", "get", "delete",
               "find"},
    "apps": {"insert", "get", "get_by_name", "get_all", "update", "delete"},
    "access_keys": {"insert", "get", "get_all", "get_by_app_id", "update",
                    "delete"},
    "channels": {"_insert", "get", "get_by_app_id", "delete"},
    "engine_instances": {"insert", "get", "get_all", "get_latest_completed",
                         "get_completed", "update", "delete"},
    "evaluation_instances": {"insert", "get", "get_all", "get_completed",
                             "update", "delete"},
    "models": {"insert", "get", "delete"},
}


class StorageServer:
    """Host a local :class:`~predictionio_tpu.data.storage.Storage` (or any
    object exposing the repository getters) over TCP."""

    def __init__(self, storage, host: str = "127.0.0.1", port: int = 0):
        self.storage = storage
        self._repos = {
            "events": storage.get_events,
            "apps": storage.get_apps,
            "access_keys": storage.get_access_keys,
            "channels": storage.get_channels,
            "engine_instances": storage.get_engine_instances,
            "evaluation_instances": storage.get_evaluation_instances,
            "models": storage.get_models,
        }
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                while True:
                    try:
                        req = _recv(self.request)
                    except (ConnectionError, OSError):
                        return
                    try:
                        result = outer._dispatch(req)
                        reply = {"ok": _enc(result)}
                    except StorageError as e:
                        reply = {"err": str(e), "storage_error": True}
                    except Exception as e:  # backend bug → client exception
                        logger.exception("storage RPC failed: %s", req.get("m"))
                        reply = {"err": f"{type(e).__name__}: {e}",
                                 "storage_error": False}
                    try:
                        _send(self.request, reply)
                    except (ConnectionError, OSError):
                        return

        class Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._srv = Server((host, port), Handler)
        self.host, self.port = self._srv.server_address
        self._thread: Optional[threading.Thread] = None

    def _dispatch(self, req: Dict) -> Any:
        repo_name, _, method = req["m"].partition(".")
        if repo_name not in self._repos or \
                method not in _ALLOWED.get(repo_name, ()):
            raise RemoteBackendError(f"unknown storage method {req['m']!r}")
        repo = self._repos[repo_name]()
        args = [_dec(a) for a in req.get("a", [])]
        kwargs = {k: _dec(v) for k, v in req.get("k", {}).items()}
        out = getattr(repo, method)(*args, **kwargs)
        if method in ("find",):  # iterator → list on the wire
            out = list(out)
        return out

    def start(self) -> int:
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)
        self._thread.start()
        logger.info("storage server on %s:%d", self.host, self.port)
        return self.port

    def serve_forever(self) -> None:
        self._srv.serve_forever()

    def stop(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()
        if self._thread:
            self._thread.join(timeout=5)


# -- client -----------------------------------------------------------------

class RemoteClient:
    """One TCP connection (thread-safe, lazily reconnecting) + adapters."""

    def __init__(self, host: str, port: int, timeout: float = 30.0):
        self.addr = (host, int(port))
        self.timeout = timeout
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()

    def _connect(self) -> socket.socket:
        s = socket.create_connection(self.addr, timeout=self.timeout)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return s

    def call(self, method: str, *args, **kwargs) -> Any:
        req = {"m": method, "a": [_enc(a) for a in args],
               "k": {k: _enc(v) for k, v in kwargs.items()}}
        # Transparent resend is only safe for READS: a write may have
        # executed server-side before the connection dropped, and
        # re-sending it would duplicate the insert/update.  Writes fail
        # fast; the next call reconnects.
        verb = method.split(".", 1)[1] if "." in method else method
        retriable = verb.startswith(("get", "find"))
        with self._lock:
            for attempt in (0, 1):
                if self._sock is None:
                    self._sock = self._connect()
                try:
                    _send(self._sock, req)
                    reply = _recv(self._sock)
                    break
                except (ConnectionError, OSError):
                    try:
                        self._sock.close()
                    except OSError:
                        pass
                    self._sock = None
                    if attempt or not retriable:
                        raise RemoteBackendError(
                            f"storage server {self.addr} unreachable "
                            f"during {method} (write not retried)"
                            if not retriable else
                            f"storage server {self.addr} unreachable")
        if "err" in reply:
            if reply.get("storage_error"):
                raise StorageError(reply["err"])
            raise RemoteBackendError(reply["err"])
        return _dec(reply["ok"])

    def close(self) -> None:
        with self._lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None

    # repo accessors
    def events(self) -> "RemoteEvents":
        return RemoteEvents(self)

    def apps(self) -> "RemoteApps":
        return RemoteApps(self)

    def access_keys(self) -> "RemoteAccessKeys":
        return RemoteAccessKeys(self)

    def channels(self) -> "RemoteChannels":
        return RemoteChannels(self)

    def engine_instances(self) -> "RemoteEngineInstances":
        return RemoteEngineInstances(self)

    def evaluation_instances(self) -> "RemoteEvaluationInstances":
        return RemoteEvaluationInstances(self)

    def models(self) -> "RemoteModels":
        return RemoteModels(self)


def _forward(repo: str, method: str, iterator: bool = False):
    def impl(self, *args, **kwargs):
        out = self._c.call(f"{repo}.{method}", *args, **kwargs)
        return iter(out) if iterator else out
    impl.__name__ = method
    return impl


class RemoteEvents(Events):
    def __init__(self, client: RemoteClient):
        self._c = client

    init = _forward("events", "init")
    remove = _forward("events", "remove")
    insert = _forward("events", "insert")
    insert_batch = _forward("events", "insert_batch")
    get = _forward("events", "get")
    delete = _forward("events", "delete")
    find = _forward("events", "find", iterator=True)

    def close(self) -> None:
        self._c.close()


class RemoteApps(Apps):
    def __init__(self, client: RemoteClient):
        self._c = client

    insert = _forward("apps", "insert")
    get = _forward("apps", "get")
    get_by_name = _forward("apps", "get_by_name")
    get_all = _forward("apps", "get_all")
    update = _forward("apps", "update")
    delete = _forward("apps", "delete")


class RemoteAccessKeys(AccessKeys):
    def __init__(self, client: RemoteClient):
        self._c = client

    insert = _forward("access_keys", "insert")
    get = _forward("access_keys", "get")
    get_all = _forward("access_keys", "get_all")
    get_by_app_id = _forward("access_keys", "get_by_app_id")
    update = _forward("access_keys", "update")
    delete = _forward("access_keys", "delete")


class RemoteChannels(Channels):
    def __init__(self, client: RemoteClient):
        self._c = client

    _insert = _forward("channels", "_insert")
    get = _forward("channels", "get")
    get_by_app_id = _forward("channels", "get_by_app_id")
    delete = _forward("channels", "delete")


class RemoteEngineInstances(EngineInstances):
    def __init__(self, client: RemoteClient):
        self._c = client

    insert = _forward("engine_instances", "insert")
    get = _forward("engine_instances", "get")
    get_all = _forward("engine_instances", "get_all")
    get_latest_completed = _forward("engine_instances", "get_latest_completed")
    get_completed = _forward("engine_instances", "get_completed")
    update = _forward("engine_instances", "update")
    delete = _forward("engine_instances", "delete")


class RemoteEvaluationInstances(EvaluationInstances):
    def __init__(self, client: RemoteClient):
        self._c = client

    insert = _forward("evaluation_instances", "insert")
    get = _forward("evaluation_instances", "get")
    get_all = _forward("evaluation_instances", "get_all")
    get_completed = _forward("evaluation_instances", "get_completed")
    update = _forward("evaluation_instances", "update")
    delete = _forward("evaluation_instances", "delete")


class RemoteModels(Models):
    def __init__(self, client: RemoteClient):
        self._c = client

    insert = _forward("models", "insert")
    get = _forward("models", "get")
    delete = _forward("models", "delete")
