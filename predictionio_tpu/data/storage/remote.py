"""Out-of-process storage backend — the pluggability proof.

Reference: the JDBC / HBase / Elasticsearch storage plugins (upstream
``storage/{jdbc,hbase,elasticsearch}/``) whose defining property is that
the event/metadata/model stores live in ANOTHER PROCESS reached over the
network, selected purely by ``PIO_STORAGE_*`` configuration.  This module
supplies both halves:

- :class:`StorageServer` — a TCP daemon hosting any configured local
  backend (sqlite by default) behind a length-prefixed JSON-RPC protocol.
  ``pio storageserver`` runs it as the "database process".
- ``type=pioserver`` backend — client adapters for all seven repository
  traits (events, apps, access keys, channels, engine/evaluation
  instances, models) that forward every call over the wire.  Selected
  with::

      PIO_STORAGE_SOURCES_REMOTE_TYPE=pioserver
      PIO_STORAGE_SOURCES_REMOTE_HOSTS=127.0.0.1
      PIO_STORAGE_SOURCES_REMOTE_PORTS=7077
      PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE=REMOTE

Wire format: 4-byte big-endian length + UTF-8 JSON per message.
Requests are ``{"m": "events.insert", "a": [...], "k": {...}}``; replies
``{"ok": ...}`` or ``{"err": "...", "storage_error": bool}``.  Values are
JSON with two tagged encodings: ``{"__dt__": iso8601}`` for datetimes and
``{"__b64__": ...}`` for byte blobs (model payloads).

Scans STREAM: ``events.find_open`` returns the first batch plus a cursor
token, ``events.find_next`` continues it, ``events.find_close`` abandons
it — so a 25M-event scan never materializes on either end (the reference's
JDBC/HBase scans stream the same way).  Cursors live on the connection
that opened them; the client pins a pooled connection per open scan.

Optional auth: when the server is started with a shared ``secret``, the
first message on every connection must be ``{"auth": <secret>}`` — anything
else closes the connection.  Configure clients with a
``PIO_STORAGE_SOURCES_<NAME>_SECRET`` property.

Write idempotency: every mutating request carries a client-generated
token (``"t"``).  The server keeps a bounded dedup window of recently
answered write tokens, so a client that loses the REPLY (connection
killed after the server committed) can resend the same request and get
the original answer back instead of a duplicate insert.  This is what
makes remote writes safely retriable — the client retries ALL RPCs with
jittered backoff, not just reads.
"""

from __future__ import annotations

import base64
import collections
import dataclasses
import datetime as _dt
import json
import logging
import socket
import socketserver
import struct
import threading
import uuid
from typing import Any, Dict, List, Optional

from predictionio_tpu.data.event import DataMap, Event
from predictionio_tpu.data.storage import base
from predictionio_tpu.data.storage.base import (
    AccessKey, AccessKeys, App, Apps, Channel, Channels, EngineInstance,
    EngineInstances, EvaluationInstance, EvaluationInstances, Events, KV,
    Model, Models, QueueRecord, SpillQueues, StorageError,
    StorageUnavailable,
)
from predictionio_tpu.obs import get_registry
from predictionio_tpu.resilience import current_idempotency_key
from predictionio_tpu.resilience.deadline import check as _deadline_check
from predictionio_tpu.resilience.faults import fault_point
from predictionio_tpu.resilience.policy import RetryPolicy

logger = logging.getLogger(__name__)

__all__ = ["StorageServer", "RemoteClient", "RemoteBackendError"]


class RemoteBackendError(StorageError):
    pass


# -- value (de)serialization ------------------------------------------------

_DATACLASSES = {
    "Event": Event, "App": App, "AccessKey": AccessKey, "Channel": Channel,
    "EngineInstance": EngineInstance,
    "EvaluationInstance": EvaluationInstance, "Model": Model,
    "QueueRecord": QueueRecord,
}


def _enc(v: Any) -> Any:
    if isinstance(v, _dt.datetime):
        return {"__dt__": v.isoformat()}
    if isinstance(v, (bytes, bytearray)):
        return {"__b64__": base64.b64encode(bytes(v)).decode()}
    if isinstance(v, DataMap):
        return {"__map__": v.to_dict()}
    if dataclasses.is_dataclass(v) and type(v).__name__ in _DATACLASSES:
        return {"__dc__": type(v).__name__,
                "f": {f.name: _enc(getattr(v, f.name))
                      for f in dataclasses.fields(v)}}
    if isinstance(v, (list, tuple)):
        return [_enc(x) for x in v]
    if isinstance(v, dict):
        return {k: _enc(x) for k, x in v.items()}
    return v


def _dec(v: Any) -> Any:
    if isinstance(v, dict):
        if "__dt__" in v:
            return _dt.datetime.fromisoformat(v["__dt__"])
        if "__b64__" in v:
            return base64.b64decode(v["__b64__"])
        if "__map__" in v:
            return DataMap(v["__map__"])
        if "__dc__" in v:
            cls = _DATACLASSES[v["__dc__"]]
            fields = {k: _dec(x) for k, x in v["f"].items()}
            if cls is AccessKey and isinstance(fields.get("events"), list):
                fields["events"] = tuple(fields["events"])  # JSON drops tuples
            return cls(**fields)
        return {k: _dec(x) for k, x in v.items()}
    if isinstance(v, list):
        return [_dec(x) for x in v]
    return v


# Per-message size cap: streamed scan pages stay far below this; only a
# legacy one-shot ``events.find`` of a huge store could hit it.
_MAX_MESSAGE = 256 << 20


def _send(sock: socket.socket, obj: Any) -> None:
    payload = json.dumps(obj).encode()
    sock.sendall(struct.pack(">I", len(payload)) + payload)


def _recv(sock: socket.socket, max_len: Optional[int] = None) -> Any:
    head = b""
    while len(head) < 4:
        chunk = sock.recv(4 - len(head))
        if not chunk:
            raise ConnectionError("storage server closed the connection")
        head += chunk
    (n,) = struct.unpack(">I", head)
    # Same frame cap both directions (client AND server): a corrupt or
    # malicious length prefix must not make either side buffer gigabytes
    # before failing.  The module-level cap is read at call time so tests
    # can shrink it.
    cap = _MAX_MESSAGE if max_len is None else max_len
    if n > cap:
        raise RemoteBackendError(
            f"oversized frame ({n} bytes > cap {cap})")
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError("storage server closed mid-reply")
        buf += chunk
    return json.loads(bytes(buf))


# -- server -----------------------------------------------------------------

# Exact RPC surface per repository — nothing outside this table is
# callable over the wire (getattr dispatch would otherwise expose
# private/backing methods).
_ALLOWED = {
    "events": {"init", "remove", "insert", "insert_batch", "create_batch",
               "get", "delete", "find", "latest_event_time"},
    "apps": {"insert", "get", "get_by_name", "get_all", "update", "delete"},
    "access_keys": {"insert", "get", "get_all", "get_by_app_id", "update",
                    "delete"},
    "channels": {"_insert", "get", "get_by_app_id", "delete"},
    "engine_instances": {"insert", "get", "get_all", "get_latest_completed",
                         "get_completed", "update", "delete"},
    "evaluation_instances": {"insert", "get", "get_all", "get_completed",
                             "update", "delete"},
    "models": {"insert", "get", "delete"},
    # Shared spill queue + KV (ISSUE 15): the fleet backplane rides the
    # same RPC surface, so N event servers on type=pioserver share one
    # queue/cache exactly like they share one event store.
    "spill_queues": {"enqueue", "lease", "ack", "nack", "dead_letter",
                     "requeue_dead", "stats", "peek"},
    "kv": {"put", "get", "delete", "count", "prune"},
}


_FIND_BATCH = 2000  # events per streamed batch (well under the reply cap)


class _DedupWindow:
    """Bounded token → reply cache shared across connections.

    Holds the last ``capacity`` successful WRITE replies keyed by the
    client's idempotency token; a resent write whose token is still in
    the window gets the original reply without re-executing.  Bounded so
    an adversarial client cannot grow server memory; a token falling out
    of the window degrades to at-least-once (documented in README).

    ``begin``/``finish`` also track IN-FLIGHT tokens: a retry that
    arrives while the original dispatch is still executing (write slower
    than the client's retry backoff) blocks until the original finishes
    instead of re-executing concurrently — the duplicate-insert race the
    tokens exist to close."""

    def __init__(self, capacity: int = 4096):
        self.capacity = max(1, int(capacity))
        self._lock = threading.Lock()
        self._replies: "collections.OrderedDict[str, Any]" = \
            collections.OrderedDict()
        self._inflight: Dict[str, threading.Event] = {}

    def begin(self, token: str, wait_s: float = 60.0) -> Optional[Any]:
        """Claim ``token`` for execution.  Returns the cached reply when
        the write already committed; None when the caller should run the
        dispatch (and MUST later call :meth:`finish`).  Waits out an
        in-flight original first; if it is still running after
        ``wait_s`` the caller proceeds (bounded at-least-once beats a
        wedged connection)."""
        while True:
            with self._lock:
                reply = self._replies.get(token)
                if reply is not None:
                    self._replies.move_to_end(token)
                    return reply
                ev = self._inflight.get(token)
                if ev is None:
                    self._inflight[token] = threading.Event()
                    return None
            if not ev.wait(wait_s):
                with self._lock:
                    # original wedged: steal the claim if still unset
                    if self._inflight.get(token) is ev:
                        self._inflight[token] = threading.Event()
                        return None
                # else: original finished in the window — loop re-checks

    def finish(self, token: str, reply: Optional[Any]) -> None:
        """Release the in-flight claim; ``reply`` is cached only when the
        write succeeded (a transient failure must re-execute on retry)."""
        with self._lock:
            if reply is not None:
                self._replies[token] = reply
                self._replies.move_to_end(token)
                while len(self._replies) > self.capacity:
                    self._replies.popitem(last=False)
            ev = self._inflight.pop(token, None)
        if ev is not None:
            ev.set()


class StorageServer:
    """Host a local :class:`~predictionio_tpu.data.storage.Storage` (or any
    object exposing the repository getters) over TCP."""

    def __init__(self, storage, host: str = "127.0.0.1", port: int = 0,
                 secret: Optional[str] = None,
                 dedup_window: int = 4096):
        self.storage = storage
        self.secret = secret
        self._dedup = _DedupWindow(dedup_window)
        if secret is None and host not in ("127.0.0.1", "localhost", "::1"):
            logger.warning(
                "Storage server binding %s WITHOUT a shared secret: anything "
                "that can reach this address gets full read/write access to "
                "every app's events, models, and access keys.  Pass "
                "secret=... (pio storageserver --secret / "
                "PIO_STORAGE_SERVER_SECRET).", host)
        self._repos = {
            "events": storage.get_events,
            "apps": storage.get_apps,
            "access_keys": storage.get_access_keys,
            "channels": storage.get_channels,
            "engine_instances": storage.get_engine_instances,
            "evaluation_instances": storage.get_evaluation_instances,
            "models": storage.get_models,
        }
        # Backplane repos are optional on the hosted storage (a backend
        # without queue support answers "unknown method", not a crash).
        for name, getter in (("spill_queues",
                              getattr(storage, "get_spill_queues", None)),
                             ("kv", getattr(storage, "get_kv", None))):
            if getter is not None:
                self._repos[name] = getter
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                import hmac

                # Per-connection scan cursors: live iterators keyed by a
                # monotonic token (never reused within a connection, so a
                # stale find_next errors instead of silently reading a
                # later scan's pages); dropped with the connection.
                cursors: Dict[int, Any] = {"_next": 1}
                authed = outer.secret is None
                first = True
                while True:
                    try:
                        # Pre-auth, a peer knows nothing worth 256 MB: cap
                        # the first frame of a secured connection at 1 KB
                        # so strangers can't make the server buffer/parse
                        # attacker-sized payloads before the secret check.
                        req = _recv(self.request,
                                    max_len=(1 << 10) if not authed
                                    else None)
                    except RemoteBackendError:
                        # Oversized pre-auth frame — likely a legitimate
                        # client missing its SECRET property whose first
                        # RPC was big: tell it why before dropping, so the
                        # operator doesn't chase a phantom network fault.
                        try:
                            _send(self.request, {"err": "auth required",
                                                 "storage_error": False})
                        except (ConnectionError, OSError):
                            pass
                        return
                    except (ConnectionError, OSError):
                        return
                    if authed and first and isinstance(req, dict) \
                            and set(req) == {"auth"}:
                        # Client configured with a secret, server without
                        # one: acknowledge the handshake instead of
                        # dispatching it as an RPC (which would fail with
                        # a misleading KeyError-shaped reply).
                        first = False
                        try:
                            _send(self.request, {"ok": True})
                            continue
                        except (ConnectionError, OSError):
                            return
                    first = False
                    if not authed:
                        # Compare as bytes: compare_digest raises on
                        # non-ASCII str inputs.
                        ok = isinstance(req, dict) and isinstance(
                            req.get("auth"), str) and hmac.compare_digest(
                            req["auth"].encode(), outer.secret.encode())
                        try:
                            _send(self.request, {"ok": True} if ok else
                                  {"err": "auth required", "storage_error": False})
                        except (ConnectionError, OSError):
                            return
                        if not ok:
                            return  # close: no unauthenticated dispatch
                        authed = True
                        continue
                    token = req.get("t") if isinstance(req, dict) else None
                    if token:
                        cached = outer._dedup.begin(token)
                        if cached is not None:
                            # Retried write whose first execution
                            # committed but whose reply was lost: answer
                            # from the dedup window, do NOT re-execute.
                            # (begin() also serialized us behind a still-
                            # running original with the same token.)
                            try:
                                _send(self.request, cached)
                                continue
                            except (ConnectionError, OSError):
                                return
                    reply = None
                    try:
                        result = outer._dispatch(req, cursors)
                        reply = {"ok": _enc(result)}
                    except StorageError as e:
                        reply = {"err": str(e), "storage_error": True}
                    except Exception as e:  # backend bug → client exception
                        logger.exception("storage RPC failed: %s", req.get("m"))
                        reply = {"err": f"{type(e).__name__}: {e}",
                                 "storage_error": False}
                    finally:
                        if token:
                            # Only successes enter the window: a transient
                            # failure must re-execute on retry.  Always
                            # releases the in-flight claim.
                            outer._dedup.finish(
                                token,
                                reply if reply and "ok" in reply else None)
                    try:
                        _send(self.request, reply)
                    except (ConnectionError, OSError):
                        return

        class Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._srv = Server((host, port), Handler)
        self.host, self.port = self._srv.server_address
        self._thread: Optional[threading.Thread] = None

    @staticmethod
    def _cursor_page(cursors: Dict[int, Any], cid: int, n: int) -> Dict:
        it = cursors[cid]
        batch = []
        for ev in it:
            batch.append(ev)
            if len(batch) >= n:
                break
        done = len(batch) < n
        if done:
            del cursors[cid]
        return {"cursor": None if done else cid, "batch": batch,
                "done": done}

    def _dispatch(self, req: Dict, cursors: Dict[int, Any]) -> Any:
        fault_point("rpc.dispatch")
        repo_name, _, method = req["m"].partition(".")
        args = [_dec(a) for a in req.get("a", [])]
        kwargs = {k: _dec(v) for k, v in req.get("k", {}).items()}
        if repo_name == "events" and method in ("find_open", "find_next",
                                                "find_close"):
            if method == "find_open":
                n = int(kwargs.pop("_n", _FIND_BATCH))
                cid = cursors["_next"]
                cursors["_next"] = cid + 1
                cursors[cid] = iter(self._repos["events"]().find(
                    *args, **kwargs))
                return self._cursor_page(cursors, cid, n)
            if method == "find_next":
                cid, n = int(args[0]), int(args[1])
                if cid not in cursors:
                    raise RemoteBackendError(f"unknown scan cursor {cid}")
                return self._cursor_page(cursors, cid, n)
            cursors.pop(int(args[0]), None)  # find_close
            return True
        if repo_name not in self._repos or \
                method not in _ALLOWED.get(repo_name, ()):
            raise RemoteBackendError(f"unknown storage method {req['m']!r}")
        repo = self._repos[repo_name]()
        out = getattr(repo, method)(*args, **kwargs)
        if method in ("find",):  # iterator → list on the wire (legacy path)
            out = list(out)
        return out

    def start(self) -> int:
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)
        self._thread.start()
        logger.info("storage server on %s:%d", self.host, self.port)
        return self.port

    def serve_forever(self) -> None:
        self._srv.serve_forever()

    def stop(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()
        if self._thread:
            self._thread.join(timeout=5)


# -- client -----------------------------------------------------------------

class _PooledConn:
    """One lazily-(re)connecting socket; leased exclusively per RPC/scan."""

    def __init__(self, client: "RemoteClient"):
        self._client = client
        self.sock: Optional[socket.socket] = None

    def ensure(self) -> socket.socket:
        if self.sock is None:
            self.sock = self._client._connect()
        return self.sock

    def drop(self) -> None:
        if self.sock is not None:
            try:
                self.sock.close()
            except OSError:
                pass
            self.sock = None


class RemoteClient:
    """A small connection pool (thread-safe, lazily reconnecting) + adapters.

    ``pool_size`` connections run RPCs concurrently instead of serializing
    every storage call behind one socket lock (round-3 weakness); an open
    scan pins its connection until the cursor drains.

    Every RPC retries on connection failure with jittered backoff
    (``retry`` policy, ``retries`` attempts after the first): reads are
    idempotent by nature, and writes carry an idempotency token the
    server dedups on, so a resend after a lost reply cannot duplicate.
    Only cursor continuations (``find_next``) stay fail-fast — a
    half-consumed cursor died with its connection and resuming it
    transparently could silently skip events.
    """

    def __init__(self, host: str, port: int, timeout: float = 30.0,
                 secret: Optional[str] = None, pool_size: int = 2,
                 retries: int = 2, retry: Optional[RetryPolicy] = None):
        self.addr = (host, int(port))
        self.timeout = timeout
        self.secret = secret
        self.retry = retry or RetryPolicy(
            max_attempts=max(1, int(retries) + 1),
            base_delay_ms=20.0, max_delay_ms=500.0)
        self._retries_total = get_registry().counter(
            "pio_rpc_retries_total",
            "Remote-storage RPCs resent after a connection failure.")
        self._pool_size = max(1, int(pool_size))
        self._idle: List[_PooledConn] = [_PooledConn(self)
                                         for _ in range(self._pool_size)]
        self._pool_lock = threading.Lock()
        self._closed = False

    def _lease(self) -> _PooledConn:
        """Take an idle connection, or mint a fresh one when all are busy.

        Never blocks: a thread holding ``pool_size`` pinned scan
        connections that issues another storage call (nested iteration)
        must not deadlock waiting on itself — overflow connections are
        simply closed instead of pooled on release.
        """
        with self._pool_lock:
            if self._idle:
                return self._idle.pop()
        return _PooledConn(self)

    def _release(self, conn: _PooledConn) -> None:
        with self._pool_lock:
            if not self._closed and len(self._idle) < self._pool_size:
                self._idle.append(conn)
                return
        conn.drop()  # overflow conn, or the client was closed mid-lease

    def _connect(self) -> socket.socket:
        s = socket.create_connection(self.addr, timeout=self.timeout)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        if self.secret is not None:
            _send(s, {"auth": self.secret})
            # Auth replies are tiny; mirror the server's pre-auth 1 KB cap
            # so a corrupt/malicious length prefix can't OOM the client.
            reply = _recv(s, max_len=1 << 10)
            if "err" in reply:
                s.close()
                raise RemoteBackendError(
                    f"storage server {self.addr} rejected auth: "
                    f"{reply['err']}")
        return s

    def _roundtrip(self, conn: _PooledConn, req: Dict, *,
                   retriable: bool, method: str) -> Any:
        attempts = self.retry.max_attempts if retriable else 1
        last: Optional[BaseException] = None
        for attempt in range(attempts):
            try:
                _deadline_check(f"storage RPC {method}")
                sock = conn.ensure()
                fault_point("rpc.send")
                _send(sock, req)
                # rpc.recv faults fire AFTER the request hit the wire —
                # the server may have committed; this is the lost-reply
                # case the idempotency tokens exist for.
                fault_point("rpc.recv")
                reply = _recv(sock)
                break
            except (ConnectionError, OSError) as e:
                conn.drop()
                last = e
                if attempt == attempts - 1:
                    raise StorageUnavailable(
                        f"storage server {self.addr} unreachable "
                        f"during {method}"
                        + ("" if retriable else " (not retried)")
                        + f": {e}") from e
                self._retries_total.inc()
                self.retry.sleep_backoff(attempt)
            except RemoteBackendError:
                # Framing-level failure (e.g. oversized reply): the payload
                # is still on the wire, so the connection is
                # protocol-desynchronized — never reuse it.
                conn.drop()
                raise
        else:  # pragma: no cover - loop always breaks or raises
            raise StorageUnavailable(
                f"storage server {self.addr} unreachable: {last}")
        if "err" in reply:
            if reply.get("storage_error"):
                raise StorageError(reply["err"])
            raise RemoteBackendError(reply["err"])
        return _dec(reply["ok"])

    def call(self, method: str, *args, **kwargs) -> Any:
        req = {"m": method, "a": [_enc(a) for a in args],
               "k": {k: _enc(v) for k, v in kwargs.items()}}
        verb = method.split(".", 1)[1] if "." in method else method
        if not verb.startswith(("get", "find", "stats", "peek", "count")):
            # Client-generated idempotency token: the server's dedup
            # window makes resending this exact request safe even when
            # the first send committed before the connection died.  The
            # spill replay pins a persisted token via idempotency_key().
            req["t"] = current_idempotency_key() or uuid.uuid4().hex
        conn = self._lease()
        try:
            return self._roundtrip(conn, req, retriable=True,
                                   method=method)
        finally:
            self._release(conn)

    def stream_find(self, *args, _batch: int = _FIND_BATCH, **kwargs):
        """Lazily yield events from a server-side cursor scan.

        The whole scan rides ONE pooled connection (cursors are
        connection-local server-side); other pool connections stay free
        for concurrent RPC.  A connection drop mid-scan raises — resuming
        a half-consumed cursor transparently could silently skip events.
        """
        conn = self._lease()
        page = None
        try:
            req = {"m": "events.find_open", "a": [_enc(a) for a in args],
                   "k": {**{k: _enc(v) for k, v in kwargs.items()},
                         "_n": _batch}}
            page = self._roundtrip(conn, req, retriable=True,
                                   method="events.find_open")
            while True:
                yield from page["batch"]
                if page["done"]:
                    page = None
                    return
                page = self._roundtrip(
                    conn, {"m": "events.find_next",
                           "a": [page["cursor"], _batch], "k": {}},
                    retriable=False, method="events.find_next")
        finally:
            if page is not None and not page.get("done", True) \
                    and conn.sock is not None:
                # Abandoned mid-scan (caller broke out): free the cursor.
                # A dropped socket needs no close — its cursors died with
                # the server-side connection; dialing a fresh connection
                # just to close a cursor it never had would be wasted.
                try:
                    self._roundtrip(
                        conn, {"m": "events.find_close",
                               "a": [page["cursor"]], "k": {}},
                        retriable=False, method="events.find_close")
                except (RemoteBackendError, StorageError):
                    conn.drop()
            self._release(conn)

    def close(self) -> None:
        with self._pool_lock:
            self._closed = True
            for conn in self._idle:
                conn.drop()
            self._idle.clear()

    # repo accessors
    def events(self) -> "RemoteEvents":
        return RemoteEvents(self)

    def apps(self) -> "RemoteApps":
        return RemoteApps(self)

    def access_keys(self) -> "RemoteAccessKeys":
        return RemoteAccessKeys(self)

    def channels(self) -> "RemoteChannels":
        return RemoteChannels(self)

    def engine_instances(self) -> "RemoteEngineInstances":
        return RemoteEngineInstances(self)

    def evaluation_instances(self) -> "RemoteEvaluationInstances":
        return RemoteEvaluationInstances(self)

    def models(self) -> "RemoteModels":
        return RemoteModels(self)

    def spill_queues(self) -> "RemoteSpillQueues":
        return RemoteSpillQueues(self)

    def kv(self) -> "RemoteKV":
        return RemoteKV(self)


def _forward(repo: str, method: str, iterator: bool = False):
    def impl(self, *args, **kwargs):
        out = self._c.call(f"{repo}.{method}", *args, **kwargs)
        return iter(out) if iterator else out
    impl.__name__ = method
    return impl


class RemoteEvents(Events):
    def __init__(self, client: RemoteClient):
        self._c = client

    init = _forward("events", "init")
    remove = _forward("events", "remove")
    insert = _forward("events", "insert")
    insert_batch = _forward("events", "insert_batch")
    # One RPC per batch; the per-item sub-tokens travel with the call, so
    # the HOSTED backend's create_batch dedups per item even when the
    # whole-call dedup window has already evicted the batch token.
    create_batch = _forward("events", "create_batch")
    get = _forward("events", "get")
    delete = _forward("events", "delete")
    # One RPC to the backend's indexed MAX — the base-class default would
    # stream a whole reversed find page for one timestamp.
    latest_event_time = _forward("events", "latest_event_time")

    def find(self, *args, **kwargs):
        # Streams via server-side cursor pages — never materializes the
        # scan on either end (the legacy one-shot "events.find" RPC
        # remains servable for old clients).
        return self._c.stream_find(*args, **kwargs)

    def close(self) -> None:
        self._c.close()


class RemoteApps(Apps):
    def __init__(self, client: RemoteClient):
        self._c = client

    insert = _forward("apps", "insert")
    get = _forward("apps", "get")
    get_by_name = _forward("apps", "get_by_name")
    get_all = _forward("apps", "get_all")
    update = _forward("apps", "update")
    delete = _forward("apps", "delete")


class RemoteAccessKeys(AccessKeys):
    def __init__(self, client: RemoteClient):
        self._c = client

    insert = _forward("access_keys", "insert")
    get = _forward("access_keys", "get")
    get_all = _forward("access_keys", "get_all")
    get_by_app_id = _forward("access_keys", "get_by_app_id")
    update = _forward("access_keys", "update")
    delete = _forward("access_keys", "delete")


class RemoteChannels(Channels):
    def __init__(self, client: RemoteClient):
        self._c = client

    _insert = _forward("channels", "_insert")
    get = _forward("channels", "get")
    get_by_app_id = _forward("channels", "get_by_app_id")
    delete = _forward("channels", "delete")


class RemoteEngineInstances(EngineInstances):
    def __init__(self, client: RemoteClient):
        self._c = client

    insert = _forward("engine_instances", "insert")
    get = _forward("engine_instances", "get")
    get_all = _forward("engine_instances", "get_all")
    get_latest_completed = _forward("engine_instances", "get_latest_completed")
    get_completed = _forward("engine_instances", "get_completed")
    update = _forward("engine_instances", "update")
    delete = _forward("engine_instances", "delete")


class RemoteEvaluationInstances(EvaluationInstances):
    def __init__(self, client: RemoteClient):
        self._c = client

    insert = _forward("evaluation_instances", "insert")
    get = _forward("evaluation_instances", "get")
    get_all = _forward("evaluation_instances", "get_all")
    get_completed = _forward("evaluation_instances", "get_completed")
    update = _forward("evaluation_instances", "update")
    delete = _forward("evaluation_instances", "delete")


class RemoteModels(Models):
    def __init__(self, client: RemoteClient):
        self._c = client

    insert = _forward("models", "insert")
    get = _forward("models", "get")
    delete = _forward("models", "delete")


class RemoteSpillQueues(SpillQueues):
    """Shared spill queue over the wire — every fleet instance's drainer
    leases from the SAME server-side table, which is what makes a crashed
    drainer's batch another instance's work (ISSUE 15)."""

    def __init__(self, client: RemoteClient):
        self._c = client

    enqueue = _forward("spill_queues", "enqueue")
    lease = _forward("spill_queues", "lease")
    ack = _forward("spill_queues", "ack")
    nack = _forward("spill_queues", "nack")
    dead_letter = _forward("spill_queues", "dead_letter")
    requeue_dead = _forward("spill_queues", "requeue_dead")
    stats = _forward("spill_queues", "stats")
    peek = _forward("spill_queues", "peek")


class RemoteKV(KV):
    def __init__(self, client: RemoteClient):
        self._c = client

    put = _forward("kv", "put")
    delete = _forward("kv", "delete")
    count = _forward("kv", "count")
    prune = _forward("kv", "prune")

    def get(self, ns: str, key: str) -> Optional[bytes]:
        out = self._c.call("kv.get", ns, key)
        # bytes ride the __b64__ tagged encoding; None passes through
        return out if out is None or isinstance(out, bytes) \
            else bytes(out)
