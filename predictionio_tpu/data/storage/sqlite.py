"""SQLite storage backend — the zero-config local default.

Reference analogue: storage/jdbc/ (PostgreSQL/MySQL via scalikejdbc) —
SURVEY.md §2.1 "JDBC storage plugin".  SQLite replaces the external RDBMS so
a fresh checkout needs no services; the SQL schema mirrors the reference's
JDBC tables (apps, accesskeys, channels, engineinstances,
evaluationinstances, events per app/channel namespace).
"""

from __future__ import annotations

import datetime as _dt
import json
import sqlite3
import threading
import time
import uuid
from pathlib import Path
from typing import Any, Dict, Iterator, List, Optional, Sequence

import pyarrow as pa

from predictionio_tpu.data.event import DataMap, Event
from predictionio_tpu.data.storage import base
from predictionio_tpu.data.storage.base import (
    AccessKey,
    App,
    Channel,
    EngineInstance,
    EvaluationInstance,
    Model,
)

__all__ = ["SQLiteClient"]


# Single source of truth for the naive-datetime-is-UTC rule lives in base.
_us = base.epoch_us
_dt_from = base.from_epoch_us


class SQLiteClient:
    """One client per database file; hands out repository adapters.

    Concurrency: sqlite3 with WAL + a process-wide lock per client.  The
    event-server hot path batches inserts; contention is not the bottleneck
    at local scale (the reference's HBase/PG backends own that regime).
    """

    def __init__(self, path: str, namespace: str = "pio"):
        self.path = path
        self.namespace = namespace
        Path(path).parent.mkdir(parents=True, exist_ok=True)
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._conn.execute("PRAGMA journal_mode=WAL")
        self._conn.execute("PRAGMA synchronous=NORMAL")
        # Default wal_autocheckpoint (1000 pages) forces a WAL->db copy
        # every ~4MB, which halves sustained bulk-ingest throughput.  Let
        # the WAL run long between checkpoints and truncate it back after.
        self._conn.execute("PRAGMA wal_autocheckpoint=20000")
        self._conn.execute("PRAGMA journal_size_limit=134217728")
        self._lock = threading.RLock()
        # Positive (app, channel) init-check cache: the ingest hot path
        # otherwise pays a SELECT per insert.  In-process only — a remove()
        # through ANOTHER process is not seen, matching the reference's
        # per-JVM metadata caching.
        self._inited_cache: set = set()
        self._ensure_schema()

    # -- schema -----------------------------------------------------------
    def _ensure_schema(self) -> None:
        ns = self.namespace
        with self._lock, self._conn:
            c = self._conn
            c.execute(
                f"""CREATE TABLE IF NOT EXISTS {ns}_apps (
                    id INTEGER PRIMARY KEY AUTOINCREMENT,
                    name TEXT NOT NULL UNIQUE,
                    description TEXT)"""
            )
            c.execute(
                f"""CREATE TABLE IF NOT EXISTS {ns}_accesskeys (
                    accesskey TEXT PRIMARY KEY,
                    appid INTEGER NOT NULL,
                    events TEXT NOT NULL)"""
            )
            c.execute(
                f"""CREATE TABLE IF NOT EXISTS {ns}_channels (
                    id INTEGER PRIMARY KEY AUTOINCREMENT,
                    name TEXT NOT NULL,
                    appid INTEGER NOT NULL,
                    UNIQUE(appid, name))"""
            )
            c.execute(
                f"""CREATE TABLE IF NOT EXISTS {ns}_engineinstances (
                    id TEXT PRIMARY KEY,
                    status TEXT NOT NULL,
                    starttime INTEGER NOT NULL,
                    endtime INTEGER,
                    engineid TEXT NOT NULL,
                    engineversion TEXT NOT NULL,
                    enginevariant TEXT NOT NULL,
                    enginefactory TEXT NOT NULL,
                    env TEXT NOT NULL,
                    runtimeconf TEXT NOT NULL,
                    datasourceparams TEXT NOT NULL,
                    preparatorparams TEXT NOT NULL,
                    algorithmsparams TEXT NOT NULL,
                    servingparams TEXT NOT NULL)"""
            )
            c.execute(
                f"""CREATE TABLE IF NOT EXISTS {ns}_evaluationinstances (
                    id TEXT PRIMARY KEY,
                    status TEXT NOT NULL,
                    starttime INTEGER NOT NULL,
                    endtime INTEGER,
                    evaluationclass TEXT NOT NULL,
                    engineparamsgeneratorclass TEXT NOT NULL,
                    env TEXT NOT NULL,
                    evaluatorresults TEXT NOT NULL,
                    evaluatorresultshtml TEXT NOT NULL,
                    evaluatorresultsjson TEXT NOT NULL)"""
            )
            c.execute(
                f"""CREATE TABLE IF NOT EXISTS {ns}_models (
                    id TEXT PRIMARY KEY,
                    models BLOB NOT NULL)"""
            )
            c.execute(
                f"""CREATE TABLE IF NOT EXISTS {ns}_events (
                    id TEXT PRIMARY KEY,
                    appid INTEGER NOT NULL,
                    channelid INTEGER,
                    event TEXT NOT NULL,
                    entitytype TEXT NOT NULL,
                    entityid TEXT NOT NULL,
                    targetentitytype TEXT,
                    targetentityid TEXT,
                    properties TEXT NOT NULL,
                    eventtime INTEGER NOT NULL,
                    prid TEXT,
                    creationtime INTEGER NOT NULL)"""
            )
            c.execute(
                f"""CREATE INDEX IF NOT EXISTS {ns}_events_scan
                    ON {ns}_events (appid, channelid, eventtime)"""
            )
            c.execute(
                f"""CREATE INDEX IF NOT EXISTS {ns}_events_entity
                    ON {ns}_events (appid, channelid, entitytype, entityid)"""
            )
            c.execute(
                f"""CREATE TABLE IF NOT EXISTS {ns}_events_inited (
                    appid INTEGER NOT NULL,
                    channelid INTEGER,
                    UNIQUE(appid, channelid))"""
            )
            # Shared spill queue (ISSUE 15): seq orders the FIFO, token is
            # the enqueue-idempotency key (a lost-reply re-enqueue must
            # not duplicate the record), events caches the payload's
            # event count so stats never parse payloads.
            c.execute(
                f"""CREATE TABLE IF NOT EXISTS {ns}_spillqueue (
                    seq INTEGER PRIMARY KEY AUTOINCREMENT,
                    id TEXT NOT NULL UNIQUE,
                    queue TEXT NOT NULL,
                    token TEXT,
                    payload TEXT NOT NULL,
                    events INTEGER NOT NULL,
                    attempts INTEGER NOT NULL DEFAULT 0,
                    state TEXT NOT NULL DEFAULT 'pending',
                    leaseowner TEXT,
                    leaseexpires REAL,
                    reason TEXT,
                    enqueued REAL NOT NULL,
                    UNIQUE(queue, token))"""
            )
            c.execute(
                f"""CREATE INDEX IF NOT EXISTS {ns}_spillqueue_scan
                    ON {ns}_spillqueue (queue, state, seq)"""
            )
            # Shared KV (ISSUE 15: durable fold-in cache).
            c.execute(
                f"""CREATE TABLE IF NOT EXISTS {ns}_kv (
                    ns TEXT NOT NULL,
                    key TEXT NOT NULL,
                    value BLOB NOT NULL,
                    updated REAL NOT NULL,
                    PRIMARY KEY (ns, key))"""
            )

    def close(self) -> None:
        with self._lock:
            self._conn.close()

    # -- repository accessors --------------------------------------------
    def apps(self) -> "SQLiteApps":
        return SQLiteApps(self)

    def access_keys(self) -> "SQLiteAccessKeys":
        return SQLiteAccessKeys(self)

    def channels(self) -> "SQLiteChannels":
        return SQLiteChannels(self)

    def engine_instances(self) -> "SQLiteEngineInstances":
        return SQLiteEngineInstances(self)

    def evaluation_instances(self) -> "SQLiteEvaluationInstances":
        return SQLiteEvaluationInstances(self)

    def models(self) -> "SQLiteModels":
        return SQLiteModels(self)

    def events(self) -> "SQLiteEvents":
        return SQLiteEvents(self)

    def spill_queues(self) -> "SQLiteSpillQueues":
        return SQLiteSpillQueues(self)

    def kv(self) -> "SQLiteKV":
        return SQLiteKV(self)


class _Repo:
    def __init__(self, client: SQLiteClient):
        self._c = client
        self._ns = client.namespace

    @property
    def _conn(self):
        return self._c._conn

    @property
    def _lock(self):
        return self._c._lock


class SQLiteApps(_Repo, base.Apps):
    def insert(self, app: App) -> Optional[int]:
        with self._lock:
            try:
                with self._conn:
                    cur = self._conn.execute(
                        f"INSERT INTO {self._ns}_apps (id, name, description) VALUES (?,?,?)",
                        (app.id, app.name, app.description),
                    )
                return cur.lastrowid if app.id is None else app.id
            except sqlite3.IntegrityError:
                return None

    def get(self, app_id: int) -> Optional[App]:
        with self._lock:
            row = self._conn.execute(
                f"SELECT id,name,description FROM {self._ns}_apps WHERE id=?", (app_id,)
            ).fetchone()
        return App(*row) if row else None

    def get_by_name(self, name: str) -> Optional[App]:
        with self._lock:
            row = self._conn.execute(
                f"SELECT id,name,description FROM {self._ns}_apps WHERE name=?", (name,)
            ).fetchone()
        return App(*row) if row else None

    def get_all(self) -> List[App]:
        with self._lock:
            rows = self._conn.execute(
                f"SELECT id,name,description FROM {self._ns}_apps ORDER BY id"
            ).fetchall()
        return [App(*r) for r in rows]

    def update(self, app: App) -> bool:
        with self._lock, self._conn:
            cur = self._conn.execute(
                f"UPDATE {self._ns}_apps SET name=?, description=? WHERE id=?",
                (app.name, app.description, app.id),
            )
            return cur.rowcount > 0

    def delete(self, app_id: int) -> bool:
        with self._lock, self._conn:
            cur = self._conn.execute(f"DELETE FROM {self._ns}_apps WHERE id=?", (app_id,))
            return cur.rowcount > 0


class SQLiteAccessKeys(_Repo, base.AccessKeys):
    def insert(self, access_key: AccessKey) -> Optional[str]:
        key = access_key.key or AccessKey.generate(access_key.app_id).key
        with self._lock:
            try:
                with self._conn:
                    self._conn.execute(
                        f"INSERT INTO {self._ns}_accesskeys (accesskey, appid, events) VALUES (?,?,?)",
                        (key, access_key.app_id, json.dumps(list(access_key.events))),
                    )
                return key
            except sqlite3.IntegrityError:
                return None

    def _row_to_key(self, row) -> AccessKey:
        return AccessKey(key=row[0], app_id=row[1], events=tuple(json.loads(row[2])))

    def get(self, key: str) -> Optional[AccessKey]:
        with self._lock:
            row = self._conn.execute(
                f"SELECT accesskey,appid,events FROM {self._ns}_accesskeys WHERE accesskey=?",
                (key,),
            ).fetchone()
        return self._row_to_key(row) if row else None

    def get_all(self) -> List[AccessKey]:
        with self._lock:
            rows = self._conn.execute(
                f"SELECT accesskey,appid,events FROM {self._ns}_accesskeys"
            ).fetchall()
        return [self._row_to_key(r) for r in rows]

    def get_by_app_id(self, app_id: int) -> List[AccessKey]:
        with self._lock:
            rows = self._conn.execute(
                f"SELECT accesskey,appid,events FROM {self._ns}_accesskeys WHERE appid=?",
                (app_id,),
            ).fetchall()
        return [self._row_to_key(r) for r in rows]

    def update(self, access_key: AccessKey) -> bool:
        with self._lock, self._conn:
            cur = self._conn.execute(
                f"UPDATE {self._ns}_accesskeys SET appid=?, events=? WHERE accesskey=?",
                (access_key.app_id, json.dumps(list(access_key.events)), access_key.key),
            )
            return cur.rowcount > 0

    def delete(self, key: str) -> bool:
        with self._lock, self._conn:
            cur = self._conn.execute(
                f"DELETE FROM {self._ns}_accesskeys WHERE accesskey=?", (key,)
            )
            return cur.rowcount > 0


class SQLiteChannels(_Repo, base.Channels):
    def _insert(self, channel: Channel) -> Optional[int]:
        with self._lock:
            try:
                with self._conn:
                    cur = self._conn.execute(
                        f"INSERT INTO {self._ns}_channels (id, name, appid) VALUES (?,?,?)",
                        (channel.id, channel.name, channel.app_id),
                    )
                return cur.lastrowid if channel.id is None else channel.id
            except sqlite3.IntegrityError:
                return None

    def get(self, channel_id: int) -> Optional[Channel]:
        with self._lock:
            row = self._conn.execute(
                f"SELECT id,name,appid FROM {self._ns}_channels WHERE id=?", (channel_id,)
            ).fetchone()
        return Channel(id=row[0], name=row[1], app_id=row[2]) if row else None

    def get_by_app_id(self, app_id: int) -> List[Channel]:
        with self._lock:
            rows = self._conn.execute(
                f"SELECT id,name,appid FROM {self._ns}_channels WHERE appid=?", (app_id,)
            ).fetchall()
        return [Channel(id=r[0], name=r[1], app_id=r[2]) for r in rows]

    def delete(self, channel_id: int) -> bool:
        with self._lock, self._conn:
            cur = self._conn.execute(
                f"DELETE FROM {self._ns}_channels WHERE id=?", (channel_id,)
            )
            return cur.rowcount > 0


class SQLiteEngineInstances(_Repo, base.EngineInstances):
    _COLS = (
        "id,status,starttime,endtime,engineid,engineversion,enginevariant,"
        "enginefactory,env,runtimeconf,datasourceparams,preparatorparams,"
        "algorithmsparams,servingparams"
    )

    def _to_row(self, i: EngineInstance):
        return (
            i.id, i.status, _us(i.start_time), _us(i.end_time), i.engine_id,
            i.engine_version, i.engine_variant, i.engine_factory,
            json.dumps(i.env), json.dumps(i.runtime_conf), i.datasource_params,
            i.preparator_params, i.algorithms_params, i.serving_params,
        )

    def _from_row(self, r) -> EngineInstance:
        return EngineInstance(
            id=r[0], status=r[1], start_time=_dt_from(r[2]), end_time=_dt_from(r[3]),
            engine_id=r[4], engine_version=r[5], engine_variant=r[6],
            engine_factory=r[7], env=json.loads(r[8]), runtime_conf=json.loads(r[9]),
            datasource_params=r[10], preparator_params=r[11],
            algorithms_params=r[12], serving_params=r[13],
        )

    def insert(self, instance: EngineInstance) -> str:
        instance.id = instance.id or uuid.uuid4().hex
        with self._lock, self._conn:
            self._conn.execute(
                f"INSERT INTO {self._ns}_engineinstances ({self._COLS}) "
                f"VALUES ({','.join('?' * 14)})",
                self._to_row(instance),
            )
        return instance.id

    def get(self, instance_id: str) -> Optional[EngineInstance]:
        with self._lock:
            row = self._conn.execute(
                f"SELECT {self._COLS} FROM {self._ns}_engineinstances WHERE id=?",
                (instance_id,),
            ).fetchone()
        return self._from_row(row) if row else None

    def get_all(self) -> List[EngineInstance]:
        with self._lock:
            rows = self._conn.execute(
                f"SELECT {self._COLS} FROM {self._ns}_engineinstances ORDER BY starttime DESC"
            ).fetchall()
        return [self._from_row(r) for r in rows]

    def get_completed(self, engine_id, engine_version, engine_variant):
        with self._lock:
            rows = self._conn.execute(
                f"SELECT {self._COLS} FROM {self._ns}_engineinstances "
                "WHERE status='COMPLETED' AND engineid=? AND engineversion=? AND enginevariant=? "
                "ORDER BY starttime DESC",
                (engine_id, engine_version, engine_variant),
            ).fetchall()
        return [self._from_row(r) for r in rows]

    def get_latest_completed(self, engine_id, engine_version, engine_variant):
        c = self.get_completed(engine_id, engine_version, engine_variant)
        return c[0] if c else None

    def update(self, instance: EngineInstance) -> bool:
        with self._lock, self._conn:
            cur = self._conn.execute(
                f"UPDATE {self._ns}_engineinstances SET status=?, starttime=?, endtime=?, "
                "engineid=?, engineversion=?, enginevariant=?, enginefactory=?, env=?, "
                "runtimeconf=?, datasourceparams=?, preparatorparams=?, algorithmsparams=?, "
                "servingparams=? WHERE id=?",
                self._to_row(instance)[1:] + (instance.id,),
            )
            return cur.rowcount > 0

    def delete(self, instance_id: str) -> bool:
        with self._lock, self._conn:
            cur = self._conn.execute(
                f"DELETE FROM {self._ns}_engineinstances WHERE id=?", (instance_id,)
            )
            return cur.rowcount > 0


class SQLiteEvaluationInstances(_Repo, base.EvaluationInstances):
    _COLS = (
        "id,status,starttime,endtime,evaluationclass,engineparamsgeneratorclass,"
        "env,evaluatorresults,evaluatorresultshtml,evaluatorresultsjson"
    )

    def _to_row(self, i: EvaluationInstance):
        return (
            i.id, i.status, _us(i.start_time), _us(i.end_time), i.evaluation_class,
            i.engine_params_generator_class, json.dumps(i.env), i.evaluator_results,
            i.evaluator_results_html, i.evaluator_results_json,
        )

    def _from_row(self, r) -> EvaluationInstance:
        return EvaluationInstance(
            id=r[0], status=r[1], start_time=_dt_from(r[2]), end_time=_dt_from(r[3]),
            evaluation_class=r[4], engine_params_generator_class=r[5],
            env=json.loads(r[6]), evaluator_results=r[7],
            evaluator_results_html=r[8], evaluator_results_json=r[9],
        )

    def insert(self, instance: EvaluationInstance) -> str:
        instance.id = instance.id or uuid.uuid4().hex
        with self._lock, self._conn:
            self._conn.execute(
                f"INSERT INTO {self._ns}_evaluationinstances ({self._COLS}) "
                f"VALUES ({','.join('?' * 10)})",
                self._to_row(instance),
            )
        return instance.id

    def get(self, instance_id: str) -> Optional[EvaluationInstance]:
        with self._lock:
            row = self._conn.execute(
                f"SELECT {self._COLS} FROM {self._ns}_evaluationinstances WHERE id=?",
                (instance_id,),
            ).fetchone()
        return self._from_row(row) if row else None

    def get_all(self) -> List[EvaluationInstance]:
        with self._lock:
            rows = self._conn.execute(
                f"SELECT {self._COLS} FROM {self._ns}_evaluationinstances ORDER BY starttime DESC"
            ).fetchall()
        return [self._from_row(r) for r in rows]

    def get_completed(self) -> List[EvaluationInstance]:
        with self._lock:
            rows = self._conn.execute(
                f"SELECT {self._COLS} FROM {self._ns}_evaluationinstances "
                "WHERE status='EVALCOMPLETED' ORDER BY starttime DESC"
            ).fetchall()
        return [self._from_row(r) for r in rows]

    def update(self, instance: EvaluationInstance) -> bool:
        with self._lock, self._conn:
            cur = self._conn.execute(
                f"UPDATE {self._ns}_evaluationinstances SET status=?, starttime=?, "
                "endtime=?, evaluationclass=?, engineparamsgeneratorclass=?, env=?, "
                "evaluatorresults=?, evaluatorresultshtml=?, evaluatorresultsjson=? "
                "WHERE id=?",
                self._to_row(instance)[1:] + (instance.id,),
            )
            return cur.rowcount > 0

    def delete(self, instance_id: str) -> bool:
        with self._lock, self._conn:
            cur = self._conn.execute(
                f"DELETE FROM {self._ns}_evaluationinstances WHERE id=?", (instance_id,)
            )
            return cur.rowcount > 0


class SQLiteModels(_Repo, base.Models):
    def insert(self, model: Model) -> None:
        with self._lock, self._conn:
            self._conn.execute(
                f"INSERT OR REPLACE INTO {self._ns}_models (id, models) VALUES (?,?)",
                (model.id, model.models),
            )

    def get(self, model_id: str) -> Optional[Model]:
        with self._lock:
            row = self._conn.execute(
                f"SELECT id, models FROM {self._ns}_models WHERE id=?", (model_id,)
            ).fetchone()
        return Model(id=row[0], models=row[1]) if row else None

    def delete(self, model_id: str) -> bool:
        with self._lock, self._conn:
            cur = self._conn.execute(
                f"DELETE FROM {self._ns}_models WHERE id=?", (model_id,)
            )
            return cur.rowcount > 0


class SQLiteEvents(_Repo, base.Events):
    def init(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        with self._lock, self._conn:
            self._conn.execute(
                f"INSERT OR IGNORE INTO {self._ns}_events_inited (appid, channelid) VALUES (?,?)",
                (app_id, channel_id),
            )
        self._c._inited_cache.add((app_id, channel_id))
        return True

    def _check_init(self, app_id: int, channel_id: Optional[int]) -> None:
        if (app_id, channel_id) in self._c._inited_cache:
            return
        with self._lock:
            row = self._conn.execute(
                f"SELECT 1 FROM {self._ns}_events_inited WHERE appid=? AND channelid IS ?",
                (app_id, channel_id),
            ).fetchone()
        if row is None:
            raise base.StorageError(
                f"Events store for app {app_id} channel {channel_id} not initialized."
            )
        self._c._inited_cache.add((app_id, channel_id))

    def remove(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        self._c._inited_cache.discard((app_id, channel_id))
        with self._lock, self._conn:
            self._conn.execute(
                f"DELETE FROM {self._ns}_events WHERE appid=? AND channelid IS ?",
                (app_id, channel_id),
            )
            cur = self._conn.execute(
                f"DELETE FROM {self._ns}_events_inited WHERE appid=? AND channelid IS ?",
                (app_id, channel_id),
            )
            return cur.rowcount > 0

    def close(self) -> None:
        pass

    def insert(self, event: Event, app_id: int, channel_id: Optional[int] = None) -> str:
        return self.insert_batch([event], app_id, channel_id)[0]

    def insert_batch(
        self, events: Sequence[Event], app_id: int, channel_id: Optional[int] = None
    ) -> List[str]:
        self._check_init(app_id, channel_id)
        ids, rows = [], []
        for ev in events:
            eid = uuid.uuid4().hex  # store-assigned, any client id ignored
            ids.append(eid)
            rows.append(
                (
                    eid, app_id, channel_id, ev.event, ev.entity_type, ev.entity_id,
                    ev.target_entity_type, ev.target_entity_id,
                    json.dumps(ev.properties.to_dict()), _us(ev.event_time),
                    ev.pr_id, _us(ev.creation_time),
                )
            )
        with self._lock, self._conn:
            self._conn.executemany(
                f"INSERT INTO {self._ns}_events VALUES ({','.join('?' * 12)})", rows
            )
        return ids

    def create_batch(
        self, events: Sequence[Event], app_id: int,
        channel_id: Optional[int] = None,
        tokens: Optional[Sequence[str]] = None,
    ) -> List[str]:
        """One transaction, one executemany, per-item exactly-once: ids
        derive from the sub-tokens and ``id`` is the PRIMARY KEY, so
        ``INSERT OR IGNORE`` makes a replay after a partial landing skip
        exactly the rows that already committed."""
        self._check_init(app_id, channel_id)
        if tokens is None:
            # One uuid4 per BATCH, not per event: at 100k+ ev/s the
            # per-event uuid4() alone costs more than the sqlite insert.
            pre = uuid.uuid4().hex
            tokens = [f"{pre}{i:x}" for i in range(len(events))]
        else:
            tokens = list(tokens)
        if len(tokens) != len(events):
            raise base.StorageError(
                f"create_batch: {len(events)} events but {len(tokens)} "
                "tokens")
        ids, rows = [], []
        dumps, empty_props, us = json.dumps, "{}", _us
        append = rows.append
        for ev, tok in zip(events, tokens):
            eid = f"bt{tok}"  # base.batch_event_id, inlined for the hot loop
            ids.append(eid)
            props = ev.properties._fields  # skip the to_dict() copy
            append(
                (
                    eid, app_id, channel_id, ev.event, ev.entity_type, ev.entity_id,
                    ev.target_entity_type, ev.target_entity_id,
                    dumps(props) if props else empty_props, us(ev.event_time),
                    ev.pr_id, us(ev.creation_time),
                )
            )
        with self._lock, self._conn:
            self._conn.executemany(
                f"INSERT OR IGNORE INTO {self._ns}_events "
                f"VALUES ({','.join('?' * 12)})", rows
            )
        return ids

    def _row_to_event(self, r) -> Event:
        return Event(
            event_id=r[0], event=r[3], entity_type=r[4], entity_id=r[5],
            target_entity_type=r[6], target_entity_id=r[7],
            properties=DataMap(json.loads(r[8])), event_time=_dt_from(r[9]),
            pr_id=r[10], creation_time=_dt_from(r[11]),
        )

    def get(self, event_id: str, app_id: int, channel_id: Optional[int] = None):
        self._check_init(app_id, channel_id)
        with self._lock:
            row = self._conn.execute(
                f"SELECT * FROM {self._ns}_events WHERE id=? AND appid=? AND channelid IS ?",
                (event_id, app_id, channel_id),
            ).fetchone()
        return self._row_to_event(row) if row else None

    def delete(self, event_id: str, app_id: int, channel_id: Optional[int] = None) -> bool:
        self._check_init(app_id, channel_id)
        with self._lock, self._conn:
            cur = self._conn.execute(
                f"DELETE FROM {self._ns}_events WHERE id=? AND appid=? AND channelid IS ?",
                (event_id, app_id, channel_id),
            )
            return cur.rowcount > 0

    def latest_event_time(
        self, app_id: int, channel_id: Optional[int] = None
    ) -> Optional[_dt.datetime]:
        """Ingest high-watermark: one indexed MAX over (appid, channelid,
        eventtime) — the freshness anchor must stay O(log n), it is
        polled per ingest batch and per refresh cycle."""
        self._check_init(app_id, channel_id)
        with self._lock:
            row = self._conn.execute(
                f"SELECT MAX(eventtime) FROM {self._ns}_events "
                "WHERE appid=? AND channelid IS ?",
                (app_id, channel_id),
            ).fetchone()
        return _dt_from(row[0]) if row and row[0] is not None else None

    def _where(
        self, app_id, channel_id, start_time, until_time, entity_type, entity_id,
        event_names, target_entity_type, target_entity_id,
    ):
        clauses = ["appid=?", "channelid IS ?"]
        params: List[Any] = [app_id, channel_id]
        if start_time is not None:
            clauses.append("eventtime>=?")
            params.append(_us(start_time))
        if until_time is not None:
            clauses.append("eventtime<?")
            params.append(_us(until_time))
        if entity_type is not None:
            clauses.append("entitytype=?")
            params.append(entity_type)
        if entity_id is not None:
            clauses.append("entityid=?")
            params.append(entity_id)
        if event_names is not None:
            clauses.append(f"event IN ({','.join('?' * len(event_names))})")
            params.extend(event_names)
        if target_entity_type is not None:
            clauses.append("targetentitytype=?")
            params.append(target_entity_type)
        if target_entity_id is not None:
            clauses.append("targetentityid=?")
            params.append(target_entity_id)
        return " AND ".join(clauses), params

    def find(
        self,
        app_id: int,
        channel_id: Optional[int] = None,
        *,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        entity_type: Optional[str] = None,
        entity_id: Optional[str] = None,
        event_names: Optional[Sequence[str]] = None,
        target_entity_type: Optional[str] = None,
        target_entity_id: Optional[str] = None,
        limit: Optional[int] = None,
        reversed: bool = False,
    ) -> Iterator[Event]:
        self._check_init(app_id, channel_id)
        where, params = self._where(
            app_id, channel_id, start_time, until_time, entity_type, entity_id,
            event_names, target_entity_type, target_entity_id,
        )
        order = "DESC" if reversed else "ASC"
        sql = (
            f"SELECT * FROM {self._ns}_events WHERE {where} "
            f"ORDER BY eventtime {order}, creationtime {order}"
        )
        if limit is not None and limit >= 0:
            sql += f" LIMIT {int(limit)}"
        # Lazy batched scan on its OWN connection: a full-store read
        # (training, streamed remote pages) never materializes every Event
        # at once, and WAL gives the reader connection snapshot isolation —
        # concurrent writes through the client's shared connection cannot
        # make an in-progress scan skip or repeat rows (a cursor on the
        # SAME connection as the writer has no such guarantee).  Query
        # errors still surface at call time (execute runs eagerly).
        if self._c.path == ":memory:":
            # No second connection can see a :memory: database.
            with self._lock:
                rows = self._conn.execute(sql, params).fetchall()
            return iter([self._row_to_event(r) for r in rows])
        rc = sqlite3.connect(self._c.path, check_same_thread=False)
        try:
            cur = rc.execute(sql, params)
        except Exception:
            rc.close()
            raise

        def gen():
            try:
                while True:
                    rows = cur.fetchmany(1024)
                    if not rows:
                        return
                    for r in rows:
                        yield self._row_to_event(r)
            finally:
                rc.close()

        return gen()

    # Arrow field -> SQL column, in EVENT_ARROW_SCHEMA order
    _SQL_COL = {
        "event_id": "id", "event": "event", "entity_type": "entitytype",
        "entity_id": "entityid", "target_entity_type": "targetentitytype",
        "target_entity_id": "targetentityid", "properties_json": "properties",
        "event_time_us": "eventtime", "pr_id": "prid",
        "creation_time_us": "creationtime",
    }

    def find_columnar(
        self,
        app_id: int,
        channel_id: Optional[int] = None,
        *,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        entity_type: Optional[str] = None,
        entity_id: Optional[str] = None,
        event_names: Optional[Sequence[str]] = None,
        target_entity_type: Optional[str] = None,
        target_entity_id: Optional[str] = None,
        ordered: bool = True,
        columns: Optional[Sequence[str]] = None,
    ) -> pa.Table:
        """Columnar scan straight out of SQL — skips Event materialization.

        Column-major extraction: rows are transposed per fetch chunk with
        ``zip(*rows)`` (one C call) instead of a Python loop appending to
        ten lists per row — the loop was the scan ceiling at the ML-25M
        shape (VERDICT r4 item 1).  ``columns`` narrows the SELECT;
        ``ordered=False`` drops the ORDER BY (training scans don't need
        time order and the sort is O(N log N) in sqlite).
        """
        self._check_init(app_id, channel_id)
        where, params = self._where(
            app_id, channel_id, start_time, until_time, entity_type, entity_id,
            event_names, target_entity_type, target_entity_id,
        )
        fields = [f for f in base.EVENT_ARROW_SCHEMA
                  if columns is None or f.name in set(columns)]
        sel = ", ".join(self._SQL_COL[f.name] for f in fields)
        sql = f"SELECT {sel} FROM {self._ns}_events WHERE {where}"
        if ordered:
            sql += " ORDER BY eventtime ASC"
        batches = []
        schema = pa.schema(fields)
        with self._lock:
            cur = self._conn.execute(sql, params)
            while True:
                rows = cur.fetchmany(262_144)
                if not rows:
                    break
                cols = list(zip(*rows))
                batches.append(pa.record_batch(
                    [pa.array(c, type=f.type)
                     for c, f in zip(cols, fields)], schema=schema))
        if not batches:
            return schema.empty_table()
        table = pa.Table.from_batches(batches, schema=schema)
        if columns is not None:
            table = table.select(list(columns))
        return table

    def insert_columnar(
        self, table: pa.Table, app_id: int, channel_id: Optional[int] = None
    ) -> int:
        """Bulk ingest via one executemany per chunk — no Event objects.
        sqlite needs Python values either way; ``zip`` over column lists
        is the cheapest way to produce them."""
        self._check_init(app_id, channel_id)
        table = base.stamp_event_ids(
            base.normalize_event_table(table),
            prefix=f"blk{uuid.uuid4().hex[:12]}-")
        sql = (
            f"INSERT INTO {self._ns}_events (id, appid, channelid, event, "
            f"entitytype, entityid, targetentitytype, targetentityid, "
            f"properties, eventtime, prid, creationtime) "
            f"VALUES (?, ?, ?, ?, ?, ?, ?, ?, ?, ?, ?, ?)"
        )
        order = ("event_id", "event", "entity_type", "entity_id",
                 "target_entity_type", "target_entity_id",
                 "properties_json", "event_time_us", "pr_id",
                 "creation_time_us")
        n = 0
        with self._lock, self._conn:
            for start in range(0, table.num_rows, 262_144):
                chunk = table.slice(start, 262_144)
                eid, ev, ety, eid2, tety, teid, props, evt, prid, ct = (
                    chunk.column(name).to_pylist() for name in order)
                rows = zip(eid, [app_id] * len(eid), [channel_id] * len(eid),
                           ev, ety, eid2, tety, teid, props, evt, prid, ct)
                self._conn.executemany(sql, rows)
                n += len(eid)
        return n


class SQLiteSpillQueues(_Repo, base.SpillQueues):
    """Shared spill queue over one sqlite file (ISSUE 15).

    Lease claims are per-row conditional UPDATEs (``WHERE id=? AND
    (pending OR expired)``), each atomic at the sqlite level, so two
    drainer processes sharing the file can race a lease and exactly one
    wins each record — no table lock held across the batch."""

    _COLS = ("id,queue,token,payload,events,attempts,state,leaseowner,"
             "leaseexpires,reason,enqueued")

    def _from_row(self, r) -> base.QueueRecord:
        return base.QueueRecord(
            id=r[0], payload=json.loads(r[3]), token=r[2], events=r[4],
            attempts=r[5], state=r[6], lease_owner=r[7],
            lease_expires_s=r[8], reason=r[9], enqueued_s=r[10])

    def enqueue(self, queue, payload, token=None, events=1, now_s=None):
        rid = uuid.uuid4().hex
        now = time.time() if now_s is None else float(now_s)
        with self._lock:
            if token is not None:
                row = self._conn.execute(
                    f"SELECT id FROM {self._ns}_spillqueue "
                    "WHERE queue=? AND token=?", (queue, token)).fetchone()
                if row is not None:
                    return row[0]  # lost-reply retry: already queued
            try:
                with self._conn:
                    self._conn.execute(
                        f"INSERT INTO {self._ns}_spillqueue "
                        "(id,queue,token,payload,events,attempts,state,"
                        "leaseowner,leaseexpires,reason,enqueued) "
                        "VALUES (?,?,?,?,?,0,'pending',NULL,NULL,NULL,?)",
                        (rid, queue, token,
                         json.dumps(payload, separators=(",", ":")),
                         int(events), now))
            except sqlite3.IntegrityError:
                # (queue, token) raced another process's enqueue
                row = self._conn.execute(
                    f"SELECT id FROM {self._ns}_spillqueue "
                    "WHERE queue=? AND token=?", (queue, token)).fetchone()
                if row is not None:
                    return row[0]
                raise
        return rid

    def lease(self, queue, owner, n, ttl_s, now_s=None):
        now = time.time() if now_s is None else float(now_s)
        expires = now + float(ttl_s)
        claimed: List[str] = []
        with self._lock, self._conn:
            rows = self._conn.execute(
                f"SELECT id FROM {self._ns}_spillqueue WHERE queue=? AND "
                "(state='pending' OR (state='leased' AND leaseexpires<?)) "
                "ORDER BY seq LIMIT ?", (queue, now, int(n))).fetchall()
            for (rid,) in rows:
                cur = self._conn.execute(
                    f"UPDATE {self._ns}_spillqueue SET state='leased', "
                    "leaseowner=?, leaseexpires=?, attempts=attempts+1 "
                    "WHERE id=? AND (state='pending' OR "
                    "(state='leased' AND leaseexpires<?))",
                    (owner, expires, rid, now))
                if cur.rowcount:
                    claimed.append(rid)
            if not claimed:
                return []
            out = self._conn.execute(
                f"SELECT {self._COLS} FROM {self._ns}_spillqueue "
                f"WHERE id IN ({','.join('?' * len(claimed))}) "
                "ORDER BY seq", claimed).fetchall()
        return [self._from_row(r) for r in out]

    def ack(self, queue, ids, owner):
        ids = list(ids)
        if not ids:
            return 0
        with self._lock, self._conn:
            cur = self._conn.execute(
                f"DELETE FROM {self._ns}_spillqueue WHERE queue=? AND "
                f"leaseowner=? AND state='leased' AND "
                f"id IN ({','.join('?' * len(ids))})",
                [queue, owner] + ids)
            return cur.rowcount

    def nack(self, queue, ids, owner):
        ids = list(ids)
        if not ids:
            return 0
        with self._lock, self._conn:
            cur = self._conn.execute(
                f"UPDATE {self._ns}_spillqueue SET state='pending', "
                f"leaseowner=NULL, leaseexpires=NULL WHERE queue=? AND "
                f"leaseowner=? AND state='leased' AND "
                f"id IN ({','.join('?' * len(ids))})",
                [queue, owner] + ids)
            return cur.rowcount

    def dead_letter(self, queue, record_id, owner, reason):
        with self._lock, self._conn:
            cur = self._conn.execute(
                f"UPDATE {self._ns}_spillqueue SET state='dead', "
                "leaseowner=NULL, leaseexpires=NULL, reason=? "
                "WHERE queue=? AND id=? AND leaseowner=? AND "
                "state='leased'", (str(reason)[:500], queue, record_id,
                                   owner))
            return cur.rowcount > 0

    def requeue_dead(self, queue):
        with self._lock, self._conn:
            row = self._conn.execute(
                f"SELECT COALESCE(SUM(events),0) FROM "
                f"{self._ns}_spillqueue WHERE queue=? AND state='dead'",
                (queue,)).fetchone()
            self._conn.execute(
                f"UPDATE {self._ns}_spillqueue SET state='pending', "
                "reason=NULL WHERE queue=? AND state='dead'", (queue,))
            return int(row[0])

    def stats(self, queue, now_s=None):
        now = time.time() if now_s is None else float(now_s)
        out = {"pending": 0, "leased": 0, "expired": 0, "dead": 0,
               "pendingEvents": 0, "leasedEvents": 0, "deadEvents": 0}
        with self._lock:
            rows = self._conn.execute(
                f"SELECT state, leaseexpires<?, COUNT(*), "
                f"COALESCE(SUM(events),0) FROM {self._ns}_spillqueue "
                "WHERE queue=? GROUP BY state, leaseexpires<?",
                (now, queue, now)).fetchall()
        for state, expired, n, ev in rows:
            out[state] = out.get(state, 0) + n
            out[f"{state}Events"] = out.get(f"{state}Events", 0) + ev
            if state == "leased" and expired:
                out["expired"] += n
        return out

    def peek(self, queue, n=5, state="pending"):
        with self._lock:
            rows = self._conn.execute(
                f"SELECT {self._COLS} FROM {self._ns}_spillqueue "
                "WHERE queue=? AND state=? ORDER BY seq LIMIT ?",
                (queue, state, int(n))).fetchall()
        return [self._from_row(r) for r in rows]


class SQLiteKV(_Repo, base.KV):
    def put(self, ns: str, key: str, value: bytes) -> None:
        with self._lock, self._conn:
            self._conn.execute(
                f"INSERT OR REPLACE INTO {self._ns}_kv "
                "(ns, key, value, updated) VALUES (?,?,?,?)",
                (ns, key, sqlite3.Binary(bytes(value)), time.time()))

    def get(self, ns: str, key: str) -> Optional[bytes]:
        with self._lock:
            row = self._conn.execute(
                f"SELECT value FROM {self._ns}_kv WHERE ns=? AND key=?",
                (ns, key)).fetchone()
        return bytes(row[0]) if row else None

    def delete(self, ns: str, key: str) -> bool:
        with self._lock, self._conn:
            cur = self._conn.execute(
                f"DELETE FROM {self._ns}_kv WHERE ns=? AND key=?",
                (ns, key))
            return cur.rowcount > 0

    def count(self, ns: str) -> int:
        with self._lock:
            row = self._conn.execute(
                f"SELECT COUNT(*) FROM {self._ns}_kv WHERE ns=?",
                (ns,)).fetchone()
        return int(row[0])

    def prune(self, ns: str, keep: int) -> int:
        with self._lock, self._conn:
            cur = self._conn.execute(
                f"DELETE FROM {self._ns}_kv WHERE ns=? AND key NOT IN "
                f"(SELECT key FROM {self._ns}_kv WHERE ns=? "
                "ORDER BY updated DESC LIMIT ?)",
                (ns, ns, max(int(keep), 0)))
            return cur.rowcount
