"""In-memory storage backend — the test double.

Reference analogue: the reference's unit suites use fake/in-memory stores
(SURVEY.md §4); this backend implements every repository trait so contract
tests and engine-workflow tests need no filesystem.
"""

from __future__ import annotations

import copy
import datetime as _dt
import itertools
import threading
import uuid
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage import base
from predictionio_tpu.data.storage.base import (
    AccessKey,
    App,
    Channel,
    EngineInstance,
    EvaluationInstance,
    Model,
)

__all__ = [
    "MemoryApps",
    "MemoryAccessKeys",
    "MemoryChannels",
    "MemoryEngineInstances",
    "MemoryEvaluationInstances",
    "MemoryModels",
    "MemoryEvents",
]


class MemoryApps(base.Apps):
    def __init__(self):
        self._lock = threading.Lock()
        self._apps: Dict[int, App] = {}
        self._next = itertools.count(1)

    def insert(self, app: App) -> Optional[int]:
        with self._lock:
            if any(a.name == app.name for a in self._apps.values()):
                return None
            if app.id is not None:
                app_id = app.id
                if app_id in self._apps:
                    return None
            else:
                app_id = next(self._next)
                while app_id in self._apps:  # skip past explicitly-taken ids
                    app_id = next(self._next)
            self._apps[app_id] = App(id=app_id, name=app.name, description=app.description)
            return app_id

    def get(self, app_id: int) -> Optional[App]:
        return copy.copy(self._apps.get(app_id))

    def get_by_name(self, name: str) -> Optional[App]:
        return copy.copy(next((a for a in self._apps.values() if a.name == name), None))

    def get_all(self) -> List[App]:
        return sorted((copy.copy(a) for a in self._apps.values()), key=lambda a: a.id)

    def update(self, app: App) -> bool:
        with self._lock:
            if app.id not in self._apps:
                return False
            self._apps[app.id] = copy.copy(app)
            return True

    def delete(self, app_id: int) -> bool:
        with self._lock:
            return self._apps.pop(app_id, None) is not None


class MemoryAccessKeys(base.AccessKeys):
    def __init__(self):
        self._keys: Dict[str, AccessKey] = {}
        self._lock = threading.Lock()

    def insert(self, access_key: AccessKey) -> Optional[str]:
        with self._lock:
            k = access_key.key or AccessKey.generate(access_key.app_id).key
            if k in self._keys:
                return None
            self._keys[k] = AccessKey(key=k, app_id=access_key.app_id, events=tuple(access_key.events))
            return k

    def get(self, key: str) -> Optional[AccessKey]:
        return self._keys.get(key)

    def get_all(self) -> List[AccessKey]:
        return list(self._keys.values())

    def get_by_app_id(self, app_id: int) -> List[AccessKey]:
        return [k for k in self._keys.values() if k.app_id == app_id]

    def update(self, access_key: AccessKey) -> bool:
        with self._lock:
            if access_key.key not in self._keys:
                return False
            self._keys[access_key.key] = access_key
            return True

    def delete(self, key: str) -> bool:
        with self._lock:
            return self._keys.pop(key, None) is not None


class MemoryChannels(base.Channels):
    def __init__(self):
        self._channels: Dict[int, Channel] = {}
        self._next = itertools.count(1)
        self._lock = threading.Lock()

    def _insert(self, channel: Channel) -> Optional[int]:
        with self._lock:
            if any(
                c.app_id == channel.app_id and c.name == channel.name
                for c in self._channels.values()
            ):
                return None
            if channel.id is not None:
                cid = channel.id
                if cid in self._channels:
                    return None
            else:
                cid = next(self._next)
                while cid in self._channels:
                    cid = next(self._next)
            self._channels[cid] = Channel(id=cid, name=channel.name, app_id=channel.app_id)
            return cid

    def get(self, channel_id: int) -> Optional[Channel]:
        return self._channels.get(channel_id)

    def get_by_app_id(self, app_id: int) -> List[Channel]:
        return [c for c in self._channels.values() if c.app_id == app_id]

    def delete(self, channel_id: int) -> bool:
        with self._lock:
            return self._channels.pop(channel_id, None) is not None


class MemoryEngineInstances(base.EngineInstances):
    def __init__(self):
        self._instances: Dict[str, EngineInstance] = {}
        self._lock = threading.Lock()

    def insert(self, instance: EngineInstance) -> str:
        with self._lock:
            iid = instance.id or uuid.uuid4().hex
            instance.id = iid
            # store a snapshot: callers mutating their object must go through
            # update(), same as on the sqlite backend
            self._instances[iid] = copy.deepcopy(instance)
            return iid

    def get(self, instance_id: str) -> Optional[EngineInstance]:
        return copy.deepcopy(self._instances.get(instance_id))

    def get_all(self) -> List[EngineInstance]:
        return [copy.deepcopy(i) for i in self._instances.values()]

    def _completed(self, engine_id, engine_version, engine_variant):
        return sorted(
            (
                copy.deepcopy(i)
                for i in self._instances.values()
                if i.status == "COMPLETED"
                and i.engine_id == engine_id
                and i.engine_version == engine_version
                and i.engine_variant == engine_variant
            ),
            key=lambda i: i.start_time,
            reverse=True,
        )

    def get_latest_completed(self, engine_id, engine_version, engine_variant):
        c = self._completed(engine_id, engine_version, engine_variant)
        return c[0] if c else None

    def get_completed(self, engine_id, engine_version, engine_variant):
        return self._completed(engine_id, engine_version, engine_variant)

    def update(self, instance: EngineInstance) -> bool:
        with self._lock:
            if instance.id not in self._instances:
                return False
            self._instances[instance.id] = copy.deepcopy(instance)
            return True

    def delete(self, instance_id: str) -> bool:
        with self._lock:
            return self._instances.pop(instance_id, None) is not None


class MemoryEvaluationInstances(base.EvaluationInstances):
    def __init__(self):
        self._instances: Dict[str, EvaluationInstance] = {}
        self._lock = threading.Lock()

    def insert(self, instance: EvaluationInstance) -> str:
        with self._lock:
            iid = instance.id or uuid.uuid4().hex
            instance.id = iid
            self._instances[iid] = copy.deepcopy(instance)
            return iid

    def get(self, instance_id: str) -> Optional[EvaluationInstance]:
        return copy.deepcopy(self._instances.get(instance_id))

    def get_all(self) -> List[EvaluationInstance]:
        return [copy.deepcopy(i) for i in self._instances.values()]

    def get_completed(self) -> List[EvaluationInstance]:
        return sorted(
            (copy.deepcopy(i) for i in self._instances.values()
             if i.status == "EVALCOMPLETED"),
            key=lambda i: i.start_time,
            reverse=True,
        )

    def update(self, instance: EvaluationInstance) -> bool:
        with self._lock:
            if instance.id not in self._instances:
                return False
            self._instances[instance.id] = copy.deepcopy(instance)
            return True

    def delete(self, instance_id: str) -> bool:
        with self._lock:
            return self._instances.pop(instance_id, None) is not None


class MemoryModels(base.Models):
    def __init__(self):
        self._models: Dict[str, bytes] = {}
        self._lock = threading.Lock()

    def insert(self, model: Model) -> None:
        with self._lock:
            self._models[model.id] = model.models

    def get(self, model_id: str) -> Optional[Model]:
        blob = self._models.get(model_id)
        return Model(id=model_id, models=blob) if blob is not None else None

    def delete(self, model_id: str) -> bool:
        with self._lock:
            return self._models.pop(model_id, None) is not None


def _match(
    ev: Event,
    start_time,
    until_time,
    entity_type,
    entity_id,
    event_names,
    target_entity_type,
    target_entity_id,
) -> bool:
    # Compare in epoch micros with the shared naive-datetime-is-UTC rule
    # (base.epoch_us) — the sqlite/parquet backends filter on converted
    # integers, so a naive bound against an aware event time must mean
    # the same instant here too, not raise or shift by the local zone.
    # Boundary contract (pinned by tests/test_storage_contract.py):
    # start_time INCLUSIVE, until_time EXCLUSIVE.
    if start_time is not None and \
            base.epoch_us(ev.event_time) < base.epoch_us(start_time):
        return False
    if until_time is not None and \
            base.epoch_us(ev.event_time) >= base.epoch_us(until_time):
        return False
    if entity_type is not None and ev.entity_type != entity_type:
        return False
    if entity_id is not None and ev.entity_id != entity_id:
        return False
    if event_names is not None and ev.event not in event_names:
        return False
    if target_entity_type is not None and ev.target_entity_type != target_entity_type:
        return False
    if target_entity_id is not None and ev.target_entity_id != target_entity_id:
        return False
    return True


class MemoryEvents(base.Events):
    def __init__(self):
        self._store: Dict[Tuple[int, Optional[int]], Dict[str, Event]] = {}
        self._lock = threading.Lock()

    def _bucket(self, app_id: int, channel_id: Optional[int]) -> Dict[str, Event]:
        key = (app_id, channel_id)
        if key not in self._store:
            raise base.StorageError(
                f"Events store for app {app_id} channel {channel_id} not initialized."
            )
        return self._store[key]

    def init(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        with self._lock:
            self._store.setdefault((app_id, channel_id), {})
            return True

    def remove(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        with self._lock:
            return self._store.pop((app_id, channel_id), None) is not None

    def close(self) -> None:
        pass

    def insert(self, event: Event, app_id: int, channel_id: Optional[int] = None) -> str:
        with self._lock:
            bucket = self._bucket(app_id, channel_id)
            eid = uuid.uuid4().hex  # store-assigned, any client id ignored
            bucket[eid] = event.with_event_id(eid)
            return eid

    def get(self, event_id: str, app_id: int, channel_id: Optional[int] = None):
        return self._bucket(app_id, channel_id).get(event_id)

    def delete(self, event_id: str, app_id: int, channel_id: Optional[int] = None) -> bool:
        with self._lock:
            return self._bucket(app_id, channel_id).pop(event_id, None) is not None

    def find(
        self,
        app_id: int,
        channel_id: Optional[int] = None,
        *,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        entity_type: Optional[str] = None,
        entity_id: Optional[str] = None,
        event_names: Optional[Sequence[str]] = None,
        target_entity_type: Optional[str] = None,
        target_entity_id: Optional[str] = None,
        limit: Optional[int] = None,
        reversed: bool = False,
    ) -> Iterator[Event]:
        evs = [
            e
            for e in self._bucket(app_id, channel_id).values()
            if _match(
                e, start_time, until_time, entity_type, entity_id,
                event_names, target_entity_type, target_entity_id,
            )
        ]
        # Same ordering key as sqlite's `ORDER BY eventtime, creationtime`
        # — through epoch_us so naive and aware stamps interleave by
        # instant instead of raising on comparison.
        evs.sort(key=lambda e: (base.epoch_us(e.event_time),
                                base.epoch_us(e.creation_time) or 0),
                 reverse=reversed)
        if limit is not None and limit >= 0:
            evs = evs[:limit]
        return iter(evs)

    def latest_event_time(
        self, app_id: int, channel_id: Optional[int] = None
    ) -> Optional[_dt.datetime]:
        bucket = self._bucket(app_id, channel_id)
        if not bucket:
            return None
        return max((e.event_time for e in bucket.values()),
                   key=base.epoch_us)
