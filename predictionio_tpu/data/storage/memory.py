"""In-memory storage backend — the test double.

Reference analogue: the reference's unit suites use fake/in-memory stores
(SURVEY.md §4); this backend implements every repository trait so contract
tests and engine-workflow tests need no filesystem.
"""

from __future__ import annotations

import collections
import copy
import datetime as _dt
import itertools
import threading
import time
import uuid
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage import base
from predictionio_tpu.data.storage.base import (
    AccessKey,
    App,
    Channel,
    EngineInstance,
    EvaluationInstance,
    Model,
)

__all__ = [
    "MemoryApps",
    "MemoryAccessKeys",
    "MemoryChannels",
    "MemoryEngineInstances",
    "MemoryEvaluationInstances",
    "MemoryModels",
    "MemoryEvents",
    "MemorySpillQueues",
    "MemoryKV",
]


class MemoryApps(base.Apps):
    def __init__(self):
        self._lock = threading.Lock()
        self._apps: Dict[int, App] = {}
        self._next = itertools.count(1)

    def insert(self, app: App) -> Optional[int]:
        with self._lock:
            if any(a.name == app.name for a in self._apps.values()):
                return None
            if app.id is not None:
                app_id = app.id
                if app_id in self._apps:
                    return None
            else:
                app_id = next(self._next)
                while app_id in self._apps:  # skip past explicitly-taken ids
                    app_id = next(self._next)
            self._apps[app_id] = App(id=app_id, name=app.name, description=app.description)
            return app_id

    def get(self, app_id: int) -> Optional[App]:
        return copy.copy(self._apps.get(app_id))

    def get_by_name(self, name: str) -> Optional[App]:
        return copy.copy(next((a for a in self._apps.values() if a.name == name), None))

    def get_all(self) -> List[App]:
        return sorted((copy.copy(a) for a in self._apps.values()), key=lambda a: a.id)

    def update(self, app: App) -> bool:
        with self._lock:
            if app.id not in self._apps:
                return False
            self._apps[app.id] = copy.copy(app)
            return True

    def delete(self, app_id: int) -> bool:
        with self._lock:
            return self._apps.pop(app_id, None) is not None


class MemoryAccessKeys(base.AccessKeys):
    def __init__(self):
        self._keys: Dict[str, AccessKey] = {}
        self._lock = threading.Lock()

    def insert(self, access_key: AccessKey) -> Optional[str]:
        with self._lock:
            k = access_key.key or AccessKey.generate(access_key.app_id).key
            if k in self._keys:
                return None
            self._keys[k] = AccessKey(key=k, app_id=access_key.app_id, events=tuple(access_key.events))
            return k

    def get(self, key: str) -> Optional[AccessKey]:
        return self._keys.get(key)

    def get_all(self) -> List[AccessKey]:
        return list(self._keys.values())

    def get_by_app_id(self, app_id: int) -> List[AccessKey]:
        return [k for k in self._keys.values() if k.app_id == app_id]

    def update(self, access_key: AccessKey) -> bool:
        with self._lock:
            if access_key.key not in self._keys:
                return False
            self._keys[access_key.key] = access_key
            return True

    def delete(self, key: str) -> bool:
        with self._lock:
            return self._keys.pop(key, None) is not None


class MemoryChannels(base.Channels):
    def __init__(self):
        self._channels: Dict[int, Channel] = {}
        self._next = itertools.count(1)
        self._lock = threading.Lock()

    def _insert(self, channel: Channel) -> Optional[int]:
        with self._lock:
            if any(
                c.app_id == channel.app_id and c.name == channel.name
                for c in self._channels.values()
            ):
                return None
            if channel.id is not None:
                cid = channel.id
                if cid in self._channels:
                    return None
            else:
                cid = next(self._next)
                while cid in self._channels:
                    cid = next(self._next)
            self._channels[cid] = Channel(id=cid, name=channel.name, app_id=channel.app_id)
            return cid

    def get(self, channel_id: int) -> Optional[Channel]:
        return self._channels.get(channel_id)

    def get_by_app_id(self, app_id: int) -> List[Channel]:
        return [c for c in self._channels.values() if c.app_id == app_id]

    def delete(self, channel_id: int) -> bool:
        with self._lock:
            return self._channels.pop(channel_id, None) is not None


class MemoryEngineInstances(base.EngineInstances):
    def __init__(self):
        self._instances: Dict[str, EngineInstance] = {}
        self._lock = threading.Lock()

    def insert(self, instance: EngineInstance) -> str:
        with self._lock:
            iid = instance.id or uuid.uuid4().hex
            instance.id = iid
            # store a snapshot: callers mutating their object must go through
            # update(), same as on the sqlite backend
            self._instances[iid] = copy.deepcopy(instance)
            return iid

    def get(self, instance_id: str) -> Optional[EngineInstance]:
        return copy.deepcopy(self._instances.get(instance_id))

    def get_all(self) -> List[EngineInstance]:
        return [copy.deepcopy(i) for i in self._instances.values()]

    def _completed(self, engine_id, engine_version, engine_variant):
        return sorted(
            (
                copy.deepcopy(i)
                for i in self._instances.values()
                if i.status == "COMPLETED"
                and i.engine_id == engine_id
                and i.engine_version == engine_version
                and i.engine_variant == engine_variant
            ),
            key=lambda i: i.start_time,
            reverse=True,
        )

    def get_latest_completed(self, engine_id, engine_version, engine_variant):
        c = self._completed(engine_id, engine_version, engine_variant)
        return c[0] if c else None

    def get_completed(self, engine_id, engine_version, engine_variant):
        return self._completed(engine_id, engine_version, engine_variant)

    def update(self, instance: EngineInstance) -> bool:
        with self._lock:
            if instance.id not in self._instances:
                return False
            self._instances[instance.id] = copy.deepcopy(instance)
            return True

    def delete(self, instance_id: str) -> bool:
        with self._lock:
            return self._instances.pop(instance_id, None) is not None


class MemoryEvaluationInstances(base.EvaluationInstances):
    def __init__(self):
        self._instances: Dict[str, EvaluationInstance] = {}
        self._lock = threading.Lock()

    def insert(self, instance: EvaluationInstance) -> str:
        with self._lock:
            iid = instance.id or uuid.uuid4().hex
            instance.id = iid
            self._instances[iid] = copy.deepcopy(instance)
            return iid

    def get(self, instance_id: str) -> Optional[EvaluationInstance]:
        return copy.deepcopy(self._instances.get(instance_id))

    def get_all(self) -> List[EvaluationInstance]:
        return [copy.deepcopy(i) for i in self._instances.values()]

    def get_completed(self) -> List[EvaluationInstance]:
        return sorted(
            (copy.deepcopy(i) for i in self._instances.values()
             if i.status == "EVALCOMPLETED"),
            key=lambda i: i.start_time,
            reverse=True,
        )

    def update(self, instance: EvaluationInstance) -> bool:
        with self._lock:
            if instance.id not in self._instances:
                return False
            self._instances[instance.id] = copy.deepcopy(instance)
            return True

    def delete(self, instance_id: str) -> bool:
        with self._lock:
            return self._instances.pop(instance_id, None) is not None


class MemoryModels(base.Models):
    def __init__(self):
        self._models: Dict[str, bytes] = {}
        self._lock = threading.Lock()

    def insert(self, model: Model) -> None:
        with self._lock:
            self._models[model.id] = model.models

    def get(self, model_id: str) -> Optional[Model]:
        blob = self._models.get(model_id)
        return Model(id=model_id, models=blob) if blob is not None else None

    def delete(self, model_id: str) -> bool:
        with self._lock:
            return self._models.pop(model_id, None) is not None


def _match(
    ev: Event,
    start_time,
    until_time,
    entity_type,
    entity_id,
    event_names,
    target_entity_type,
    target_entity_id,
) -> bool:
    # Compare in epoch micros with the shared naive-datetime-is-UTC rule
    # (base.epoch_us) — the sqlite/parquet backends filter on converted
    # integers, so a naive bound against an aware event time must mean
    # the same instant here too, not raise or shift by the local zone.
    # Boundary contract (pinned by tests/test_storage_contract.py):
    # start_time INCLUSIVE, until_time EXCLUSIVE.
    if start_time is not None and \
            base.epoch_us(ev.event_time) < base.epoch_us(start_time):
        return False
    if until_time is not None and \
            base.epoch_us(ev.event_time) >= base.epoch_us(until_time):
        return False
    if entity_type is not None and ev.entity_type != entity_type:
        return False
    if entity_id is not None and ev.entity_id != entity_id:
        return False
    if event_names is not None and ev.event not in event_names:
        return False
    if target_entity_type is not None and ev.target_entity_type != target_entity_type:
        return False
    if target_entity_id is not None and ev.target_entity_id != target_entity_id:
        return False
    return True


class MemoryEvents(base.Events):
    def __init__(self):
        self._store: Dict[Tuple[int, Optional[int]], Dict[str, Event]] = {}
        self._lock = threading.Lock()

    def _bucket(self, app_id: int, channel_id: Optional[int]) -> Dict[str, Event]:
        key = (app_id, channel_id)
        if key not in self._store:
            raise base.StorageError(
                f"Events store for app {app_id} channel {channel_id} not initialized."
            )
        return self._store[key]

    def init(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        with self._lock:
            self._store.setdefault((app_id, channel_id), {})
            return True

    def remove(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        with self._lock:
            return self._store.pop((app_id, channel_id), None) is not None

    def close(self) -> None:
        pass

    def insert(self, event: Event, app_id: int, channel_id: Optional[int] = None) -> str:
        with self._lock:
            bucket = self._bucket(app_id, channel_id)
            eid = uuid.uuid4().hex  # store-assigned, any client id ignored
            bucket[eid] = event.with_event_id(eid)
            return eid

    def create_batch(
        self,
        events: Sequence[Event],
        app_id: int,
        channel_id: Optional[int] = None,
        tokens: Optional[Sequence[str]] = None,
    ) -> List[str]:
        """One pass under the lock; ids derive from the sub-tokens, and a
        key already present (prior partial landing of the same batch) is
        left untouched — per-item exactly-once on replay."""
        if tokens is None:
            # One uuid4 per BATCH, not per event (see sqlite.create_batch).
            pre = uuid.uuid4().hex
            tokens = [f"{pre}{i:x}" for i in range(len(events))]
        else:
            tokens = list(tokens)
        if len(tokens) != len(events):
            raise base.StorageError(
                f"create_batch: {len(events)} events but {len(tokens)} "
                "tokens")
        with self._lock:
            bucket = self._bucket(app_id, channel_id)
            ids = []
            for ev, tok in zip(events, tokens):
                eid = f"bt{tok}"  # base.batch_event_id, inlined
                ids.append(eid)
                if eid not in bucket:
                    bucket[eid] = ev.with_event_id(eid)
            return ids

    def get(self, event_id: str, app_id: int, channel_id: Optional[int] = None):
        return self._bucket(app_id, channel_id).get(event_id)

    def delete(self, event_id: str, app_id: int, channel_id: Optional[int] = None) -> bool:
        with self._lock:
            return self._bucket(app_id, channel_id).pop(event_id, None) is not None

    def find(
        self,
        app_id: int,
        channel_id: Optional[int] = None,
        *,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        entity_type: Optional[str] = None,
        entity_id: Optional[str] = None,
        event_names: Optional[Sequence[str]] = None,
        target_entity_type: Optional[str] = None,
        target_entity_id: Optional[str] = None,
        limit: Optional[int] = None,
        reversed: bool = False,
    ) -> Iterator[Event]:
        evs = [
            e
            for e in self._bucket(app_id, channel_id).values()
            if _match(
                e, start_time, until_time, entity_type, entity_id,
                event_names, target_entity_type, target_entity_id,
            )
        ]
        # Same ordering key as sqlite's `ORDER BY eventtime, creationtime`
        # — through epoch_us so naive and aware stamps interleave by
        # instant instead of raising on comparison.
        evs.sort(key=lambda e: (base.epoch_us(e.event_time),
                                base.epoch_us(e.creation_time) or 0),
                 reverse=reversed)
        if limit is not None and limit >= 0:
            evs = evs[:limit]
        return iter(evs)

    def latest_event_time(
        self, app_id: int, channel_id: Optional[int] = None
    ) -> Optional[_dt.datetime]:
        bucket = self._bucket(app_id, channel_id)
        if not bucket:
            return None
        return max((e.event_time for e in bucket.values()),
                   key=base.epoch_us)


class MemorySpillQueues(base.SpillQueues):
    """In-process shared spill queue (ISSUE 15).

    "Shared" here means shared by every server in THIS process that holds
    the same Storage object — exactly what the multi-instance tier-1
    tests stand up; cross-process deployments ride sqlite or pioserver."""

    def __init__(self):
        self._lock = threading.Lock()
        self._queues: Dict[str, "collections.OrderedDict[str, base.QueueRecord]"] = {}
        self._by_token: Dict[Tuple[str, str], str] = {}

    def _q(self, queue: str):
        return self._queues.setdefault(queue, collections.OrderedDict())

    def enqueue(self, queue, payload, token=None, events=1, now_s=None):
        now = time.time() if now_s is None else float(now_s)
        with self._lock:
            if token is not None:
                rid = self._by_token.get((queue, token))
                if rid is not None:
                    return rid  # lost-reply retry: already queued
            rid = uuid.uuid4().hex
            self._q(queue)[rid] = base.QueueRecord(
                id=rid, payload=copy.deepcopy(payload), token=token,
                events=int(events), enqueued_s=now)
            if token is not None:
                self._by_token[(queue, token)] = rid
            return rid

    def lease(self, queue, owner, n, ttl_s, now_s=None):
        now = time.time() if now_s is None else float(now_s)
        out: List[base.QueueRecord] = []
        with self._lock:
            for rec in self._q(queue).values():
                if len(out) >= int(n):
                    break
                claimable = rec.state == "pending" or (
                    rec.state == "leased"
                    and rec.lease_expires_s is not None
                    and rec.lease_expires_s < now)
                if not claimable:
                    continue
                rec.state = "leased"
                rec.lease_owner = owner
                rec.lease_expires_s = now + float(ttl_s)
                rec.attempts += 1
                out.append(copy.deepcopy(rec))
        return out

    def _owned(self, queue, ids, owner):
        q = self._q(queue)
        return [rid for rid in ids
                if rid in q and q[rid].state == "leased"
                and q[rid].lease_owner == owner]

    def ack(self, queue, ids, owner):
        with self._lock:
            q = self._q(queue)
            owned = self._owned(queue, ids, owner)
            for rid in owned:
                rec = q.pop(rid)
                if rec.token is not None:
                    self._by_token.pop((queue, rec.token), None)
            return len(owned)

    def nack(self, queue, ids, owner):
        with self._lock:
            q = self._q(queue)
            owned = self._owned(queue, ids, owner)
            for rid in owned:
                q[rid].state = "pending"
                q[rid].lease_owner = None
                q[rid].lease_expires_s = None
            return len(owned)

    def dead_letter(self, queue, record_id, owner, reason):
        with self._lock:
            owned = self._owned(queue, [record_id], owner)
            if not owned:
                return False
            rec = self._q(queue)[record_id]
            rec.state = "dead"
            rec.lease_owner = None
            rec.lease_expires_s = None
            rec.reason = str(reason)[:500]
            return True

    def requeue_dead(self, queue):
        with self._lock:
            n_events = 0
            for rec in self._q(queue).values():
                if rec.state == "dead":
                    rec.state = "pending"
                    rec.reason = None
                    n_events += rec.events
            return n_events

    def stats(self, queue, now_s=None):
        now = time.time() if now_s is None else float(now_s)
        out = {"pending": 0, "leased": 0, "expired": 0, "dead": 0,
               "pendingEvents": 0, "leasedEvents": 0, "deadEvents": 0}
        with self._lock:
            for rec in self._q(queue).values():
                out[rec.state] = out.get(rec.state, 0) + 1
                key = f"{rec.state}Events"
                out[key] = out.get(key, 0) + rec.events
                if rec.state == "leased" and rec.lease_expires_s is not None \
                        and rec.lease_expires_s < now:
                    out["expired"] += 1
        return out

    def peek(self, queue, n=5, state="pending"):
        with self._lock:
            return [copy.deepcopy(rec) for rec in self._q(queue).values()
                    if rec.state == state][: int(n)]


class MemoryKV(base.KV):
    def __init__(self):
        self._lock = threading.Lock()
        self._data: Dict[str, Dict[str, Tuple[bytes, float]]] = {}

    def put(self, ns: str, key: str, value: bytes) -> None:
        with self._lock:
            self._data.setdefault(ns, {})[key] = (bytes(value), time.time())

    def get(self, ns: str, key: str) -> Optional[bytes]:
        hit = self._data.get(ns, {}).get(key)
        return hit[0] if hit is not None else None

    def delete(self, ns: str, key: str) -> bool:
        with self._lock:
            return self._data.get(ns, {}).pop(key, None) is not None

    def count(self, ns: str) -> int:
        return len(self._data.get(ns, {}))

    def prune(self, ns: str, keep: int) -> int:
        with self._lock:
            entries = self._data.get(ns, {})
            if len(entries) <= keep:
                return 0
            ordered = sorted(entries.items(), key=lambda kv: kv[1][1],
                             reverse=True)
            drop = ordered[max(int(keep), 0):]
            for k, _ in drop:
                del entries[k]
            return len(drop)
