"""Event JSON wire codec.

Reference: data/src/main/scala/org/apache/predictionio/data/storage/
EventJson4sSupport.scala — reads/writes the public event JSON schema
(SURVEY.md Appendix A) with ISO-8601 timestamps.
"""

from __future__ import annotations

import datetime as _dt
from typing import Any, Dict, Mapping, Optional

from predictionio_tpu.data.event import (
    DataMap,
    Event,
    EventValidationError,
    validate_event,
)

__all__ = ["event_to_json", "event_from_json", "parse_iso8601", "format_iso8601"]


def parse_iso8601(s: str) -> _dt.datetime:
    """Parse an ISO-8601 timestamp; naive times are taken as UTC.

    The reference uses joda-time's ISODateTimeFormat which accepts
    ``Z`` / ``+HH:MM`` offsets and fractional seconds.
    """
    if not isinstance(s, str):
        raise EventValidationError(f"Cannot convert {s!r} to a timestamp.")
    text = s.strip()
    if text.endswith(("Z", "z")):
        text = text[:-1] + "+00:00"
    try:
        dt = _dt.datetime.fromisoformat(text)
    except ValueError as e:
        raise EventValidationError(f"Invalid ISO-8601 timestamp: {s!r}") from e
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=_dt.timezone.utc)
    return dt


def format_iso8601(dt: _dt.datetime) -> str:
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=_dt.timezone.utc)
    return dt.isoformat(timespec="milliseconds")


def event_from_json(obj: Mapping[str, Any], *, validate: bool = True) -> Event:
    """Deserialize the public event JSON into an :class:`Event`.

    Unknown top-level keys are rejected to match the reference's strict
    extractor behavior on required fields while tolerating the documented
    optional ones.
    """
    if not isinstance(obj, Mapping):
        raise EventValidationError("Event JSON must be an object.")
    try:
        name = obj["event"]
        entity_type = obj["entityType"]
        entity_id = obj["entityId"]
    except KeyError as e:
        raise EventValidationError(f"field {e.args[0]} is required.") from None
    for fld, v in (("event", name), ("entityType", entity_type), ("entityId", entity_id)):
        if not isinstance(v, str):
            raise EventValidationError(f"field {fld} must be a string.")
    props = obj.get("properties") or {}
    if not isinstance(props, Mapping):
        raise EventValidationError("properties must be a JSON object.")
    event_time_raw = obj.get("eventTime")
    event_time = parse_iso8601(event_time_raw) if event_time_raw is not None else None
    creation_raw = obj.get("creationTime")
    creation_time = parse_iso8601(creation_raw) if creation_raw is not None else None
    kwargs: Dict[str, Any] = dict(
        event=name,
        entity_type=entity_type,
        entity_id=entity_id,
        target_entity_type=obj.get("targetEntityType"),
        target_entity_id=obj.get("targetEntityId"),
        properties=DataMap(props),
        tags=tuple(obj.get("tags") or ()),
        pr_id=obj.get("prId"),
        event_id=obj.get("eventId"),
    )
    if event_time is not None:
        kwargs["event_time"] = event_time
    if creation_time is not None:
        kwargs["creation_time"] = creation_time
    ev = Event(**kwargs)
    if validate:
        validate_event(ev)
    return ev


def event_to_json(event: Event) -> Dict[str, Any]:
    """Serialize an :class:`Event` to the public JSON schema."""
    out: Dict[str, Any] = {
        "eventId": event.event_id,
        "event": event.event,
        "entityType": event.entity_type,
        "entityId": event.entity_id,
    }
    if event.target_entity_type is not None:
        out["targetEntityType"] = event.target_entity_type
    if event.target_entity_id is not None:
        out["targetEntityId"] = event.target_entity_id
    out["properties"] = event.properties.to_dict()
    out["eventTime"] = format_iso8601(event.event_time)
    if event.tags:
        out["tags"] = list(event.tags)
    if event.pr_id is not None:
        out["prId"] = event.pr_id
    out["creationTime"] = format_iso8601(event.creation_time)
    return out
