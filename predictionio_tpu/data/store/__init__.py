"""User-facing event read API used inside engines.

Reference: data/src/main/scala/org/apache/predictionio/data/store/ —
``PEventStore`` (RDD reads for training) and ``LEventStore`` (iterator reads
at predict time).  The P path returns `pyarrow` tables here — the host-side
columnar form that feeds sharded ``jax.Array`` construction (SURVEY.md §7
build step 3) — instead of RDD partitions.
"""

from __future__ import annotations

import datetime as _dt
from typing import Dict, Iterator, List, Optional, Sequence

import pyarrow as pa

from predictionio_tpu.data.event import Event, PropertyMap
from predictionio_tpu.data.storage import Storage, StorageError

__all__ = ["EventStore", "PEventStore", "LEventStore",
           "WindowedEventStore"]


class EventStore:
    """Resolves app/channel names to ids and exposes reads.

    Reference: data/.../data/store/Common.scala (appNameToId) plus the
    PEventStore/LEventStore objects.  One class serves both roles; the
    ``P*``/``L*`` aliases below preserve the reference vocabulary.
    """

    def __init__(self, storage: Storage):
        self._storage = storage

    def _resolve(self, app_name: str, channel_name: Optional[str]) -> tuple:
        app = self._storage.get_apps().get_by_name(app_name)
        if app is None:
            raise StorageError(f"App {app_name!r} does not exist.")
        channel_id = None
        if channel_name is not None:
            chans = self._storage.get_channels().get_by_app_id(app.id)
            match = next((c for c in chans if c.name == channel_name), None)
            if match is None:
                raise StorageError(
                    f"Channel {channel_name!r} does not exist in app {app_name!r}."
                )
            channel_id = match.id
        return app.id, channel_id

    # -- P path (training) -------------------------------------------------
    def find_columnar(
        self,
        app_name: str,
        channel_name: Optional[str] = None,
        *,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        entity_type: Optional[str] = None,
        entity_id: Optional[str] = None,
        event_names: Optional[Sequence[str]] = None,
        target_entity_type: Optional[str] = None,
        target_entity_id: Optional[str] = None,
        ordered: bool = True,
        columns: Optional[Sequence[str]] = None,
    ) -> pa.Table:
        """Columnar batch read (reference: PEventStore.find returning RDD).

        Training reads should pass ``ordered=False`` (the reference's RDD
        scans are unordered too) and project ``columns`` to what the
        trainer consumes — both are large constant-factor wins at the
        ML-25M scan scale (see Events.find_columnar).
        """
        app_id, channel_id = self._resolve(app_name, channel_name)
        return self._storage.get_events().find_columnar(
            app_id,
            channel_id,
            start_time=start_time,
            until_time=until_time,
            entity_type=entity_type,
            entity_id=entity_id,
            event_names=event_names,
            target_entity_type=target_entity_type,
            target_entity_id=target_entity_id,
            ordered=ordered,
            columns=columns,
        )

    def insert_columnar(
        self,
        table: pa.Table,
        app_name: str,
        channel_name: Optional[str] = None,
    ) -> int:
        """Bulk columnar event ingest (reference analogue: HBase bulk
        import).  See :meth:`Events.insert_columnar` for the schema
        contract; returns the number of events ingested."""
        app_id, channel_id = self._resolve(app_name, channel_name)
        return self._storage.get_events().insert_columnar(
            table, app_id, channel_id)

    def aggregate_properties(
        self,
        app_name: str,
        entity_type: str,
        channel_name: Optional[str] = None,
        *,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        required: Optional[Sequence[str]] = None,
    ) -> Dict[str, PropertyMap]:
        """Reference: PEventStore.aggregateProperties."""
        app_id, channel_id = self._resolve(app_name, channel_name)
        return self._storage.get_events().aggregate_properties(
            app_id,
            channel_id,
            entity_type=entity_type,
            start_time=start_time,
            until_time=until_time,
            required=required,
        )

    # -- L path (serving) --------------------------------------------------
    def find(
        self,
        app_name: str,
        channel_name: Optional[str] = None,
        *,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        entity_type: Optional[str] = None,
        entity_id: Optional[str] = None,
        event_names: Optional[Sequence[str]] = None,
        target_entity_type: Optional[str] = None,
        target_entity_id: Optional[str] = None,
        limit: Optional[int] = None,
        reversed: bool = False,
    ) -> Iterator[Event]:
        """Iterator read (reference: LEventStore.find)."""
        app_id, channel_id = self._resolve(app_name, channel_name)
        return self._storage.get_events().find(
            app_id,
            channel_id,
            start_time=start_time,
            until_time=until_time,
            entity_type=entity_type,
            entity_id=entity_id,
            event_names=event_names,
            target_entity_type=target_entity_type,
            target_entity_id=target_entity_id,
            limit=limit,
            reversed=reversed,
        )

    def find_by_entity(
        self,
        app_name: str,
        entity_type: str,
        entity_id: str,
        channel_name: Optional[str] = None,
        *,
        event_names: Optional[Sequence[str]] = None,
        target_entity_type: Optional[str] = None,
        limit: Optional[int] = None,
        latest: bool = True,
    ) -> List[Event]:
        """Recent events of one entity (reference: LEventStore.findByEntity),
        used for realtime business rules at predict time."""
        return list(
            self.find(
                app_name,
                channel_name,
                entity_type=entity_type,
                entity_id=entity_id,
                event_names=event_names,
                target_entity_type=target_entity_type,
                limit=limit,
                reversed=latest,
            )
        )


    def latest_event_time(
        self, app_name: str, channel_name: Optional[str] = None
    ) -> Optional[_dt.datetime]:
        """Ingest high-watermark by app NAME (the freshness anchor the
        refresh daemon compares against the serving generation's data
        watermark — ISSUE 10)."""
        app_id, channel_id = self._resolve(app_name, channel_name)
        return self._storage.get_events().latest_event_time(
            app_id, channel_id)


class WindowedEventStore(EventStore):
    """An :class:`EventStore` view scoped to one training data window.

    The online-refresh loop (ISSUE 10) pins every train run to an
    explicit ``(start_time, until_time]``-style window so consecutive
    generations never gap or overlap: ``run_train`` stamps the watermark
    BEFORE the datasource reads, wraps ``ctx.event_store`` in this view,
    and records the bound on the EngineInstance.  DataSources need no
    changes — any read that does not pass its own ``start_time`` /
    ``until_time`` inherits the window (an explicit caller bound inside
    the window is narrower and kept; one outside it is clamped so a
    datasource can never read past its generation's watermark).
    """

    _SEGMENTS_UNSET = object()

    def __init__(self, storage: Storage,
                 start_time: Optional[_dt.datetime],
                 until_time: Optional[_dt.datetime]):
        super().__init__(storage)
        self.window_start = start_time
        self.window_until = until_time
        # Columnar segment store (ISSUE 17), resolved lazily on the first
        # windowed read: the event server tees landed writes into sealed
        # per-window segment files, so the delta read below can serve the
        # covered prefix from window-sized segment slices and only read
        # the uncovered tail from the primary store — delta cost stops
        # scaling with total store size.
        self._segments = self._SEGMENTS_UNSET

    def _clamped(self, kwargs: dict, *, inject_start: bool = True) -> dict:
        from predictionio_tpu.data.storage.base import epoch_us

        st = kwargs.get("start_time")
        if st is None:
            st = self.window_start if inject_start else None
        elif self.window_start is not None and inject_start \
                and epoch_us(st) < epoch_us(self.window_start):
            st = self.window_start
        ut = kwargs.get("until_time")
        if ut is None:
            ut = self.window_until
        elif self.window_until is not None \
                and epoch_us(ut) > epoch_us(self.window_until):
            ut = self.window_until
        out = dict(kwargs)
        out["start_time"] = st
        out["until_time"] = ut
        return out

    def _segment_slice(self, app_name, channel_name, kw):
        """Covered-prefix read from sealed segments: ``(table,
        covered_until_us)`` or None when segments cannot prove coverage
        from the window start (then the whole read falls back to the
        primary store — the reader never guesses)."""
        from predictionio_tpu.data.columnar import SegmentStore
        from predictionio_tpu.data.storage.base import epoch_us

        if kw.get("start_time") is None:
            return None  # full-history read — not a delta
        if self._segments is self._SEGMENTS_UNSET:
            try:
                self._segments = SegmentStore.open_default()
            except Exception:
                self._segments = None
        if self._segments is None:
            return None
        try:
            app_id, channel_id = self._resolve(app_name, channel_name)
            start_us = epoch_us(kw["start_time"])
            until_us = (epoch_us(kw["until_time"])
                        if kw.get("until_time") is not None else 1 << 62)
            return self._segments.read_window(
                app_id, channel_id, start_us, until_us,
                entity_type=kw.get("entity_type"),
                entity_id=kw.get("entity_id"),
                event_names=kw.get("event_names"),
                target_entity_type=kw.get("target_entity_type"),
                target_entity_id=kw.get("target_entity_id"))
        except Exception:
            # any surprise (damaged manifest, resolve failure) degrades
            # to the primary-store read, never to a broken training scan
            return None

    def find_columnar(self, app_name, channel_name=None, **kwargs):
        kw = self._clamped(kwargs)
        sliced = self._segment_slice(app_name, channel_name, kw)
        if sliced is None:
            return super(WindowedEventStore, self).find_columnar(
                app_name, channel_name, **kw)
        seg_table, covered_us = sliced
        # tail: [covered, until) — only the uncovered recent sliver (plus
        # any sub-floor prefix never exists here: coverage was proven
        # from start) still touches the primary store
        tail_kw = dict(kw)
        tail_kw["start_time"] = _dt.datetime.fromtimestamp(
            covered_us // 10**6, _dt.timezone.utc
        ) + _dt.timedelta(microseconds=covered_us % 10**6)
        tail = super(WindowedEventStore, self).find_columnar(
            app_name, channel_name, **tail_kw)
        if kwargs.get("ordered", True):
            seg_table = seg_table.sort_by("event_time_us")
        cols = kw.get("columns")
        if cols:
            seg_table = seg_table.select(list(cols))
        if seg_table.schema != tail.schema:
            try:
                seg_table = seg_table.cast(tail.schema)
            except (pa.ArrowInvalid, pa.ArrowNotImplementedError):
                # columnar backends may answer dictionary-encoded or
                # otherwise reshaped columns — if the slice cannot be
                # unified, correctness wins: one full primary read
                return super(WindowedEventStore, self).find_columnar(
                    app_name, channel_name, **kw)
        return pa.concat_tables([seg_table, tail])

    def find(self, app_name, channel_name=None, **kwargs):
        return super().find(app_name, channel_name, **self._clamped(kwargs))

    def aggregate_properties(self, app_name, entity_type, channel_name=None,
                             **kwargs):
        # $set/$unset/$delete property state is CUMULATIVE from t=0 — a
        # delta-windowed aggregation would drop every property written
        # before the window and hand the trainer phantom-empty entities.
        # Only the until bound applies (the generation still must not
        # see past its watermark); the window start is never injected.
        return super().aggregate_properties(
            app_name, entity_type, channel_name,
            **self._clamped(kwargs, inject_start=False))


# Reference-vocabulary aliases: both stores are views of the same class.
PEventStore = EventStore
LEventStore = EventStore
