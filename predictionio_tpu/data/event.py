"""Core event model: Event, DataMap, PropertyMap, BiMap.

Behavioral parity targets (reference paths are upstream Apache PredictionIO;
the mount at /root/reference was empty at survey time — SURVEY.md header):

- ``Event``        ← data/src/main/scala/org/apache/predictionio/data/storage/Event.scala
- ``DataMap``      ← data/.../data/storage/DataMap.scala
- ``PropertyMap``  ← data/.../data/storage/PropertyMap.scala
- ``BiMap``        ← data/.../data/storage/BiMap.scala
- validation rules ← data/.../data/storage/EventValidation (object in Event.scala)

Semantics that silently shape training data and therefore must match the
reference exactly (SURVEY.md §7 "hard parts"):

- Reserved events start with ``$``; only ``$set`` / ``$unset`` / ``$delete``
  are allowed for generic entities.
- Property names starting with ``pio_`` are reserved.
- ``aggregate_properties`` folds ``$set`` / ``$unset`` / ``$delete`` events in
  **event-time order** (last-write-wins per key); ``$delete`` drops the whole
  entity; the fold tracks ``first_updated`` / ``last_updated``.
"""

from __future__ import annotations

import datetime as _dt
from dataclasses import dataclass, field, replace
from typing import Any, Dict, Generic, Iterable, Iterator, List, Mapping, Optional, Sequence, TypeVar

import numpy as np

__all__ = [
    "DataMap",
    "DataMapError",
    "Event",
    "EventValidationError",
    "PropertyMap",
    "BiMap",
    "aggregate_properties",
    "validate_event",
    "is_reserved_event",
    "RESERVED_EVENTS",
]

# Reference: EventValidation.specialEvents in Event.scala.
RESERVED_EVENTS = frozenset({"$set", "$unset", "$delete"})
_RESERVED_PROP_PREFIX = "pio_"


class DataMapError(KeyError):
    """Missing / mistyped property access (reference: DataMapException)."""


class EventValidationError(ValueError):
    """Event failed validation (reference: EventValidation.validate)."""


def _utcnow() -> _dt.datetime:
    return _dt.datetime.now(_dt.timezone.utc)


class DataMap(Mapping[str, Any]):
    """An immutable JSON property bag with typed getters.

    Reference: DataMap.scala — wraps a ``JObject`` and exposes
    ``get[T](name)`` / ``getOpt[T](name)``.  Here values are plain Python
    JSON values (None/bool/int/float/str/list/dict).
    """

    __slots__ = ("_fields",)

    def __init__(self, fields: Optional[Mapping[str, Any]] = None):
        self._fields: Dict[str, Any] = dict(fields or {})

    # -- Mapping protocol -------------------------------------------------
    def __getitem__(self, key: str) -> Any:
        try:
            return self._fields[key]
        except KeyError:
            raise DataMapError(f"The field {key} is required.") from None

    def __iter__(self) -> Iterator[str]:
        return iter(self._fields)

    def __len__(self) -> int:
        return len(self._fields)

    def __contains__(self, key: object) -> bool:
        return key in self._fields

    def __eq__(self, other: object) -> bool:
        if isinstance(other, DataMap):
            return self._fields == other._fields
        if isinstance(other, Mapping):
            return self._fields == dict(other)
        return NotImplemented

    def __repr__(self) -> str:
        return f"DataMap({self._fields!r})"

    # -- typed getters (reference: DataMap.get[T] / getOpt[T]) ------------
    def _get_typed(self, name: str, types: tuple, conv=None) -> Any:
        v = self[name]
        if v is None:
            raise DataMapError(f"The field {name} is required.")
        if isinstance(v, bool) and bool not in types:
            raise DataMapError(f"The field {name} has type bool, expected {types}.")
        if not isinstance(v, types):
            raise DataMapError(f"The field {name} has type {type(v).__name__}, expected {types}.")
        return conv(v) if conv else v

    def get_string(self, name: str) -> str:
        return self._get_typed(name, (str,))

    def get_int(self, name: str) -> int:
        return self._get_typed(name, (int,))

    def get_double(self, name: str) -> float:
        return float(self._get_typed(name, (int, float)))

    def get_boolean(self, name: str) -> bool:
        return self._get_typed(name, (bool,))

    def get_string_list(self, name: str) -> List[str]:
        v = self._get_typed(name, (list,))
        if not all(isinstance(x, str) for x in v):
            raise DataMapError(f"The field {name} is not a list of strings.")
        return list(v)

    def get_double_list(self, name: str) -> List[float]:
        v = self._get_typed(name, (list,))
        out = []
        for x in v:
            if isinstance(x, bool) or not isinstance(x, (int, float)):
                raise DataMapError(f"The field {name} is not a list of numbers.")
            out.append(float(x))
        return out

    def opt_string(self, name: str) -> Optional[str]:
        return self.get_string(name) if self._has_non_null(name) else None

    def opt_int(self, name: str) -> Optional[int]:
        return self.get_int(name) if self._has_non_null(name) else None

    def opt_double(self, name: str) -> Optional[float]:
        return self.get_double(name) if self._has_non_null(name) else None

    def opt_boolean(self, name: str) -> Optional[bool]:
        return self.get_boolean(name) if self._has_non_null(name) else None

    def opt_string_list(self, name: str) -> Optional[List[str]]:
        return self.get_string_list(name) if self._has_non_null(name) else None

    def _has_non_null(self, name: str) -> bool:
        return self._fields.get(name) is not None

    # -- set algebra (reference: DataMap ++ / --) -------------------------
    def union(self, other: "DataMap | Mapping[str, Any]") -> "DataMap":
        """Right-biased merge (reference ``++``): other's keys win."""
        merged = dict(self._fields)
        merged.update(dict(other))
        return DataMap(merged)

    def subtract_keys(self, keys: Iterable[str]) -> "DataMap":
        """Remove keys (reference ``--``)."""
        drop = set(keys)
        return DataMap({k: v for k, v in self._fields.items() if k not in drop})

    def to_dict(self) -> Dict[str, Any]:
        return dict(self._fields)

    @property
    def fields(self) -> Dict[str, Any]:
        return dict(self._fields)

    def keyset(self) -> frozenset:
        return frozenset(self._fields)

    @property
    def is_empty(self) -> bool:
        return not self._fields


class PropertyMap(DataMap):
    """Aggregated entity state from ``$set``/``$unset``/``$delete`` events.

    Reference: PropertyMap.scala — a DataMap plus ``firstUpdated`` /
    ``lastUpdated`` timestamps.
    """

    __slots__ = ("first_updated", "last_updated")

    def __init__(
        self,
        fields: Optional[Mapping[str, Any]] = None,
        first_updated: Optional[_dt.datetime] = None,
        last_updated: Optional[_dt.datetime] = None,
    ):
        super().__init__(fields)
        self.first_updated = first_updated
        self.last_updated = last_updated

    def __repr__(self) -> str:
        return (
            f"PropertyMap({self._fields!r}, first_updated={self.first_updated},"
            f" last_updated={self.last_updated})"
        )


@dataclass(frozen=True)
class Event:
    """A single behavioral event (reference: Event.scala case class).

    JSON wire format (Appendix A of SURVEY.md)::

        {"event": ..., "entityType": ..., "entityId": ...,
         "targetEntityType"?: ..., "targetEntityId"?: ...,
         "properties"?: {...}, "eventTime"?: ISO-8601,
         "prId"?: ..., "creationTime"?: ISO-8601}
    """

    event: str
    entity_type: str
    entity_id: str
    target_entity_type: Optional[str] = None
    target_entity_id: Optional[str] = None
    properties: DataMap = field(default_factory=DataMap)
    event_time: _dt.datetime = field(default_factory=_utcnow)
    tags: Sequence[str] = ()
    pr_id: Optional[str] = None
    creation_time: _dt.datetime = field(default_factory=_utcnow)
    event_id: Optional[str] = None

    def with_event_id(self, event_id: str) -> "Event":
        return replace(self, event_id=event_id)


def is_reserved_event(name: str) -> bool:
    return name.startswith("$")


def validate_event(event: Event) -> None:
    """Validation per reference EventValidation.validate.

    - non-empty event name / entityType / entityId;
    - ``$``-prefixed events must be one of the reserved set;
    - ``$unset`` must carry a non-empty properties map;
    - reserved events must not target another entity;
    - property names must not start with ``pio_`` (reserved prefix).
    """
    if not event.event:
        raise EventValidationError("event must not be empty.")
    if not event.entity_type:
        raise EventValidationError("entityType must not be empty string.")
    if not event.entity_id:
        raise EventValidationError("entityId must not be empty string.")
    if event.target_entity_type is not None and not event.target_entity_type:
        raise EventValidationError("targetEntityType must not be empty string.")
    if event.target_entity_id is not None and not event.target_entity_id:
        raise EventValidationError("targetEntityId must not be empty string.")
    if (event.target_entity_type is None) != (event.target_entity_id is None):
        raise EventValidationError(
            "targetEntityType and targetEntityId must be specified together."
        )
    if is_reserved_event(event.event):
        if event.event not in RESERVED_EVENTS:
            raise EventValidationError(
                f"{event.event} is not a supported reserved event name "
                f"(supported: {sorted(RESERVED_EVENTS)})."
            )
        if event.target_entity_type is not None or event.target_entity_id is not None:
            raise EventValidationError(
                f"Reserved event {event.event} must not have targetEntity."
            )
        if event.event == "$unset" and event.properties.is_empty:
            raise EventValidationError("$unset event must have non-empty properties.")
    for key in event.properties:
        if key.startswith(_RESERVED_PROP_PREFIX):
            raise EventValidationError(
                f"Property name {key!r} is reserved (prefix {_RESERVED_PROP_PREFIX!r})."
            )


def aggregate_properties(events: Iterable[Event]) -> Optional[PropertyMap]:
    """Fold ``$set``/``$unset``/``$delete`` events into entity state.

    Reference: LEventAggregator.aggregateProperties — events are processed in
    event-time order; ``$set`` merges keys (later wins), ``$unset`` removes its
    property keys, ``$delete`` resets the entity to "absent".  Returns ``None``
    if the entity ends up deleted or never ``$set``.
    """
    ordered = sorted(events, key=lambda e: (e.event_time, e.creation_time))
    props: Optional[Dict[str, Any]] = None
    first: Optional[_dt.datetime] = None
    last: Optional[_dt.datetime] = None
    for e in ordered:
        if e.event == "$set":
            if props is None:
                props = {}
                first = e.event_time
            props.update(e.properties.to_dict())
            last = e.event_time
        elif e.event == "$unset":
            if props is not None:
                for k in e.properties:
                    props.pop(k, None)
                last = e.event_time
        elif e.event == "$delete":
            props, first, last = None, None, None
        # non-reserved events do not affect properties
    if props is None:
        return None
    return PropertyMap(props, first_updated=first, last_updated=last)


K = TypeVar("K")


class BiMap(Generic[K]):
    """Immutable bidirectional map, typically key → contiguous int index.

    Reference: BiMap.scala — used to index entity-id strings into dense int
    ids for ML (``BiMap.stringInt``).  Inverse lookups via ``inverse``.
    """

    __slots__ = ("_fwd", "_rev")

    def __init__(self, mapping: Mapping[K, Any]):
        self._fwd: Dict[K, Any] = dict(mapping)
        self._rev: Dict[Any, K] = {v: k for k, v in self._fwd.items()}
        if len(self._rev) != len(self._fwd):
            raise ValueError("BiMap values must be unique.")

    @staticmethod
    def string_int(keys: Iterable[str]) -> "BiMap[str]":
        """Assign contiguous ints (0..n-1) to unique keys in first-seen order.

        Reference: BiMap.stringInt / stringLong.
        """
        seen: Dict[str, int] = {}
        for k in keys:
            if k not in seen:
                seen[k] = len(seen)
        return BiMap(seen)

    def __getitem__(self, key: K) -> Any:
        return self._fwd[key]

    def get(self, key: K, default: Any = None) -> Any:
        return self._fwd.get(key, default)

    def __contains__(self, key: K) -> bool:
        return key in self._fwd

    def __len__(self) -> int:
        return len(self._fwd)

    def __iter__(self) -> Iterator[K]:
        return iter(self._fwd)

    def items(self):
        return self._fwd.items()

    def keys(self):
        return self._fwd.keys()

    def values(self):
        return self._fwd.values()

    @property
    def inverse(self) -> "BiMap":
        inv = BiMap.__new__(BiMap)
        inv._fwd = self._rev
        inv._rev = self._fwd
        return inv

    def to_numpy_keys(self) -> np.ndarray:
        """Keys ordered by their int value — decode table for device ids."""
        items = sorted(self._fwd.items(), key=lambda kv: kv[1])
        return np.array([k for k, _ in items])
