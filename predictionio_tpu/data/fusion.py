"""K-step fusion plan + the HBM-guided fusion/batch autotuner.

BENCH_r06's attribution left one dominant residual: after the PR-5
prefetched pipeline closed serialized H2D, the feeder-vs-realized gap is
~99% ``device_wait`` — the per-step jit dispatch/sync cadence itself.
The fix is to fuse K optimizer steps into ONE XLA dispatch
(``lax.scan`` over a K-batch superbatch staged by
:class:`~predictionio_tpu.data.prefetch.DevicePrefetcher`), which this
module configures and — in ``auto`` mode — tunes:

- :func:`fuse_steps_config` reads ``PIO_FUSE_STEPS`` (``pio train
  --fuse-steps``): an integer pins the fusion depth (default 1 — exactly
  the pre-fusion per-step dispatch, so the change is opt-in-safe);
  ``auto`` starts at 1 and hands control to the autotuner.
- :class:`FusionPlan` is the mutable (fuse_steps, batch_scale) pair the
  prefetcher's prep thread snapshots per assembled window — the
  autotuner retargets it between windows without stopping the stream.
- :class:`FusionAutotuner` grows fusion depth (and, with
  ``PIO_BATCH_AUTOSCALE=on`` / ``pio train --batch-autoscale``, the
  effective batch size — K consecutive prepped batches concatenated into
  one wider step, an opt-in that trades bitwise-reproducible semantics
  for throughput) every ``round_windows`` dispatches until the PR-5 HBM
  headroom guardrail (``PIO_HBM_WARN_FRACTION`` of the allocator
  ``bytes_limit``, via :class:`~predictionio_tpu.obs.runtime.
  DeviceMemorySampler`) pushes back, then backs off ONE notch and pins —
  one knob-free ``pio train`` finds the hardware's ceiling.  On backends
  whose allocator reports no ``bytes_limit`` (CPU) the guardrail cannot
  push back, so growth stops at ``PIO_FUSE_STEPS_MAX`` (default 32).

Importing this module never imports jax (the sampler resolves lazily),
same discipline as the rest of ``data/``/``obs/``.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Optional, Tuple

logger = logging.getLogger(__name__)

__all__ = [
    "FusionPlan",
    "FusionAutotuner",
    "fuse_steps_config",
    "fuse_steps_max",
    "batch_autoscale_enabled",
    "slot_steps",
    "crossed_save_point",
]

DEFAULT_MAX_FUSE_STEPS = 32
DEFAULT_MAX_BATCH_SCALE = 8
DEFAULT_ROUND_WINDOWS = 4


def fuse_steps_config(
        value: Optional[object] = None, default: int = 1) -> Tuple[int, bool]:
    """Resolve the fusion depth: ``(fuse_steps, auto)``.

    ``value`` overrides the environment (the models' ``train()`` keyword,
    tests); otherwise ``PIO_FUSE_STEPS`` is read.  ``"auto"`` yields
    ``(1, True)`` — start unfused, let the autotuner grow.
    """
    if value is None:
        value = os.environ.get("PIO_FUSE_STEPS", "")
    text = str(value).strip().lower()
    if text == "auto":
        return 1, True
    try:
        k = int(text) if text else int(default)
    except ValueError:
        k = int(default)
    return max(k, 1), False


def fuse_steps_max(default: int = DEFAULT_MAX_FUSE_STEPS) -> int:
    """``PIO_FUSE_STEPS_MAX``: autotune growth ceiling (min 1)."""
    try:
        k = int(os.environ.get("PIO_FUSE_STEPS_MAX", str(default)))
    except ValueError:
        k = default
    return max(k, 1)


def batch_autoscale_enabled() -> bool:
    """``PIO_BATCH_AUTOSCALE``: let the autotuner also widen the
    effective batch (concatenate consecutive prepped batches) once
    fusion depth is capped.  Opt-in: fewer, wider optimizer steps are a
    semantics change, not a scheduling change."""
    return os.environ.get("PIO_BATCH_AUTOSCALE", "").strip().lower() in (
        "1", "on", "true", "yes")


def slot_steps(batch) -> list:
    """Global step number of each scan slot of a prefetched batch — the
    divergence guard's loss-vector → step mapping.  With batch scale M,
    slot j's step is the LAST raw batch it consumed."""
    k = max(int(getattr(batch, "k", 1)), 1)
    steps = max(int(getattr(batch, "steps", 1)), 1)
    m = steps // k
    first = batch.step - steps + 1
    return [first + (j + 1) * m - 1 for j in range(k)]


def crossed_save_point(step: int, steps: int, save_every: int) -> bool:
    """True when the window ending at ``step`` (covering ``steps`` raw
    steps) crossed a checkpoint-cadence point.  Reduces to
    ``step % save_every == 0`` for unfused steps; for fused windows the
    save lands on the window boundary just past the cadence point — a
    rollback target is therefore always a fusion boundary."""
    if save_every <= 0:
        return False
    return (step // save_every) > ((step - max(int(steps), 1)) // save_every)


class FusionPlan:
    """Thread-safe (fuse_steps, batch_scale) target.

    The prefetcher's prep thread snapshots the plan once per window
    (never mid-window — a window is assembled under one snapshot), the
    autotuner retargets it between windows."""

    def __init__(self, fuse_steps: int = 1, batch_scale: int = 1):
        self._lock = threading.Lock()
        self._k = max(int(fuse_steps), 1)
        self._m = max(int(batch_scale), 1)

    def get(self) -> Tuple[int, int]:
        with self._lock:
            return self._k, self._m

    def set(self, fuse_steps: Optional[int] = None,
            batch_scale: Optional[int] = None) -> None:
        with self._lock:
            if fuse_steps is not None:
                self._k = max(int(fuse_steps), 1)
            if batch_scale is not None:
                self._m = max(int(batch_scale), 1)

    @property
    def window_batches(self) -> int:
        """Raw prepped batches one window consumes (k * m)."""
        k, m = self.get()
        return k * m


class FusionAutotuner:
    """Grow fusion depth / batch scale until HBM headroom pushes back.

    Policy (one decision every ``round_windows`` dispatched windows):

    - headroom exceeded (train-run peak ``bytes_in_use`` above
      ``PIO_HBM_WARN_FRACTION`` of ``bytes_limit``) → back off ONE notch
      on whatever grew last and **pin** — the guardrail spoke, the
      previous setting is the ceiling;
    - otherwise grow: double ``fuse_steps`` up to ``max_fuse_steps``,
      then (only with batch autoscale enabled) double ``batch_scale`` up
      to ``max_batch_scale``, then pin at the cap.

    ``sampler`` is injectable (tests drive scripted headroom verdicts
    with no devices); the default resolves the process
    :class:`DeviceMemorySampler` lazily so constructing a tuner never
    imports jax.
    """

    def __init__(self, model: str, plan: FusionPlan, *,
                 sampler=None,
                 round_windows: int = DEFAULT_ROUND_WINDOWS,
                 max_fuse_steps: Optional[int] = None,
                 batch_scale: Optional[bool] = None,
                 max_batch_scale: int = DEFAULT_MAX_BATCH_SCALE,
                 registry=None):
        self.model = model
        self.plan = plan
        self._sampler = sampler
        self.round_windows = max(int(round_windows), 1)
        self.max_fuse_steps = (fuse_steps_max() if max_fuse_steps is None
                               else max(int(max_fuse_steps), 1))
        self.batch_scale_enabled = (batch_autoscale_enabled()
                                    if batch_scale is None else bool(batch_scale))
        self.max_batch_scale = max(int(max_batch_scale), 1)
        self.pinned = False
        self._windows = 0
        self._registry = registry
        self._publish_gauges()

    # -- wiring --------------------------------------------------------------

    def _reg(self):
        if self._registry is not None:
            return self._registry
        from predictionio_tpu.obs.metrics import get_registry

        return get_registry()

    def _publish_gauges(self) -> None:
        k, m = self.plan.get()
        reg = self._reg()
        reg.gauge(
            "pio_train_fuse_steps",
            "Fused optimizer steps per XLA dispatch (lax.scan depth).",
            ("model",)).set(k, model=self.model)
        reg.gauge(
            "pio_train_batch_scale",
            "Autoscaled batch multiplier (prepped batches concatenated "
            "per optimizer step).", ("model",)).set(m, model=self.model)

    def _headroom_exceeded(self) -> bool:
        sampler = self._sampler
        if sampler is None:
            from predictionio_tpu.obs.runtime import get_memory_sampler

            sampler = self._sampler = get_memory_sampler()
        try:
            return bool(sampler.headroom_exceeded())
        except Exception:
            logger.debug("fusion autotune headroom probe failed",
                         exc_info=True)
            return False

    # -- the policy ----------------------------------------------------------

    def on_window(self) -> None:
        """One dispatched window observed; decide at round boundaries.

        The cadence counts DISPATCHES, deliberately unweighted by each
        window's step count: a K=1 tail flush still gave the sampler one
        settle-and-sample interval, which is what a round is for."""
        self._windows += 1
        if self.pinned or self._windows % self.round_windows:
            return
        self._decide()

    def _decide(self) -> None:
        from predictionio_tpu.obs.runtime import publish_event

        k, m = self.plan.get()
        if self._headroom_exceeded():
            # Back off ONE notch on whatever grew last, and pin: the
            # guardrail names the ceiling, re-probing it each round
            # would thrash the allocator at its limit.
            if m > 1:
                m = max(m // 2, 1)
            elif k > 1:
                k = max(k // 2, 1)
            self.pinned = True
            logger.warning(
                "%s: HBM headroom guardrail pushed back — pinning fused "
                "training at fuse_steps=%d batch_scale=%d", self.model, k, m)
            publish_event("train.fusion_autotune", model=self.model,
                          fuseSteps=k, batchScale=m, action="backoff_pin")
        elif k < self.max_fuse_steps:
            k = min(k * 2, self.max_fuse_steps)
            publish_event("train.fusion_autotune", model=self.model,
                          fuseSteps=k, batchScale=m, action="grow_fuse")
        elif self.batch_scale_enabled and m < self.max_batch_scale:
            m = min(m * 2, self.max_batch_scale)
            publish_event("train.fusion_autotune", model=self.model,
                          fuseSteps=k, batchScale=m, action="grow_batch")
        else:
            self.pinned = True
            logger.info(
                "%s: fusion autotune pinned at the growth cap "
                "(fuse_steps=%d batch_scale=%d) with HBM headroom to "
                "spare — a larger PIO_FUSE_STEPS_MAX (or batch size) "
                "may still help", self.model, k, m)
            publish_event("train.fusion_autotune", model=self.model,
                          fuseSteps=k, batchScale=m, action="cap_pin")
        self.plan.set(k, m)
        self._publish_gauges()
