"""Overlapped input pipeline: background batch prep + device prefetch.

BENCH_r05 measured realized training throughput far below what the feeder
delivers (45.9% pipeline gap for two-tower, 87.0% for DLRM) and the PR-3
attribution (`tools/attribute_gap.py`) pinned the serialized host work:
every step paid tail-batch padding, dtype conversion, and the H2D
transfer **between** device steps, on the main thread, after blocking on
step N-1.  :class:`DevicePrefetcher` moves that whole stage off the step
loop:

- a background **prep thread** pulls raw batches from the host iterator
  (``numpy_epochs`` / ``feeder_epochs``), runs the caller's ``prep_fn``
  (pad + convert + transforms) and eagerly issues the device transfer
  (``jax.device_put``, or a caller ``put_fn`` applying ``NamedSharding``
  when a mesh is active) — so batch N+1's H2D rides **under** batch N's
  device compute instead of serializing after it;
- a **bounded queue** (depth ``PIO_PREFETCH_DEPTH``, default 2) gives
  double-buffering semantics: the prep thread stays at most ``depth``
  batches ahead and blocks when the device is the bottleneck, bounding
  host+device memory held by staged batches;
- **resume fast-forward** (``skip_steps``): batches a checkpoint restore
  already covers are consumed from the source for determinism (the
  per-epoch shuffles must advance identically) but skipped *before* any
  prep/transfer work is spent on them;
- **superbatch staging** (``fuse_steps`` K / a shared
  :class:`~predictionio_tpu.data.fusion.FusionPlan`): K consecutive
  prepped batches are stacked along a new leading axis and transferred
  as ONE superbatch (``fused_put_fn``), feeding the models' K-step fused
  ``lax.scan`` dispatch — the ISSUE-7 attack on the per-step
  dispatch/sync cadence.  ``batch_scale`` M additionally concatenates M
  prepped batches per scan slot (opt-in batch autoscaling).  A stream
  ending mid-window flushes complete slots singly and leftovers at their
  base shape; a resume landing mid-window (``skip_steps`` not on a K·M
  boundary) replays the remainder unfused so windows stay aligned to the
  absolute boundaries an uninterrupted run would use;
- **clean shutdown + exception propagation**: errors raised by the
  source, ``prep_fn`` or the transfer surface in the consuming thread at
  the next ``next()``; ``close()`` (or leaving the ``with`` block — also
  on ``TrainPreempted`` / ``TrainDiverged`` / watchdog aborts) stops the
  thread, closes the source generator on the prep thread (temp dirs and
  native feeders release deterministically), and joins.

``device_put``/clock are injectable so unit tests exercise ordering,
backpressure and shutdown with fakes and no accelerator stack; importing
this module never imports jax (the default ``put`` resolves lazily).
"""

from __future__ import annotations

import atexit
import os
import queue
import threading
import time
import weakref
from typing import Any, Callable, Iterable, Iterator, List, Optional, Tuple

from predictionio_tpu.data.fusion import FusionPlan

__all__ = ["DevicePrefetcher", "PrefetchedBatch", "prefetch_depth",
           "StagingPool"]

# Live prefetchers, swept at interpreter exit: a prep thread still inside
# a device transfer or a native-feeder call while CPython tears down is a
# crash (daemon threads are frozen mid-C-call; C++ static destructors
# then run under them).  Normal lifecycles never reach this — the sweep
# is the backstop for abandoned iterators.
_live: "weakref.WeakSet" = weakref.WeakSet()


@atexit.register
def _close_live_prefetchers() -> None:
    for pf in list(_live):
        try:
            pf.close()
        except Exception:
            pass

DEFAULT_DEPTH = 2

# Producer-side poll granularity for stop/backpressure checks.  Queue
# put/get with a timeout wake immediately on space/data; the timeout only
# bounds how stale a stop request can go unnoticed.
_POLL_S = 0.05

_PAGE_ALIGN = 4096  # host staging buffers align to a page boundary


def _aligned_empty(shape, dtype, align: int = _PAGE_ALIGN):
    """Uninitialized host array whose data pointer is page-aligned —
    what a PCIe DMA engine wants to see on the staging side."""
    import numpy as np

    dt = np.dtype(dtype)
    nbytes = int(np.prod(shape)) * dt.itemsize
    raw = np.empty(nbytes + align, dtype=np.uint8)
    off = (-raw.ctypes.data) % align
    return raw[off:off + nbytes].view(dt).reshape(shape)


class StagingPool:
    """Ring of page-aligned, REUSABLE host buffers for superbatch
    assembly (carried since PR 5: PCIe hosts paid a fresh multi-MB
    allocation + page-fault walk per fused window).

    One ring per (shape, dtype) key; the first ``slots`` requests
    allocate, later ones rotate through the ring.  Safety contract: a
    buffer handed out is rewritten only after ``slots`` newer windows
    were staged — with ``slots = depth + 2`` the transfer of the batch
    it carried completed long before reuse *provided the device put
    COPIES the host memory* (every PCIe backend does; the CPU backend
    may alias numpy buffers zero-copy, which is why pooling is gated
    off there — see ``DevicePrefetcher`` ``pin_buffers``).

    Single-producer by design: only the prep thread touches a pool.
    """

    __slots__ = ("slots", "_rings", "_next", "reused", "allocated")

    def __init__(self, slots: int):
        self.slots = max(int(slots), 2)
        self._rings: dict = {}
        self._next: dict = {}
        self.reused = 0
        self.allocated = 0

    def take(self, shape, dtype, tag: int = 0):
        import numpy as np

        # ``tag`` separates pytree leaves that share a shape/dtype —
        # two leaves drawing from one ring would halve the rotation
        # distance the safety contract is built on.
        key = (tag, tuple(int(s) for s in shape), np.dtype(dtype).str)
        ring = self._rings.setdefault(key, [])
        if len(ring) < self.slots:
            buf = _aligned_empty(shape, dtype)
            ring.append(buf)
            self.allocated += 1
            return buf
        i = self._next.get(key, 0)
        self._next[key] = (i + 1) % self.slots
        self.reused += 1
        return ring[i]


def prefetch_depth(default: int = DEFAULT_DEPTH) -> int:
    """``PIO_PREFETCH_DEPTH`` (min 1): staged batches the prep thread may
    run ahead.  2 = classic double buffering (one in flight on the
    device, one staged)."""
    try:
        depth = int(os.environ.get("PIO_PREFETCH_DEPTH", str(default)))
    except ValueError:
        depth = default
    return max(depth, 1)


class PrefetchedBatch:
    """One staged batch: device args + the overlap-window bookkeeping.

    A fused superbatch (``k > 1``) carries ``k`` scan slots stacked on a
    new leading axis; ``steps`` counts the raw source batches consumed
    (``k`` · batch_scale), so ``step`` — the LAST raw batch number — and
    ``step - steps + 1`` bound the window."""

    __slots__ = ("step", "args", "examples", "h2d_ms", "staged_s",
                 "steps", "k")

    def __init__(self, step: int, args: Any, examples: int,
                 h2d_ms: float, staged_s: float,
                 steps: int = 1, k: int = 1):
        self.step = step          # 1-based global batch number (post-skip)
        self.args = args          # device arrays, ready to dispatch
        self.examples = examples  # real (pre-padding) examples
        self.h2d_ms = h2d_ms      # prep + transfer time on the prep thread
        self.staged_s = staged_s  # wall clock when staging finished
        self.steps = steps        # raw source batches in this dispatch
        self.k = k                # scan slots (fused depth; 1 = unfused)


class _Done:
    """End-of-stream sentinel (the producer's last queue item)."""

    __slots__ = ()


_DONE = _Done()


class DevicePrefetcher:
    """Background batch-prep + bounded device prefetch over a host iterator.

    Integration shape (two_tower/dlrm ``_train_attempt``)::

        with DevicePrefetcher(epochs(), prep_fn, put_fn=put,
                              skip_steps=start_step, model="dlrm") as pf:
            for batch in probe.iter_prefetched(pf):   # PrefetchedBatch
                probe.sync()                          # wait on step N-1
                state, loss = train_step(state, *batch.args, cfg)
                probe.dispatched(state, examples=batch.examples)

    ``prep_fn(raw_batch)`` runs on the prep thread and returns the padded,
    dtype-converted host arrays; ``put_fn(arrays)`` issues the device
    transfer (default: lazy ``jax.device_put``) — on an async backend the
    transfer proceeds while the device executes the previous step, which
    is the point.  ``count_fn(raw_batch)`` reports the real example count
    before padding (default ``len(batch[0])``).

    ``fuse_steps`` / ``batch_scale`` (or a live ``fuse_plan`` the
    autotuner retargets between windows) turn on superbatch staging:
    each window consumes K·M prepped batches, concatenates M per scan
    slot, stacks the K slots on a new leading axis and transfers the
    result via ``fused_put_fn`` (default: ``put_fn``) — sharded models
    pass a fused put applying the leading-axis-aware ``NamedSharding``.
    """

    def __init__(
        self,
        source: Iterable,
        prep_fn: Callable[[Any], Any],
        *,
        put_fn: Optional[Callable[[Any], Any]] = None,
        fused_put_fn: Optional[Callable[[Any], Any]] = None,
        depth: Optional[int] = None,
        skip_steps: int = 0,
        fuse_steps: int = 1,
        batch_scale: int = 1,
        fuse_plan: Optional[FusionPlan] = None,
        pin_buffers: Optional[bool] = None,
        count_fn: Optional[Callable[[Any], int]] = None,
        clock: Callable[[], float] = time.perf_counter,
        wall_clock: Callable[[], float] = time.time,
        model: str = "",
        registry=None,
    ):
        self.depth = prefetch_depth() if depth is None else max(int(depth), 1)
        self._source = source
        self._prep_fn = prep_fn
        self._put_fn = put_fn if put_fn is not None else _default_put
        self._fused_put_fn = fused_put_fn if fused_put_fn is not None \
            else self._put_fn
        self._count_fn = count_fn if count_fn is not None \
            else (lambda batch: len(batch[0]))
        self._skip = max(int(skip_steps), 0)
        self._plan = fuse_plan if fuse_plan is not None \
            else FusionPlan(fuse_steps, batch_scale)
        # K-aware resume: a restore landing mid-window replays the
        # remainder unfused so fused windows stay aligned to the absolute
        # K·M boundaries an uninterrupted run would dispatch (and the
        # divergence-rollback target — always a window boundary — stays
        # reachable by the same grouping).
        w = self._plan.window_batches
        self._realign = (w - self._skip % w) % w if (self._skip and w > 1) \
            else 0
        # Pinned host staging (ISSUE 13 satellite): superbatch assembly
        # reuses page-aligned buffers instead of allocating per window.
        # None = resolve lazily at the first multi-batch emit
        # (PIO_PINNED_STAGING on|off|auto; auto = any non-CPU backend —
        # the CPU backend may alias numpy buffers into its "device"
        # arrays zero-copy, and a reused buffer would then rewrite a
        # staged batch in flight).
        self._pin = pin_buffers
        self._pool: Optional[StagingPool] = None
        self._clock = clock
        self._wall_clock = wall_clock
        self._q: "queue.Queue" = queue.Queue(maxsize=self.depth)
        self._stop = threading.Event()
        self._done = threading.Event()
        self._exc: Optional[BaseException] = None
        self._closed = False
        # Real batches in the queue (gauge source — qsize() would count
        # the _DONE sentinel too).  Updated from both threads, so the
        # read-modify-write rides a lock.
        self._staged = 0
        self._staged_lock = threading.Lock()
        self._depth_gauge = None
        self._pinned_counter = None
        if model:
            from predictionio_tpu.obs.metrics import get_registry

            reg = registry or get_registry()
            self._depth_gauge = reg.gauge(
                "pio_prefetch_queue_depth",
                "Staged batches waiting in the prefetch queue.",
                ("model",))
            self._pinned_counter = reg.counter(
                "pio_prefetch_pinned_reuse_total",
                "Superbatch stagings that reused a pinned host buffer "
                "instead of allocating.", ("model",))
            self._model = model
        self._thread = threading.Thread(
            target=self._run, name=f"pio-prefetch-{model or 'batch'}",
            daemon=True)
        _live.add(self)
        self._thread.start()

    # -- producer ------------------------------------------------------------

    def _run(self) -> None:
        it = iter(self._source)
        try:
            step = 0
            realign = self._realign
            window: List[Tuple[Any, int, float, int]] = []
            km = (1, 1)
            while not self._stop.is_set():
                try:
                    raw = next(it)
                except StopIteration:
                    break
                step += 1
                if step <= self._skip:
                    continue  # resume fast-forward: no prep, no transfer
                t0 = self._clock()
                examples = int(self._count_fn(raw))
                prepped = self._prep_fn(raw)
                prep_ms = (self._clock() - t0) * 1e3
                if realign > 0:
                    # Mid-window resume: replay to the next absolute
                    # window boundary at the base (unfused) shape.
                    realign -= 1
                    if not self._emit_slot([(prepped, examples, prep_ms,
                                             step)]):
                        return
                    continue
                if not window:
                    # Plan snapshot per window: the autotuner retargets
                    # between windows, never inside one.
                    km = self._plan.get()
                if km[0] * km[1] <= 1:
                    if not self._emit_slot([(prepped, examples, prep_ms,
                                             step)]):
                        return
                    continue
                window.append((prepped, examples, prep_ms, step))
                if len(window) < km[0] * km[1]:
                    continue
                if not self._emit_window(window, *km):
                    return
                window = []
            # End of stream mid-window: flush complete slots at their
            # slot shape, leftover raw batches at the base shape —
            # every compiled program involved already exists.
            if not self._stop.is_set() and window:
                k, m = km
                while len(window) >= m and m > 1:
                    if not self._emit_slot(window[:m]):
                        return
                    window = window[m:]
                for entry in window:
                    if not self._emit_slot([entry]):
                        return
        except BaseException as e:  # noqa: BLE001 — must reach the consumer
            self._exc = e
        finally:
            self._done.set()
            self._offer(_DONE, brief=True)
            close = getattr(it, "close", None)
            if close is not None:
                # Close the source generator ON the prep thread: its
                # finally blocks (temp dirs, native feeders) belong to
                # the thread that was executing it.
                try:
                    close()
                except Exception:
                    pass

    def _offer(self, item: Any, brief: bool = False) -> bool:
        """Bounded put that stays responsive to close(); ``brief`` makes
        one best-effort attempt (the terminal sentinel — the consumer
        also watches ``_done``, so a full queue loses nothing)."""
        while True:
            try:
                self._q.put(item, timeout=_POLL_S)
            except queue.Full:
                if brief or self._stop.is_set():
                    return False
                continue
            if not isinstance(item, _Done):
                with self._staged_lock:
                    self._staged += 1
                    staged = self._staged
                if self._depth_gauge is not None:
                    self._depth_gauge.set(staged, model=self._model)
            return True

    def _staging_pool(self) -> Optional[StagingPool]:
        """The prep thread's buffer pool, or None when pinned staging is
        off.  Resolved once, at the first multi-batch emit, so the
        unfused path never pays the backend probe (and a jax-free test
        process never imports jax unless it opted in)."""
        if self._pin is None:
            raw = os.environ.get("PIO_PINNED_STAGING",
                                 "auto").strip().lower()
            if raw in ("on", "1", "true", "yes"):
                self._pin = True
            elif raw in ("off", "0", "false", "no"):
                self._pin = False
            else:
                try:
                    import jax

                    self._pin = jax.default_backend() != "cpu"
                except Exception:
                    self._pin = False
        if self._pin and self._pool is None:
            # depth staged + 1 in the consumer's hands + 1 margin for an
            # asynchronously-draining transfer = safe rotation distance.
            self._pool = StagingPool(self.depth + 2)
        return self._pool if self._pin else None

    def _note_pinned(self, pool: Optional[StagingPool],
                     reused_before: int) -> None:
        if pool is not None and self._pinned_counter is not None \
                and pool.reused > reused_before:
            self._pinned_counter.inc(pool.reused - reused_before,
                                     model=self._model)

    def _emit_slot(self, entries: List[Tuple[Any, int, float, int]]) -> bool:
        """Stage one optimizer step's batch: a single prepped batch, or
        ``batch_scale`` prepped batches concatenated (both ride
        ``put_fn`` — no leading scan axis)."""
        t0 = self._clock()
        pool = self._staging_pool() if len(entries) > 1 else None
        reused = pool.reused if pool is not None else 0
        arrays = entries[0][0] if len(entries) == 1 \
            else _tree_concat([e[0] for e in entries], pool)
        self._note_pinned(pool, reused)
        staged = self._put_fn(arrays)
        h2d_ms = sum(e[2] for e in entries) + (self._clock() - t0) * 1e3
        return self._offer(PrefetchedBatch(
            entries[-1][3], staged, sum(e[1] for e in entries), h2d_ms,
            self._wall_clock(), steps=len(entries), k=1))

    def _emit_window(self, window: List[Tuple[Any, int, float, int]],
                     k: int, m: int) -> bool:
        """Stage one fused superbatch: K slots (each M prepped batches
        concatenated) stacked on a new leading axis, transferred via
        ``fused_put_fn`` in one go."""
        if k <= 1:
            return self._emit_slot(window)
        t0 = self._clock()
        pool = self._staging_pool()
        reused = pool.reused if pool is not None else 0
        slots = [window[i * m:(i + 1) * m] for i in range(k)]
        # Only the FINAL superbatch rides the pool — inner batch-scale
        # concats are transients the stack copies out of immediately.
        arrays = _tree_stack([
            s[0][0] if m == 1 else _tree_concat([e[0] for e in s])
            for s in slots], pool)
        self._note_pinned(pool, reused)
        staged = self._fused_put_fn(arrays)
        h2d_ms = sum(e[2] for e in window) + (self._clock() - t0) * 1e3
        return self._offer(PrefetchedBatch(
            window[-1][3], staged, sum(e[1] for e in window), h2d_ms,
            self._wall_clock(), steps=len(window), k=k))

    # -- consumer ------------------------------------------------------------

    def __iter__(self) -> Iterator[PrefetchedBatch]:
        return self

    def __next__(self) -> PrefetchedBatch:
        if self._closed:
            raise StopIteration
        while True:
            try:
                item = self._q.get(timeout=_POLL_S)
            except queue.Empty:
                if not self._done.is_set():
                    continue
                # Producer exited.  ``_done`` is set only after every real
                # batch was enqueued, so one non-blocking drain closes the
                # timed-out-get vs late-put race.
                try:
                    item = self._q.get_nowait()
                except queue.Empty:
                    item = _DONE
            if isinstance(item, _Done):
                self._finish()
                raise StopIteration
            with self._staged_lock:
                self._staged -= 1
                staged = self._staged
            if self._depth_gauge is not None:
                self._depth_gauge.set(staged, model=self._model)
            return item

    def _finish(self) -> None:
        """End of stream: join the producer and surface its error."""
        self._thread.join(timeout=5.0)
        if self._exc is not None:
            exc, self._exc = self._exc, None
            self._closed = True
            raise exc

    # -- lifecycle -----------------------------------------------------------

    def close(self) -> None:
        """Stop the prep thread and release staged batches (idempotent).

        Safe mid-stream: a producer blocked on a full queue observes the
        stop flag within one poll tick; staged device buffers are dropped
        (the arrays are garbage-collected, nothing to flush).
        """
        if self._closed:
            return
        self._closed = True
        _live.discard(self)
        self._stop.set()
        while True:  # unblock a producer waiting for queue space
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        if self._thread.is_alive():
            self._thread.join(timeout=5.0)
        if self._depth_gauge is not None:
            self._depth_gauge.set(0, model=self._model)

    def __enter__(self) -> "DevicePrefetcher":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False


def _default_put(arrays: Any) -> Any:
    """Eager transfer of a pytree of host arrays (lazy jax import so the
    module — and tests injecting a fake — never need an accelerator)."""
    import jax

    return jax.device_put(arrays)


def _pooled_stack(leaves: List[Any], pool: Optional[StagingPool],
                  tag: int = 0):
    """np.stack, assembled into a reusable page-aligned buffer when a
    pool is active and the leaves agree on shape/dtype (a ragged window
    falls back to a fresh allocation — correctness over reuse)."""
    import numpy as np

    first = leaves[0]
    if pool is None or any(
            getattr(leaf, "shape", None) != first.shape
            or getattr(leaf, "dtype", None) != first.dtype
            for leaf in leaves):
        return np.stack(leaves)
    out = pool.take((len(leaves),) + tuple(first.shape), first.dtype,
                    tag=tag)
    for i, leaf in enumerate(leaves):
        np.copyto(out[i], leaf)
    return out


def _pooled_concat(leaves: List[Any], pool: Optional[StagingPool],
                   tag: int = 0):
    """np.concatenate into a reusable buffer (same fallback rules as
    :func:`_pooled_stack`; rows may differ, trailing dims may not)."""
    import numpy as np

    first = leaves[0]
    if pool is None or any(
            getattr(leaf, "shape", ())[1:] != first.shape[1:]
            or getattr(leaf, "dtype", None) != first.dtype
            for leaf in leaves):
        return np.concatenate(leaves)
    rows = sum(leaf.shape[0] for leaf in leaves)
    out = pool.take((rows,) + tuple(first.shape[1:]), first.dtype,
                    tag=tag)
    off = 0
    for leaf in leaves:
        np.copyto(out[off:off + leaf.shape[0]], leaf)
        off += leaf.shape[0]
    return out


def _tree_stack(items: List[Any],
                pool: Optional[StagingPool] = None) -> Any:
    """Stack prepped batches leaf-wise along a NEW leading axis (the scan
    axis of a fused superbatch).  Batches are tuples/lists of arrays by
    the prep convention; a bare array stacks directly."""
    if isinstance(items[0], (tuple, list)):
        return type(items[0])(
            _pooled_stack([it[j] for it in items], pool, tag=j)
            for j in range(len(items[0])))
    return _pooled_stack(items, pool)


def _tree_concat(items: List[Any],
                 pool: Optional[StagingPool] = None) -> Any:
    """Concatenate prepped batches leaf-wise along the batch axis (the
    batch-autoscale widening)."""
    if isinstance(items[0], (tuple, list)):
        return type(items[0])(
            _pooled_concat([it[j] for it in items], pool, tag=j)
            for j in range(len(items[0])))
    return _pooled_concat(items, pool)
