"""Vectorized Arrow-native transforms for the P training path.

Reference: the reference's RDD path (SURVEY.md §2.1 "User-facing stores")
keeps event data distributed/columnar from storage scan to trainer input.
Round 1's templates broke that by `.to_pylist()` + per-row ``json.loads``
over every event — a Python loop that walls out long before the ML-25M
north star.  These helpers keep everything in Arrow/numpy kernels:

- ``encode_ids``: dictionary-encode an id column → dense int codes + the
  :class:`BiMap` over *unique* ids (Arrow assigns dictionary codes in
  first-appearance order, matching ``BiMap.string_int`` semantics).
- ``numeric_property``: extract one numeric property from the
  ``properties_json`` column with an Arrow regex kernel — C speed, no
  JSON parse.  Sound for numbers because ``DataMap`` serializes via
  ``json.dumps`` (numbers appear as bare literals); not usable for
  string/nested values, which keep the slow path.
- ``event_mask``: boolean numpy mask for event-name membership.
"""

from __future__ import annotations

import re
from typing import Optional, Sequence, Tuple, Union

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from predictionio_tpu.data.event import BiMap

__all__ = ["encode_ids", "numeric_property", "bool_property", "event_mask",
           "dict_take"]

_ColumnLike = Union[pa.Array, pa.ChunkedArray]


def _as_array(col: _ColumnLike) -> pa.Array:
    if isinstance(col, pa.ChunkedArray):
        return col.combine_chunks()
    return col


def dict_take(per_value: np.ndarray, arr: pa.Array, default) -> np.ndarray:
    """Fan a per-DICTIONARY-VALUE result out to per-row via one numpy take.

    The shared core of every dictionary fast path here (and the parquet
    scan filters): null rows surface as null *indices*, which
    ``to_numpy`` converts to float NaN — they must be routed to slot 0
    BEFORE the integer cast and then overwritten with ``default``.
    """
    idx = arr.indices.to_numpy(zero_copy_only=False)
    if arr.null_count:
        nulls = np.asarray(pc.is_null(arr))
        out = per_value[np.where(nulls, 0, idx).astype(np.int64)]
        out[nulls] = default
        return out
    return per_value[idx.astype(np.int64)]


def encode_ids(col: _ColumnLike) -> Tuple[np.ndarray, BiMap]:
    """Id strings → (dense int64 codes, BiMap) without touching Python rows.

    The BiMap is built from the *unique ids present*, in first-appearance
    order (``BiMap.string_int`` semantics), so cost scales with unique
    entities, not events.  Already-dictionary-encoded input (a parquet
    training scan) skips the hash pass entirely: the stored indices are
    re-coded to first-appearance order with two O(events) numpy passes,
    and dictionary entries no surviving row references (a filtered scan
    keeps the full file dictionary) are dropped — the BiMap must not
    invent entities the training data does not contain.
    """
    arr = _as_array(col)
    if arr.null_count:
        raise ValueError(
            f"encode_ids: id column contains {arr.null_count} null(s) — "
            "entity ids must be non-null (filter or fill before encoding)")
    if not pa.types.is_dictionary(arr.type):
        arr = arr.dictionary_encode()
    idx = arr.indices.to_numpy(zero_copy_only=False)
    n_dict = len(arr.dictionary)
    sentinel = np.iinfo(np.int64).max
    first = np.full(n_dict, sentinel, np.int64)
    np.minimum.at(first, idx, np.arange(len(idx), dtype=np.int64))
    present = np.flatnonzero(first < sentinel)
    if len(present) == n_dict and (
            n_dict < 2 or bool(np.all(first[1:] > first[:-1]))):
        # fresh dictionary_encode output: already first-appearance order
        codes = idx.astype(np.int64)
        keys = arr.dictionary.to_pylist()
        return codes, BiMap({k: i for i, k in enumerate(keys)})
    order = present[np.argsort(first[present], kind="stable")]
    remap = np.full(n_dict, -1, np.int64)
    remap[order] = np.arange(len(order))
    codes = remap[idx]
    keys = arr.dictionary.take(pa.array(order)).to_pylist()
    return codes, BiMap({k: i for i, k in enumerate(keys)})


def numeric_property(
    table_or_col: Union[pa.Table, _ColumnLike],
    key: str,
    default: float = 0.0,
) -> np.ndarray:
    """Extract a numeric property per event as float64, ``default`` where
    absent/null.  One Arrow regex kernel over the JSON column."""
    col = (table_or_col.column("properties_json")
           if isinstance(table_or_col, pa.Table) else table_or_col)
    arr = _as_array(col)
    if len(arr) == 0:
        return np.empty(0, dtype=np.float64)
    if pa.types.is_dictionary(arr.type):
        # Low-cardinality property bags (ML-25M has ten distinct rating
        # JSONs across 25M events): run the extraction over the DICTIONARY
        # (O(unique)), then fan out by index — one numpy take.
        if len(arr.dictionary) == 0:
            return np.full(len(arr), default, np.float64)
        return dict_take(numeric_property(arr.dictionary, key,
                                          default=default), arr, default)
    filled = pc.fill_null(arr, "")
    # json.dumps emits numbers bare: "key": -1.5e3, — capture to , } or ].
    pattern = '"' + re.escape(key) + '"\\s*:\\s*(?P<v>-?[0-9][0-9eE+\\-.]*)'
    hit = pc.extract_regex(filled, pattern=pattern)
    vals = pc.struct_field(hit, "v")
    nums = pc.cast(vals, pa.float64())
    out = pc.fill_null(nums, default).to_numpy(zero_copy_only=False).copy()
    # Slow-path guard (round-2 advisor): the regex is only trustworthy when
    # the key text appears EXACTLY once and matched a bare number.  A key
    # repeated inside a nested object / string value, or a numeric value
    # serialized as a string ("rating": "4.5"), falls back to a real JSON
    # parse of just those rows — top-level key only, matching the flat
    # DataMap property-bag semantics.
    lit = '"' + key + '"'
    cnt = pc.count_substring(filled, lit)
    present = pc.greater(cnt, 0)
    # The regex is trusted only when the key text occurs exactly once,
    # matched a bare number, and sits BEFORE any nested object's opening
    # brace — then it provably bound a top-level key.  A flat bag with a
    # trailing nested value ({"rating": 4, "ctx": {...}}) stays on the
    # vectorized path; only key-after-brace rows pay the JSON parse.
    key_off = pc.find_substring(filled, lit)
    brace2 = pc.find_substring(pc.utf8_slice_codeunits(filled, 1), "{")
    nested_before_key = pc.and_(pc.greater_equal(brace2, 0),
                                pc.greater(key_off, brace2))  # off-by-1 safe
    ambiguous = pc.and_(present,
                        pc.or_(pc.or_(pc.greater(cnt, 1), pc.is_null(nums)),
                               nested_before_key))
    amb_idx = np.flatnonzero(ambiguous.to_numpy(zero_copy_only=False))
    if len(amb_idx):
        import json as _json

        raw = filled.take(pa.array(amb_idx)).to_pylist()
        for i, s in zip(amb_idx, raw):
            try:
                v = _json.loads(s).get(key, default)
                out[i] = float(v) if not isinstance(v, bool) else default
            except (ValueError, TypeError, AttributeError):
                out[i] = default
    return out


def bool_property(
    table_or_col: Union[pa.Table, _ColumnLike],
    key: str,
) -> np.ndarray:
    """True where property ``key`` is JSON ``true`` or ``1`` — one regex
    kernel (json.dumps emits booleans as bare ``true``/``false``)."""
    col = (table_or_col.column("properties_json")
           if isinstance(table_or_col, pa.Table) else table_or_col)
    arr = _as_array(col)
    if len(arr) == 0:
        return np.empty(0, dtype=bool)
    if pa.types.is_dictionary(arr.type):
        if len(arr.dictionary) == 0:
            return np.zeros(len(arr), bool)
        return dict_take(bool_property(arr.dictionary, key), arr, False)
    pattern = '"' + re.escape(key) + '"\\s*:\\s*(true|1(?:\\.0*)?)([,}\\s]|$)'
    return pc.match_substring_regex(
        pc.fill_null(arr, ""), pattern
    ).to_numpy(zero_copy_only=False)


def event_mask(
    table: pa.Table,
    names: Sequence[str],
    column: str = "event",
) -> np.ndarray:
    """Boolean mask of rows whose event name is in ``names``."""
    arr = _as_array(table.column(column))
    if pa.types.is_dictionary(arr.type) and len(arr.dictionary):
        # O(unique event names) membership + one numpy take
        vm = pc.is_in(arr.dictionary, value_set=pa.array(list(names)))
        return dict_take(vm.to_numpy(zero_copy_only=False), arr, False)
    return pc.is_in(
        arr, value_set=pa.array(list(names))
    ).to_numpy(zero_copy_only=False)
